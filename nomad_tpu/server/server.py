"""Server — the composition root: state, queues, applier, workers.

Reference: nomad/server.go (:95-259 Server, :293 NewServer) and
nomad/leader.go (:230-347 establishLeadership: enable plan queue, spawn
planApply, enable eval broker + blocked evals, restore queues from durable
state, pause half the workers).

Every cluster write is a typed FSM message (server/fsm.py) submitted
through ``raft_apply`` — backed by InlineRaft (single server, optional WAL
durability + replay-on-boot) or a full RaftNode consensus group
(nomad_tpu.raft) when peers are configured. Mirrors nomad/server.go:
endpoints build requests, the FSM is the only state-store writer.
"""

from __future__ import annotations

import logging
import threading
from typing import Iterable, Optional

from ..broker.blocked import BlockedEvals
from ..broker.eval_broker import EvalBroker
from ..broker.plan_queue import PlanApplyLoop, PlanQueue
from ..state import StateStore
from ..structs import (
    EVAL_STATUS_BLOCKED,
    EVAL_STATUS_PENDING,
    Allocation,
    Evaluation,
    Job,
    Node,
    TRIGGER_JOB_REGISTER,
    TRIGGER_NODE_UPDATE,
    new_id,
)
from ..structs.job import validate_job
from ..structs.evaluation import (
    EVAL_STATUS_COMPLETE,
    TRIGGER_JOB_DEREGISTER,
    TRIGGER_RETRY_FAILED_ALLOC,
)
from .worker import Worker

log = logging.getLogger("nomad_tpu.server")


class ServerConfig:
    def __init__(
        self,
        num_workers: int = 2,
        region: str = "global",
        heartbeat_ttl: float = 5.0,
        deployment_watch_interval: float = 0.25,
        acl_enabled: bool = False,
        data_dir: Optional[str] = None,
        num_batch_workers: int = 1,
        num_lanes: int = 16,
        lane_mode: Optional[bool] = None,
        clock=None,
        eval_deadline: Optional[float] = None,
        eval_attempt_limit: Optional[int] = None,
        admission_overrides: Optional[dict] = None,
        calibration_artifact: Optional[str] = None,
        defrag_interval: float = 0.0,
        defrag_budget: int = 4,
    ):
        import os

        self.num_workers = num_workers
        self.region = region
        self.heartbeat_ttl = heartbeat_ttl
        self.deployment_watch_interval = deployment_watch_interval
        self.acl_enabled = acl_enabled
        self.data_dir = data_dir
        # per-eval processing deadline in the worker (resilience layer):
        # an eval whose pass outlives this is nacked with escalating
        # delay; after eval_attempt_limit expiries it is marked failed
        # with a structured reason. <= 0 disables the deadline.
        if eval_deadline is None:
            eval_deadline = float(
                os.environ.get("NOMAD_TPU_EVAL_DEADLINE", "60")
            )
        self.eval_deadline = eval_deadline
        if eval_attempt_limit is None:
            eval_attempt_limit = int(
                os.environ.get("NOMAD_TPU_EVAL_ATTEMPT_LIMIT", "3")
            )
        self.eval_attempt_limit = eval_attempt_limit
        # injectable cluster clock: an object with time() and
        # monotonic() (e.g. chaos.ChaosClock). Threaded into the eval
        # broker's delay/unack deadlines and the heartbeater's TTL
        # timers so clock-skew faults reach every time-based decision;
        # None means the real clock.
        self.clock = clock
        # workers 0..n-1 run batched device passes, each on its own
        # job-hash partition of the eval stream (the rest drain solo
        # evals). >1 needs the broker's partitioned queues so two
        # batched passes never carry the same jobs.
        self.num_batch_workers = max(1, min(num_batch_workers, num_workers or 1))
        # deterministic lane map size (server/lanes.py). A CONSTANT with
        # respect to the worker count — placement must be a function of
        # (job, cluster state) only, so re-running with more workers
        # yields byte-identical placements — clamped so every batching
        # worker owns at least one lane.
        self.num_lanes = max(int(num_lanes), self.num_batch_workers, 1)
        # lane mode auto-enables with >1 batching worker. The explicit
        # override exists for the byte-identity harness: a 1-worker
        # reference run must take the SAME code path (lane-salted batch
        # passes, lane-partitioned broker) as the N-worker run it is
        # compared against.
        self.lane_mode = (
            self.num_batch_workers > 1 if lane_mode is None else bool(lane_mode)
        )
        # threshold/dwell overrides for the admission controller
        # (server/admission.py); None keeps the production defaults,
        # under which NORMAL behavior is identical to pre-admission.
        self.admission_overrides = admission_overrides
        # path to a persisted saturation-probe artifact (obs/calibrate.py
        # CALIB_r01.json): loaded into the server's calibration table at
        # startup, deriving the admission backlog thresholds from the
        # measured sustainable rate (source: probe). None = shipped
        # defaults.
        self.calibration_artifact = calibration_artifact
        # continuous defragmentation (server/defrag.py): periodic live
        # migration of allocs onto fewer nodes, bounded moves per cycle.
        # <= 0 keeps the periodic scan off (explicit operator triggers
        # still work); budget caps moves per cycle.
        self.defrag_interval = defrag_interval
        self.defrag_budget = defrag_budget


class Server:
    def __init__(self, config: Optional[ServerConfig] = None):
        self.config = config or ServerConfig()
        self.store = StateStore()
        clock = self.config.clock
        # Deterministic lane ownership (server/lanes.py): active only
        # with >1 batching worker. The broker then partitions by LANE
        # (num_lanes sub-queues, same crc32 job hash as LaneMap) so the
        # partitioned dequeue IS lane-affine routing; at one batching
        # worker everything stays on the legacy single-queue path,
        # bit-identical to r5 behavior.
        from .lanes import LaneClaims, LaneMap

        self.lane_mode = self.config.lane_mode
        self.lanes = LaneMap(
            num_lanes=self.config.num_lanes,
            num_batch_workers=self.config.num_batch_workers,
        )
        self.eval_broker = EvalBroker(
            n_partitions=self.lanes.num_lanes if self.lane_mode else 1,
            clock=clock.time if clock is not None else None,
        )
        self.blocked_evals = BlockedEvals(broker=self.eval_broker)
        # overload protection (server/admission.py): one controller per
        # server, fed by the broker's own depth/ack counters and the
        # always-on eval-latency histogram; handed to the broker so its
        # enqueue gate can defer over-watermark external evals.
        from .admission import AdmissionController, HistWindow

        # calibration plane (obs/calibrate.py): a per-server table serves
        # /v1/agent/calibration and derives the admission defaults; a
        # configured probe artifact rewrites the backlog thresholds with
        # source: probe before the controller is built. The throughput
        # estimator is the PROCESS-global one (the learned-mode kernels
        # read it), refcount-attached to the flight recorder for the
        # server's lifetime.
        from ..obs.calibrate import CalibrationTable, global_estimator

        self.calibration = CalibrationTable()
        if self.config.calibration_artifact:
            self.calibration.load_probe_artifact(self.config.calibration_artifact)
        self.throughput_estimator = global_estimator
        self.throughput_estimator.attach()
        admission_cfg = self.calibration.admission_overrides()
        admission_cfg.update(self.config.admission_overrides or {})
        self.admission = AdmissionController(
            clock=clock.monotonic if clock is not None else None,
            depth_fn=self.eval_broker.queue_depths,
            p99_window=HistWindow(
                clock=clock.monotonic if clock is not None else None
            ),
            completions_fn=lambda: self.eval_broker.counters["acks"],
            **admission_cfg,
        )
        self.eval_broker.admission = self.admission
        self.plan_queue = PlanQueue()
        self.plan_apply_loop = PlanApplyLoop(
            self.store, self.plan_queue,
            on_evals_created=self.eval_broker.enqueue_all,
            commit=self._commit_plan_result,
            commit_merged=self._commit_merged_plan_result,
            lanes=self.lanes if self.lane_mode else None,
            token_check=self._plan_token_current,
        )
        self.workers: list[Worker] = []
        # resident device tensors shared by all workers, refreshed
        # incrementally by state index (SURVEY.md §7 'latency floor')
        from ..device.cache import DeviceStateCache

        self.device_cache = DeviceStateCache()
        # per-worker epoch overlays for pipelined batched passes
        # (server/overlay.py). In lane mode each batching worker owns
        # its own overlay — no shared mutable optimistic state; at one
        # batching worker the container delegates to a single overlay,
        # preserving the legacy shared behavior bit-for-bit.
        from .overlay import LaneOverlays

        self.placement_overlay = LaneOverlays(self.config.num_batch_workers)
        # cross-lane handoff table (reserve → confirm → release)
        self.lane_claims = LaneClaims(
            self.lanes,
            overlays=self.placement_overlay,
            snapshot_fn=self.store.snapshot,
        )
        self._raft_lock = threading.Lock()
        self._leader = False
        from ..broker.event_broker import EventBroker as StreamBroker
        from .core_gc import CoreScheduler
        from .deployment_watcher import DeploymentWatcher
        from .drainer import NodeDrainer
        from .heartbeat import NodeHeartbeater
        from .periodic import PeriodicDispatch

        self.drainer = NodeDrainer(self)
        from .defrag import DefragController

        self.defrag = DefragController(
            self,
            interval=self.config.defrag_interval,
            budget=self.config.defrag_budget,
        )
        self.heartbeater = NodeHeartbeater(
            self,
            ttl=self.config.heartbeat_ttl,
            clock=clock.monotonic if clock is not None else None,
        )
        self.deployment_watcher = DeploymentWatcher(
            self, interval=self.config.deployment_watch_interval
        )
        self.periodic = PeriodicDispatch(self)
        self.core_gc = CoreScheduler(self)
        from .volume_watcher import VolumeWatcher

        self.volume_watcher = VolumeWatcher(self)
        self.events = StreamBroker()
        from .acl import ACLService

        self.acl = ACLService(self)
        # capacity changes unblock blocked evals (blocked_evals.go:55)
        self.store.add_listener(self._on_state_change)
        # the raft seam: FSM messages through InlineRaft (single server;
        # WAL-durable when data_dir is set). A consensus RaftNode swaps in
        # via attach_raft() for clustered servers.
        from ..raft import InlineRaft
        from ..state.snapshot import restore_snapshot, save_snapshot
        from .fsm import FSM, MsgType

        self._msg = MsgType
        self.fsm = FSM(lambda: self.store)
        self.raft = InlineRaft(
            self.fsm,
            data_dir=self.config.data_dir,
            snapshot_fn=lambda path: save_snapshot(self.store, path),
            restore_fn=lambda path: self._install_store(restore_snapshot(path)),
        )
        if self.config.data_dir:
            self.raft.restore()

    def _install_store(self, store) -> int:
        """Swap in a restored StateStore (snapshot restore / install)."""
        self.store = store
        self.plan_apply_loop.applier.store = store
        store.add_listener(self._on_state_change)
        # the restored store has a fresh journal that never names entities
        # deleted across the swap — resident tensors must rebuild
        self.device_cache.invalidate()
        return store.latest_index

    def attach_raft(self, raft) -> None:
        """Replace the inline seam with a consensus RaftNode (cluster)."""
        self.raft = raft

    @classmethod
    def from_snapshot(cls, path: str, config: Optional[ServerConfig] = None):
        """Boot a server from a saved state snapshot (the restore half of
        checkpoint/resume; nomadFSM.Restore + leader queue restoration)."""
        from ..state.snapshot import restore_snapshot

        server = cls(config)
        server._install_store(restore_snapshot(path))
        return server

    # -- API: namespaces (nomad/namespace_endpoint.go) ---------------------
    def upsert_namespace(self, ns) -> None:
        if not ns.name or not ns.name.replace("-", "").replace("_", "").isalnum():
            raise ValueError(f"invalid namespace name {ns.name!r}")
        self.raft_apply_checked(
            self._msg.NAMESPACE_UPSERT, {"namespace": ns}
        )

    def delete_namespace(self, name: str) -> None:
        self.raft_apply_checked(self._msg.NAMESPACE_DELETE, {"name": name})

    # -- API: scaling (nomad/job_endpoint.go Scale + scaling_endpoint.go) --
    def scale_job(self, namespace: str, job_id: str, group: str,
                  count: int, message: str = "", error: bool = False):
        """Job.Scale: adjust one group's count (a new job version) and
        record a scaling event; autoscalers drive this endpoint."""
        import copy as _copy

        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            raise KeyError(f"job not found: {job_id}")
        tg = job.lookup_task_group(group)
        if tg is None:
            raise KeyError(f"group not found: {group}")
        from ..structs.evaluation import TRIGGER_JOB_SCALING
        from .admission import job_cost_demand

        self.admission.check_intake(
            job.priority, TRIGGER_JOB_SCALING,
            cost_demand=job_cost_demand(job),
        )
        if tg.scaling is not None and tg.scaling.enabled:
            if count < tg.scaling.min or (
                tg.scaling.max and count > tg.scaling.max
            ):
                raise ValueError(
                    f"count {count} outside scaling bounds "
                    f"[{tg.scaling.min}, {tg.scaling.max}]"
                )
        scaled = _copy.deepcopy(job)
        scaled.lookup_task_group(group).count = count
        ev = Evaluation(
            namespace=namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=TRIGGER_JOB_REGISTER,
            job_id=job_id,
            status=EVAL_STATUS_PENDING,
        )
        event = {
            "group": group, "count": count, "previous_count": tg.count,
            "message": message, "error": error,
        }
        self.raft_apply(
            self._msg.JOB_SCALE,
            {"job": scaled, "evals": [ev], "event": event},
        )
        (ev,) = self._fresh_evals([ev])
        self.eval_broker.enqueue(ev)
        self._publish(
            "Job", "JobScaled", job_id, namespace,
            {"group": group, "count": count},
        )
        return ev

    def _plan_token_current(self, eval_id: str, token: str) -> bool:
        """Is ``token`` still the eval's outstanding broker token? Used
        by the plan applier to drop plans from workers whose eval was
        redelivered out from under them (unack-deadline expiry) — the
        reference's plan-submission token validation."""
        return self.eval_broker.outstanding_token(eval_id) == token

    def _commit_plan_result(self, result, eval_id, evals) -> int:
        index, _ = self.raft_apply(
            self._msg.PLAN_RESULT,
            {"result": result, "eval_id": eval_id, "evals": evals},
        )
        return index

    def _commit_merged_plan_result(self, results, eval_ids, evals) -> int:
        """One batched pass's member results land as ONE log entry — the
        merged-commit analog of _commit_plan_result."""
        index, _ = self.raft_apply(
            self._msg.MERGED_PLAN_RESULT,
            {"results": results, "eval_ids": eval_ids, "evals": evals},
        )
        return index

    def _fresh_evals(self, evals):
        """Re-read evals from the store after a raft commit: with a real
        consensus group the FSM applies unpickled COPIES, so the submitted
        objects lack the committed modify_index the worker's
        snapshot-min-index wait (worker.py:88) depends on."""
        out = []
        for ev in evals:
            out.append(self.store.eval_by_id(ev.id) or ev)
        return out

    # -- raft seam ---------------------------------------------------------
    def raft_apply(self, mtype, payload=None):
        """Submit one FSM message through the raft seam; returns
        (index, applier_result). Raises NotLeaderError on a follower —
        the RPC layer forwards to the leader (nomad/rpc.go forward())."""
        return self.raft.apply(mtype, payload)

    def raft_apply_checked(self, mtype, payload=None):
        """raft_apply for user-facing endpoints: a rejection the FSM
        returned as a result (appliers never raise) is re-raised here, on
        the submitting server only."""
        index, result = self.raft.apply(mtype, payload)
        if isinstance(result, Exception):
            raise result
        return index, result

    # -- leadership --------------------------------------------------------
    def establish_leadership(self) -> None:
        """leader.go:230-347."""
        self._leader = True
        self.plan_queue.set_enabled(True)
        self.plan_apply_loop.start()
        self.eval_broker.set_enabled(True)
        self.blocked_evals.set_enabled(True)
        self.heartbeater.initialize_from_store()
        self.heartbeater.start()
        self.deployment_watcher.start()
        self.drainer.start()
        self.defrag.start()
        self.periodic.restore()
        self.periodic.start()
        self.core_gc.start()
        self.volume_watcher.start()
        self._restore_evals()
        for i in range(self.config.num_workers):
            w = Worker(self, worker_id=i)
            self.workers.append(w)
            w.start()

    def revoke_leadership(self) -> None:
        for w in self.workers:
            w.stop()
        self.workers.clear()
        self.heartbeater.stop()
        self.deployment_watcher.stop()
        self.drainer.stop()
        self.defrag.stop()
        self.periodic.stop()
        self.core_gc.stop()
        self.volume_watcher.stop()
        self.plan_apply_loop.stop()
        self.plan_queue.set_enabled(False)
        self.eval_broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self._leader = False

    def shutdown(self) -> None:
        if self._leader:
            self.revoke_leadership()
        # release this server's hold on the process-global estimator
        # (refcounted; the listener detaches with the last server)
        est = getattr(self, "throughput_estimator", None)
        if est is not None:
            est.detach()
            self.throughput_estimator = None
        # flush + release the durable log (InlineRaft.close is idempotent;
        # a consensus RaftNode is owned and closed by its ClusterServer)
        close = getattr(self.raft, "close", None)
        if close is not None:
            close()

    def _restore_evals(self) -> None:
        """Re-populate broker/blocked from durable state on leadership
        (leader.go:269 restoreEvals)."""
        for ev in self.store.evals():
            if ev.should_enqueue():
                self.eval_broker.enqueue(ev)
            elif ev.should_block():
                self.blocked_evals.block(ev)

    # -- API: jobs ---------------------------------------------------------
    def register_job(self, job: Job) -> Evaluation:
        """Job.Register (nomad/job_endpoint.go): upsert job + create eval
        in one commit, then enqueue."""
        validate_job(job)
        # overload gate BEFORE any state commit: a shed register raises
        # AdmissionRejected (HTTP: 429 + Retry-After) with nothing
        # written, so job/eval conservation laws never see it
        from .admission import job_cost_demand

        self.admission.check_intake(
            job.priority, TRIGGER_JOB_REGISTER,
            cost_demand=job_cost_demand(job),
        )
        # periodic/parameterized jobs are templates: no eval until a child
        # is derived (job_endpoint.go Register skips eval creation for them)
        needs_eval = not job.is_periodic() and not job.is_parameterized()
        ev = Evaluation(
            namespace=job.namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=TRIGGER_JOB_REGISTER,
            job_id=job.id,
            status=EVAL_STATUS_PENDING,
        )

        self.raft_apply(
            self._msg.JOB_UPSERT,
            {"job": job, "evals": [ev] if needs_eval else []},
        )
        self.blocked_evals.untrack(job.namespace, job.id)
        self._publish(
            "Job", "JobRegistered", job.id, job.namespace, {"job_id": job.id}
        )
        if job.is_periodic():
            self.periodic.add(job)
        if needs_eval:
            (ev,) = self._fresh_evals([ev])
            self.eval_broker.enqueue(ev)
        return ev

    def dispatch_job(
        self, namespace: str, job_id: str, payload: bytes = b"", meta=None
    ):
        """Dispatch a parameterized job: derive a one-shot child
        (nomad/job_endpoint.go Job.Dispatch)."""
        import copy as _copy
        import time as _t

        parent = self.store.job_by_id(namespace, job_id)
        if parent is None or not parent.is_parameterized():
            raise ValueError(f"job {job_id} is not parameterized")
        cfg = parent.parameterized
        meta = dict(meta or {})
        missing = [k for k in cfg.meta_required if k not in meta]
        if missing:
            raise ValueError(f"missing required dispatch meta: {missing}")
        unknown = [
            k
            for k in meta
            if k not in cfg.meta_required and k not in cfg.meta_optional
        ]
        if unknown:
            raise ValueError(f"dispatch meta not allowed: {unknown}")
        if cfg.payload == "required" and not payload:
            raise ValueError("dispatch payload is required")
        if cfg.payload == "forbidden" and payload:
            raise ValueError("dispatch payload is forbidden")
        child = _copy.deepcopy(parent)
        child.id = f"{parent.id}/dispatch-{int(_t.time())}-{new_id()[:8]}"
        child.name = child.id
        child.parameterized = None
        child.parent_id = parent.id
        child.payload = payload
        child.meta = {**parent.meta, **meta}
        ev = self.register_job(child)
        return child, ev

    def deregister_job(self, namespace: str, job_id: str) -> Optional[Evaluation]:
        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            return None
        import copy

        stopped = copy.deepcopy(job)
        stopped.stop = True
        ev = Evaluation(
            namespace=namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=TRIGGER_JOB_DEREGISTER,
            job_id=job_id,
            status=EVAL_STATUS_PENDING,
        )

        self.raft_apply(self._msg.JOB_UPSERT, {"job": stopped, "evals": [ev]})
        self.blocked_evals.untrack(namespace, job_id)
        self.periodic.remove(namespace, job_id)
        self._publish(
            "Job", "JobDeregistered", job_id, namespace, {"job_id": job_id}
        )
        (ev,) = self._fresh_evals([ev])
        self.eval_broker.enqueue(ev)
        return ev

    # -- API: nodes --------------------------------------------------------
    def register_node(self, node: Node) -> None:
        self.raft_apply(self._msg.NODE_UPSERT, {"node": node})
        self._publish(
            "Node", "NodeRegistration", node.id, "default", {"node_id": node.id}
        )

    def update_node_status(self, node_id: str, status: str) -> list[Evaluation]:
        """Node.UpdateStatus: commit + fan out node-update evals for every
        job with allocs on the node (nomad/node_endpoint.go createNodeEvals)."""
        self.raft_apply(
            self._msg.NODE_STATUS, {"node_id": node_id, "status": status}
        )
        self._publish(
            "Node", "NodeStatusUpdate", node_id, "default", {"status": status}
        )
        return self._create_node_evals(node_id)

    def update_node_drain(self, node_id: str, drain) -> list[Evaluation]:
        """Node.UpdateDrain: stamp the force deadline and commit; the
        NodeDrainer picks the node up on its next scan. Cancelling a
        drain clears any pending migrate marks so wave accounting and
        future drains start clean (drainer.go Remove)."""
        import time as _t

        if drain is not None and drain.deadline_s > 0 and not drain.force_deadline_unix:
            drain.force_deadline_unix = _t.time() + drain.deadline_s

        resets = {}
        if drain is None:
            from ..structs.alloc import DesiredTransition as _DT

            for a in self.store.allocs_by_node(node_id):
                if not a.terminal_status() and a.desired_transition.migrate:
                    resets[a.id] = _DT(migrate=False)

        self.raft_apply(
            self._msg.NODE_DRAIN,
            {"node_id": node_id, "drain": drain, "transitions": resets},
        )
        return self._create_node_evals(node_id)

    def stop_alloc(self, alloc_id: str) -> Optional[Evaluation]:
        """Alloc.Stop (nomad/alloc_endpoint.go): mark the allocation for
        migration and evaluate its job — the reconciler replaces it on
        another node. Returns the eval (None if the alloc is unknown or
        already terminal)."""
        from ..structs.alloc import DesiredTransition as _DT
        from ..structs.evaluation import (
            EVAL_STATUS_PENDING,
            TRIGGER_ALLOC_STOP,
        )

        alloc = self.store.alloc_by_id(alloc_id)
        if alloc is None or alloc.terminal_status():
            return None
        job = self.store.job_by_id(alloc.namespace, alloc.job_id)
        ev = Evaluation(
            namespace=alloc.namespace,
            priority=job.priority if job else 50,
            type=job.type if job else "service",
            triggered_by=TRIGGER_ALLOC_STOP,
            job_id=alloc.job_id,
            status=EVAL_STATUS_PENDING,
        )
        self.raft_apply(
            self._msg.ALLOC_DESIRED_TRANSITION,
            {
                "transitions": {alloc_id: _DT(migrate=True)},
                "evals": [ev],
            },
        )
        (ev,) = self._fresh_evals([ev])
        self.eval_broker.enqueue(ev)
        return ev

    def _create_node_evals(self, node_id: str) -> list[Evaluation]:
        jobs = {}
        for a in self.store.allocs_by_node(node_id):
            if not a.terminal_status() or a.client_status == "failed":
                jobs[(a.namespace, a.job_id)] = a
        evals = []
        for (ns, job_id), a in jobs.items():
            job = self.store.job_by_id(ns, job_id)
            evals.append(
                Evaluation(
                    namespace=ns,
                    priority=job.priority if job else 50,
                    type=job.type if job else "service",
                    triggered_by=TRIGGER_NODE_UPDATE,
                    job_id=job_id,
                    node_id=node_id,
                    status=EVAL_STATUS_PENDING,
                )
            )
        # system jobs must also react to new/changed nodes
        node = self.store.node_by_id(node_id)
        if node is not None and node.ready():
            for job in self.store.jobs():
                if job.type in ("system", "sysbatch") and not job.stopped():
                    evals.append(
                        Evaluation(
                            namespace=job.namespace,
                            priority=job.priority,
                            type=job.type,
                            triggered_by=TRIGGER_NODE_UPDATE,
                            job_id=job.id,
                            node_id=node_id,
                            status=EVAL_STATUS_PENDING,
                        )
                    )
        if evals:
            self.raft_apply(self._msg.EVAL_UPSERT, {"evals": evals})
            evals = self._fresh_evals(evals)
            self.eval_broker.enqueue_all(evals)
        return evals

    # -- API: client alloc updates ----------------------------------------
    # -- CSI volumes (csi_endpoint.go Register/Deregister/Claim) -----------
    def register_csi_volume(self, vol) -> None:
        self.raft_apply_checked(self._msg.CSI_VOLUME_UPSERT, {"volume": vol})

    def deregister_csi_volume(self, volume_id: str, force: bool = False) -> None:
        self.raft_apply_checked(
            self._msg.CSI_VOLUME_DEREGISTER,
            {"volume_id": volume_id, "force": force},
        )

    def claim_csi_volume(
        self, volume_id: str, alloc_id: str, node_id: str, read_only: bool
    ) -> bool:
        """Client-initiated claim (CSIVolume.Claim RPC) — plan apply claims
        eagerly, so this is for external/API claimants. Claims whose id is
        not a live alloc are marked external so the volume watcher never
        reaps them as "alloc gone"."""
        _i, ok = self.raft_apply(
            self._msg.CSI_CLAIM,
            {
                "volume_id": volume_id, "claim_id": alloc_id,
                "node_id": node_id, "read_only": read_only,
            },
        )
        return bool(ok)

    def update_allocs_from_client(self, updates: Iterable[Allocation]) -> None:
        updates = list(updates)
        self.raft_apply(self._msg.ALLOC_CLIENT_UPDATE, {"updates": updates})
        for u in updates:
            self._publish(
                "Allocation",
                "AllocationClientUpdated",
                u.id,
                u.namespace,
                {"client_status": u.client_status, "job_id": u.job_id},
            )
        # terminal client statuses free capacity ⇒ unblock held evals
        if any(
            u.client_status in ("complete", "failed", "lost") for u in updates
        ):
            self.blocked_evals.unblock(index=self.store.latest_index)
        # failed allocs trigger reschedule evals (node_endpoint.go)
        evals = []
        seen = set()
        for upd in updates:
            if upd.client_status != "failed":
                continue
            a = self.store.alloc_by_id(upd.id)
            if a is None or (a.namespace, a.job_id) in seen:
                continue
            seen.add((a.namespace, a.job_id))
            job = self.store.job_by_id(a.namespace, a.job_id)
            if job is None or job.stopped():
                continue
            evals.append(
                Evaluation(
                    namespace=a.namespace,
                    priority=job.priority,
                    type=job.type,
                    triggered_by=TRIGGER_RETRY_FAILED_ALLOC,
                    job_id=a.job_id,
                    status=EVAL_STATUS_PENDING,
                )
            )
        if evals:
            self.raft_apply(self._msg.EVAL_UPSERT, {"evals": evals})
            self.eval_broker.enqueue_all(self._fresh_evals(evals))

    # -- eval lifecycle (worker callbacks) ---------------------------------
    def apply_eval_update(self, evals: list[Evaluation]) -> None:
        self.raft_apply(self._msg.EVAL_UPSERT, {"evals": evals})
        for ev in self._fresh_evals(evals):
            if ev.status == EVAL_STATUS_BLOCKED:
                self.blocked_evals.block(ev)

    def apply_eval_create(self, evals: list[Evaluation]) -> None:
        self.raft_apply(self._msg.EVAL_UPSERT, {"evals": evals})
        for ev in self._fresh_evals(evals):
            if ev.status == EVAL_STATUS_BLOCKED:
                self.blocked_evals.block(ev)
            elif ev.wait_until_unix:
                self.eval_broker.enqueue(ev)
            elif ev.should_enqueue():
                self.eval_broker.enqueue(ev)

    # -- state-change fan-out ----------------------------------------------
    def _on_state_change(self, table: str, index: int) -> None:
        if table == "nodes":
            # capacity may have appeared: unblock everything eligible
            self.blocked_evals.unblock(index=index)

    def _publish(
        self, topic: str, type_: str, key: str, namespace: str, payload: dict
    ) -> None:
        from ..broker.event_broker import Event

        self.events.publish(
            [Event(topic=topic, type=type_, key=key, namespace=namespace, payload=payload)],
            self.store.latest_index,
        )

    # -- client RPC seam ---------------------------------------------------
    def client_rpc(self) -> "InProcessClientRPC":
        return InProcessClientRPC(self)

    def pull_allocs(
        self, node_id: str, min_index: int, timeout: float = 1.0
    ) -> tuple[list[Allocation], int]:
        """Blocking query: the client's alloc pull (node_endpoint.go
        Node.GetClientAllocs semantics — return once state moves past the
        client's known index, or on timeout)."""
        if self.store.latest_index <= min_index:
            self.store.wait_for_index(min_index + 1, timeout=timeout)
        return self.store.allocs_by_node(node_id), self.store.latest_index

    # -- convenience -------------------------------------------------------
    def wait_for_evals(self, timeout: float = 10.0) -> bool:
        """Test/ops helper: wait until no ready or in-flight evals remain."""
        import time

        deadline = time.time() + timeout
        while time.time() < deadline:
            with self.eval_broker._lock:
                busy = (
                    self.eval_broker.ready_count()
                    + len(self.eval_broker._unack)
                    + len(self.eval_broker._delayed)
                )
            if busy == 0 and self.plan_queue.depth() == 0:
                return True
            time.sleep(0.01)
        return False


class InProcessClientRPC:
    """The client↔server transport seam, in-process flavor (the reference's
    msgpack-RPC client/rpc.go collapses to method calls for the dev agent)."""

    def __init__(self, server: Server):
        self.server = server

    def register_node(self, node) -> None:
        self.server.register_node(node)
        self.server.heartbeater.heartbeat(node.id)

    def heartbeat(self, node_id: str) -> float:
        node = self.server.store.node_by_id(node_id)
        if node is not None and node.status == "down":
            # node recovered after missed TTLs (heartbeat.go resurrection)
            self.server.update_node_status(node_id, "ready")
        return self.server.heartbeater.heartbeat(node_id)

    def pull_allocs(self, node_id: str, min_index: int, timeout: float):
        return self.server.pull_allocs(node_id, min_index, timeout)

    def update_allocs(self, updates) -> None:
        self.server.update_allocs_from_client(updates)

    def csi_volume_info(self, volume_id: str):
        """(resolved_volume_id, plugin_id) or None — the client's volume
        resolver for CSI publish routing (CSIVolume.Get's role). The
        caller may pass a per-alloc id (``source[idx]``); resolution
        falls back to the base source exactly like the scheduler and the
        plan applier do."""
        store = self.server.store
        vol = store.csi_volume_by_id(volume_id)
        if vol is None and "[" in volume_id:
            base = volume_id.split("[", 1)[0]
            vol = store.csi_volume_by_id(base)
        if vol is None:
            return None
        return vol.id, vol.plugin_id
