"""Periodic dispatcher — cron-style job launching (leader-only).

Reference: nomad/periodic.go (PeriodicDispatch): tracks registered
periodic jobs, sleeps until the next launch time, derives a child job
``<parent>/periodic-<epoch>`` and registers it, honoring
prohibit_overlap. Restored from durable state on leadership
(leader.go:287).
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Optional

from ..structs import Job
from ..utils.cron import Cron, CronParseError


class PeriodicDispatch:
    def __init__(self, server, tick: float = 0.5):
        self.server = server
        self.tick = tick
        self._tracked: dict[tuple[str, str], tuple[Job, Cron]] = {}
        self._next_launch: dict[tuple[str, str], float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="periodic-dispatch", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    # -- tracking ----------------------------------------------------------
    def add(self, job: Job) -> None:
        if not job.is_periodic() or not job.periodic.enabled or job.stopped():
            self.remove(job.namespace, job.id)
            return
        try:
            cron = Cron(job.periodic.spec)
        except CronParseError:
            return
        with self._lock:
            key = job.namespaced_id()
            self._tracked[key] = (job, cron)
            self._next_launch[key] = cron.next_after(time.time())

    def remove(self, namespace: str, job_id: str) -> None:
        with self._lock:
            self._tracked.pop((namespace, job_id), None)
            self._next_launch.pop((namespace, job_id), None)

    def restore(self) -> None:
        for job in self.server.store.jobs():
            if job.is_periodic():
                self.add(job)

    def tracked_count(self) -> int:
        with self._lock:
            return len(self._tracked)

    # -- launch loop -------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.tick):
            now = time.time()
            due = []
            with self._lock:
                for key, when in list(self._next_launch.items()):
                    if when <= now:
                        job, cron = self._tracked[key]
                        due.append((key, job, cron))
            for key, job, cron in due:
                try:
                    self.force_launch(job, launch_time=now)
                finally:
                    with self._lock:
                        if key in self._tracked:
                            self._next_launch[key] = cron.next_after(now)

    def force_launch(self, job: Job, launch_time: Optional[float] = None) -> Optional[Job]:
        """Derive and register the child for one launch
        (periodic.go createEval / derivedJob)."""
        launch_time = launch_time or time.time()
        store = self.server.store
        child_id = f"{job.id}/periodic-{int(launch_time)}"
        while store.job_by_id(job.namespace, child_id) is not None:
            # same-second launches must not silently upsert the prior child
            import uuid as _uuid

            child_id = f"{job.id}/periodic-{int(launch_time)}-{_uuid.uuid4().hex[:6]}"
        if job.periodic.prohibit_overlap:
            prefix = job.id + "/periodic-"
            for child_job in store.jobs():
                if (
                    child_job.namespace != job.namespace
                    or not child_job.id.startswith(prefix)
                    or child_job.stopped()
                    or child_job.status == "dead"
                ):
                    continue
                # a child is "still running" if any of its allocs OR evals
                # are non-terminal — a blocked eval with zero allocs still
                # means the previous launch hasn't finished
                allocs = store.allocs_by_job(child_job.namespace, child_job.id)
                evs = store.evals_by_job(child_job.namespace, child_job.id)
                if (allocs or evs) and (
                    any(not a.terminal_status() for a in allocs)
                    or any(not e.terminal_status() for e in evs)
                ):
                    return None  # previous launch still in flight
        child = copy.deepcopy(job)
        child.id = child_id
        child.name = child_id
        child.periodic = None
        child.parent_id = job.id
        self.server.register_job(child)
        return child
