"""Server ACL endpoints + token resolution.

Reference: nomad/acl_endpoint.go (Bootstrap, UpsertPolicies, DeletePolicies,
GetPolicy/ListPolicies, UpsertTokens, DeleteTokens, ResolveToken) and
nomad/acl.go (Server.ResolveToken → compiled ACL with cache; anonymous
token handling).
"""

from __future__ import annotations

from typing import Iterable, Optional

from .fsm import MsgType
from ..acl import (
    ACL,
    AclCache,
    MANAGEMENT_ACL,
    ACLPolicyRecord,
    ACLToken,
    compile_acl,
    parse_policy,
)
from ..acl.tokens import ANONYMOUS_POLICY_NAME, TOKEN_TYPE_MANAGEMENT


class TokenError(Exception):
    """Unknown or invalid token (maps to HTTP 403)."""


class ACLService:
    """Bound to a Server; owns the resolution cache and endpoint logic."""

    def __init__(self, server):
        self.server = server
        self.cache = AclCache()

    @property
    def enabled(self) -> bool:
        return self.server.config.acl_enabled

    # -- bootstrap ---------------------------------------------------------
    def bootstrap(self) -> ACLToken:
        """One-time creation of the initial management token
        (acl_endpoint.go Bootstrap)."""
        if not self.enabled:
            raise PermissionError("ACL support disabled")
        token = ACLToken(
            name="Bootstrap Token", type=TOKEN_TYPE_MANAGEMENT, global_=True
        )
        self.server.raft_apply_checked(MsgType.ACL_BOOTSTRAP, {"token": token})
        return token

    # -- policies ----------------------------------------------------------
    def upsert_policies(self, policies: Iterable[ACLPolicyRecord]) -> None:
        policies = list(policies)
        for p in policies:
            parse_policy(p.rules)  # validates; raises AclPolicyError
            if not p.name:
                raise ValueError("policy name required")
        self.server.raft_apply_checked(MsgType.ACL_POLICY_UPSERT, {"policies": policies})
        self.cache = AclCache()  # rules changed: drop compiled ACLs

    def delete_policies(self, names: Iterable[str]) -> None:
        names = list(names)
        self.server.raft_apply_checked(MsgType.ACL_POLICY_DELETE, {"names": names})
        self.cache = AclCache()

    # -- tokens ------------------------------------------------------------
    def upsert_tokens(self, tokens: Iterable[ACLToken]) -> list[ACLToken]:
        tokens = list(tokens)
        for t in tokens:
            errs = t.validate()
            if errs:
                raise ValueError("; ".join(errs))
            for pname in t.policies:
                if self.server.store.acl_policy_by_name(pname) is None:
                    raise ValueError(f"policy {pname!r} does not exist")
        self.server.raft_apply_checked(MsgType.ACL_TOKEN_UPSERT, {"tokens": tokens})
        return tokens

    def delete_tokens(self, accessor_ids: Iterable[str]) -> None:
        ids = list(accessor_ids)
        self.server.raft_apply_checked(MsgType.ACL_TOKEN_DELETE, {"accessor_ids": ids})

    # -- resolution --------------------------------------------------------
    def resolve_token(self, secret_id: str) -> Optional[ACL]:
        """nomad/acl.go ResolveToken. Returns None when ACLs are disabled
        (callers skip enforcement); raises TokenError on unknown secrets."""
        if not self.enabled:
            return None
        if not secret_id:
            return self._anonymous_acl()
        token = self.server.store.acl_token_by_secret(secret_id)
        if token is None:
            raise TokenError("ACL token not found")
        if token.is_management():
            return MANAGEMENT_ACL
        return self._compile_for(token.policies)

    def _anonymous_acl(self) -> ACL:
        anon = self.server.store.acl_policy_by_name(ANONYMOUS_POLICY_NAME)
        if anon is None:
            return ACL(management=False)  # denies everything
        return self._compile_for([ANONYMOUS_POLICY_NAME])

    def _compile_for(self, policy_names: list[str]) -> ACL:
        records = []
        for name in sorted(set(policy_names)):
            rec = self.server.store.acl_policy_by_name(name)
            if rec is None:
                raise TokenError(f"token policy {name!r} does not exist")
            records.append(rec)
        key = tuple((r.name, r.modify_index) for r in records)
        return self.cache.get_or_compile(
            key, lambda: [parse_policy(r.rules) for r in records]
        )
