"""Deterministic lane ownership — the structurally conflict-free
multi-worker commit path.

The optimistic posture (reference Nomad, and this repo through r5) lets
any worker place on any node and relies on the serialized plan applier
to bounce whatever went stale. That is correct but not *stable*: two
pipelined batching workers racing commits under CPU starvation swung
the conflict rate 0.0–0.96 run to run (PERF_NOTES_r05.md). This module
replaces hope with a contract:

``LaneMap``
    every job and every node hash onto exactly one of ``num_lanes``
    lanes (the job hash is byte-identical to the eval broker's
    partition key, so broker routing IS lane routing), and each lane is
    owned by exactly one batching worker (``lane % num_batch_workers``).
    ``num_lanes`` is a constant independent of the worker count — a
    placement decision must be a function of (job, cluster state) only,
    never of how many workers happen to be running, or a 2-worker run
    could not be byte-identical to the 1-worker reference run.

``LaneClaims``
    the ordered two-phase cross-lane handoff. A batched pass scores the
    FULL cluster (minus actively-claimed nodes), so an eval whose best
    node belongs to a peer's lane is normal, not an error; before that
    placement may ride a merged commit, the committing worker must
    ``reserve`` the foreign nodes (refused if any is already claimed or
    settled) and ``confirm`` the claim (peer's scoring quiesced, no
    peer in-flight delta on the node, and a FRESH store-snapshot
    capacity re-check). A confirmed claim is attached to the MergedPlan
    so the applier can *assert* disjointness instead of discovering
    conflicts. ``release`` always runs (finally — even a chaos
    thread-kill cannot skip it), so a dropped handoff can never leak a
    reservation.

Settled nodes: once a handoff COMMITS, the node's owner still holds a
frozen overlay base that predates the foreign write, so the node stays
blocked for everyone until the owner's next epoch reset rebases it
(``clear_settled``). That closing of the stale-base window is what makes
``nomad.plan.lane_conflicts == 0`` an invariant rather than a hope.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Optional

from ..chaos.plane import chaos_site
from ..utils.metrics import global_metrics as metrics

#: lanes in the deterministic map. A constant (not the worker count!)
#: so lane_of_job/lane_of_node — and therefore placement salts and
#: handoff boundaries — never move when the cluster is re-run with a
#: different ``num_batch_workers``.
DEFAULT_NUM_LANES = 16

#: how long ``confirm`` waits for a claimed node's owner to finish its
#: in-flight scoring pass before rejecting the handoff. Passes are
#: bounded device work; a peer that cannot quiesce in this window is
#: busy enough that falling back (solo, own-lane) is the cheaper move.
CONFIRM_QUIESCE_TIMEOUT = 0.25


class LaneMap:
    """Pure deterministic assignment: job → lane, node → lane,
    lane → owning batch worker. Stateless after construction."""

    def __init__(
        self,
        num_lanes: int = DEFAULT_NUM_LANES,
        num_batch_workers: int = 1,
    ):
        # every worker must own at least one lane
        self.num_lanes = max(int(num_lanes), int(num_batch_workers), 1)
        self.num_batch_workers = max(1, int(num_batch_workers))

    # -- assignment (the contract) -----------------------------------------
    def lane_of_job(self, namespace: str, job_id: str) -> int:
        """Byte-identical to EvalBroker._queue_key's partition hash, so
        the broker's partitioned dequeue IS lane-affine routing."""
        return zlib.crc32(f"{namespace}/{job_id}".encode()) % self.num_lanes

    def lane_of_node(self, node_id: str) -> int:
        return zlib.crc32(node_id.encode()) % self.num_lanes

    def owner_of_lane(self, lane: int) -> int:
        return lane % self.num_batch_workers

    def owner_of_job(self, namespace: str, job_id: str) -> int:
        return self.owner_of_lane(self.lane_of_job(namespace, job_id))

    def owner_of_node(self, node_id: str) -> int:
        return self.owner_of_lane(self.lane_of_node(node_id))

    def lanes_of_worker(self, worker_id: int) -> tuple[int, ...]:
        """The disjoint lane set one batching worker owns (empty for
        solo workers — they never touch the lane-affine queues)."""
        if worker_id >= self.num_batch_workers:
            return ()
        return tuple(
            lane
            for lane in range(self.num_lanes)
            if lane % self.num_batch_workers == worker_id
        )

    def assignments(self) -> dict[int, tuple[int, ...]]:
        """worker → owned lanes, for the resilience status surfaces."""
        return {
            w: self.lanes_of_worker(w) for w in range(self.num_batch_workers)
        }


class LaneClaim:
    """One cross-lane handoff: ``claimant`` (worker id) holding foreign
    ``nodes`` (node id → list of proposed new Allocations) for one
    eval's merged-plan member."""

    __slots__ = (
        "claimant", "eval_id", "nodes", "confirmed", "submitted", "released",
    )

    def __init__(self, claimant: int, eval_id: str, nodes: dict):
        self.claimant = claimant
        self.eval_id = eval_id
        self.nodes = nodes
        self.confirmed = False
        # set right before the merged plan is enqueued: past this point
        # the applier may land the claim's placements even if the commit
        # thread dies, so release() must settle the nodes either way
        self.submitted = False
        self.released = False

    def node_ids(self) -> tuple[str, ...]:
        return tuple(self.nodes)

    def __repr__(self):
        state = (
            "released" if self.released
            else "confirmed" if self.confirmed
            else "reserved"
        )
        return (
            f"LaneClaim(w{self.claimant} eval={self.eval_id[:8]} "
            f"nodes={sorted(self.nodes)} {state})"
        )


class LaneClaims:
    """The cross-lane handoff table: reserve → confirm → release.

    ``overlays`` is the per-worker LaneOverlays container (the confirm
    step interrogates the node owner's epoch) and ``snapshot_fn``
    returns a fresh store snapshot for the capacity re-check; both are
    injected by the Server so this table stays unit-testable."""

    def __init__(self, lanes: LaneMap, overlays=None, snapshot_fn=None,
                 sleep=time.sleep):
        self.lanes = lanes
        self.overlays = overlays
        self.snapshot_fn = snapshot_fn
        # the quiesce-wait poll interval sleeper: injectable so chaos
        # skew and unit tests can steer the confirm wait
        self._sleep = sleep
        self._lock = threading.Lock()
        # node id → the active claim holding it (reserve refuses overlap,
        # so at most one claim per node)
        self._by_node: dict[str, LaneClaim] = {}
        # owner worker → nodes committed by a peer's handoff and not yet
        # rebased into the owner's overlay epoch
        self._settled: dict[int, set[str]] = {}
        self.counters = {
            "reserves": 0,
            "reserve_refused": 0,
            "confirms": 0,
            "confirm_rejected": 0,
            "handoff_drops": 0,
            "releases": 0,
            "settled": 0,
        }

    # -- phase 1: reserve --------------------------------------------------
    def reserve(
        self, claimant: int, eval_id: str, nodes: dict
    ) -> Optional[LaneClaim]:
        """Stake the claim: refuse if any node is already actively
        claimed or is settled (its owner has not rebased a prior
        handoff yet). Returns None on refusal — the caller falls back,
        nothing to undo."""
        chaos_site("lane.handoff_delay")
        with self._lock:
            for node_id in nodes:
                if node_id in self._by_node:
                    self.counters["reserve_refused"] += 1
                    return None
                owner = self.lanes.owner_of_node(node_id)
                if node_id in self._settled.get(owner, ()):
                    self.counters["reserve_refused"] += 1
                    return None
            claim = LaneClaim(claimant, eval_id, nodes)
            for node_id in nodes:
                self._by_node[node_id] = claim
            self.counters["reserves"] += 1
            return claim

    # -- phase 2: confirm --------------------------------------------------
    def confirm(self, claim: LaneClaim) -> bool:
        """The peer-lane acknowledgement, in three checks per claimed
        node's owner: (1) the owner's scoring pass has quiesced (bounded
        wait — while a pass is in flight the owner may still be choosing
        the node), (2) the owner's overlay carries NO in-flight delta on
        the node (a nonzero delta means an uncommitted peer placement is
        already riding toward it), (3) a FRESH store snapshot still fits
        the claim's allocations. Anything less and the handoff is
        rejected; the member retries solo in its own lane."""
        action = chaos_site("lane.handoff_drop")
        if action == "drop":
            # the peer's confirmation was lost: the handoff fails and
            # the caller must release the reservation (no leaked claims)
            self.counters["handoff_drops"] += 1
            metrics.incr("nomad.lane.handoff_drops")
            return False
        owners = {
            self.lanes.owner_of_node(n)
            for n in claim.nodes
            if self.lanes.owner_of_node(n) != claim.claimant
        }
        if self.overlays is not None:
            deadline = time.monotonic() + CONFIRM_QUIESCE_TIMEOUT
            for owner in sorted(owners):
                ov = self.overlays.for_worker(owner)
                while ov.passes_in_flight():
                    if time.monotonic() >= deadline:
                        return self._reject(claim)
                    self._sleep(0.002)
            for node_id in claim.nodes:
                owner = self.lanes.owner_of_node(node_id)
                if owner == claim.claimant:
                    continue
                if self.overlays.for_worker(owner).pending_on(node_id):
                    return self._reject(claim)
        if not self._capacity_ok(claim):
            return self._reject(claim)
        claim.confirmed = True
        with self._lock:
            self.counters["confirms"] += 1
        metrics.incr("nomad.plan.cross_lane_handoffs")
        return True

    def _reject(self, claim: LaneClaim) -> bool:
        with self._lock:
            self.counters["confirm_rejected"] += 1
        metrics.incr("nomad.lane.confirm_rejected")
        return False

    def _capacity_ok(self, claim: LaneClaim) -> bool:
        """Exact host-side re-check against a snapshot taken AFTER the
        owners quiesced: live allocs + the claim's allocs must fit every
        claimed node (the same allocs_fit the applier's verify uses, so
        a confirmed claim cannot be rejected for capacity)."""
        if self.snapshot_fn is None:
            return True
        from ..structs import allocs_fit

        snap = self.snapshot_fn()
        for node_id, new_allocs in claim.nodes.items():
            node = snap.node_by_id(node_id)
            if node is None or node.terminal_status():
                return False
            new_ids = {a.id for a in new_allocs}
            proposed = [
                a
                for a in snap.allocs_by_node(node_id)
                if not a.terminal_status() and a.id not in new_ids
            ]
            proposed.extend(new_allocs)
            ok, _dim, _used = allocs_fit(node, proposed, check_devices=True)
            if not ok:
                return False
        return True

    # -- phase 3: release --------------------------------------------------
    def release(self, claim: LaneClaim, committed: bool = False) -> None:
        """Drop the reservation. Idempotent, and ALWAYS reached (the
        worker releases in a finally, which even ChaosThreadKill cannot
        skip). ``committed=True`` moves the nodes to their owners'
        settled sets: the placements are (or may be, if the thread died
        mid-submit) in the store, but each owner's frozen overlay base
        predates them — the node stays blocked until that owner
        rebases."""
        with self._lock:
            if claim.released:
                return
            claim.released = True
            self.counters["releases"] += 1
            for node_id in claim.nodes:
                if self._by_node.get(node_id) is claim:
                    del self._by_node[node_id]
                if committed:
                    owner = self.lanes.owner_of_node(node_id)
                    if owner != claim.claimant:
                        self._settled.setdefault(owner, set()).add(node_id)
                        self.counters["settled"] += 1

    def clear_settled(self, worker_id: int) -> None:
        """Owner rebased (fresh epoch, next snapshot includes every
        committed handoff): its settled nodes become schedulable again."""
        with self._lock:
            s = self._settled.get(worker_id)
            if s:
                s.clear()

    # -- queries -----------------------------------------------------------
    def blocked_node_ids(self) -> frozenset[str]:
        """Nodes no scoring pass may offer right now: actively claimed
        (a peer's handoff is in flight) or settled (the owner's epoch
        still predates a committed handoff)."""
        with self._lock:
            blocked = set(self._by_node)
            for nodes in self._settled.values():
                blocked.update(nodes)
            return frozenset(blocked)

    def active_count(self) -> int:
        with self._lock:
            return len({id(c) for c in self._by_node.values()})

    def settled_count(self) -> int:
        with self._lock:
            return sum(len(s) for s in self._settled.values())

    def drained(self) -> bool:
        """No active claims — the lane_isolation invariant's quiesce
        predicate (settled nodes clear lazily at owner rebase and are
        merely conservative, so they do not count as leaked state)."""
        with self._lock:
            return not self._by_node

    def snapshot(self) -> dict:
        """Status surface (CLI / HTTP): counters + live table sizes."""
        with self._lock:
            return {
                "active_claims": len({id(c) for c in self._by_node.values()}),
                "claimed_nodes": sorted(self._by_node),
                "settled_nodes": sorted(
                    n for s in self._settled.values() for n in s
                ),
                "counters": dict(self.counters),
            }
