"""Worker — the scheduling worker loop.

Reference: nomad/worker.go — run (:385-432): dequeue an eval, wait for the
state store to catch up to the eval's index (snapshotMinIndex :536-549),
invoke the scheduler on a snapshot (:552-581), ack on success / nack on
failure (:818-838). The worker is also the scheduler's Planner: SubmitPlan
(:585-652) attaches the eval token + snapshot index, submits to the plan
queue, waits the future, and on a RefreshIndex result hands the scheduler
a fresher snapshot.

The TPU twist (SURVEY.md §2.7): one worker drives a *batched* device pass,
so a single worker replaces N CPU-bound Go workers for placement; multiple
workers still make sense to overlap host-side reconcile/flatten work.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..scheduler import new_scheduler
from ..structs import Evaluation, Plan
from ..utils.metrics import global_metrics as metrics

log = logging.getLogger("nomad_tpu.worker")

SCHEDULER_TYPES = ["service", "batch", "system", "sysbatch", "_core"]

# evals packed into one batched device pass (SURVEY.md §7 step 5): the
# batch dimension of the placement kernel replaces the reference's
# worker-per-core concurrency (nomad/config.go:468). Each eval still
# submits its own plan; the serialized applier resolves conflicts exactly
# as it does for the reference's parallel workers. Sized so a burst of
# registrations drains in a handful of passes — each pass costs ~2 tunnel
# round trips regardless of depth, and lane decorrelation + host repair
# keep wide batches conflict-free.
#
# Only worker 0 runs the batched pass: two workers batching the same
# snapshot double-book capacity and the applier bounces the later plans
# (measured conflict_rate 0 → 0.46 at 64-deep with two batching
# workers). The remaining workers drain evals one at a time, overlapping
# host-side reconcile/flatten work with the batch worker's device pass.
EVAL_BATCH_SIZE = 64


class Worker:
    def __init__(self, server, worker_id: int = 0, schedulers=None):
        self.server = server
        self.id = worker_id
        self.schedulers = schedulers or SCHEDULER_TYPES
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._eval_token: str = ""
        self.stats = {"processed": 0, "acked": 0, "nacked": 0}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run, name=f"worker-{self.id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    def pause(self) -> None:
        """Leader pauses half its workers (nomad/leader.go:231-233)."""
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    # -- main loop ---------------------------------------------------------
    def run(self) -> None:
        while not self._stop.is_set():
            if self._paused.is_set():
                self._stop.wait(0.1)
                continue
            with metrics.timer("nomad.worker.dequeue_eval"):
                batch = self.server.eval_broker.dequeue_many(
                    self.schedulers,
                    EVAL_BATCH_SIZE if self.id == 0 else 1,
                    timeout=0.2,
                )
            if not batch:
                continue
            try:
                if len(batch) == 1:
                    # batch accounting reconciliation: evals dequeued solo
                    # never enter a batched pass at all
                    metrics.incr("nomad.worker.solo_evals")
                    self._run_one(*batch[0])
                else:
                    self._run_batch(batch)
            except Exception:
                # a worker thread must never die silently: dequeued evals
                # would stay unacked forever and per-job serialization
                # would wedge those jobs (the broker has no redelivery
                # deadline). Nack everything still outstanding.
                log.exception("worker %d: batch failed", self.id)
                for ev, token in batch:
                    try:
                        self.server.eval_broker.nack(ev.id, token)
                        self.stats["nacked"] += 1
                    except ValueError:
                        pass  # already acked/nacked

    def _run_one(self, ev: Evaluation, token: str) -> None:
        self._eval_token = token
        try:
            self.process_eval(ev)
            self.server.eval_broker.ack(ev.id, token)
            self.stats["acked"] += 1
        except Exception:
            log.exception("worker %d: eval %s failed", self.id, ev.id)
            try:
                self.server.eval_broker.nack(ev.id, token)
            except ValueError:
                pass
            self.stats["nacked"] += 1
        self.stats["processed"] += 1
        # per-eval counter: the invoke_scheduler TIMER emits one sample per
        # batched pass, so throughput accounting reads this counter instead
        metrics.incr("nomad.worker.evals_processed")

    def _run_batch(self, batch: list[tuple[Evaluation, str]]) -> None:
        """Process a batch of evals through one combined device pass.
        Evals the batch path can't take (system jobs, eviction-coupled
        plans, failed batch attempts) fall back to the individual path."""
        with metrics.timer("nomad.worker.wait_for_index"):
            self.server.store.wait_for_index(
                max(ev.modify_index for ev, _ in batch), timeout=5.0
            )
        snapshot = self.server.store.snapshot()
        # One ClusterTensors for the WHOLE batch: if each scheduler fetched
        # its own, a concurrent worker advancing the cache generation
        # mid-batch would hand later schedulers a transient build whose row
        # order differs (sorted-by-id vs incremental append) — their masks
        # would silently misalign with the capacity/used arrays in the
        # combined kernel call.
        ct = self.server.device_cache.tensors(snapshot)

        prepared = []  # (ev, token, sched, n_asks)
        all_asks: list = []
        lane_groups: list[int] = []  # lane -> eval ordinal (for repair)
        singles: list[tuple[Evaluation, str]] = []
        for ev, token in batch:
            if ev.type not in ("service", "batch"):
                singles.append((ev, token))
                continue
            self._eval_token = token
            sched = new_scheduler(
                ev.type, snapshot, self, cache=self.server.device_cache
            )
            try:
                asks = sched.prepare_batch_attempt(ev, ct=ct)
            except Exception:
                log.exception("worker %d: batch prepare %s", self.id, ev.id)
                asks = None
                singles.append((ev, token))
                continue
            if asks is None:
                singles.append((ev, token))
            else:
                assert sched._batch_ctx[0] is ct
                lane_groups.extend([len(prepared)] * len(asks))
                prepared.append((ev, token, sched, len(asks)))
                all_asks.extend(asks)

        results = None
        lane_ok: list[bool] = []
        if all_asks:
            try:
                kernel = prepared[0][2].kernel
                with metrics.timer("nomad.worker.invoke_scheduler"):
                    # decorrelate: each lane scores a disjoint node stripe
                    # (the vector analog of per-worker shuffle sampling,
                    # stack.go:74-90) so concurrent lanes stop argmaxing
                    # onto the same nodes; repair re-scores any remainder
                    results = kernel.place(
                        ct,
                        all_asks,
                        decorrelate=True,
                        decorrelate_salt=self.id,
                        overflow=32,
                    )
                from ..device.score import repair_batch_conflicts

                lane_ok = repair_batch_conflicts(
                    ct,
                    all_asks,
                    results,
                    algorithm_spread=kernel.algorithm_spread,
                    # multi-TG evals span lanes; a failed lane discards
                    # the WHOLE eval, so repair must release (and stop
                    # reserving for) every sibling lane too
                    lane_groups=lane_groups,
                )
            except Exception:
                # shared pass failed — every prepared eval falls back to
                # the individual path rather than dying unacked
                log.exception("worker %d: combined kernel pass", self.id)
                metrics.incr("nomad.worker.batch_kernel_errors")
                singles.extend((ev, token) for ev, token, _, _ in prepared)
                prepared = []

        off = 0
        for ev, token, sched, n in prepared:
            span = results[off : off + n]
            span_ok = all(lane_ok[off : off + n])
            off += n
            if not span_ok:
                # a conflicted placement had no usable overflow candidate
                metrics.incr("nomad.worker.batch_conflict_fallbacks")
                metrics.incr("nomad.worker.batch_repair_fallbacks")
                singles.append((ev, token))
                continue
            self._eval_token = token
            try:
                if sched.complete_batch_attempt(span):
                    self.server.eval_broker.ack(ev.id, token)
                    self.stats["acked"] += 1
                    self.stats["processed"] += 1
                    metrics.incr("nomad.worker.batch_evals_completed")
                    metrics.incr("nomad.worker.evals_processed")
                else:
                    # optimistic conflict: re-run individually on fresh state
                    metrics.incr("nomad.worker.batch_conflict_fallbacks")
                    metrics.incr("nomad.worker.batch_commit_fallbacks")
                    singles.append((ev, token))
            except Exception:
                log.exception("worker %d: batch complete %s", self.id, ev.id)
                try:
                    self.server.eval_broker.nack(ev.id, token)
                except ValueError:
                    pass
                self.stats["nacked"] += 1
                self.stats["processed"] += 1
                metrics.incr("nomad.worker.evals_processed")

        for ev, token in singles:
            metrics.incr("nomad.worker.batch_single_fallbacks")
            self._run_one(ev, token)

    def process_eval(self, ev: Evaluation) -> None:
        # raft catch-up barrier (worker.go:536-549)
        with metrics.timer("nomad.worker.wait_for_index"):
            self.server.store.wait_for_index(ev.modify_index, timeout=5.0)
        snapshot = self.server.store.snapshot()
        # all workers share the server's resident device-state cache —
        # tensors refresh incrementally by state index, not per eval
        sched = new_scheduler(
            ev.type, snapshot, self, cache=self.server.device_cache
        )
        with metrics.timer("nomad.worker.invoke_scheduler"):
            sched.process(ev)

    # -- Planner interface (worker.go:585-767) -----------------------------
    def submit_plan(self, plan: Plan):
        plan.eval_token = self._eval_token
        plan.normalize()
        with metrics.timer("nomad.worker.submit_plan"):
            future = self.server.plan_queue.enqueue(plan)
            result = future.result(timeout=30)
        new_snapshot = None
        if result.refresh_index:
            self.server.store.wait_for_index(result.refresh_index, timeout=5.0)
            new_snapshot = self.server.store.snapshot()
        return result, new_snapshot

    def update_eval(self, ev: Evaluation) -> None:
        self.server.apply_eval_update([ev])

    def create_eval(self, ev: Evaluation) -> None:
        self.server.apply_eval_create([ev])

    def reblock_eval(self, ev: Evaluation) -> None:
        self.server.eval_broker.enqueue(ev)
