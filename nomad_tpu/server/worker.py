"""Worker — the scheduling worker loop.

Reference: nomad/worker.go — run (:385-432): dequeue an eval, wait for the
state store to catch up to the eval's index (snapshotMinIndex :536-549),
invoke the scheduler on a snapshot (:552-581), ack on success / nack on
failure (:818-838). The worker is also the scheduler's Planner: SubmitPlan
(:585-652) attaches the eval token + snapshot index, submits to the plan
queue, waits the future, and on a RefreshIndex result hands the scheduler
a fresher snapshot.

The TPU twist (SURVEY.md §2.7): one worker drives a *batched* device pass,
so a single worker replaces N CPU-bound Go workers for placement; multiple
workers still make sense to overlap host-side reconcile/flatten work.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..scheduler import new_scheduler
from ..structs import Evaluation, Plan

log = logging.getLogger("nomad_tpu.worker")

SCHEDULER_TYPES = ["service", "batch", "system", "sysbatch", "_core"]


class Worker:
    def __init__(self, server, worker_id: int = 0, schedulers=None):
        self.server = server
        self.id = worker_id
        self.schedulers = schedulers or SCHEDULER_TYPES
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._eval_token: str = ""
        self.stats = {"processed": 0, "acked": 0, "nacked": 0}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run, name=f"worker-{self.id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    def pause(self) -> None:
        """Leader pauses half its workers (nomad/leader.go:231-233)."""
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    # -- main loop ---------------------------------------------------------
    def run(self) -> None:
        while not self._stop.is_set():
            if self._paused.is_set():
                self._stop.wait(0.1)
                continue
            ev, token = self.server.eval_broker.dequeue(
                self.schedulers, timeout=0.2
            )
            if ev is None:
                continue
            self._eval_token = token
            try:
                self.process_eval(ev)
                self.server.eval_broker.ack(ev.id, token)
                self.stats["acked"] += 1
            except Exception:
                log.exception("worker %d: eval %s failed", self.id, ev.id)
                try:
                    self.server.eval_broker.nack(ev.id, token)
                except ValueError:
                    pass
                self.stats["nacked"] += 1
            self.stats["processed"] += 1

    def process_eval(self, ev: Evaluation) -> None:
        # raft catch-up barrier (worker.go:536-549)
        self.server.store.wait_for_index(ev.modify_index, timeout=5.0)
        snapshot = self.server.store.snapshot()
        sched = new_scheduler(ev.type, snapshot, self)
        sched.process(ev)

    # -- Planner interface (worker.go:585-767) -----------------------------
    def submit_plan(self, plan: Plan):
        plan.eval_token = self._eval_token
        plan.normalize()
        future = self.server.plan_queue.enqueue(plan)
        result = future.result(timeout=30)
        new_snapshot = None
        if result.refresh_index:
            self.server.store.wait_for_index(result.refresh_index, timeout=5.0)
            new_snapshot = self.server.store.snapshot()
        return result, new_snapshot

    def update_eval(self, ev: Evaluation) -> None:
        self.server.apply_eval_update([ev])

    def create_eval(self, ev: Evaluation) -> None:
        self.server.apply_eval_create([ev])

    def reblock_eval(self, ev: Evaluation) -> None:
        self.server.eval_broker.enqueue(ev)
