"""Worker — the scheduling worker loop.

Reference: nomad/worker.go — run (:385-432): dequeue an eval, wait for the
state store to catch up to the eval's index (snapshotMinIndex :536-549),
invoke the scheduler on a snapshot (:552-581), ack on success / nack on
failure (:818-838). The worker is also the scheduler's Planner: SubmitPlan
(:585-652) attaches the eval token + snapshot index, submits to the plan
queue, waits the future, and on a RefreshIndex result hands the scheduler
a fresher snapshot.

The TPU twist (SURVEY.md §2.7): one worker drives a *batched* device pass,
so a single worker replaces N CPU-bound Go workers for placement; multiple
workers still make sense to overlap host-side reconcile/flatten work.

Pipelining (the plan_apply.go:49-69 analog): the device pass for batch
k+1 overlaps the host-side COMMIT of batch k. The worker hands each
finished pass to a commit thread and immediately dequeues + prepares the
next one; the next pass scores against an OPTIMISTIC usage overlay (the
previous pass's placements, not yet committed), exactly how the
reference's applier evaluates plan N+1 against the optimistic post-N
snapshot. The serialized plan applier remains the authority — an overlay
mis-guess surfaces as a partial commit and an individual retry.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import numpy as np

from ..broker.plan_apply import PlanTokenMismatch
from ..chaos.plane import ChaosThreadKill, chaos_site
from ..obs.trace import global_tracer as tracer
from ..resilience.errors import EvalDeadlineExceeded
from ..scheduler import new_scheduler
from ..structs import Evaluation, MergedPlan, Plan
from ..structs.evaluation import EVAL_STATUS_FAILED
from ..utils.metrics import count_swallowed
from ..utils.metrics import global_metrics as metrics

log = logging.getLogger("nomad_tpu.worker")

SCHEDULER_TYPES = ["service", "batch", "system", "sysbatch", "_core"]

# evals packed into one batched device pass (SURVEY.md §7 step 5): the
# batch dimension of the placement kernel replaces the reference's
# worker-per-core concurrency (nomad/config.go:468). Each eval still
# submits its own plan; the serialized applier resolves conflicts exactly
# as it does for the reference's parallel workers. Sized so a burst of
# registrations drains in a handful of passes — each pass costs ~2 tunnel
# round trips regardless of depth, and lane decorrelation + host repair
# keep wide batches conflict-free.
#
# Workers 0..num_batch_workers-1 run batched passes, each on a disjoint
# JOB-HASH PARTITION of the eval stream (broker n_partitions), a disjoint
# hashed NODE UNIVERSE, and its own lane-stripe salt — r3 measured a
# 0.46 conflict rate with two batching workers sharing one stream;
# partitioning removes the shared hot set (measured 6.8× single-worker
# eval throughput with conflict 0 at the 8-deep repro shape). Remaining
# workers drain solo evals through the same shared optimistic overlay.
#
# Concurrency caveat, measured honestly: on a SINGLE-core host at the
# 10k-node config-3 shape, any second worker (solo or batching) races
# the pipelined commits under CPU starvation and conflict rates swing
# run-to-run (0.0–0.96); one pipelined batching worker is bit-stable
# there (conflict 0.0 across every instrumented run). The bench pins
# num_workers=1 for reproducibility; multi-worker batching is for
# multi-core servers.
#
# Depth 16 beats 64 on BOTH axes with the single pipelined worker at
# the config-3 shape (true-CPU A/B: 5.5 vs 4.7 evals/s and invoke p99
# 2.7 s vs 9.0 s, conflict 0.0 in every run): the pipeline hides the
# extra pass dispatches while smaller passes commit sooner and cap the
# p99 at one-quarter the device time.
EVAL_BATCH_SIZE = 16


class _EvalBuffer:
    """Deferred eval writes for one batch commit. Every member's
    finalize-time status update (and followup/blocked eval creates)
    coalesces into ONE raft apply per flush instead of one per eval —
    the eval-side analog of the merged plan commit."""

    def __init__(self, server):
        self._server = server
        self.updates: list[Evaluation] = []
        self.creates: list[Evaluation] = []

    def flush(self) -> None:
        creates, self.creates = self.creates, []
        if creates:
            self._server.apply_eval_create(creates)
        updates, self.updates = self.updates, []
        if updates:
            self._server.apply_eval_update(updates)


class _TokenPlanner:
    """Planner bound to ONE eval's broker token. Batch completion runs on
    the commit thread concurrently with the next pass's prepare, so the
    token cannot live as mutable worker state (worker.go keeps it as
    per-worker state because its workers are strictly serial)."""

    def __init__(self, worker: "Worker", token: str):
        self._worker = worker
        self.token = token
        # when the commit thread sets this, eval writes buffer for a
        # batch-wide flush instead of raft-applying one at a time
        self.buffer: Optional[_EvalBuffer] = None
        # absolute processing deadline (worker clock) set at dequeue by
        # Worker._planner; None = no deadline (direct callers)
        self.deadline: Optional[float] = None

    def check_deadline(self, eval_id: str = "") -> None:
        """Raise EvalDeadlineExceeded once this eval's processing pass
        has outlived the server's eval_deadline — checked at the plan
        submission boundary and before each commit-thread build, the
        two places a pass commits to more expensive work."""
        if self.deadline is not None and self._worker._clock() > self.deadline:
            raise EvalDeadlineExceeded(
                eval_id, self._worker._eval_deadline or 0.0
            )

    def submit_plan(self, plan: Plan):
        self.check_deadline(plan.eval_id)
        plan.eval_token = self.token
        plan.normalize()
        server = self._worker.server
        # the enqueue captures this span's context onto the pending plan,
        # so the applier thread's plan_apply spans parent under it
        with tracer.span(
            "submit_plan", timer="nomad.worker.submit_plan"
        ) as sp:
            future = server.plan_queue.enqueue(plan)
            result = future.result(timeout=30)
            if sp is not None:
                sp.tags["rejected_nodes"] = len(result.rejected_nodes)
        new_snapshot = None
        if result.refresh_index:
            with tracer.span(
                "refresh_snapshot",
                tags={"refresh_index": result.refresh_index},
            ):
                server.store.wait_for_index(result.refresh_index, timeout=5.0)
                new_snapshot = server.store.snapshot()
        return result, new_snapshot

    def update_eval(self, ev: Evaluation) -> None:
        if self.buffer is not None:
            self.buffer.updates.append(ev)
            return
        self._worker.server.apply_eval_update([ev])

    def create_eval(self, ev: Evaluation) -> None:
        if self.buffer is not None:
            self.buffer.creates.append(ev)
            return
        self._worker.server.apply_eval_create([ev])

    def reblock_eval(self, ev: Evaluation) -> None:
        self._worker.server.eval_broker.enqueue(ev)


class Worker:
    # class-level defaults so partially-constructed workers (tests build
    # them via __new__) still plan without an eval deadline
    _eval_deadline: Optional[float] = None
    _eval_attempt_limit: int = 3
    _clock = staticmethod(time.time)

    def __init__(self, server, worker_id: int = 0, schedulers=None):
        self.server = server
        self.id = worker_id
        self.schedulers = schedulers or SCHEDULER_TYPES
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # the commit thread and the worker thread both account evals —
        # bare dict increments would lose counts across the interleave
        self.stats = {"processed": 0, "acked": 0, "nacked": 0}
        self._stats_lock = threading.Lock()
        # Pipelining state (batch worker only): this worker's in-flight
        # commit thread. Optimistic usage accounting lives in the
        # SERVER-SHARED overlay (server/overlay.py) so concurrent
        # batching workers see each other's in-flight placements too.
        self._commit_thread: Optional[threading.Thread] = None
        # perf_counter stamp the commit thread writes in its finally;
        # the next pass's join site reads it to account how much of the
        # commit's wall time genuinely overlapped device work
        self._commit_done_at: float = 0.0
        # eval-lifecycle deadlines (resilience layer): the injectable
        # cluster clock when configured, else wall time
        cfg = getattr(server, "config", None)
        clock = getattr(cfg, "clock", None)
        self._clock = clock.time if clock is not None else time.time
        deadline = getattr(cfg, "eval_deadline", 0.0) or 0.0
        self._eval_deadline: Optional[float] = (
            deadline if deadline > 0 else None
        )
        self._eval_attempt_limit: int = getattr(cfg, "eval_attempt_limit", 3)

    def _planner(self, token: str) -> _TokenPlanner:
        p = _TokenPlanner(self, token)
        if self._eval_deadline is not None:
            p.deadline = self._clock() + self._eval_deadline
        return p

    # -- lane plumbing -----------------------------------------------------
    def _lane_mode(self) -> bool:
        """Deterministic lane ownership is active only with >1 batching
        worker; at 1 every path below reduces to the legacy behavior."""
        return getattr(self.server, "lane_mode", False)

    def _my_overlay(self):
        """This worker's epoch overlay. In lane mode each batching
        worker scores against (and writes deltas into) its OWN overlay;
        solo workers — and everything at num_batch_workers=1 — use the
        legacy shared view (LaneOverlays delegates it to worker 0)."""
        ov = self.server.placement_overlay
        for_worker = getattr(ov, "for_worker", None)
        n_batchers = getattr(self.server.config, "num_batch_workers", 1)
        if for_worker is not None and n_batchers > 1 and self.id < n_batchers:
            return for_worker(self.id)
        return ov

    def _rebase_lanes(self, overlay) -> None:
        """An overlay epoch reset (or a fresh epoch) means this worker's
        next snapshot includes every committed cross-lane handoff onto
        its nodes — unblock them."""
        claims = getattr(self.server, "lane_claims", None)
        if claims is not None and self._lane_mode():
            if overlay.is_fresh():
                claims.clear_settled(self.id)

    def _lane_node_filter(self, ct) -> np.ndarray:
        """Eligibility mask for a batch worker's SOLO fallback in lane
        mode: own lanes only, minus claim-blocked nodes. The batched
        path scores the full cluster and hands off cross-lane winners;
        the solo fallback has no handoff step, so it stays home — a
        shortfall becomes a blocked eval, never a foreign-node write."""
        claims = self.server.lane_claims
        blocked = claims.blocked_node_ids()
        lanes = self.server.lanes
        mask = np.zeros(ct.padded_n, dtype=bool)
        for i, node in enumerate(ct.nodes):
            mask[i] = (
                lanes.owner_of_node(node.id) == self.id
                and node.id not in blocked
            )
        return mask

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run, name=f"worker-{self.id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        self._join_commit(timeout=5)

    def pause(self) -> None:
        """Leader pauses half its workers (nomad/leader.go:231-233)."""
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    def _bump(self, *keys: str) -> None:
        with self._stats_lock:
            for k in keys:
                self.stats[k] += 1

    def _join_commit(self, timeout: float = 60.0) -> None:
        t = self._commit_thread
        if t is not None:
            t.join(timeout=timeout)
            self._commit_thread = None

    # -- main loop ---------------------------------------------------------
    def run(self) -> None:
        while not self._stop.is_set():
            if self._paused.is_set():
                self._join_commit()
                self._stop.wait(0.1)
                continue
            n_batchers = getattr(self.server.config, "num_batch_workers", 1)
            batching = self.id < n_batchers
            lane_mode = batching and self._lane_mode()
            # Lane-affine dequeue: a batching worker scans exactly the
            # lane set it owns (the broker partitions by the SAME job
            # hash LaneMap uses, so partition keys ARE lanes); solo
            # workers scan everything, but in lane mode they must not
            # steal service/batch evals from their lane owners — they
            # drain only the solo-native types.
            scan_types = self.schedulers
            if self._lane_mode() and not batching:
                scan_types = [
                    t for t in self.schedulers
                    if t not in ("service", "batch")
                ]
            # pre-trace interval: no eval (hence no trace) exists until the
            # dequeue returns — the sample feeds /v1/metrics directly and
            # the span is attached retroactively per dequeued eval below
            # brownout lever: past the brownout point the batch worker
            # widens its dequeue window (bigger batch, longer wait) so
            # each device pass amortizes more evals instead of
            # thrashing small kernel invocations; NORMAL keeps the
            # baseline 16/0.2 exactly.
            max_n, deq_timeout = EVAL_BATCH_SIZE if batching else 1, 0.2
            adm = getattr(self.server, "admission", None)
            if adm is not None and batching:
                max_n, deq_timeout = adm.batch_params(max_n, deq_timeout)
            t0 = time.perf_counter()
            batch = self.server.eval_broker.dequeue_many(
                scan_types,
                max_n,
                timeout=deq_timeout,
                partition=(
                    self.server.lanes.lanes_of_worker(self.id)
                    if lane_mode
                    else None
                ),
            )
            dequeue_s = time.perf_counter() - t0
            metrics.measure("nomad.worker.dequeue_eval", dequeue_s)
            if not batch:
                self._join_commit()
                if lane_mode:
                    # idle is the rebase point: drop a drained epoch and
                    # unblock any handoff-settled nodes (the next
                    # snapshot includes those committed placements)
                    ov = self._my_overlay()
                    if ov.maybe_reset():
                        metrics.incr("nomad.worker.pipeline_epoch_resets")
                    self._rebase_lanes(ov)
                continue
            for ev, _token in batch:
                queue_wait = self.server.eval_broker.take_queue_wait(ev.id)
                root = tracer.begin(
                    ev.id,
                    tags={
                        "job_id": ev.job_id,
                        "namespace": ev.namespace,
                        "type": ev.type,
                        "triggered_by": ev.triggered_by,
                        "priority": ev.priority,
                        "worker": self.id,
                        "batch_size": len(batch),
                    },
                )
                if root is not None:
                    tracer.add_span(
                        ev.id,
                        "dequeue",
                        dequeue_s,
                        tags={
                            "queue_wait_ms": round(queue_wait * 1000.0, 3),
                            "shared": len(batch) > 1,
                        },
                    )
            try:
                if len(batch) == 1 and not lane_mode:
                    # batch accounting reconciliation: evals dequeued solo
                    # never enter a batched pass at all
                    metrics.incr("nomad.worker.solo_evals")
                    self._run_one(*batch[0])
                else:
                    # in lane mode even a batch of one goes through the
                    # batched pass: byte-identity with the 1-worker
                    # reference requires every service/batch eval to
                    # take the SAME code path (same salt, same overlay,
                    # same merged-commit route) regardless of load
                    self._run_batch(batch)
            except Exception as e:
                # a worker thread must never die silently: dequeued evals
                # would stay unacked forever and per-job serialization
                # would wedge those jobs (the broker has no redelivery
                # deadline). Nack everything still outstanding.
                log.exception("worker %d: batch failed", self.id)
                count_swallowed("worker", e)
                for ev, token in batch:
                    try:
                        self.server.eval_broker.nack(ev.id, token)
                        self._bump("nacked")
                    except ValueError as e2:
                        count_swallowed("worker", e2)  # already acked/nacked
                    tracer.finish(ev.id, status="nacked", error=repr(e))
        self._join_commit()

    def _run_one(self, ev: Evaluation, token: str) -> None:
        planner = self._planner(token)
        # idempotent: run() already opened the trace for dequeued evals;
        # this covers direct callers (tests, batch single-path fallbacks
        # keep appending to the tree they started in)
        tracer.begin(ev.id, tags={"job_id": ev.job_id, "type": ev.type})
        try:
            with tracer.activate(ev.id):
                self.process_eval(ev, planner)
            self.server.eval_broker.ack(ev.id, token)
            self._bump("acked")
            tracer.finish(ev.id, status="acked")
        except EvalDeadlineExceeded as e:
            self._deadline_nack(ev, token, e)
            return  # _deadline_nack did all the accounting
        except PlanTokenMismatch:
            # the unack deadline redelivered this eval mid-flight: the
            # redelivered copy owns it now. Drop — no ack/nack (our token
            # is already dead at the broker) and no retry (retrying would
            # race the new owner into exactly the double-commit the token
            # guard exists to prevent).
            metrics.incr("nomad.worker.stale_token_drops")
            self._bump("processed")
            tracer.finish(ev.id, status="stale_token")
            return
        except Exception as e:
            log.exception("worker %d: eval %s failed", self.id, ev.id)
            count_swallowed("worker", e)
            try:
                self.server.eval_broker.nack(ev.id, token)
            except ValueError as e2:
                count_swallowed("worker", e2)
            self._bump("nacked", "processed")
            tracer.finish(ev.id, status="nacked", error=repr(e))
        # per-eval counter: the invoke_scheduler TIMER emits one sample per
        # batched pass, so throughput accounting reads this counter instead
        metrics.incr("nomad.worker.evals_processed")

    def _run_batch(self, batch: list[tuple[Evaluation, str]]) -> None:
        """Run a batch of evals through one combined device pass, then
        hand the commit to the pipeline thread and return — the NEXT
        pass's prepare + device time overlaps this pass's commit."""
        # Reap a finished commit thread and (only when NOTHING is in
        # flight anywhere) reset the shared overlay epoch — strictly
        # BEFORE the snapshot, so the snapshot taken next is guaranteed
        # to include everything the dropped overlay was predicting
        # (resetting from the commit thread let the next pass freeze a
        # pre-commit base and cascade into applier rejections).
        commit_alive_at_start = (
            self._commit_thread is not None
            and self._commit_thread.is_alive()
        )
        t_pass0 = time.perf_counter()
        if self._commit_thread is not None and (
            not self._commit_thread.is_alive()
        ):
            self._join_commit()
        overlay = self._my_overlay()
        if overlay.maybe_reset():
            metrics.incr("nomad.worker.pipeline_epoch_resets")
        lane_mode = self._lane_mode()
        if lane_mode:
            # a fresh epoch rebases this worker onto the committed
            # store — any nodes settled by peers' handoffs unblock now
            self._rebase_lanes(overlay)
        t0 = time.perf_counter()
        self.server.store.wait_for_index(
            max(ev.modify_index for ev, _ in batch), timeout=5.0
        )
        wfi_s = time.perf_counter() - t0
        metrics.measure("nomad.worker.wait_for_index", wfi_s)
        t0 = time.perf_counter()
        snapshot = self.server.store.snapshot()
        # One ClusterTensors for the WHOLE batch: if each scheduler fetched
        # its own, a concurrent worker advancing the cache generation
        # mid-batch would hand later schedulers a transient build whose row
        # order differs (sorted-by-id vs incremental append) — their masks
        # would silently misalign with the capacity/used arrays in the
        # combined kernel call.
        ct = self.server.device_cache.tensors(snapshot)
        snap_s = time.perf_counter() - t0
        # shared phases happen once for the whole batch; record the same
        # interval into every member's trace, tagged shared
        for ev, _tok in batch:
            tracer.add_span(ev.id, "wait_for_index", wfi_s, tags={"shared": True})
            tracer.add_span(ev.id, "snapshot", snap_s, tags={"shared": True})

        prepared = []  # (ev, token, sched, n_asks)
        all_asks: list = []
        lane_groups: list[int] = []  # lane -> eval ordinal (for repair)
        singles: list[tuple[Evaluation, str]] = []
        for ev, token in batch:
            if ev.type not in ("service", "batch"):
                singles.append((ev, token))
                continue
            sched = new_scheduler(
                ev.type,
                snapshot,
                self._planner(token),
                cache=self.server.device_cache,
                overlay=overlay,
            )
            t0 = time.perf_counter()
            try:
                asks = sched.prepare_batch_attempt(ev, ct=ct)
            except Exception as e:
                log.exception("worker %d: batch prepare %s", self.id, ev.id)
                count_swallowed("worker", e)
                asks = None
                singles.append((ev, token))
                continue
            tracer.add_span(ev.id, "prepare", time.perf_counter() - t0)
            if asks is None:
                singles.append((ev, token))
            else:
                assert sched._batch_ctx[0] is ct
                lane_groups.extend([len(prepared)] * len(asks))
                prepared.append((ev, token, sched, len(asks)))
                all_asks.extend(asks)

        results = None
        lane_ok: list[bool] = []
        if all_asks:
            if lane_mode:
                # mask out claim-blocked nodes: a peer's handoff is in
                # flight on them (or their owner has not yet rebased a
                # committed one) — scoring them would race the claim.
                # Everything ELSE stays scorable: lane mode scores the
                # FULL cluster and hands off foreign winners, because
                # restricting each worker to its own lanes would change
                # placements vs the 1-worker reference.
                blocked = self.server.lane_claims.blocked_node_ids()
                if blocked:
                    rows = [
                        ct.node_row[n] for n in blocked if n in ct.node_row
                    ]
                    if rows:
                        for a in all_asks:
                            a.eligible[rows] = False
            # Optimistic overlay: in-flight passes of THIS worker's
            # pipeline are not committed yet, but the applier WILL land
            # most of them — scoring against bare ct.used would
            # double-book those nodes (server/overlay.py). In lane mode
            # this overlay is the worker's own; peers' in-flight state
            # is irrelevant by construction (disjoint lanes + claims).
            used_override = overlay.begin_pass(ct)
            if used_override is not None:
                metrics.incr("nomad.worker.pipeline_override_passes")
            try:
                kernel = prepared[0][2].kernel
                # all scheds in a batch share one scheduler config, so
                # the first lane's explain gate speaks for the pass
                explain = bool(getattr(prepared[0][2], "_explain", False))
                t0 = time.perf_counter()
                # decorrelate: each lane scores a disjoint node stripe
                # (the vector analog of per-worker shuffle sampling,
                # stack.go:74-90) so concurrent lanes stop argmaxing
                # onto the same nodes; repair re-scores any remainder.
                # The tie-break salt must be a function of the WORK, not
                # the worker: lane mode derives it from the first eval's
                # job lane so an N-worker run reproduces the 1-worker
                # reference byte for byte, and the legacy cross-worker
                # node-universe carving (decorrelate_workers) is retired
                # — structural claims replace it.
                results = kernel.place(
                    ct,
                    all_asks,
                    decorrelate=True,
                    decorrelate_salt=(
                        self.server.lanes.lane_of_job(
                            prepared[0][0].namespace, prepared[0][0].job_id
                        )
                        if lane_mode
                        else self.id
                    ),
                    decorrelate_workers=(
                        1
                        if lane_mode
                        else getattr(
                            self.server.config, "num_batch_workers", 1
                        )
                    ),
                    overflow=32,
                    used_override=used_override,
                    explain=explain,
                )
                from ..device.score import repair_batch_conflicts

                lane_ok = repair_batch_conflicts(
                    ct,
                    all_asks,
                    results,
                    algorithm_spread=kernel.algorithm_spread,
                    # multi-TG evals span lanes; a failed lane
                    # discards the WHOLE eval, so repair must release
                    # (and stop reserving for) every sibling lane too
                    lane_groups=lane_groups,
                    used_override=used_override,
                )
                if explain:
                    # post-repair: stamp the committed rows into each
                    # lane's explanation (obs/explain.py)
                    from ..obs.explain import finalize_explanations

                    finalize_explanations(
                        ct, all_asks, results, used_override=used_override
                    )
                invoke_s = time.perf_counter() - t0
                metrics.measure("nomad.worker.invoke_scheduler", invoke_s)
                for ev, _tok, _sched, _n in prepared:
                    tracer.add_span(
                        ev.id,
                        "invoke_scheduler",
                        invoke_s,
                        tags={
                            "shared": True,
                            "evals": len(prepared),
                            "lanes": len(all_asks),
                            "explain": explain,
                        },
                    )
            except Exception as e:
                # shared pass failed — every prepared eval falls back to
                # the individual path rather than dying unacked
                log.exception("worker %d: combined kernel pass", self.id)
                count_swallowed("worker", e)
                metrics.incr("nomad.worker.batch_kernel_errors")
                singles.extend((ev, token) for ev, token, _, _ in prepared)
                prepared = []
                results = None
            finally:
                # Reserve THIS pass's submitted placements in the shared
                # overlay, take the COMMIT marker, and only then release
                # the pass marker: a gap between the two markers would
                # let another worker's maybe_reset() drop the overlay
                # while these placements are neither "in a pass" nor "in
                # a commit" — exactly the dropped-reservation cascade the
                # reset discipline exists to prevent. The commit thread
                # below runs unconditionally, releasing the marker.
                try:
                    if results is not None and prepared:
                        off = 0
                        for _ev, _tok, _sched, n in prepared:
                            span_ok = all(lane_ok[off : off + n])
                            for lane in range(off, off + n):
                                if not span_ok:
                                    continue
                                a = all_asks[lane]
                                rows = results[lane].node_rows
                                rows = rows[rows >= 0]
                                if rows.size:
                                    overlay.add_delta(
                                        ct, rows, a.ask, writer=self.id
                                    )
                            off += n
                finally:
                    overlay.commit_started()
                    overlay.pass_finished()

        # pipeline: the previous commit must finish before this pass's
        # commit starts (plan order per job; one in-flight commit bounds
        # memory), but the NEXT device pass overlaps THIS commit.
        self._join_commit()
        if commit_alive_at_start:
            # the previous commit ran concurrently with this pass's
            # prepare/flatten/device phases from t_pass0 until it
            # finished (or until the join, whichever came first) —
            # that interval is wall time the pipeline genuinely hid
            t_join_end = time.perf_counter()
            overlap_s = max(
                0.0, min(self._commit_done_at, t_join_end) - t_pass0
            )
            metrics.measure("nomad.worker.pipeline_overlap", overlap_s)
            self.server.device_cache.note_overlap(overlap_s * 1000.0)
        if not all_asks:
            # the marker is taken in the device-pass block; a batch with
            # no kernel work (all singles) still needs it for the commit
            # thread's finally to balance
            overlay.commit_started()
        args = (prepared, all_asks, results, lane_ok, singles)
        self._commit_thread = threading.Thread(
            target=self._commit_batch, args=args,
            name=f"worker-{self.id}-commit", daemon=True,
        )
        self._commit_thread.start()

    def _commit_batch(
        self, prepared, all_asks, results, lane_ok, singles
    ) -> None:
        """Commit one finished pass: per-eval plan submission + ack/nack.
        Runs on the commit thread while the worker's next device pass is
        in flight."""
        try:
            self._commit_batch_inner(
                prepared, all_asks, results, lane_ok, singles
            )
        except ChaosThreadKill as e:
            # injected cooperative crash: die exactly like a killed
            # commit thread — whatever was not yet acked stays unacked
            # and the broker's redelivery deadline must recover it.
            # BaseException, so no recovery handler above could absorb
            # it; accounted here at the thread boundary, never silent.
            metrics.incr("nomad.chaos.thread_kills")
            count_swallowed("chaos", e)
        finally:
            # Promote the pass's staged score generation (device/cache.py):
            # the swap carries the ONE transfer fence of the pipeline, so
            # it lands here at the merge point — after the commit's store
            # writes, before the overlay releases. Runs on the kill path
            # too: the staged buffer is still an exact mirror of the used
            # matrix it was built from, and any store rows the killed
            # commit never landed show up as dirty bytes next pass.
            self.server.device_cache.score_commit()
            self._commit_done_at = time.perf_counter()
            # must release the SAME overlay whose commit_started marker
            # the device pass took (the worker's own in lane mode)
            self._my_overlay().commit_finished()

    def _nack_member(self, ev, token, e, what: str) -> None:
        if isinstance(e, EvalDeadlineExceeded):
            self._deadline_nack(ev, token, e)
            return
        log.exception("worker %d: %s %s", self.id, what, ev.id)
        count_swallowed("worker", e)
        try:
            self.server.eval_broker.nack(ev.id, token)
        except ValueError as e2:
            count_swallowed("worker", e2)
        self._bump("nacked", "processed")
        metrics.incr("nomad.worker.evals_processed")
        tracer.finish(ev.id, status="nacked", error=repr(e))

    def _deadline_nack(self, ev, token, e) -> None:
        """Escalation path for a processing-deadline expiry. Below the
        attempt cap: nack — the broker re-enqueues with attempt-indexed
        delay. At the cap: mark the eval failed with a structured
        reason (durable BEFORE the ack releases the per-job gate) and
        ack — terminal parking, not another spin of the hot loop."""
        ev.attempts += 1
        limit = self._eval_attempt_limit
        log.warning(
            "worker %d: eval %s blew its %ss processing deadline "
            "(attempt %d/%d)",
            self.id, ev.id, self._eval_deadline, ev.attempts, limit,
        )
        metrics.incr("nomad.resilience.eval.deadline_nacks")
        count_swallowed("worker", e)
        if ev.attempts >= limit:
            ev.status = EVAL_STATUS_FAILED
            ev.status_description = (
                f"eval-deadline-exceeded: attempts={ev.attempts} "
                f"limit={limit} deadline_s={self._eval_deadline}"
            )
            try:
                self.server.apply_eval_update([ev])
            except Exception as e2:
                count_swallowed("worker", e2)
            try:
                self.server.eval_broker.ack(ev.id, token)
            except ValueError as e2:
                count_swallowed("worker", e2)
            self._bump("processed")
            metrics.incr("nomad.worker.evals_processed")
            metrics.incr("nomad.resilience.eval.deadline_failed")
            tracer.finish(ev.id, status="failed", error=repr(e))
        else:
            try:
                self.server.eval_broker.nack(ev.id, token)
            except ValueError as e2:
                count_swallowed("worker", e2)
            self._bump("nacked", "processed")
            metrics.incr("nomad.worker.evals_processed")
            tracer.finish(ev.id, status="nacked", error=repr(e))

    def _commit_batch_inner(
        self, prepared, all_asks, results, lane_ok, singles
    ) -> None:
        """Coalesced commit: build every member's plan from its result
        slice, then submit the WHOLE pass as one MergedPlan — one plan
        queue entry, one vectorized applier verify, one raft apply — and
        resolve each member from its own result future. A stale member
        falls back to the individual path without failing its siblings."""
        # cooperative crash flag, checked where a real commit thread
        # spends its life: once on entry, and again mid merged-plan
        # commit (below) after the submit is in flight
        chaos_site("worker.commit")
        server = self.server
        buf = _EvalBuffer(server)
        members: list[tuple] = []  # (ev, token, sched, member plan)
        done: list[tuple] = []  # acked after the status flush below
        claims: list = []  # confirmed cross-lane claims riding this commit
        try:
            # 1. build: turn each member's lane slice into a plan. A lane
            # conflict with no usable overflow candidate drops the member
            # to the individual path before any submit.
            off = 0
            for ev, token, sched, n in prepared:
                span = results[off : off + n]
                span_ok = all(lane_ok[off : off + n])
                off += n
                if not span_ok:
                    metrics.incr("nomad.worker.batch_conflict_fallbacks")
                    metrics.incr("nomad.worker.batch_repair_fallbacks")
                    singles.append((ev, token))
                    continue
                sched.planner.buffer = buf
                try:
                    # adopt this eval's trace on the commit thread so the
                    # spans recorded below parent into it
                    with tracer.activate(ev.id):
                        # a member whose pass outlived the eval deadline
                        # escalates (nack w/ delay, then failed) instead
                        # of committing stale work
                        sched.planner.check_deadline(ev.id)
                        member = sched.build_batch_plan(span)
                except Exception as e:  # nta: allow=NTA003 — _nack_member logs+counts
                    self._nack_member(ev, token, e, "batch build")
                    continue
                if member is None:
                    # no-op eval: finalized already (status buffered)
                    done.append((ev, token))
                    metrics.incr("nomad.worker.batch_evals_completed")
                else:
                    members.append((ev, token, sched, member))

            # 1b. cross-lane handoff (lane mode): a member placing on a
            # peer's nodes must hold a confirmed claim on them before
            # riding the merged commit — reserve (refused if any node is
            # already claimed/settled), then confirm (peer quiesced, no
            # peer in-flight delta, fresh-snapshot capacity re-check).
            # Either phase failing drops the member to the solo fallback
            # in its own lanes; the reservation is released either way.
            if self._lane_mode() and members:
                kept: list[tuple] = []
                for ev, token, sched, member in members:
                    foreign = {
                        node_id: list(allocs)
                        for node_id, allocs in member.node_allocation.items()
                        if server.lanes.owner_of_node(node_id) != self.id
                    }
                    if not foreign:
                        kept.append((ev, token, sched, member))
                        continue
                    claim = server.lane_claims.reserve(
                        self.id, ev.id, foreign
                    )
                    if claim is not None:
                        # register with the finally BEFORE confirm: a
                        # thread kill inside confirm must not leak the
                        # reservation (release is idempotent, so the
                        # immediate release below stays safe)
                        claims.append(claim)
                        if server.lane_claims.confirm(claim):
                            kept.append((ev, token, sched, member))
                            continue
                        server.lane_claims.release(claim, committed=False)
                    metrics.incr("nomad.worker.lane_handoff_fallbacks")
                    singles.append((ev, token))
                members = kept

            # 2. followup evals must exist BEFORE the plans that reference
            # them commit; one raft apply covers the whole batch's creates
            buf.flush()

            # 3. submit: ONE merged entry for the whole pass
            mresults: list = [None] * len(members)
            if members:
                ctxs = []
                for ev, token, _sched, member in members:
                    member.eval_token = token
                    member.normalize()
                    with tracer.activate(ev.id):
                        ctxs.append(tracer.current_ctx())
                t0 = time.perf_counter()
                # past this point the applier may land the claimed
                # placements even if this thread dies — the finally
                # below must settle (not just drop) the claimed nodes
                for claim in claims:
                    claim.submitted = True
                futures = server.plan_queue.enqueue_merged(
                    MergedPlan(
                        plans=[m[3] for m in members],
                        owner_worker=self.id if self._lane_mode() else -1,
                        claims=list(claims),
                    ),
                    trace_ctxs=ctxs,
                )
                # a kill here crashes the thread AFTER the merged plan
                # is in flight: the applier still commits it, nobody
                # acks, and redelivered members must converge to no-ops
                # (never lose or double-commit a member)
                chaos_site("worker.commit")
                for i, (ev, token, _sched, _member) in enumerate(members):
                    try:
                        mresults[i] = futures[i].result(timeout=30)
                    except Exception as e:  # nta: allow=NTA003 — _nack_member logs+counts
                        self._nack_member(ev, token, e, "merged submit")
                submit_s = time.perf_counter() - t0
                metrics.measure("nomad.worker.submit_plan", submit_s)
                for i, (ev, _t, _s, _m) in enumerate(members):
                    if mresults[i] is None:
                        continue
                    tracer.add_span(
                        ev.id, "submit_plan", submit_s,
                        tags={
                            "shared": True,
                            "rejected_nodes": len(mresults[i].rejected_nodes),
                        },
                    )

                # 4. one shared refresh barrier for every partially
                # committed member (each previously waited on its own)
                refresh = max(
                    (r.refresh_index for r in mresults if r is not None),
                    default=0,
                )
                if refresh:
                    t0 = time.perf_counter()
                    server.store.wait_for_index(refresh, timeout=5.0)
                    refresh_s = time.perf_counter() - t0
                    for i, (ev, _t, _s, _m) in enumerate(members):
                        if mresults[i] is not None and mresults[i].refresh_index:
                            tracer.add_span(
                                ev.id, "refresh_snapshot", refresh_s,
                                tags={"shared": True, "refresh_index": refresh},
                            )

                # 5. complete: full commits finalize (status buffered);
                # stale members retry individually on fresh state (the
                # trace stays open; _run_one below appends the retry)
                for i, (ev, token, sched, _member) in enumerate(members):
                    if mresults[i] is None:
                        continue  # nacked above
                    if mresults[i].token_stale:
                        # the applier dropped this member: the broker
                        # redelivered the eval mid-pass and another
                        # worker owns it now — no ack/nack (our token is
                        # dead) and no singles retry (that would race
                        # the new owner into a double commit)
                        metrics.incr("nomad.worker.stale_token_drops")
                        self._bump("processed")
                        tracer.finish(ev.id, status="stale_token")
                        continue
                    try:
                        with tracer.activate(ev.id):
                            completed = sched.complete_merged_attempt(
                                mresults[i]
                            )
                    except Exception as e:  # nta: allow=NTA003 — _nack_member logs+counts
                        self._nack_member(ev, token, e, "batch complete")
                        continue
                    if completed:
                        done.append((ev, token))
                        metrics.incr("nomad.worker.batch_evals_completed")
                    else:
                        metrics.incr("nomad.worker.batch_conflict_fallbacks")
                        metrics.incr("nomad.worker.batch_commit_fallbacks")
                        singles.append((ev, token))

            # 6. land every member's finalize-time status (and blocked
            # eval creates) in one raft apply, then ack — status must be
            # durable before the ack releases the per-job gate
            buf.flush()
            for ev, token in done:
                try:
                    server.eval_broker.ack(ev.id, token)
                except ValueError as e:
                    count_swallowed("worker", e)
                self._bump("acked", "processed")
                metrics.incr("nomad.worker.evals_processed")
                tracer.finish(ev.id, status="acked")

            for ev, token in singles:
                metrics.incr("nomad.worker.batch_single_fallbacks")
                self._run_one(ev, token)
        except Exception as e:
            # the commit thread must never die with evals unacked —
            # including the singles that accumulated from fallbacks
            log.exception("worker %d: commit thread failed", self.id)
            count_swallowed("worker", e)
            outstanding = [
                (ev, token) for ev, token, _s, _n in prepared
            ] + list(singles)
            for ev, token in outstanding:
                try:
                    self.server.eval_broker.nack(ev.id, token)
                except Exception as e2:  # best-effort cleanup
                    count_swallowed("worker", e2)
                # finish() no-ops for evals already acked/finished above
                tracer.finish(ev.id, status="nacked", error=repr(e))
        finally:
            # no leaked claims, EVER: release is idempotent and this
            # finally runs even on ChaosThreadKill (a BaseException). A
            # claim that made it to enqueue_merged settles its nodes (the
            # applier may land it regardless of this thread's fate); one
            # that did not is simply dropped.
            for claim in claims:
                server.lane_claims.release(
                    claim, committed=claim.submitted
                )

    def process_eval(self, ev: Evaluation, planner=None) -> None:
        # solo evals score against the shared overlay too (an overlay-
        # blind pass would seed the very conflicts it predicts), so they
        # must also retire its epoch before snapshotting — a long solo-
        # only stretch otherwise accumulates every past ask against a
        # frozen base until placements fail on a near-empty cluster.
        # Safe from the commit thread's singles fallback: the commit
        # marker is still held there, so maybe_reset() is a no-op.
        overlay = self._my_overlay()
        if overlay.maybe_reset():
            metrics.incr("nomad.worker.pipeline_epoch_resets")
        lane_mode = self._lane_mode()
        if lane_mode:
            self._rebase_lanes(overlay)
        # raft catch-up barrier (worker.go:536-549)
        with tracer.span(
            "wait_for_index", timer="nomad.worker.wait_for_index"
        ):
            self.server.store.wait_for_index(ev.modify_index, timeout=5.0)
        with tracer.span("snapshot"):
            snapshot = self.server.store.snapshot()
        # all workers share the server's resident device-state cache —
        # tensors refresh incrementally by state index, not per eval
        kw = {}
        if lane_mode and ev.type in ("service", "batch") and (
            self.id < getattr(self.server.config, "num_batch_workers", 1)
        ):
            # a batch worker's SOLO fallback stays in its own lanes:
            # the solo path has no cross-lane handoff, so foreign nodes
            # are off the table (a shortfall blocks the eval, it never
            # writes a peer's node). system/sysbatch/_core evals stay
            # unrestricted — they are single-plan optimistic commits
            # outside the merged-plan lane contract.
            kw["node_filter"] = self._lane_node_filter
        sched = new_scheduler(
            ev.type,
            snapshot,
            planner if planner is not None else _TokenPlanner(self, ""),
            cache=self.server.device_cache,
            overlay=overlay,
            **kw,
        )
        with tracer.span(
            "invoke_scheduler", timer="nomad.worker.invoke_scheduler"
        ):
            sched.process(ev)

    # -- Planner interface kept for direct (non-batch) callers -------------
    def submit_plan(self, plan: Plan):
        return _TokenPlanner(self, getattr(plan, "eval_token", "")).submit_plan(
            plan
        )

    def update_eval(self, ev: Evaluation) -> None:
        self.server.apply_eval_update([ev])

    def create_eval(self, ev: Evaluation) -> None:
        self.server.apply_eval_create([ev])

    def reblock_eval(self, ev: Evaluation) -> None:
        self.server.eval_broker.enqueue(ev)
