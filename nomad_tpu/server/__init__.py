"""L1 server core: composition root, workers, leader services."""

from .server import Server, ServerConfig
from .worker import Worker

__all__ = ["Server", "ServerConfig", "Worker"]
