"""DefragController — live migration with capacity-conserved two-phase
move sequencing.

A long-lived cluster fragments: churn leaves load smeared thinly across
many nodes, so gang asks and large allocs block even though the total
free capacity is ample. This controller continuously repacks the fleet
by *live-migrating* allocs — bounded moves per cycle, chosen by the
migration auction (``device/migrate.py`` via ``scheduler/migrate.py``'s
batch assembler, NumPy-oracle path — byte-identical to the jitted
kernel by that module's parity contract).

The safety contract is the whole point (invariant law 16,
``migration_conservation``):

**Two-phase, place-first.** Every move is (A) place the replacement
alloc on the destination — through the lane-claim protocol and the
serialized plan applier, exactly like any scheduler placement — then
(B) stop the old alloc with a separate stop-only plan. Free capacity
never goes negative mid-flight: between A and B both halves exist and
both are counted (the auction's used-only-increases pricing model is
this exact invariant, priced on device). A killed controller thread
leaves a *completed pair*, never a torn one — phase A either fully
committed through the applier or not at all, and phase B is a pure
capacity release. Orphaned half-moves (replacement placed, stop never
submitted) are finished by the recovery scan at the top of the next
cycle.

**Everything through the commit path.** Replacements ride a
``MergedPlan`` with a confirmed cross-lane claim (claimant −1: the
controller owns no lanes, so every destination is foreign and must be
reserved → confirmed → released, ``finally``-guaranteed). Stops go
through ``Plan.append_stopped_alloc`` — the applier's stops-always-
commit rule makes phase B unconditional.

**Preemption-aware sequencing.** Candidates are filtered, not fought
over: allocs the drainer already marked (``desired_transition.migrate``),
gang-job members (law 15 owns their atomicity), system jobs, jobs with
an active deployment, and non-running allocs are all skipped, so the
controller never races another subsystem for the same alloc.

Chaos sites: ``migrate.move_drop`` (a planned move is dropped before
phase A — nothing committed, conservation trivial) and
``migrate.kill_mid_move`` (thread kill or lost phase B between the
phases — the half-move must be recovered, never doubled).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import numpy as np

from ..chaos.plane import ChaosThreadKill, chaos_site
from ..structs import MergedPlan, Plan, allocs_fit, new_id
from ..structs.alloc import (
    ALLOC_CLIENT_PENDING,
    ALLOC_DESIRED_RUN,
    DesiredTransition,
)
from ..structs.resources import node_comparable_capacity
from ..utils.metrics import count_swallowed, global_metrics as metrics

log = logging.getLogger("nomad_tpu.defrag")

#: desired_description marker on a defrag replacement alloc. Law 16 uses
#: it to recognize the legitimate mid-move pair (old + replacement, same
#: group slot, linked by previous_allocation) at a quiesce point.
DEFRAG_DESC = "alloc migrated by defrag"

#: desired_description on the old alloc's phase-B stop.
DEFRAG_STOP_DESC = "alloc stopped after defrag migration"

#: the controller's synthetic worker id on MergedPlans: it owns no
#: lanes, so every destination node rides a confirmed cross-lane claim.
DEFRAG_CLAIMANT = -1


class DefragController:
    """Periodic + event-triggered defragmentation bound to a Server.

    ``interval <= 0`` disables the periodic scan (the production-safe
    default): the thread still runs and serves explicit ``trigger()``
    calls (operator API), but nothing moves unasked. Drain-completion
    nudges (``notify_drain_complete``) only fire when periodic mode is
    enabled — a freed node is prime repacking space, but only clusters
    that opted into continuous defrag want it acted on."""

    def __init__(
        self,
        server,
        interval: float = 0.0,
        budget: int = 4,
        min_gain_moves: int = 1,
    ):
        self.server = server
        self.interval = float(interval)
        self.budget = int(budget)
        self.min_gain_moves = int(min_gain_moves)
        self.paused = False
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._busy = False
        self._lock = threading.Lock()
        self.cycles = 0
        self.last_efficiency = 1.0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="defrag", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=2)
            self._thread = None

    def trigger(self) -> None:
        """Run a cycle soon regardless of the periodic interval (the
        operator endpoint's knob)."""
        self._wake.set()

    def notify_drain_complete(self) -> None:
        """A node finished draining: its freed capacity makes this the
        cheapest moment to repack — but only in continuous mode."""
        if self.interval > 0:
            self._wake.set()

    def drained(self) -> bool:
        """No cycle in flight — the chaos runner's quiesce predicate."""
        with self._lock:
            return not self._busy

    def recover(self) -> None:
        """Synchronously finish any outstanding half-moves (phase B
        only — no new moves are planned). The chaos runner calls this
        after quiesce so a ``kill_mid_move`` landing on the *last* cycle
        still resolves before law 16 judges the cluster."""
        self._recover_half_moves(self.server.store.snapshot())

    def status(self) -> dict:
        snap = metrics.snapshot()["counters"]
        return {
            "enabled": self.interval > 0,
            "paused": self.paused,
            "interval": self.interval,
            "budget": self.budget,
            "cycles": self.cycles,
            "packing_efficiency": round(self.last_efficiency, 6),
            "counters": {
                k: v for k, v in sorted(snap.items())
                if k.startswith("nomad.migrate.")
            },
        }

    # -- the loop ----------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            timeout = self.interval if self.interval > 0 else None
            fired = self._wake.wait(timeout)
            if self._stop.is_set():
                return
            if fired:
                self._wake.clear()
            try:
                self.run_cycle()
            except ChaosThreadKill as e:
                # injected crash mid-move: the cycle dies exactly like a
                # killed controller thread — phase A either committed
                # whole or not at all, the lane claim released via its
                # finally — and the loop supervises a fresh cycle, whose
                # recovery scan finishes any half-move left behind.
                metrics.incr("nomad.chaos.thread_kills")
                count_swallowed("chaos", e)
                with self._lock:
                    self._busy = False
            except Exception:  # noqa: BLE001
                log.exception("defrag cycle failed")

    # -- one cycle ---------------------------------------------------------
    def run_cycle(self) -> int:
        """One bounded defrag pass. Returns the number of moves fully
        completed (phase B landed)."""
        if self.paused or not self.server._leader:
            return 0
        with self._lock:
            self._busy = True
        try:
            return self._cycle_inner()
        finally:
            with self._lock:
                self._busy = False

    def _cycle_inner(self) -> int:
        from ..device.migrate import packing_efficiency
        from ..scheduler.migrate import build_defrag_batch, _steps_for
        from ..device.migrate import oracle_migrate_plan

        snap = self.server.store.snapshot()
        if self._recover_half_moves(snap):
            # recovery stopped allocs the snapshot still shows live —
            # replan from the post-recovery state, or a stopped source
            # could be re-migrated (a double-committed move, law 16)
            snap = self.server.store.snapshot()

        nodes = [n for n in snap.nodes() if n.ready()]
        if len(nodes) < 2:
            return 0
        node_row = {n.id: i for i, n in enumerate(nodes)}
        capacity = np.stack(
            [node_comparable_capacity(n).to_vector() for n in nodes]
        ).astype(np.float32)
        used = np.zeros_like(capacity)
        for n in nodes:
            for a in snap.allocs_by_node(n.id):
                if not a.terminal_status():
                    used[node_row[n.id]] += (
                        a.comparable_resources().to_vector()
                    )

        ready = np.ones(len(nodes), dtype=bool)
        eff = packing_efficiency(capacity, used, ready)
        self.last_efficiency = eff
        metrics.set_gauge("nomad.migrate.packing_efficiency", eff)

        movable = self._candidates(snap, node_row)
        if not movable:
            return 0
        sizes = np.stack(
            [a.comparable_resources().to_vector() for a, _ in movable]
        ).astype(np.float32)
        cur = np.array(
            [node_row[a.node_id] for a, _ in movable], dtype=np.int32
        )
        args = build_defrag_batch(capacity, used, sizes, cur)
        lam0 = np.zeros(len(nodes), dtype=np.float32)
        dest, _gains, _used_mid, moves, _rounds, _lam = oracle_migrate_plan(
            *args, np.int32(self.budget), lam0, _steps_for(len(movable))
        )
        if moves == 0:
            return 0
        if moves >= self.budget:
            metrics.incr("nomad.migrate.budget_exhausted")

        completed = 0
        for i in np.flatnonzero(dest >= 0):
            old, job = movable[int(i)]
            if self._stop.is_set():
                break
            if self._execute_move(old, job, nodes[int(dest[i])].id):
                completed += 1
        self.cycles += 1
        return completed

    # -- candidate selection ----------------------------------------------
    def _candidates(self, snap, node_row) -> list:
        """(alloc, job) pairs the controller may move. Everything another
        subsystem owns — or whose atomicity law is stricter than a
        per-alloc move — is excluded up front."""
        out = []
        # sources of in-flight moves: any live defrag replacement's
        # previous_allocation is mid-move — planning a SECOND move of
        # that source would double-commit the slot (law 16's first
        # violation class), so both halves of a pair are off the table
        in_flight_sources = {
            a.previous_allocation
            for a in snap.allocs()
            if not a.terminal_status()
            and a.desired_description == DEFRAG_DESC
            and a.previous_allocation
        }
        for a in snap.allocs():
            if a.terminal_status() or a.client_status != "running":
                continue
            if a.id in in_flight_sources:
                continue  # mid-move source: phase B owns its exit
            if a.desired_transition.migrate:
                continue  # drainer owns this alloc's exit
            if a.desired_description == DEFRAG_DESC and a.previous_allocation:
                prev = snap.alloc_by_id(a.previous_allocation)
                if prev is not None and not prev.terminal_status():
                    continue  # mid-move: the recovery scan owns it
            if a.node_id not in node_row:
                continue
            job = snap.job_by_id(a.namespace, a.job_id)
            if job is None or job.stopped():
                continue
            if job.type in ("system", "sysbatch"):
                continue  # pinned per-node by definition
            if job.gang:
                continue  # law 15 (gang atomicity) owns these
            dep = snap.latest_deployment_by_job(a.namespace, a.job_id)
            if dep is not None and dep.active():
                continue  # deployment watcher owns placement churn
            out.append((a, job))
        # deterministic order: by (namespace, job, name) so a seeded run
        # builds the identical batch every time
        out.sort(key=lambda p: (p[0].namespace, p[0].job_id, p[0].name))
        return out

    # -- the two-phase move ------------------------------------------------
    def _execute_move(self, old, job, dest_node_id: str) -> bool:
        """Phase A (place replacement, verified commit) then phase B
        (stop old). Returns True when both phases landed."""
        metrics.incr("nomad.migrate.planned")
        if chaos_site("migrate.move_drop") == "drop":
            # the planned move was lost before anything committed —
            # conservation holds trivially, the next cycle replans it
            metrics.incr("nomad.migrate.aborted")
            return False

        replacement = self._replacement_for(old, job, dest_node_id)
        plan_a = Plan(
            eval_id=new_id(), priority=job.priority, job=job
        )
        plan_a.append_alloc(replacement)

        claim = self.server.lane_claims.reserve(
            DEFRAG_CLAIMANT, plan_a.eval_id, {dest_node_id: [replacement]}
        )
        if claim is None:
            metrics.incr("nomad.migrate.aborted")
            return False
        placed = False
        try:
            if not self.server.lane_claims.confirm(claim):
                metrics.incr("nomad.migrate.aborted")
                return False
            # past this point the applier may land the placement even if
            # this thread dies — release must settle the node either way
            claim.submitted = True
            futures = self.server.plan_queue.enqueue_merged(
                MergedPlan(
                    plans=[plan_a],
                    owner_worker=DEFRAG_CLAIMANT,
                    claims=[claim],
                )
            )
            result = futures[0].result(timeout=5.0)
            placed, _, _ = result.full_commit(plan_a)
        except ChaosThreadKill:
            raise  # thread boundary accounts it; finally releases
        except Exception:  # noqa: BLE001
            log.exception("defrag phase A failed for %s", old.id)
        finally:
            # settling exists to cover a lane owner's frozen overlay
            # base predating this commit. Outside lane mode the single
            # applier's re-verify already bounces stale optimism, so
            # settling would only wedge idle clusters (nobody rebases).
            committed = claim.submitted and self.server.lane_mode
            self.server.lane_claims.release(claim, committed=committed)
            if committed:
                # mirror the worker's rebase idiom: a fresh owner
                # overlay has no stale base, so its settled nodes are
                # immediately schedulable again — without this an idle
                # owner never rebases and the node stays blocked
                owner = self.server.lanes.owner_of_node(dest_node_id)
                ov = self.server.placement_overlay.for_worker(owner)
                if ov.is_fresh():
                    self.server.lane_claims.clear_settled(owner)
        if not placed:
            metrics.incr("nomad.migrate.aborted")
            return False

        # mid-move capacity audit: with both halves live the destination
        # must still fit — the applier's verify guarantees it, law 16
        # pins the counter at zero
        self._audit_capacity(dest_node_id)

        # the seam chaos rehearses: a kill here leaves the committed
        # pair for the recovery scan; a drop loses phase B the same way
        if chaos_site("migrate.kill_mid_move") == "drop":
            metrics.incr("nomad.migrate.interrupted")
            return False

        self._stop_old(old)
        metrics.incr("nomad.migrate.completed")
        return True

    def _replacement_for(self, old, job, dest_node_id: str):
        a = old.copy_for_update()
        a.id = new_id()
        a.node_id = dest_node_id
        a.previous_allocation = old.id
        a.next_allocation = ""
        a.eval_id = ""
        a.job = job
        a.desired_status = ALLOC_DESIRED_RUN
        a.desired_description = DEFRAG_DESC
        a.desired_transition = DesiredTransition()
        a.client_status = ALLOC_CLIENT_PENDING
        a.client_description = ""
        a.deployment_id = ""
        a.deployment_status = None
        a.create_index = 0
        a.modify_index = 0
        return a

    def _stop_old(self, old) -> None:
        """Phase B: a stop-only plan through the same serialized commit
        path (stops always commit — they only free capacity)."""
        plan_b = Plan(eval_id=new_id())
        plan_b.append_stopped_alloc(old, DEFRAG_STOP_DESC)
        futures = self.server.plan_queue.enqueue_merged(
            MergedPlan(plans=[plan_b], owner_worker=DEFRAG_CLAIMANT)
        )
        futures[0].result(timeout=5.0)

    def _audit_capacity(self, node_id: str) -> None:
        snap = self.server.store.snapshot()
        node = snap.node_by_id(node_id)
        if node is None:
            return
        live = [
            a for a in snap.allocs_by_node(node_id)
            if not a.terminal_status()
        ]
        ok, _dim, _used = allocs_fit(node, live, check_devices=True)
        if not ok:
            metrics.incr("nomad.migrate.capacity_violations")

    # -- recovery ----------------------------------------------------------
    def _recover_half_moves(self, snap) -> int:
        """Finish moves a dead controller left half-done: a live defrag
        replacement whose source alloc is still live means phase A
        committed but phase B never ran — complete it (stop the old
        half). The pair is exactly what law 16 tolerates mid-move; this
        scan is what bounds 'mid-move' to one cycle. Returns the number
        of half-moves completed."""
        recovered = 0
        for a in snap.allocs():
            if a.desired_description != DEFRAG_DESC or a.terminal_status():
                continue
            if not a.previous_allocation:
                continue
            old = snap.alloc_by_id(a.previous_allocation)
            if old is None or old.terminal_status():
                continue
            try:
                self._stop_old(old)
                metrics.incr("nomad.migrate.recovered")
                metrics.incr("nomad.migrate.completed")
                recovered += 1
            except Exception:  # noqa: BLE001
                log.exception("defrag recovery failed for %s", old.id)
        return recovered
