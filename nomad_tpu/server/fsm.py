"""FSM — typed, replicable state-mutation messages.

Reference: nomad/fsm.go — every cluster write is a ``structs.MessageType``
log entry applied by a registered applier (:62-73); the FSM is the ONLY
writer of the state store, so replaying the Raft log on any server
reproduces identical state. Here each message is (MsgType, payload dict of
plain structs, pickled in the log); appliers are deterministic functions
of (store state, payload, index).

Decision logic (validation, eval construction, plan evaluation) stays in
the endpoints/leader — exactly like the reference, where Job.Register
builds the request and the FSM only applies it.
"""

from __future__ import annotations

import logging
from enum import IntEnum
from typing import Any, Optional

log = logging.getLogger(__name__)


class MsgType(IntEnum):
    """nomad/structs MessageType analog (fsm.go:36-59)."""

    NOOP = 0                      # leadership-change barrier entries
    JOB_UPSERT = 1                # {job, evals}
    JOB_BATCH_GC = 2              # {eval_ids, alloc_ids, jobs, node_ids, deployment_ids}
    JOB_STABLE = 3                # {job}  (stable rollback target)
    NODE_UPSERT = 4               # {node}
    NODE_STATUS = 5               # {node_id, status}
    NODE_DRAIN = 6                # {node_id, drain, eligibility, transitions, evals}
    NODE_ELIGIBILITY = 7          # {node_id, eligibility}
    EVAL_UPSERT = 8               # {evals}
    ALLOC_CLIENT_UPDATE = 9       # {updates}
    ALLOC_DESIRED_TRANSITION = 10 # {transitions, evals}
    ALLOC_HEALTH = 11             # {healthy_ids, unhealthy_ids}
    PLAN_RESULT = 12              # {result, eval_id, evals}
    DEPLOYMENT_STATUS = 13        # {deployment_id, status, description}
    DEPLOYMENT_UPSERT = 14        # {deployment}
    CSI_VOLUME_UPSERT = 15        # {volume}
    CSI_VOLUME_DEREGISTER = 16    # {volume_id, force}
    CSI_CLAIM = 17                # {volume_id, claim_id, node_id, read_only}
    CSI_RELEASE = 18              # {volume_id, claim_id}
    ACL_BOOTSTRAP = 19            # {token}
    ACL_POLICY_UPSERT = 20        # {policies}
    ACL_POLICY_DELETE = 21        # {names}
    ACL_TOKEN_UPSERT = 22         # {tokens}
    ACL_TOKEN_DELETE = 23         # {accessor_ids}
    SCHED_CONFIG = 24             # {config}
    NAMESPACE_UPSERT = 25         # {namespace}
    NAMESPACE_DELETE = 26         # {name}
    JOB_SCALE = 27                # {job, evals, event}
    RAFT_REMOVE_PEER = 28         # {node_id} — membership change; the
                                  # raft layer consumes it (autopilot
                                  # dead-server cleanup, operator raft
                                  # remove-peer); no state-store effect
    MERGED_PLAN_RESULT = 29       # {results, eval_ids, evals} — one
                                  # batched pass's member PlanResults as
                                  # a single log entry / store txn


class FSM:
    """Applies committed log entries to the state store. ``store`` is
    swappable (snapshot restore installs a fresh store), so the FSM
    resolves it through a getter."""

    def __init__(self, get_store):
        self._get_store = get_store

    @property
    def store(self):
        return self._get_store()

    def apply(self, index: int, mtype: int, payload: Optional[dict]) -> Any:
        """Apply one committed entry; returns the applier's result (used by
        the submitting endpoint on the leader; followers discard it).
        Appliers must be deterministic — no wall-clock, no randomness."""
        try:
            handler = _APPLIERS[MsgType(mtype)]
        except (ValueError, KeyError):
            # Unknown message from a newer version: tolerate, don't crash
            # the FSM (fsm.go ignores with an error log for forward compat).
            log.error("fsm: unknown message type %s at index %d", mtype, index)
            self.store.bump_index(index)
            return None
        # Appliers must NEVER let an exception escape: the entry is already
        # durably logged/replicated, so raising would desync the index
        # sequence (poisoning WAL contiguity) and crash log replay on boot.
        # A rejection is a deterministic no-op + error result — identical
        # on every replica since it depends only on store state. (e.g. a
        # NODE_STATUS for a node that GC reaped between submit and apply.)
        try:
            result = handler(self, self.store, index, payload or {})
            # Some store ops no-op on rejection (csi_claim → False) without
            # touching indexes; latest_index MUST advance for every applied
            # entry or the next append desyncs from the log (bump is a max,
            # so this is free when the applier already bumped).
            self.store.bump_index(index)
            return result
        except Exception as e:  # noqa: BLE001 — invariant, see above
            log.warning(
                "fsm: applier %s rejected entry at index %d: %s",
                MsgType(mtype).name, index, e,
            )
            self.store.bump_index(index)
            return e


# -- appliers (fsm.go:62-73 LogAppliers table) ------------------------------

def _apply_noop(fsm, store, index, p):
    store.bump_index(index)


def _apply_job_upsert(fsm, store, index, p):
    store.upsert_job(index, p["job"])
    if p.get("evals"):
        for ev in p["evals"]:
            ev.job_modify_index = index
        store.upsert_evals(index, p["evals"])


def _apply_job_batch_gc(fsm, store, index, p):
    if p.get("eval_ids"):
        store.delete_evals(index, p["eval_ids"])
    if p.get("alloc_ids"):
        store.delete_allocs(index, p["alloc_ids"])
    for ns, job_id in p.get("jobs", ()):
        store.delete_job(index, ns, job_id)
    for node_id in p.get("node_ids", ()):
        store.delete_node(index, node_id)
    for dep_id in p.get("deployment_ids", ()):
        store.delete_deployment(index, dep_id)


def _apply_job_stable(fsm, store, index, p):
    store.mark_job_stable(index, p["job"])


def _apply_node_upsert(fsm, store, index, p):
    store.upsert_node(index, p["node"])


def _apply_node_status(fsm, store, index, p):
    store.update_node_status(index, p["node_id"], p["status"])


def _apply_node_drain(fsm, store, index, p):
    store.update_node_drain(
        index, p["node_id"], p.get("drain"),
        eligibility=p.get("eligibility"),
    )
    if p.get("transitions"):
        store.update_allocs_desired_transition(index, p["transitions"])
    if p.get("evals"):
        store.upsert_evals(index, p["evals"])


def _apply_node_eligibility(fsm, store, index, p):
    store.update_node_eligibility(index, p["node_id"], p["eligibility"])


def _apply_eval_upsert(fsm, store, index, p):
    store.upsert_evals(index, p["evals"])


def _apply_alloc_client_update(fsm, store, index, p):
    store.update_allocs_from_client(index, p["updates"])


def _apply_alloc_desired_transition(fsm, store, index, p):
    store.update_allocs_desired_transition(index, p["transitions"])
    if p.get("evals"):
        store.upsert_evals(index, p["evals"])


def _apply_alloc_health(fsm, store, index, p):
    store.update_alloc_health(
        index, p.get("healthy_ids", []), p.get("unhealthy_ids", [])
    )


def _apply_plan_result(fsm, store, index, p):
    store.upsert_plan_results(index, p["result"], p.get("eval_id", ""))
    if p.get("evals"):  # preemption follow-ups ride the same commit
        store.upsert_evals(index, p["evals"])


def _apply_merged_plan_result(fsm, store, index, p):
    store.upsert_merged_plan_results(index, p["results"])
    if p.get("evals"):  # preemption follow-ups ride the same commit
        store.upsert_evals(index, p["evals"])


def _apply_deployment_status(fsm, store, index, p):
    store.update_deployment_status(
        index, p["deployment_id"], p["status"], p.get("description", "")
    )


def _apply_deployment_upsert(fsm, store, index, p):
    store.update_deployment(index, p["deployment"])


def _apply_csi_volume_upsert(fsm, store, index, p):
    # rejections (spec change on in-use volume) surface via the generic
    # never-raise guard in FSM.apply as a returned ValueError
    store.upsert_csi_volume(index, p["volume"])


def _apply_csi_volume_deregister(fsm, store, index, p):
    store.deregister_csi_volume(
        index, p["volume_id"], force=p.get("force", False)
    )


def _apply_csi_claim(fsm, store, index, p):
    # external-claim classification is deterministic: it depends only on
    # store state at this index, identical on every replica
    external = store.alloc_by_id(p["claim_id"]) is None
    return store.csi_claim(
        index, p["volume_id"], p["claim_id"], p["node_id"],
        p["read_only"], external=external,
    )


def _apply_csi_release(fsm, store, index, p):
    return store.csi_release(index, p["volume_id"], p["claim_id"])


def _apply_acl_bootstrap(fsm, store, index, p):
    store.bootstrap_acl_token(index, p["token"])


def _apply_acl_policy_upsert(fsm, store, index, p):
    store.upsert_acl_policies(index, p["policies"])


def _apply_acl_policy_delete(fsm, store, index, p):
    store.delete_acl_policies(index, p["names"])


def _apply_acl_token_upsert(fsm, store, index, p):
    store.upsert_acl_tokens(index, p["tokens"])


def _apply_acl_token_delete(fsm, store, index, p):
    store.delete_acl_tokens(index, p["accessor_ids"])


def _apply_sched_config(fsm, store, index, p):
    store.set_scheduler_config(index, p["config"])


def _apply_namespace_upsert(fsm, store, index, p):
    store.upsert_namespace(index, p["namespace"])


def _apply_namespace_delete(fsm, store, index, p):
    store.delete_namespace(index, p["name"])


def _apply_job_scale(fsm, store, index, p):
    store.upsert_job(index, p["job"])
    if p.get("evals"):
        for ev in p["evals"]:
            ev.job_modify_index = index
        store.upsert_evals(index, p["evals"])
    job = p["job"]
    store.add_scaling_event(index, job.namespace, job.id, p["event"])


_APPLIERS = {
    MsgType.NOOP: _apply_noop,
    MsgType.JOB_UPSERT: _apply_job_upsert,
    MsgType.JOB_BATCH_GC: _apply_job_batch_gc,
    MsgType.JOB_STABLE: _apply_job_stable,
    MsgType.NODE_UPSERT: _apply_node_upsert,
    MsgType.NODE_STATUS: _apply_node_status,
    MsgType.NODE_DRAIN: _apply_node_drain,
    MsgType.NODE_ELIGIBILITY: _apply_node_eligibility,
    MsgType.EVAL_UPSERT: _apply_eval_upsert,
    MsgType.ALLOC_CLIENT_UPDATE: _apply_alloc_client_update,
    MsgType.ALLOC_DESIRED_TRANSITION: _apply_alloc_desired_transition,
    MsgType.ALLOC_HEALTH: _apply_alloc_health,
    MsgType.PLAN_RESULT: _apply_plan_result,
    MsgType.DEPLOYMENT_STATUS: _apply_deployment_status,
    MsgType.DEPLOYMENT_UPSERT: _apply_deployment_upsert,
    MsgType.CSI_VOLUME_UPSERT: _apply_csi_volume_upsert,
    MsgType.CSI_VOLUME_DEREGISTER: _apply_csi_volume_deregister,
    MsgType.CSI_CLAIM: _apply_csi_claim,
    MsgType.CSI_RELEASE: _apply_csi_release,
    MsgType.ACL_BOOTSTRAP: _apply_acl_bootstrap,
    MsgType.ACL_POLICY_UPSERT: _apply_acl_policy_upsert,
    MsgType.ACL_POLICY_DELETE: _apply_acl_policy_delete,
    MsgType.ACL_TOKEN_UPSERT: _apply_acl_token_upsert,
    MsgType.ACL_TOKEN_DELETE: _apply_acl_token_delete,
    MsgType.SCHED_CONFIG: _apply_sched_config,
    MsgType.NAMESPACE_UPSERT: _apply_namespace_upsert,
    MsgType.NAMESPACE_DELETE: _apply_namespace_delete,
    MsgType.JOB_SCALE: _apply_job_scale,
    # membership change rides the log for ordering/durability but mutates
    # raft config, not the store (RaftNode._applier intercepts it)
    MsgType.RAFT_REMOVE_PEER: _apply_noop,
    MsgType.MERGED_PLAN_RESULT: _apply_merged_plan_result,
}
