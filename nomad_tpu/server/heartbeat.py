"""Server-side node heartbeat tracking.

Reference: nomad/heartbeat.go (:34-50 nodeHeartbeater): a TTL timer per
node, reset on every heartbeat; expiry marks the node down, which fans
out node-update evals so allocations are rescheduled (→ SURVEY.md §3.4).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..chaos.plane import chaos_site
from ..structs import NODE_STATUS_DOWN

DEFAULT_HEARTBEAT_TTL = 5.0


class NodeHeartbeater:
    def __init__(self, server, ttl: float = DEFAULT_HEARTBEAT_TTL, clock=None):
        self.server = server
        self.ttl = ttl
        # injectable monotonic clock (the GenericScheduler clock=
        # pattern, NTA008): TTL deadlines read it, so chaos clock-skew
        # faults can expire or extend heartbeats deterministically
        self._clock = clock if clock is not None else time.monotonic
        self._deadlines: dict[str, float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="heartbeater", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    def heartbeat(self, node_id: str) -> float:
        """Reset the node's TTL timer; returns the TTL the client should
        beat within (Node.UpdateStatus heartbeat path)."""
        with self._lock:
            self._deadlines[node_id] = self._clock() + self.ttl
        return self.ttl

    def initialize_from_store(self) -> None:
        """Seed a TTL timer for every live node — a freshly-elected leader
        must detect clients that died during the failover window
        (leader.go:318 initializeHeartbeatTimers)."""
        for node in self.server.store.nodes():
            if not node.terminal_status():
                self.heartbeat(node.id)

    def untrack(self, node_id: str) -> None:
        with self._lock:
            self._deadlines.pop(node_id, None)

    def _run(self) -> None:
        while not self._stop.wait(min(self.ttl / 4.0, 0.5)):
            now = self._clock()
            expired = []
            with self._lock:
                for node_id, deadline in list(self._deadlines.items()):
                    if deadline < now:
                        expired.append(node_id)
                        del self._deadlines[node_id]
            for node_id in expired:
                node = self.server.store.node_by_id(node_id)
                if node is None or node.terminal_status():
                    continue
                if chaos_site("heartbeat.expiry") == "drop":
                    # missed sweep: the expiry is deferred, not lost —
                    # re-arm the timer so the next sweep fires it
                    self.heartbeat(node_id)
                    continue
                # missed TTL ⇒ node down ⇒ reschedule evals fan out
                self.server.update_node_status(node_id, NODE_STATUS_DOWN)
