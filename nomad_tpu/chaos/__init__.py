"""nomad_tpu.chaos — deterministic fault injection + cluster invariants.

A seeded :class:`FaultPlane` injects faults (raise, delay, duplicate
delivery, drop, cooperative thread-kill, clock skew) at named *sites*
compiled into the production seams (broker dequeue/ack, plan queue,
plan apply verify/commit, raft apply, worker commit thread, heartbeat
expiry, store snapshot, kernel execute). The plane is off by default:
every site is a single global load + ``is None`` branch when no plane
is installed, the same zero-overhead-when-unset contract as
``NOMAD_TPU_RACECHECK`` (analysis/race.py). Set ``NOMAD_TPU_CHAOS`` to
a spec (``seed=7,steps=200,faults=raise+delay``) to auto-install one.

:mod:`.invariants` checks the cluster's conservation laws after a run;
:mod:`.runner` drives a seeded in-process cluster through a randomized
workload and re-runs bit-identically from the same seed
(``nomad-tpu chaos run --seed 7 --steps 200``).
"""

from .plane import (  # noqa: F401
    ENV_VAR,
    FAULT_KINDS,
    SITES,
    ChaosClock,
    ChaosFault,
    ChaosThreadKill,
    FaultPlane,
    FaultSpec,
    active_plane,
    chaos_site,
    install,
    make_fault,
    note_committed,
    uninstall,
)
from .invariants import InvariantReport, Violation, check_cluster  # noqa: F401
from .runner import ChaosRun, run_chaos, shrink_schedule  # noqa: F401
