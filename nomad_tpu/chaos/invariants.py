"""Cluster conservation laws, checked over a live quiesced cluster.

The checks encode what the eval→plan→apply pipeline promises to keep
true no matter which faults fired:

``node_capacity``
    no node's committed non-terminal allocations exceed its
    reserved-adjusted capacity (the plan applier's verify step is the
    only writer of placements, so an overcommit means verify lied).
``plan_ledger``
    every *fresh* placement the applier reported committed landed in
    the store exactly once — no loss after a reported commit, no
    double-commit of a merged-plan member. In-place updates of an
    existing alloc (job scaled / re-registered) are not placements and
    are excluded (requires an installed FaultPlane ledger).
``index_monotonic``
    the change journal's raft indexes never go backwards and the
    store's latest index bounds every journaled write.
``overlay_drained``
    the SharedOverlay's pass/commit markers drain to zero once the
    cluster quiesces — a leaked marker wedges ``maybe_reset`` forever.
``broker_conservation``
    every dequeue is resolved by exactly one of ack, nack, or
    unack-deadline redelivery (at-least-once bookkeeping balances).
``swallow_ring``
    no swallowed-error counter increments without a matching flight-
    recorder error-ring event (swallows can't hide from the obs plane).
``job_conservation``
    after quiesce every service job runs exactly its desired count of
    allocations, or a live eval (pending/blocked in the store, or
    parked in the broker's failed queue) accounts for the difference;
    an unexplained surplus is the double-commit smoking gun.
``eval_terminal``
    no eval is stranded: every non-terminal eval in the store is still
    tracked somewhere (broker queues, delayed heap, job gate, failed
    queue, or the blocked-evals tracker).
``lane_isolation``
    with deterministic lane ownership active, structural disjointness
    held: zero lane conflicts (``nomad.plan.lane_conflicts`` — a merged
    plan touching a foreign node without a confirmed claim, or bounced
    on one), zero cross-lane overlay writes
    (``nomad.overlay.cross_lane_writes``), and the claim table drained
    (no leaked reservations after quiesce). Handoffs themselves are
    fine and counted separately (``nomad.plan.cross_lane_handoffs``).
``admission_conservation``
    the admission controller's per-tier decision ledger balances:
    ``admitted + deferred + shed == submitted`` for every priority
    tier — no decision is lost or double-counted, even through
    ``admission.flap`` forced-level windows (server/admission.py).
``class_capacity``
    per-device-class conservation: within every device class (including
    the class-less ""), summed live-allocation usage never exceeds the
    class's summed reserved-adjusted capacity on non-terminal nodes. A
    per-node overcommit is already ``node_capacity``; this catches the
    heterogeneity-specific failure where a policy pass (or its cache's
    class column going stale) books work against a class that doesn't
    hold it (scheduler/hetero.py, device/cache.py).
``shard_consistency``
    with a multi-chip mesh active, the DeviceStateCache's sharded
    device-resident capacity, re-gathered to host per shard, equals the
    store-derived reference tensors *exactly* (bitwise) — per-shard
    incremental refresh (dirty-region tracking) and the
    ``mesh.shard_refresh_drop`` chaos recovery path never leave a stale
    slice on any device (device/cache.py, utils/backend.py).
``cp_assignment_conservation``
    every group that entered a CP joint pass (scheduler/cp.py) ended
    exactly one of placed / deferred / failed — the ``nomad.cp.*``
    pass ledger balances — and no pass ever committed usage beyond a
    node's capacity (``nomad.cp.capacity_violations`` stays 0), even
    through ``cp.round_perturb`` price-perturbation windows.
``calibration_sanity``
    the calibration plane (obs/calibrate.py) degrades to declared,
    never to garbage: every throughput-estimator cell is finite and
    positive, a cell below the sample floor reports ``source: default``
    (and only then), a learned read stays inside the clamp band of its
    anchor, and every calibration-table constant is finite with a known
    provenance source — including through ``calib.telemetry_drop``
    starvation windows.
``gang_atomicity``
    after quiesce every gang job (structs/job.py ``gang`` stanza) is
    fully placed or fully absent: its member task groups all run
    exactly their desired counts, or all run zero — never a striped
    partial gang. Holds through ``gang.commit_drop`` dropped/killed
    commits and cp-gang in-pass releases (scheduler/generic.py
    ``_enforce_gang_atomicity``, invariant law 15).
``migration_conservation``
    live migration conserves identity and capacity (server/defrag.py).
    After quiesce every migrated alloc serves exactly once: no group
    slot holds two live defrag replacements (a double-committed move),
    and no replacement's source alloc is still live (an unrecovered
    half-move — the recovery scan bounds mid-move to one cycle). The
    controller's mid-move capacity audit never fired
    (``nomad.migrate.capacity_violations`` stays 0): free capacity was
    conserved at every point between phase A and phase B, including
    through ``migrate.move_drop`` and ``migrate.kill_mid_move`` faults.
"""

from __future__ import annotations

from typing import Optional

from ..structs import allocs_fit
from ..structs.evaluation import (
    EVAL_STATUS_BLOCKED,
    EVAL_STATUS_FAILED,
    EVAL_STATUS_PENDING,
)

INVARIANTS = (
    "node_capacity",
    "plan_ledger",
    "index_monotonic",
    "overlay_drained",
    "broker_conservation",
    "swallow_ring",
    "job_conservation",
    "eval_terminal",
    "lane_isolation",
    "admission_conservation",
    "class_capacity",
    "shard_consistency",
    "cp_assignment_conservation",
    "calibration_sanity",
    "gang_atomicity",
    "migration_conservation",
)


class Violation:
    __slots__ = ("invariant", "subject", "detail")

    def __init__(self, invariant: str, subject: str, detail: str):
        self.invariant = invariant
        self.subject = subject
        self.detail = detail

    def row(self) -> str:
        return f"{self.invariant}: {self.subject}: {self.detail}"

    def __repr__(self):
        return f"Violation({self.row()})"


class InvariantReport:
    def __init__(self):
        self.checked: dict[str, bool] = {}
        self.violations: list[Violation] = []
        # free-form run stats for human rendering; excluded from the
        # canonical dict because some (queue depths, retry counts) are
        # timing-dependent while the verdicts are not
        self.info: dict[str, object] = {}

    @property
    def ok(self) -> bool:
        return not self.violations

    def _fail(self, invariant: str, subject: str, detail: str) -> None:
        self.checked[invariant] = False
        self.violations.append(Violation(invariant, subject, detail))

    def to_dict(self) -> dict:
        """Canonical form: deterministic for a deterministic workload."""
        return {
            "ok": self.ok,
            "invariants": {
                name: ("ok" if self.checked.get(name, True) else "violated")
                for name in INVARIANTS
            },
            "violations": sorted(v.row() for v in self.violations),
        }

    def render(self) -> str:
        lines = []
        for name in INVARIANTS:
            state = "ok" if self.checked.get(name, True) else "VIOLATED"
            if name not in self.checked:
                state = "skipped"
            lines.append(f"  {name:<20s} {state}")
        for v in self.violations:
            lines.append(f"  !! {v.row()}")
        return "\n".join(lines)


def metrics_baseline() -> dict:
    """Snapshot the swallow counters + error-ring total before a run so
    the swallow_ring check measures only the run's own deltas."""
    from ..obs.recorder import flight_recorder
    from ..utils.metrics import global_metrics

    counters = global_metrics.snapshot()["counters"]
    swallowed = sum(
        v for k, v in counters.items() if k.endswith(".swallowed_errors")
    )
    return {
        "swallowed": swallowed,
        "ring": flight_recorder.errors_total,
        "lane_conflicts": counters.get("nomad.plan.lane_conflicts", 0),
        "cross_lane_writes": counters.get(
            "nomad.overlay.cross_lane_writes", 0
        ),
    }


def check_cluster(
    server,
    plane=None,
    baseline: Optional[dict] = None,
) -> InvariantReport:
    """Run every conservation check against a (quiesced) live Server."""
    from ..obs.recorder import flight_recorder
    from ..utils.metrics import global_metrics

    report = InvariantReport()
    store = server.store
    snap = store.snapshot()
    broker = server.eval_broker

    # -- node_capacity + class_capacity ------------------------------------
    from ..structs.resources import node_comparable_capacity

    report.checked["node_capacity"] = True
    report.checked["class_capacity"] = True
    n_nodes = 0
    class_cap: dict[str, object] = {}
    class_used: dict[str, object] = {}
    for node in snap.nodes():
        if node.terminal_status():
            continue
        n_nodes += 1
        live = [
            a for a in snap.allocs_by_node(node.id) if not a.terminal_status()
        ]
        fits, dim, used = allocs_fit(node, live, check_devices=True)
        if not fits:
            report._fail(
                "node_capacity",
                node.id,
                f"{len(live)} live allocs overcommit {dim} (used {used})",
            )
        dc = getattr(node, "device_class", "")
        cap_vec = node_comparable_capacity(node).to_vector()
        if dc in class_cap:
            class_cap[dc] = class_cap[dc] + cap_vec
        else:
            class_cap[dc] = cap_vec
        for a in live:
            use_vec = a.comparable_resources().to_vector()
            if dc in class_used:
                class_used[dc] = class_used[dc] + use_vec
            else:
                class_used[dc] = use_vec
    for dc, used_vec in sorted(class_used.items()):
        cap_vec = class_cap.get(dc)
        if cap_vec is None or (used_vec > cap_vec).any():
            report._fail(
                "class_capacity",
                dc or "(class-less)",
                f"summed live usage {used_vec} exceeds class capacity "
                f"{cap_vec}",
            )
    report.info["nodes"] = n_nodes
    report.info["device_classes"] = len(class_cap)

    # -- plan_ledger -------------------------------------------------------
    if plane is not None:
        report.checked["plan_ledger"] = True
        for alloc_id, count in sorted(plane.committed.items()):
            if count != 1:
                report._fail(
                    "plan_ledger",
                    alloc_id,
                    f"placement committed {count} times (expected exactly 1)",
                )
            elif snap.alloc_by_id(alloc_id) is None:
                report._fail(
                    "plan_ledger",
                    alloc_id,
                    "committed placement missing from the state store",
                )
        report.info["ledger_commits"] = len(plane.committed)

    # -- index_monotonic ---------------------------------------------------
    report.checked["index_monotonic"] = True
    journal = store.journal
    with journal._lock:
        entries = list(journal._entries)
    prev = 0
    for idx, table, key in entries:
        if idx < prev:
            report._fail(
                "index_monotonic",
                f"{table}/{key}",
                f"journal index went backwards ({prev} -> {idx})",
            )
            break
        prev = idx
    if entries and entries[-1][0] > store.latest_index:
        report._fail(
            "index_monotonic",
            "latest_index",
            f"journal head {entries[-1][0]} > store latest "
            f"{store.latest_index}",
        )

    # -- overlay_drained ---------------------------------------------------
    overlay = getattr(server, "placement_overlay", None)
    if overlay is not None:
        report.checked["overlay_drained"] = True
        if hasattr(overlay, "snapshot_markers"):
            # LaneOverlays: every per-worker overlay must drain
            markers = overlay.snapshot_markers()
            if not isinstance(markers, list):
                markers = [markers]
        else:
            with overlay._lock:
                markers = [(overlay._passes, overlay._commits)]
        for w, (passes, commits) in enumerate(markers):
            if passes or commits:
                report._fail(
                    "overlay_drained",
                    f"placement_overlay[{w}]",
                    f"markers leaked after quiesce: passes={passes} "
                    f"commits={commits}",
                )

    # -- broker_conservation -----------------------------------------------
    report.checked["broker_conservation"] = True
    c = broker.counters
    with broker._lock:
        outstanding = len(broker._unack)
    resolved = c["acks"] + c["nacks"] + c["unack_timeouts"]
    if c["dequeues"] != resolved + outstanding:
        report._fail(
            "broker_conservation",
            "eval_broker",
            f"dequeues={c['dequeues']} != acks={c['acks']} + "
            f"nacks={c['nacks']} + unack_timeouts={c['unack_timeouts']} "
            f"+ outstanding={outstanding}",
        )
    if outstanding:
        report._fail(
            "broker_conservation",
            "eval_broker",
            f"{outstanding} evals still unacked after quiesce",
        )
    report.info["broker"] = dict(c)

    # -- swallow_ring ------------------------------------------------------
    report.checked["swallow_ring"] = True
    now = metrics_baseline()
    base = baseline or {"swallowed": 0, "ring": 0}
    d_swallowed = now["swallowed"] - base["swallowed"]
    d_ring = now["ring"] - base["ring"]
    if d_swallowed > d_ring:
        report._fail(
            "swallow_ring",
            "count_swallowed",
            f"{d_swallowed} swallow counter bumps but only {d_ring} "
            "error-ring events",
        )
    report.info["swallowed"] = d_swallowed

    # -- job_conservation --------------------------------------------------
    report.checked["job_conservation"] = True
    failed_ids = set(broker.failed_eval_ids())
    jobs_seen: set[tuple[str, str]] = set()
    for job in snap.jobs():
        jobs_seen.add((job.namespace, job.id))
    # jobs that were deregistered but still have allocs on the books
    for alloc in snap.allocs():
        jobs_seen.add((alloc.namespace, alloc.job_id))
    blocked = server.blocked_evals
    for namespace, job_id in sorted(jobs_seen):
        job = snap.job_by_id(namespace, job_id)
        if job is not None and job.type != "service":
            continue
        desired = 0
        if job is not None:
            desired = sum(job.required_allocs().values())
        live = [
            a
            for a in snap.allocs_by_job(namespace, job_id)
            if not a.terminal_status()
        ]
        if len(live) == desired:
            continue
        # failed is terminal parking like the broker's failed queue: a
        # deadline-capped eval explains its job's shortfall the same way
        # a delivery-limit-capped one does
        accounted = any(
            ev.status
            in (EVAL_STATUS_PENDING, EVAL_STATUS_BLOCKED, EVAL_STATUS_FAILED)
            or ev.id in failed_ids
            for ev in snap.evals_by_job(namespace, job_id)
        ) or blocked.get_blocked(namespace, job_id) is not None
        if accounted:
            continue
        kind = "surplus" if len(live) > desired else "shortfall"
        report._fail(
            "job_conservation",
            f"{namespace}/{job_id}",
            f"unaccounted {kind}: {len(live)} live allocs vs desired "
            f"{desired} with no outstanding eval",
        )
    report.info["jobs"] = len(jobs_seen)

    # -- eval_terminal -----------------------------------------------------
    report.checked["eval_terminal"] = True
    tracked = broker.tracked_eval_ids()
    tracked |= {ev.id for ev in server.blocked_evals.captured()}
    for ev in snap.evals():
        if ev.terminal_status() or ev.status == EVAL_STATUS_BLOCKED:
            continue
        if ev.id not in tracked:
            report._fail(
                "eval_terminal",
                ev.id,
                f"eval for {ev.namespace}/{ev.job_id} is {ev.status} but "
                "tracked by no queue",
            )

    # -- lane_isolation ----------------------------------------------------
    # Checked whenever the lane machinery exists (it is structural, so
    # the counters must stay zero even at one worker); the claim-table
    # drain additionally proves no reservation leaked past quiesce —
    # including through handoff_drop faults and kill-mid-handoff.
    claims = getattr(server, "lane_claims", None)
    if claims is not None:
        report.checked["lane_isolation"] = True
        base = baseline or {}
        d_conflicts = now["lane_conflicts"] - base.get("lane_conflicts", 0)
        d_xwrites = now["cross_lane_writes"] - base.get(
            "cross_lane_writes", 0
        )
        if d_conflicts:
            report._fail(
                "lane_isolation",
                "plan_applier",
                f"{d_conflicts} lane conflicts (merged plans escaped "
                "ownership or bounced on foreign nodes)",
            )
        if d_xwrites:
            report._fail(
                "lane_isolation",
                "placement_overlay",
                f"{d_xwrites} cross-lane overlay writes refused (a worker "
                "wrote into a peer's epoch)",
            )
        if not claims.drained():
            report._fail(
                "lane_isolation",
                "lane_claims",
                f"{claims.active_count()} claims still active after "
                f"quiesce (nodes {sorted(claims.blocked_node_ids())})",
            )
        report.info["lanes"] = claims.snapshot()

    # -- admission_conservation --------------------------------------------
    # Law 10: the admission controller's per-tier decision ledger must
    # balance — every submitted decision resolved as exactly one of
    # admitted, deferred, or shed. Per-server counters, so no baseline
    # is needed; checked whenever the controller exists, including
    # through admission.flap forced-level windows.
    adm = getattr(server, "admission", None)
    if adm is not None:
        report.checked["admission_conservation"] = True
        adm_counters = adm.counters()
        for tier in sorted(adm_counters):
            c2 = adm_counters[tier]
            resolved = c2["admitted"] + c2["deferred"] + c2["shed"]
            if resolved != c2["submitted"]:
                report._fail(
                    "admission_conservation",
                    f"tier:{tier}",
                    f"submitted={c2['submitted']} != "
                    f"admitted={c2['admitted']} + deferred={c2['deferred']} "
                    f"+ shed={c2['shed']}",
                )
        report.info["admission"] = adm.snapshot()

    # -- cp_assignment_conservation ----------------------------------------
    # Law 13: the CP dispatcher's pass ledger must balance — every group
    # submitted to a joint pass resolved as exactly one of placed,
    # deferred, or failed — and no pass may ever have committed usage
    # beyond capacity. Checked whenever any CP pass ran this process
    # (counter-based, like law 10; perturbation windows included).
    cp_counters = global_metrics.snapshot()["counters"]
    cp_groups = cp_counters.get("nomad.cp.groups_in", 0)
    if cp_groups:
        report.checked["cp_assignment_conservation"] = True
        resolved = (
            cp_counters.get("nomad.cp.placed_groups", 0)
            + cp_counters.get("nomad.cp.deferred_groups", 0)
            + cp_counters.get("nomad.cp.failed_groups", 0)
        )
        if resolved != cp_groups:
            report._fail(
                "cp_assignment_conservation",
                "cp_pass_ledger",
                f"groups_in={cp_groups} != placed+deferred+failed="
                f"{resolved}",
            )
        cp_viol = cp_counters.get("nomad.cp.capacity_violations", 0)
        if cp_viol:
            report._fail(
                "cp_assignment_conservation",
                "cp_capacity",
                f"{cp_viol} node-rounds committed usage beyond capacity",
            )

    # -- shard_consistency -------------------------------------------------
    # Law 12: with a multi-chip mesh active, the device-resident capacity
    # shards (per-shard incremental refresh, device/cache.py) re-gathered
    # to host must equal the store-derived reference bitwise — including
    # after mesh.shard_refresh_drop recovery. Skipped when no device view
    # ever materialized (mesh off / single shard).
    from ..utils.backend import get_mesh

    cache = getattr(server, "device_cache", None)
    if get_mesh().active and cache is not None:
        mismatches = cache.verify_device_view()
        if mismatches is not None:
            report.checked["shard_consistency"] = True
            for detail in mismatches:
                report._fail("shard_consistency", "device_cache", detail)
            report.info["device_cache"] = cache.device_counters()
    # Score half of law 12: the persisted score-state shards (incremental
    # rescoring, device/cache.py) re-gathered to host must equal their
    # generation mirror bitwise — including after cache.score_refresh_drop
    # recovery and killed commits. Checked whenever a score view ever
    # materialized; unlike the capacity half it also exists with the mesh
    # off (the degenerate path persists a whole-tensor buffer).
    if cache is not None:
        score_mismatches = cache.verify_score_view()
        if score_mismatches is not None:
            report.checked["shard_consistency"] = True
            for detail in score_mismatches:
                report._fail("shard_consistency", "score_view", detail)
            report.info["device_cache"] = cache.device_counters()

    # -- calibration_sanity ------------------------------------------------
    # Law 14: estimation degrades to declared, never to garbage. Checked
    # whenever the server carries a calibration plane (estimator/table);
    # telemetry-drop starvation must leave every cell honest.
    import math as _math

    est = getattr(server, "throughput_estimator", None)
    table = getattr(server, "calibration", None)
    if est is not None or table is not None:
        report.checked["calibration_sanity"] = True
    if est is not None:
        esnap = est.snapshot()
        floor = esnap["sample_floor"]
        band = esnap["clamp_band"]
        for key, cell in esnap["cells"].items():
            ema = cell["ema"]
            if not (_math.isfinite(ema) and ema > 0):
                report._fail(
                    "calibration_sanity",
                    f"cell:{key}",
                    f"non-finite/non-positive ema {ema!r}",
                )
            want = "default" if cell["samples"] < floor else "learned"
            if cell["source"] != want:
                report._fail(
                    "calibration_sanity",
                    f"cell:{key}",
                    f"samples={cell['samples']} (floor {floor}) but "
                    f"source={cell['source']!r}, want {want!r}",
                )
            value, source = est.value(
                cell["device_class"], cell["profile"], declared=1.0
            )
            if source == "learned" and not (
                1.0 / band <= value <= band
            ):
                report._fail(
                    "calibration_sanity",
                    f"cell:{key}",
                    f"learned value {value} outside clamp band "
                    f"[{1.0 / band}, {band}] of unit anchor",
                )
        report.info["calibration_estimator"] = {
            k: esnap[k]
            for k in ("cell_count", "learned_cells", "samples", "dropped")
        }
    if table is not None:
        tsnap = table.snapshot()
        for name, entry in tsnap["constants"].items():
            if not _math.isfinite(entry["value"]):
                report._fail(
                    "calibration_sanity",
                    f"constant:{name}",
                    f"non-finite value {entry['value']!r}",
                )
            if entry["source"] not in ("default", "probe", "learned"):
                report._fail(
                    "calibration_sanity",
                    f"constant:{name}",
                    f"unknown provenance source {entry['source']!r}",
                )
        report.info["calibration_by_source"] = tsnap["by_source"]

    # -- gang_atomicity ----------------------------------------------------
    # Law 15: a gang is fully placed or fully absent. For every live gang
    # job, each member group runs exactly its desired count or every
    # member runs zero — a mixed state means a release path (scheduler/
    # generic.py _enforce_gang_atomicity, or the cp-gang kernel's
    # release_incomplete_gangs) let a fragment stripe through, including
    # under gang.commit_drop dropped/killed commits.
    gang_jobs = 0
    for job in snap.jobs():
        gang = getattr(job, "gang", None) or {}
        members = [m for m in (gang.get("groups") or ())]
        if not members or job.stopped():
            continue
        gang_jobs += 1
        report.checked["gang_atomicity"] = True
        desired = job.required_allocs()
        counts = {}
        for m in members:
            counts[m] = sum(
                1
                for a in snap.allocs_by_job(job.namespace, job.id)
                if a.task_group == m and not a.terminal_status()
            )
        full = all(counts[m] == desired.get(m, 0) for m in members)
        absent = all(counts[m] == 0 for m in members)
        if not (full or absent):
            report._fail(
                "gang_atomicity",
                f"{job.namespace}/{job.id}",
                "gang striped: member live counts "
                f"{sorted(counts.items())} vs desired "
                f"{sorted((m, desired.get(m, 0)) for m in members)} "
                "(want all-full or all-zero)",
            )
    report.info["gang_jobs"] = gang_jobs

    # -- migration_conservation --------------------------------------------
    # Law 16: every migrated alloc serves exactly once after quiesce.
    # The two-phase protocol (server/defrag.py) may hold both halves of
    # a move live BETWEEN phases, but quiesce includes the recovery
    # scan, so a surviving pair means phase B was lost AND never
    # recovered; two live replacements for one slot means one planned
    # move committed twice. The controller's own mid-move audits
    # (capacity with both halves counted) must never have fired.
    from ..server.defrag import DEFRAG_DESC

    counters_now = global_metrics.snapshot()["counters"]
    migrate_active = any(
        k.startswith("nomad.migrate.") for k in counters_now
    )
    reps_by_slot: dict[tuple, int] = {}
    for a in snap.allocs():
        if a.terminal_status() or a.desired_description != DEFRAG_DESC:
            continue
        migrate_active = True
        report.checked.setdefault("migration_conservation", True)
        slot = (a.namespace, a.job_id, a.task_group, a.name)
        reps_by_slot[slot] = reps_by_slot.get(slot, 0) + 1
        if reps_by_slot[slot] > 1:
            report._fail(
                "migration_conservation",
                "/".join(slot),
                f"{reps_by_slot[slot]} live defrag replacements for one "
                "group slot (a move double-committed)",
            )
        if a.previous_allocation:
            old = snap.alloc_by_id(a.previous_allocation)
            if old is not None and not old.terminal_status():
                report._fail(
                    "migration_conservation",
                    a.id,
                    f"half-move unresolved at quiesce: source alloc "
                    f"{old.id} still live beside its replacement",
                )
    if migrate_active:
        report.checked.setdefault("migration_conservation", True)
        cap_viol = counters_now.get("nomad.migrate.capacity_violations", 0)
        if cap_viol:
            report._fail(
                "migration_conservation",
                "capacity",
                f"mid-move capacity audit fired {cap_viol} times "
                "(free capacity went negative between phases)",
            )

    # context for the human-facing dump
    from ..resilience.breaker import snapshot_all

    report.info["breakers"] = snapshot_all()
    report.info["ring_errors"] = len(flight_recorder.errors())
    report.info["counters"] = {
        k: v
        for k, v in global_metrics.snapshot()["counters"].items()
        if k.startswith((
            "nomad.chaos.", "nomad.resilience.", "nomad.lane.",
            "nomad.overlay.", "nomad.plan.lane", "nomad.plan.cross_lane",
            "nomad.admission.", "nomad.cp.", "nomad.gang.",
            "nomad.migrate.", "nomad.drain.",
        ))
        or k == "nomad.broker.nack_redelivery_delayed"
        or k.endswith(".swallowed_errors")
    }
    return report
