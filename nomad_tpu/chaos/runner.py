"""Chaos runner — a seeded in-process cluster under a fault schedule.

``run_chaos(seed, steps)`` boots a single-server cluster (one pipelined
batching worker, the chaos clock threaded into broker + heartbeater,
short redelivery deadlines so recovery paths actually run), installs a
:class:`FaultPlane`, drives a seeded job workload (register / scale /
deregister), quiesces, and checks every cluster invariant.

Determinism contract: the *canonical* output — seed, fault schedule,
invariant verdicts — is a pure function of the arguments, so two runs
with the same seed emit byte-identical reports. Runtime detail that
depends on thread interleaving (which faults actually fired, queue
depths, retry counts) is reported separately as diagnostics.

On a violation, ``shrink_schedule`` greedily re-runs with ever-smaller
fault subsets until no single fault can be removed without the failure
disappearing — the minimal failing schedule to attach to a bug report.
"""

from __future__ import annotations

import json
import random
import time
from typing import Optional

from .invariants import InvariantReport, check_cluster, metrics_baseline
from .plane import FAULT_KINDS, FaultPlane, FaultSpec, install, uninstall

DEFAULT_NODES = 6
# recovery latencies scaled for a test run: redelivery must happen in
# milliseconds-to-seconds, not the production 60 s deadline
RUN_UNACK_TIMEOUT = 1.5
RUN_NACK_DELAY = 0.1
RUN_INITIAL_NACK_DELAY = 0.05


class ChaosRun:
    """Result of one chaos run: canonical report + diagnostics."""

    def __init__(
        self,
        seed: int,
        steps: int,
        faults: tuple[str, ...],
        schedule_rows: list[str],
        report: InvariantReport,
        workload: dict,
        triggered: list,
        duration_s: float,
        recorder_errors: list,
    ):
        self.seed = seed
        self.steps = steps
        self.faults = faults
        self.schedule_rows = schedule_rows
        self.report = report
        self.workload = workload
        self.triggered = triggered
        self.duration_s = duration_s
        self.recorder_errors = recorder_errors

    @property
    def ok(self) -> bool:
        return self.report.ok

    def canonical(self) -> dict:
        """The bit-reproducible part: pure function of (seed, steps,
        faults) plus the invariant verdicts. ``rejected`` is excluded
        from the workload — whether an injected raft drop lands on a
        workload RPC or on an applier commit depends on which call
        reaches the site Nth, i.e. on thread interleaving."""
        return {
            "seed": self.seed,
            "steps": self.steps,
            "faults": sorted(self.faults),
            "schedule": list(self.schedule_rows),
            "workload": {
                k: v for k, v in self.workload.items() if k != "rejected"
            },
            "invariants": self.report.to_dict(),
        }

    def canonical_json(self) -> str:
        return json.dumps(self.canonical(), sort_keys=True, indent=2)

    def render(self, verbose: bool = False) -> str:
        lines = [
            f"chaos run: seed={self.seed} steps={self.steps} "
            f"faults={'+'.join(sorted(self.faults))}",
            f"fault schedule ({len(self.schedule_rows)} planned):",
        ]
        lines += [f"  {row}" for row in self.schedule_rows]
        lines.append(
            "workload: "
            + " ".join(f"{k}={v}" for k, v in sorted(self.workload.items()))
        )
        lines.append("invariants:")
        lines.append(self.report.render())
        lines.append("PASS" if self.ok else "FAIL")
        if verbose or not self.ok:
            lines.append(
                f"-- diagnostics (timing-dependent; {self.duration_s:.2f}s) --"
            )
            lines.append(f"triggered ({len(self.triggered)}):")
            lines += [
                f"  {site}[{n}] {action}" for site, n, action in self.triggered
            ]
            for k, v in sorted(self.report.info.items()):
                lines.append(f"  {k}: {v}")
        if not self.ok and self.recorder_errors:
            lines.append("-- flight recorder error ring (newest first) --")
            for e in self.recorder_errors[:25]:
                lines.append(f"  [{e.get('component')}] {e.get('error')}")
        return "\n".join(lines)


def _build_node(i: int):
    from .. import mock

    return mock.node(id=f"chaos-node-{i:02d}", name=f"chaos-node-{i:02d}")


def _build_job(seq: int, count: int, priority: int):
    from .. import mock
    from ..structs import Resources, Task, TaskGroup

    j = mock.job(id=f"chaos-job-{seq:04d}", name=f"chaos-job-{seq:04d}")
    j.priority = priority

    def _tg(name: str) -> TaskGroup:
        return TaskGroup(
            name=name,
            count=count,
            tasks=[
                Task(
                    name=name,
                    driver="exec",
                    # sized so the seeded workload fills well under the
                    # fleet: deregister churn leaves holes AND headroom,
                    # which is what live migration needs to act on — a
                    # saturated fleet has no destination for any move
                    resources=Resources(cpu=128, memory_mb=64),
                )
            ],
        )

    if seq % 5 == 4:
        # every fifth job is a two-group gang: the atomic-commit seam
        # (law 15, scheduler/generic.py) only gets exercised if gangs
        # flow through the ordinary op stream — registers, scales, and
        # deregisters alike — under the same faults as everything else.
        # Keyed off seq (not an rng draw) so the workload's draw count
        # per step is unchanged and canonical reports stay comparable.
        j.task_groups = [_tg("a"), _tg("b")]
        j.gang = {
            "groups": ["a", "b"],
            "colocate": {"level": "rack", "weight": 1.0},
        }
    else:
        j.task_groups = [_tg("web")]
    return j


def _flip_pending(server) -> None:
    """The run's stand-in for a client plane: pending allocs come up
    ``running`` through the ordinary client-update path. Without it the
    fleet never serves — drainer health checks and the defrag candidate
    filter (server/defrag.py: only running allocs migrate) would see
    nothing to act on. Failures are a client's problem — it retries."""
    import copy

    updates = []
    for a in server.store.allocs():
        if a.desired_status == "run" and a.client_status == "pending":
            u = copy.copy(a)
            u.client_status = "running"
            updates.append(u)
    if updates:
        try:
            server.update_allocs_from_client(updates)
        except Exception:
            pass  # injected raft drop: a real client retries next poll


def _drive_workload(server, seed: int, steps: int) -> dict:
    """Seeded register/scale/deregister stream. The generator's state
    depends ONLY on its rng — a register the cluster rejected (injected
    raft drop) is still remembered as attempted, so the op sequence and
    draw count per step are identical across runs no matter which
    faults fired."""
    rng = random.Random(f"{seed}:workload")
    attempted: list[str] = []
    seq = 0
    counts = {
        "registers": 0,
        "gang_registers": 0,
        "scales": 0,
        "deregisters": 0,
        "rejected": 0,
    }

    def _submit(fn):
        try:
            fn()
            return True
        except Exception:
            # injected raft drop / plan-time fault surfaced on the
            # endpoint: a real client would retry; the workload moves on
            counts["rejected"] += 1
            return False

    for _step in range(steps):
        r = rng.random()
        if r < 0.55 or len(attempted) < 3:
            count = rng.randint(1, 3)
            priority = rng.choice((30, 50, 70))
            job_id = f"chaos-job-{seq:04d}"
            _submit(
                lambda: server.register_job(_build_job(seq, count, priority))
            )
            attempted.append(job_id)
            if seq % 5 == 4:
                counts["gang_registers"] += 1
            seq += 1
            counts["registers"] += 1
        elif r < 0.85:
            target = rng.choice(attempted)
            count = rng.randint(1, 4)
            target_seq = int(target.rsplit("-", 1)[1])
            _submit(
                lambda: server.register_job(_build_job(target_seq, count, 50))
            )
            counts["scales"] += 1
        else:
            target = rng.choice(attempted)
            _submit(
                lambda: server.deregister_job("default", target)
            )
            counts["deregisters"] += 1
        if _step % 16 == 15:
            # let the pipeline interleave with the op stream so faults
            # land mid-flight, not only against a drained cluster —
            # and bring placed allocs up so migration has live targets
            _flip_pending(server)
            time.sleep(0.01)
    _flip_pending(server)
    return counts


def _quiesce(server, timeout: float) -> bool:
    """Wait until the broker (ready/unacked/delayed/deferred), the plan
    queue, and the workers' commit threads are all drained. The failed
    queue and blocked evals are terminal parking, not work."""
    deadline = time.time() + timeout
    calm = 0
    while time.time() < deadline:
        d = server.eval_broker.queue_depths()
        busy = d["ready"] + d["unacked"] + d["delayed"] + d["deferred"]
        threads_busy = any(
            w._commit_thread is not None and w._commit_thread.is_alive()
            for w in server.workers
        )
        defrag_busy = not server.defrag.drained()
        if (
            busy == 0
            and server.plan_queue.depth() == 0
            and not threads_busy
            and not defrag_busy
        ):
            calm += 1
            if calm >= 3:  # stable across three polls, not a gap between ops
                return True
        else:
            calm = 0
        time.sleep(0.02)
    return False


def run_chaos(
    seed: int = 7,
    steps: int = 200,
    faults: tuple[str, ...] = FAULT_KINDS,
    nodes: int = DEFAULT_NODES,
    rate: float = 0.04,
    schedule: Optional[list[FaultSpec]] = None,
    quiesce_timeout: float = 60.0,
    num_batch_workers: int = 1,
    incremental: Optional[bool] = None,
    defrag_interval: float = 0.05,
) -> ChaosRun:
    """One full chaos cycle: boot, inject, quiesce, check, tear down.

    ``incremental`` pins the score-state cache (device/cache.py) on or
    off for the run; None inherits the ambient NOMAD_TPU_INCREMENTAL
    resolution. Chaos runs with it on exercise cache.score_refresh_drop
    and the score half of invariant law 12.

    ``defrag_interval`` enables continuous defragmentation for the run
    (server/defrag.py) so live migration churns concurrently with the
    workload and the ``migrate.*`` fault sites land on real two-phase
    moves; ``<= 0`` turns the controller's periodic scan off."""
    import os

    from ..obs.recorder import flight_recorder
    from ..server.server import Server, ServerConfig
    from ..utils import backend as _backend

    _incr_prev: Optional[str] = None
    if incremental is not None:
        _incr_prev = os.environ.get("NOMAD_TPU_INCREMENTAL")
        os.environ["NOMAD_TPU_INCREMENTAL"] = "on" if incremental else "off"
        _backend.reset_incremental()

    faults = tuple(faults)
    plane = FaultPlane(
        seed=seed, steps=steps, faults=faults, rate=rate, schedule=schedule
    )
    baseline = metrics_baseline()
    # breaker deadlines scaled like the broker deadlines above: injected
    # kernel hangs run 0.2-0.5 s, so a 0.1 s execute deadline trips on
    # the first hang (≤3-consecutive-failures acceptance bound) while
    # legitimate executes at this cluster size stay sub-millisecond;
    # compile still gets the full production allowance via the
    # trace-started probe
    from ..resilience import breaker as _breaker

    _breaker.reset_all()
    _prev_breaker = _breaker.configure(
        execute_deadline=0.1,
        backoff_base=0.05,
        backoff_cap=0.25,
    )
    t_start = time.perf_counter()
    server = Server(
        ServerConfig(
            # every worker batches: the chaos workload is service-only,
            # and system/_core evals ride the batch workers' singles
            # path, so solo workers would only add nondeterminism
            num_workers=num_batch_workers,
            num_batch_workers=num_batch_workers,
            # heartbeats come from no client here; a real TTL would mark
            # every node down mid-run (heartbeat expiry has its own
            # deterministic unit test — see tests/test_chaos.py)
            heartbeat_ttl=3600.0,
            clock=plane.clock,
            # continuous defrag runs hot so bounded live migration —
            # and the migrate.* fault sites — interleave with the
            # op stream (law 16, migration_conservation)
            defrag_interval=defrag_interval,
            defrag_budget=2,
        )
    )
    broker = server.eval_broker
    broker.unack_timeout = RUN_UNACK_TIMEOUT
    broker.nack_delay = RUN_NACK_DELAY
    broker.initial_nack_delay = RUN_INITIAL_NACK_DELAY
    report: InvariantReport
    try:
        server.establish_leadership()
        for i in range(nodes):
            server.register_node(_build_node(i))
        # faults start with the workload: setup above is the fixture
        install(plane)
        try:
            workload = _drive_workload(server, seed, steps)
            quiesced = _quiesce(server, quiesce_timeout)
        finally:
            uninstall()
        # one fault-free settling pass: anything the faults parked on
        # the delayed heap drains at normal speed now
        if not quiesced:
            quiesced = _quiesce(server, 10.0)
        # no new moves past this point; a kill_mid_move that landed on
        # the *last* defrag cycle left a committed half-move with no
        # next cycle to recover it — finish phase B synchronously so
        # law 16 judges a settled cluster, not a mid-flight one
        server.defrag.stop()
        server.defrag.recover()
        report = check_cluster(server, plane=plane, baseline=baseline)
        report.info["quiesced"] = quiesced
        report.info["batch_workers"] = num_batch_workers
        if not quiesced:
            report._fail(
                "eval_terminal",
                "quiesce",
                f"cluster failed to quiesce within {quiesce_timeout}s",
            )
    finally:
        try:
            server.shutdown()
        except Exception:
            from ..utils.metrics import count_swallowed

            count_swallowed("chaos", None)
        _breaker.configure(**_prev_breaker)
        _breaker.reset_all()
        if incremental is not None:
            if _incr_prev is None:
                os.environ.pop("NOMAD_TPU_INCREMENTAL", None)
            else:
                os.environ["NOMAD_TPU_INCREMENTAL"] = _incr_prev
            _backend.reset_incremental()
    return ChaosRun(
        seed=seed,
        steps=steps,
        faults=faults,
        schedule_rows=plane.schedule_rows(),
        report=report,
        workload=workload,
        triggered=list(plane.triggered),
        duration_s=time.perf_counter() - t_start,
        recorder_errors=flight_recorder.errors(),
    )


def shrink_schedule(
    seed: int,
    steps: int,
    faults: tuple[str, ...] = FAULT_KINDS,
    nodes: int = DEFAULT_NODES,
    rate: float = 0.04,
    schedule: Optional[list[FaultSpec]] = None,
    num_batch_workers: int = 1,
    log=None,
) -> tuple[list[FaultSpec], Optional[ChaosRun]]:
    """Greedy 1-minimal shrink of a failing schedule: drop one planned
    fault at a time, keep the drop whenever the run still violates an
    invariant. Returns (minimal schedule, last failing run) — or the
    original schedule and None if the failure did not reproduce."""
    if schedule is None:
        plane = FaultPlane(seed=seed, steps=steps, faults=faults, rate=rate)
        schedule = list(plane.schedule)
    base = run_chaos(
        seed=seed, steps=steps, faults=faults, nodes=nodes,
        schedule=schedule, num_batch_workers=num_batch_workers,
    )
    if base.ok:
        return schedule, None
    current = list(schedule)
    last_fail = base
    i = 0
    while i < len(current):
        trial = current[:i] + current[i + 1 :]
        if log:
            log(
                f"shrink: retry without {current[i].row()} "
                f"({len(trial)} faults)"
            )
        run = run_chaos(
            seed=seed, steps=steps, faults=faults, nodes=nodes,
            schedule=trial, num_batch_workers=num_batch_workers,
        )
        if not run.ok:
            current = trial  # still fails without it: drop for good
            last_fail = run
        else:
            i += 1  # load-bearing fault: keep it, try the next
    return current, last_fail
