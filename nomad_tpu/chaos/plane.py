"""FaultPlane — seeded, deterministic fault injection at named sites.

The production seams call :func:`chaos_site` with a site name; when no
plane is installed that is one module-global load and an ``is None``
branch (the ``NOMAD_TPU_RACECHECK`` zero-overhead-when-off contract).
When a plane is installed, each site keeps a monotone *effective-call*
counter, and the plane's precomputed schedule — a pure function of
``(seed, site)`` — decides whether the Nth effective call at that site
injects a fault:

``raise``
    raise :class:`ChaosFault` (an ``Exception``: ordinary recovery
    paths — nack/redeliver, singles fallback — must absorb it, and any
    swallow site that does must go through ``count_swallowed``).
``delay``
    sleep a small deterministic duration at the site (lock-holding
    sites stall their peers, exactly the hazard being rehearsed).
``duplicate``
    duplicate delivery (broker ack: the eval is re-enqueued once after
    the ack, the classic at-least-once duplicate).
``drop``
    site-specific loss: a dequeue that never reaches the worker (unack
    deadline must redeliver), a lost ack, a rejected raft apply, a
    skipped heartbeat-expiry sweep.
``kill``
    cooperative thread crash: raises :class:`ChaosThreadKill` (a
    ``BaseException`` so ``except Exception`` recovery code cannot
    hide it); the worker commit thread catches it only at its thread
    boundary and simply dies, leaving its evals unacked.
``skew``
    step the shared :class:`ChaosClock` offset; components that took
    the injectable clock (broker unack sweep, heartbeat TTLs) see time
    jump.
``hang``
    block the site for ``arg`` seconds — a wedged PJRT call or a stuck
    connection. Unlike ``delay`` (a stall the caller rides out), a hang
    is scheduled only at sites guarded by a deadline (the kernel
    watchdog), which must get the caller's thread back.

Schedules are deterministic per (seed, site, call-index), so a re-run
with the same seed plans — and, for a deterministic workload, fires —
the identical faults.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

ENV_VAR = "NOMAD_TPU_CHAOS"

#: site name → fault kinds that stay inside the system's recovery
#: contract at that seam. Kinds outside the tuple are never scheduled
#: there (e.g. silently dropping a plan commit the caller was told
#: succeeded is a loss *injected below the contract*, not a test).
SITES: dict[str, tuple[str, ...]] = {
    "broker.dequeue": ("delay", "drop", "skew"),
    "broker.ack": ("raise", "delay", "drop", "duplicate", "skew"),
    "plan_queue.enqueue": ("raise", "delay"),
    "plan_queue.enqueue_merged": ("raise", "delay", "kill"),
    "plan_apply.verify": ("raise", "delay"),
    "plan_apply.commit": ("raise", "delay"),
    "fsm.apply": ("delay", "drop"),
    "worker.commit": ("kill", "delay"),
    "heartbeat.expiry": ("drop", "delay", "skew"),
    "store.snapshot": ("raise", "delay"),
    "kernel.execute": ("raise", "delay"),
    "kernel.hang": ("hang",),
    "rpc.conn_drop": ("drop",),
    # cross-lane handoff protocol (server/lanes.py): a dropped confirm
    # must release the reservation (no leaked claims), a kill mid-
    # handoff must still settle/release via the worker's finally
    "lane.handoff_drop": ("drop", "kill"),
    "lane.handoff_delay": ("delay",),
    # admission controller (server/admission.py): force the overload
    # level to SHED for a bounded window mid-run — shed accounting
    # (invariant law 10) and NORMAL recovery must survive the flapping
    "admission.flap": ("force",),
    # mesh sharding (device/cache.py): drop a per-shard incremental
    # capacity upload — recovery must be a whole-tensor re-upload on
    # the same access, never a stale device shard (invariant law 12)
    "mesh.shard_refresh_drop": ("drop",),
    # CP dispatcher (scheduler/cp.py): perturb the solver's initial
    # prices for one joint pass — the assignment may legitimately shift,
    # but conservation (invariant law 13) must hold: every group ends
    # exactly one of placed/deferred/failed and capacity is never
    # exceeded post-round
    "cp.round_perturb": ("perturb",),
    # incremental score state (device/cache.py): drop one per-shard
    # score patch — recovery must be a full score rebuild on the same
    # access, never a stale device row; the staged/committed mirrors
    # stay bitwise-exact either way (invariant law 12, score half)
    "cache.score_refresh_drop": ("drop",),
    # calibration plane (obs/calibrate.py): drop estimator input samples
    # before they reach their cell — starved cells must keep reporting
    # source: default and answer the declared anchor, never a garbage
    # estimate (invariant law 14)
    "calib.telemetry_drop": ("drop",),
    # gang atomic commit (scheduler/generic.py): drop a healthy gang's
    # commit — every member must release and the whole gang ride one
    # blocked eval, never a striped partial plan; a kill mid-commit
    # leaves the plan unsubmitted (trivially atomic). Invariant law 15:
    # after quiesce a gang job is fully placed or fully absent.
    "gang.commit_drop": ("drop", "kill"),
    # defrag two-phase moves (server/defrag.py): a dropped move commits
    # nothing (conservation trivial); a kill or drop BETWEEN phase A
    # (replacement placed) and phase B (old stopped) leaves a committed
    # half-move that the recovery scan must finish, never double.
    # Invariant law 16: after quiesce every migrating alloc serves
    # exactly once, and capacity was conserved at every mid-move point.
    "migrate.move_drop": ("drop",),
    "migrate.kill_mid_move": ("kill", "drop"),
}

FAULT_KINDS = (
    "raise", "delay", "duplicate", "drop", "kill", "skew", "hang", "force",
    "perturb",
)

# Expected effective-call budget per site for a `steps`-op workload,
# as a fraction of steps (with a floor). Fault indices are sampled
# inside this horizon so a quiesced run has consumed them all.
_HORIZON = {
    "broker.dequeue": (1.0, 8),
    "broker.ack": (1.0, 8),
    "plan_queue.enqueue": (0.125, 2),
    "plan_queue.enqueue_merged": (0.125, 2),
    "plan_apply.verify": (0.125, 2),
    "plan_apply.commit": (0.125, 2),
    "fsm.apply": (1.0, 8),
    "worker.commit": (0.25, 2),
    "heartbeat.expiry": (0.0, 2),
    "store.snapshot": (0.25, 4),
    "kernel.execute": (0.125, 2),
    "kernel.hang": (0.125, 2),
    "rpc.conn_drop": (0.25, 2),
    "lane.handoff_drop": (0.25, 2),
    "lane.handoff_delay": (0.25, 2),
    # hit once per controller re-eval tick, not per workload op
    "admission.flap": (0.5, 4),
    # hit per cache device-view access with dirty regions pending
    "mesh.shard_refresh_drop": (0.125, 2),
    # hit once per joint CP placement pass, not per workload op
    "cp.round_perturb": (0.125, 2),
    # hit per score-view access with dirty rows pending (incremental on)
    "cache.score_refresh_drop": (0.125, 2),
    # hit once per gang-job scheduling pass, not per workload op
    "gang.commit_drop": (0.125, 2),
    # hit once per estimator input sample (span fan-out rate)
    "calib.telemetry_drop": (1.0, 8),
    # hit once per planned defrag move, a few moves per cycle
    "migrate.move_drop": (0.125, 2),
    "migrate.kill_mid_move": (0.125, 2),
}


class ChaosFault(RuntimeError):
    """Injected failure. An ``Exception`` on purpose: the same recovery
    paths that absorb infrastructure errors must absorb it, and
    ``count_swallowed`` tags it (``nomad.chaos.swallowed_faults``) so a
    swallow site can never absorb one silently."""

    nta_chaos_fault = True

    def __init__(self, site: str, index: int):
        super().__init__(f"chaos: injected fault at {site}[{index}]")
        self.site = site
        self.index = index
        self.accounted = False


class ChaosThreadKill(BaseException):
    """Cooperative thread crash. Derives from ``BaseException`` so the
    ``except Exception`` recovery handlers between the site and the
    thread boundary cannot absorb it — the thread dies with its work
    half done (``finally`` blocks still run; Python cannot skip them)."""

    nta_chaos_fault = True

    def __init__(self, site: str, index: int):
        super().__init__(f"chaos: thread kill at {site}[{index}]")
        self.site = site
        self.index = index


class ChaosClock:
    """Skewable clock: real time plus a plane-controlled offset. Both
    faces move together, so broker deadlines (``time``-like) and
    heartbeat TTLs (``monotonic``-like) observe the same jumps."""

    def __init__(self):
        self._offset = 0.0
        self._lock = threading.Lock()

    def time(self) -> float:
        return time.time() + self._offset

    def monotonic(self) -> float:
        return time.monotonic() + self._offset

    def skew(self, delta: float) -> float:
        with self._lock:
            self._offset += delta
            return self._offset

    @property
    def offset(self) -> float:
        return self._offset


class FaultSpec:
    """One planned injection: the Nth effective call at ``site`` runs
    ``action`` (arg = sleep seconds for delay, offset delta for skew)."""

    __slots__ = ("site", "index", "action", "arg")

    def __init__(self, site: str, index: int, action: str, arg: float = 0.0):
        if site not in SITES:
            raise ValueError(f"unknown chaos site {site!r}")
        if action not in SITES[site]:
            raise ValueError(f"action {action!r} not allowed at {site}")
        self.site = site
        self.index = index
        self.action = action
        self.arg = arg

    def row(self) -> str:
        return f"{self.site}[{self.index}] {self.action} {self.arg:.6f}"

    def __repr__(self):
        return f"FaultSpec({self.row()})"


def build_schedule(
    seed: int,
    steps: int,
    faults: tuple[str, ...] = FAULT_KINDS,
    sites: Optional[tuple[str, ...]] = None,
    rate: float = 0.04,
) -> list[FaultSpec]:
    """Deterministic schedule: a pure function of the arguments. Each
    site gets its own ``random.Random(f"{seed}:{site}")`` stream, so
    adding or removing one site never perturbs another's plan."""
    specs: list[FaultSpec] = []
    for site in sorted(sites if sites is not None else SITES):
        allowed = tuple(a for a in SITES[site] if a in faults)
        if not allowed:
            continue
        frac, floor = _HORIZON[site]
        horizon = max(floor, int(steps * frac))
        k = min(horizon, max(1, int(horizon * rate)))
        rng = random.Random(f"{seed}:{site}")
        for index in sorted(rng.sample(range(horizon), k)):
            action = rng.choice(allowed)
            arg = 0.0
            if action == "delay":
                arg = rng.uniform(0.001, 0.025)
            elif action == "hang":
                # long enough that any sane kernel deadline fires, short
                # enough that an abandoned watchdog thread drains fast
                arg = rng.uniform(0.2, 0.5)
            elif action == "skew":
                arg = rng.choice((-1.0, 1.0)) * rng.uniform(0.25, 1.5)
            specs.append(FaultSpec(site, index, action, arg))
    return specs


class FaultPlane:
    def __init__(
        self,
        seed: int = 0,
        steps: int = 200,
        faults: tuple[str, ...] = FAULT_KINDS,
        sites: Optional[tuple[str, ...]] = None,
        rate: float = 0.04,
        schedule: Optional[list[FaultSpec]] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.seed = seed
        self.steps = steps
        self.faults = tuple(faults)
        self.clock = ChaosClock()
        self._sleep = sleep
        if schedule is None:
            schedule = build_schedule(seed, steps, self.faults, sites, rate)
        self.schedule = schedule
        self._by_site: dict[str, dict[int, FaultSpec]] = {}
        for spec in schedule:
            self._by_site.setdefault(spec.site, {})[spec.index] = spec
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        # runtime log: (site, effective index, action) actually fired
        self.triggered: list[tuple[str, int, str]] = []
        # every ChaosFault object this plane raised (swallow accounting)
        self.raised: list[ChaosFault] = []
        self.kills = 0
        # plan-commit ledger: alloc id → times committed. The plan
        # applier reports every committed placement through
        # note_committed(); the invariant checker demands each id lands
        # exactly once (no loss after a reported commit, no
        # double-commit of a merged-plan member).
        self.committed: dict[str, int] = {}

    # -- the hot path ------------------------------------------------------
    def hit(self, site: str) -> Optional[str]:
        """Consult the schedule for one effective call at ``site``.
        Returns the action name for caller-interpreted kinds
        ("drop"/"duplicate"), performs delay/skew inline, raises for
        raise/kill, and returns None when nothing is scheduled."""
        with self._lock:
            n = self._counts.get(site, 0)
            self._counts[site] = n + 1
            per_site = self._by_site.get(site)
            spec = per_site.get(n) if per_site else None
            if spec is None:
                return None
            self.triggered.append((site, n, spec.action))
        action = spec.action
        if action == "delay":
            self._sleep(spec.arg)
            return "delay"
        if action == "hang":
            self._sleep(spec.arg)
            return "hang"
        if action == "skew":
            self.clock.skew(spec.arg)
            return "skew"
        if action == "raise":
            fault = ChaosFault(site, n)
            with self._lock:
                self.raised.append(fault)
            raise fault
        if action == "kill":
            with self._lock:
                self.kills += 1
            raise ChaosThreadKill(site, n)
        # "drop" / "duplicate" / "force" / "perturb": the site decides
        # what it means
        return action

    def ledger_commit(self, alloc_ids) -> None:
        with self._lock:
            for aid in alloc_ids:
                self.committed[aid] = self.committed.get(aid, 0) + 1

    # -- reporting ---------------------------------------------------------
    def schedule_rows(self) -> list[str]:
        """Canonical planned schedule — deterministic for a seed."""
        return [s.row() for s in self.schedule]

    def site_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    # -- construction ------------------------------------------------------
    @classmethod
    def from_env(cls, spec: str) -> "FaultPlane":
        """Parse ``seed=7,steps=200,rate=0.05,faults=raise+delay``."""
        kw: dict = {}
        for part in spec.split(","):
            part = part.strip()
            if not part or part in ("1", "on", "true"):
                continue
            key, _, val = part.partition("=")
            key = key.strip()
            if key == "seed":
                kw["seed"] = int(val)
            elif key == "steps":
                kw["steps"] = int(val)
            elif key == "rate":
                kw["rate"] = float(val)
            elif key == "faults":
                kw["faults"] = tuple(v for v in val.split("+") if v)
            elif key == "sites":
                kw["sites"] = tuple(v for v in val.split("+") if v)
            else:
                raise ValueError(f"unknown {ENV_VAR} key {key!r}")
        return cls(**kw)


# -- global install point (the zero-overhead-when-off seam) ----------------
_ACTIVE: Optional[FaultPlane] = None


def active_plane() -> Optional[FaultPlane]:
    return _ACTIVE


def install(plane: FaultPlane) -> FaultPlane:
    global _ACTIVE
    if _ACTIVE is not None and _ACTIVE is not plane:
        raise RuntimeError("a FaultPlane is already installed")
    _ACTIVE = plane
    return plane


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def chaos_site(site: str) -> Optional[str]:
    """The hook compiled into production seams. One global load and an
    ``is None`` branch when chaos is off."""
    p = _ACTIVE
    if p is None:
        return None
    return p.hit(site)


def make_fault(site: str) -> ChaosFault:
    """For sites where a caller-interpreted action ("drop") surfaces as
    an error: builds the fault AND registers it with the active plane so
    swallow accounting still sees it."""
    fault = ChaosFault(site, -1)
    p = _ACTIVE
    if p is not None:
        with p._lock:
            p.raised.append(fault)
    return fault


def note_committed(alloc_ids) -> None:
    """Plan applier → ledger: these placements were committed."""
    p = _ACTIVE
    if p is None:
        return
    p.ledger_commit(alloc_ids)


def _maybe_autoinstall() -> None:
    import os

    spec = os.environ.get(ENV_VAR, "")
    if spec not in ("", "0"):
        install(FaultPlane.from_env(spec))


_maybe_autoinstall()
