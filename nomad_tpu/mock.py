"""Canonical fake objects for tests and benchmarks.

Reference: nomad/mock/mock.go (mock.Node, mock.Job, mock.Alloc,
mock.SystemJob, mock.Eval — 1,909 LoC of fixture factories that every
reference test builds on). Shapes are chosen to match the reference
fixtures' resource footprints so parity tests are comparable.
"""

from __future__ import annotations

import itertools
import uuid

from .structs import (
    Allocation,
    ComparableResources,
    Evaluation,
    Job,
    JOB_TYPE_BATCH,
    JOB_TYPE_SERVICE,
    JOB_TYPE_SYSTEM,
    NODE_STATUS_READY,
    Node,
    NodeResources,
    NodeReservedResources,
    Resources,
    Task,
    TaskGroup,
)

_counter = itertools.count()


def short_id(prefix: str) -> str:
    return f"{prefix}-{next(_counter):06d}-{uuid.uuid4().hex[:8]}"


def node(**overrides) -> Node:
    """mock.Node (mock.go:23-90): 4 GHz CPU, 8 GiB RAM, linux, dc1."""
    n = Node(
        id=str(uuid.uuid4()),
        name=short_id("node"),
        datacenter="dc1",
        node_class="",
        attributes={
            "kernel.name": "linux",
            "arch": "x86",
            "cpu.frequency": "2000",
            "cpu.numcores": "2",
            "driver.exec": "1",
            "driver.mock_driver": "1",
            "nomad.version": "1.2.3",
        },
        drivers={"exec": True, "mock_driver": True},
        node_resources=NodeResources(cpu=4000, memory_mb=8192, disk_mb=100 * 1024),
        reserved=NodeReservedResources(cpu=100, memory_mb=256, disk_mb=4 * 1024),
        status=NODE_STATUS_READY,
    )
    for k, v in overrides.items():
        setattr(n, k, v)
    n.compute_class()
    return n


def job(**overrides) -> Job:
    """mock.Job (mock.go:500-600): 1 service group × 10 allocs of
    web tasks at 500 MHz / 256 MiB."""
    j = Job(
        id=short_id("job"),
        name="my-job",
        type=JOB_TYPE_SERVICE,
        priority=50,
        datacenters=["dc1"],
        task_groups=[
            TaskGroup(
                name="web",
                count=10,
                tasks=[
                    Task(
                        name="web",
                        driver="exec",
                        resources=Resources(cpu=500, memory_mb=256),
                    )
                ],
            )
        ],
        status="pending",
        version=0,
    )
    for k, v in overrides.items():
        setattr(j, k, v)
    return j


def batch_job(**overrides) -> Job:
    j = job(type=JOB_TYPE_BATCH, name="batch-job", **overrides)
    j.task_groups[0].name = "worker"
    j.task_groups[0].tasks[0].name = "worker"
    return j


def system_job(**overrides) -> Job:
    """mock.SystemJob: runs on every feasible node."""
    j = Job(
        id=short_id("sysjob"),
        name="my-sysjob",
        type=JOB_TYPE_SYSTEM,
        priority=100,
        datacenters=["dc1"],
        task_groups=[
            TaskGroup(
                name="sys",
                count=1,
                tasks=[
                    Task(
                        name="sys",
                        driver="exec",
                        resources=Resources(cpu=100, memory_mb=64),
                    )
                ],
            )
        ],
    )
    for k, v in overrides.items():
        setattr(j, k, v)
    return j


def eval_for(j: Job, **overrides) -> Evaluation:
    e = Evaluation(
        namespace=j.namespace,
        priority=j.priority,
        type=j.type,
        job_id=j.id,
        triggered_by="job-register",
    )
    for k, v in overrides.items():
        setattr(e, k, v)
    return e


def alloc(j: Job | None = None, n: Node | None = None, **overrides) -> Allocation:
    """mock.Alloc: a placed instance of job's first group."""
    j = j or job()
    tg = j.task_groups[0]
    ask = tg.combined_resources()
    a = Allocation(
        id=str(uuid.uuid4()),
        namespace=j.namespace,
        name=f"{j.id}.{tg.name}[0]",
        job_id=j.id,
        job=j,
        job_version=j.version,
        task_group=tg.name,
        node_id=n.id if n else str(uuid.uuid4()),
        resources=ComparableResources(
            cpu=ask.cpu,
            memory_mb=ask.memory_mb,
            disk_mb=ask.disk_mb,
            bandwidth_mbits=ask.bandwidth_mbits(),
        ),
        desired_status="run",
        client_status="running",
    )
    for k, v in overrides.items():
        setattr(a, k, v)
    return a
