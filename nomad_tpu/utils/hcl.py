"""Minimal HCL2 reader — tokenizer, block/attribute parser, expressions.

The reference consumes HCL in two places: ACL policy rules
(acl/policy.go:237 ``Parse`` via hashicorp/hcl) and job specifications
(jobspec2/parse.go:19 via hcl/v2 + hclsimple). This module is a compact,
dependency-free reader covering the HCL2 subset those two grammars use:

- blocks with 0..n string labels: ``job "web" { ... }``
- attributes: ``count = 3``
- expressions: strings (with ``${...}`` interpolation), numbers, bools,
  null, heredocs, lists, objects, unary/binary operators, ternaries,
  variable traversals (``var.region``, ``a[0].b``), function calls
- comments: ``#``, ``//``, ``/* ... */``

Parsing yields an AST (`Body` of `Attr`/`Block`); evaluation happens
against an `EvalContext` of variables + functions, so jobspec2-style
two-phase use (collect ``variable`` blocks, then evaluate the rest) works.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Optional


class HCLError(Exception):
    """Parse or evaluation failure, annotated with line/col."""

    def __init__(self, msg: str, line: int = 0, col: int = 0):
        super().__init__(f"{msg} (line {line}, col {col})" if line else msg)
        self.line = line
        self.col = col


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>[ \t\r]+)
  | (?P<comment>\#[^\n]*|//[^\n]*|/\*.*?\*/)
  | (?P<newline>\n)
  | (?P<heredoc><<-?(?P<hd_tag>[A-Za-z_][A-Za-z0-9_]*)\n)
  | (?P<number>-?\d+\.\d+([eE][+-]?\d+)?|-?\d+([eE][+-]?\d+)?)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_-]*)
  | (?P<string>")
  | (?P<op><=|>=|==|!=|&&|\|\||\.\.\.|[-+*/%<>!?:=.,(){}\[\]])
    """,
    re.VERBOSE | re.DOTALL,
)


@dataclass
class Token:
    kind: str  # number|ident|string|op|newline|heredoc|eof
    value: Any
    line: int
    col: int


def _scan_quoted(src: str, pos: int, line: int) -> tuple[list, int]:
    """Scan a double-quoted string starting after the opening quote.
    Returns (parts, new_pos) where parts alternate literal str and
    ('interp', expr_src) tuples for ${...} segments."""
    parts: list = []
    lit: list[str] = []
    i = pos
    n = len(src)
    while i < n:
        c = src[i]
        if c == '"':
            if lit:
                parts.append("".join(lit))
            return parts, i + 1
        if c == "\\":
            if i + 1 >= n:
                raise HCLError("unterminated escape", line)
            esc = src[i + 1]
            lit.append(
                {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\"}.get(esc, esc)
            )
            i += 2
            continue
        if c == "$" and i + 1 < n and src[i + 1] == "$":
            # HCL2 '$${' escape: literal '${' deferred to runtime
            if i + 2 < n and src[i + 2] == "{":
                lit.append("${")
                i += 3
                depth = 1
                while i < n and depth:
                    if src[i] == "{":
                        depth += 1
                    elif src[i] == "}":
                        depth -= 1
                    lit.append(src[i])
                    i += 1
                continue
            lit.append("$")
            i += 1
            continue
        if c == "$" and i + 1 < n and src[i + 1] == "{":
            if lit:
                parts.append("".join(lit))
                lit = []
            depth = 1
            j = i + 2
            while j < n and depth:
                if src[j] == "{":
                    depth += 1
                elif src[j] == "}":
                    depth -= 1
                j += 1
            if depth:
                raise HCLError("unterminated ${ interpolation", line)
            parts.append(("interp", src[i + 2 : j - 1]))
            i = j
            continue
        if c == "\n":
            raise HCLError("newline in string literal", line)
        lit.append(c)
        i += 1
    raise HCLError("unterminated string", line)


def tokenize(src: str) -> list[Token]:
    tokens: list[Token] = []
    pos = 0
    line = 1
    line_start = 0
    n = len(src)
    while pos < n:
        m = _TOKEN_RE.match(src, pos)
        if not m:
            raise HCLError(f"unexpected character {src[pos]!r}", line, pos - line_start)
        col = pos - line_start + 1
        if m.lastgroup == "ws":
            pass
        elif m.lastgroup == "comment":
            line += m.group().count("\n")
        elif m.lastgroup == "newline":
            tokens.append(Token("newline", "\n", line, col))
            line += 1
            line_start = m.end()
        elif m.lastgroup == "heredoc":
            tag = m.group("hd_tag")
            indent_mode = m.group().startswith("<<-")
            line += 1
            end_re = re.compile(
                r"^[ \t]*" + re.escape(tag) + r"[ \t]*$", re.MULTILINE
            )
            em = end_re.search(src, m.end())
            if not em:
                raise HCLError(f"unterminated heredoc <<{tag}", line)
            body = src[m.end() : em.start()]
            if indent_mode:
                lines = body.split("\n")
                pad = min(
                    (len(l) - len(l.lstrip()) for l in lines if l.strip()),
                    default=0,
                )
                body = "\n".join(l[pad:] if len(l) >= pad else l for l in lines)
            if body.endswith("\n"):
                body = body[:-1]
            tokens.append(Token("string", [body], line, col))
            line += src[m.end() : em.end()].count("\n")
            pos = em.end()
            line_start = pos
            continue
        elif m.lastgroup == "number":
            text = m.group()
            val = float(text) if ("." in text or "e" in text or "E" in text) else int(text)
            tokens.append(Token("number", val, line, col))
        elif m.lastgroup == "ident":
            tokens.append(Token("ident", m.group(), line, col))
        elif m.lastgroup == "string":
            parts, newpos = _scan_quoted(src, m.end(), line)
            tokens.append(Token("string", parts, line, col))
            pos = newpos
            continue
        else:  # op
            tokens.append(Token("op", m.group(), line, col))
        pos = m.end()
    tokens.append(Token("eof", None, line, pos - line_start + 1))
    return tokens


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass
class Attr:
    name: str
    expr: "Expr"
    line: int


@dataclass
class Block:
    type: str
    labels: list[str]
    body: "Body"
    line: int = 0


@dataclass
class Body:
    attrs: dict[str, Attr] = field(default_factory=dict)
    blocks: list[Block] = field(default_factory=list)

    def blocks_of(self, btype: str) -> list[Block]:
        return [b for b in self.blocks if b.type == btype]

    def first(self, btype: str) -> Optional[Block]:
        for b in self.blocks:
            if b.type == btype:
                return b
        return None


# Expressions are closures: Expr(ctx) -> value
Expr = Callable[["EvalContext"], Any]


class EvalContext:
    """Variable + function scope for expression evaluation."""

    def __init__(
        self,
        variables: Optional[dict[str, Any]] = None,
        functions: Optional[dict[str, Callable]] = None,
    ):
        self.variables = variables or {}
        self.functions = dict(_STD_FUNCTIONS)
        if functions:
            self.functions.update(functions)

    def child(self, extra: dict[str, Any]) -> "EvalContext":
        ctx = EvalContext(dict(self.variables), self.functions)
        ctx.variables.update(extra)
        return ctx


def _std_format(fmt: str, *args: Any) -> str:
    # HCL %v ≈ python str; map the common verbs
    out = []
    i = 0
    ai = 0
    while i < len(fmt):
        c = fmt[i]
        if c == "%" and i + 1 < len(fmt):
            v = fmt[i + 1]
            if v == "%":
                out.append("%")
            elif v in "vsdfq":
                arg = args[ai]
                ai += 1
                if v == "q":
                    out.append('"%s"' % arg)
                elif v == "d":
                    out.append(str(int(arg)))
                elif v == "f":
                    out.append(str(float(arg)))
                else:
                    out.append(_to_string(arg))
            else:
                out.append(c + v)
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _to_string(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return ""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


_STD_FUNCTIONS: dict[str, Callable] = {
    # the jobspec2 function table subset (jobspec2/functions.go)
    "upper": lambda s: s.upper(),
    "lower": lambda s: s.lower(),
    "join": lambda sep, xs: sep.join(_to_string(x) for x in xs),
    "split": lambda sep, s: s.split(sep),
    "length": lambda x: len(x),
    "min": lambda *xs: min(xs),
    "max": lambda *xs: max(xs),
    "abs": lambda x: abs(x),
    "ceil": lambda x: -(-int(x) // 1) if x == int(x) else int(x) + (x > 0),
    "floor": lambda x: int(x) if x >= 0 or x == int(x) else int(x) - 1,
    "contains": lambda xs, v: v in xs,
    "coalesce": lambda *xs: next((x for x in xs if x not in (None, "")), None),
    "concat": lambda *xs: [v for x in xs for v in x],
    "keys": lambda m: sorted(m.keys()),
    "values": lambda m: [m[k] for k in sorted(m.keys())],
    "lookup": lambda m, k, default=None: m.get(k, default),
    "merge": lambda *ms: {k: v for m in ms for k, v in m.items()},
    "range": lambda *a: list(range(*[int(x) for x in a])),
    "format": _std_format,
    "trimspace": lambda s: s.strip(),
    "replace": lambda s, a, b: s.replace(a, b),
    "substr": lambda s, off, ln: s[off : off + ln] if ln >= 0 else s[off:],
    "tostring": _to_string,
    "tonumber": lambda v: float(v) if "." in str(v) else int(v),
    "toset": lambda xs: sorted(set(xs)),
    "flatten": lambda xs: [v for x in xs for v in (x if isinstance(x, list) else [x])],
    "distinct": lambda xs: list(dict.fromkeys(xs)),
    "reverse": lambda xs: list(reversed(xs)),
    "sort": lambda xs: sorted(xs),
    "element": lambda xs, i: xs[int(i) % len(xs)],
    "chunklist": lambda xs, size: [
        xs[i : i + int(size)] for i in range(0, len(xs), int(size))
    ],
    "regex": lambda pat, s: (re.search(pat, s) or [""])[0],
}
# try()/can() are NOT in this table: they must see their arguments
# UNevaluated to catch evaluation errors (cty semantics) — special-cased
# in _call.


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------


class _Parser:
    def __init__(self, tokens: list[Token]):
        self.toks = tokens
        self.i = 0

    # -- token helpers ----------------------------------------------------
    def peek(self, skip_nl: bool = False) -> Token:
        j = self.i
        if skip_nl:
            while self.toks[j].kind == "newline":
                j += 1
        return self.toks[j]

    def next(self, skip_nl: bool = False) -> Token:
        if skip_nl:
            while self.toks[self.i].kind == "newline":
                self.i += 1
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def expect_op(self, op: str, skip_nl: bool = False) -> Token:
        t = self.next(skip_nl=skip_nl)
        if t.kind != "op" or t.value != op:
            raise HCLError(f"expected {op!r}, got {t.value!r}", t.line, t.col)
        return t

    # -- body -------------------------------------------------------------
    def parse_body(self, until: Optional[str] = "}") -> Body:
        body = Body()
        while True:
            t = self.peek(skip_nl=True)
            if t.kind == "eof":
                if until is None:
                    return body
                raise HCLError("unexpected EOF, unclosed block", t.line, t.col)
            if until and t.kind == "op" and t.value == until:
                self.next(skip_nl=True)
                return body
            self.parse_item(body)

    def parse_item(self, body: Body) -> None:
        t = self.next(skip_nl=True)
        if t.kind != "ident" and not (t.kind == "string" and len(t.value) == 1):
            raise HCLError(
                f"expected identifier, got {t.value!r}", t.line, t.col
            )
        name = t.value if t.kind == "ident" else t.value[0]
        nxt = self.peek()
        if nxt.kind == "op" and nxt.value == "=":
            self.next()
            expr = self.parse_expr()
            body.attrs[name] = Attr(name, expr, t.line)
            return
        # block: labels* {
        labels: list[str] = []
        while True:
            nxt = self.peek()
            if nxt.kind == "string":
                parts = nxt.value
                if len(parts) != 1 or not isinstance(parts[0], str):
                    raise HCLError(
                        "block label must be a plain string", nxt.line, nxt.col
                    )
                labels.append(parts[0])
                self.next()
            elif nxt.kind == "ident":
                labels.append(nxt.value)
                self.next()
            elif nxt.kind == "op" and nxt.value == "{":
                self.next()
                inner = self.parse_body("}")
                body.blocks.append(Block(name, labels, inner, t.line))
                return
            else:
                raise HCLError(
                    f"expected block label or '{{', got {nxt.value!r}",
                    nxt.line,
                    nxt.col,
                )

    # -- expressions (precedence climbing) --------------------------------
    def parse_expr(self) -> Expr:
        return self.parse_ternary()

    def parse_ternary(self) -> Expr:
        cond = self.parse_binary(0)
        t = self.peek()
        if t.kind == "op" and t.value == "?":
            self.next()
            a = self.parse_ternary()
            self.expect_op(":", skip_nl=True)
            b = self.parse_ternary()
            return lambda ctx: a(ctx) if cond(ctx) else b(ctx)
        return cond

    _BINOPS: list[dict[str, Callable[[Any, Any], Any]]] = [
        {"||": lambda a, b: a or b},
        {"&&": lambda a, b: a and b},
        {"==": lambda a, b: a == b, "!=": lambda a, b: a != b},
        {
            "<": lambda a, b: a < b,
            ">": lambda a, b: a > b,
            "<=": lambda a, b: a <= b,
            ">=": lambda a, b: a >= b,
        },
        {"+": lambda a, b: a + b, "-": lambda a, b: a - b},
        {
            "*": lambda a, b: a * b,
            "/": lambda a, b: a / b,
            "%": lambda a, b: a % b,
        },
    ]

    def parse_binary(self, level: int) -> Expr:
        if level >= len(self._BINOPS):
            return self.parse_unary()
        lhs = self.parse_binary(level + 1)
        ops = self._BINOPS[level]
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ops:
                self.next()
                rhs = self.parse_binary(level + 1)
                fn = ops[t.value]
                prev = lhs
                lhs = (lambda p, r, f: lambda ctx: f(p(ctx), r(ctx)))(prev, rhs, fn)
            else:
                return lhs

    def parse_unary(self) -> Expr:
        t = self.peek()
        if t.kind == "op" and t.value in ("-", "!"):
            self.next()
            inner = self.parse_unary()
            if t.value == "-":
                return lambda ctx: -inner(ctx)
            return lambda ctx: not inner(ctx)
        return self.parse_postfix()

    def parse_postfix(self) -> Expr:
        expr = self.parse_primary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value == ".":
                # traversal: .ident or .number (tuple index)
                self.next()
                nt = self.next()
                if nt.kind == "ident":
                    key = nt.value
                    prev = expr
                    expr = (lambda p, k: lambda ctx: _traverse(p(ctx), k, nt))(
                        prev, key
                    )
                elif nt.kind == "number":
                    prev = expr
                    expr = (lambda p, k: lambda ctx: p(ctx)[int(k)])(prev, nt.value)
                else:
                    raise HCLError("expected attribute name", nt.line, nt.col)
            elif t.kind == "op" and t.value == "[":
                self.next()
                idx = self.parse_expr()
                self.expect_op("]", skip_nl=True)
                prev = expr
                expr = (lambda p, ix: lambda ctx: _index(p(ctx), ix(ctx)))(prev, idx)
            else:
                return expr

    def parse_primary(self) -> Expr:
        t = self.next(skip_nl=True)
        if t.kind == "number":
            v = t.value
            return lambda ctx: v
        if t.kind == "string":
            parts = t.value
            compiled = [
                p if isinstance(p, str) else parse_expression(p[1])
                for p in parts
            ]
            if not compiled:
                return lambda ctx: ""
            if len(compiled) == 1 and isinstance(compiled[0], str):
                s = compiled[0]
                return lambda ctx: s
            return lambda ctx: "".join(
                p if isinstance(p, str) else _to_string(p(ctx)) for p in compiled
            )
        if t.kind == "ident":
            name = t.value
            if name == "true":
                return lambda ctx: True
            if name == "false":
                return lambda ctx: False
            if name == "null":
                return lambda ctx: None
            nxt = self.peek()
            if nxt.kind == "op" and nxt.value == "(":
                self.next()
                args: list[Expr] = []
                spread = False
                while True:
                    pt = self.peek(skip_nl=True)
                    if pt.kind == "op" and pt.value == ")":
                        self.next(skip_nl=True)
                        break
                    args.append(self.parse_expr())
                    pt = self.peek(skip_nl=True)
                    if pt.kind == "op" and pt.value == "...":
                        self.next(skip_nl=True)
                        spread = True
                        pt = self.peek(skip_nl=True)
                    if pt.kind == "op" and pt.value == ",":
                        self.next(skip_nl=True)
                return (
                    lambda ctx, n=name, a=tuple(args), sp=spread: _call(
                        ctx, n, a, sp, t
                    )
                )
            return lambda ctx: _lookup_var(ctx, name, t)
        if t.kind == "op" and t.value == "(":
            inner = self.parse_expr()
            self.expect_op(")", skip_nl=True)
            return inner
        if t.kind == "op" and t.value == "[":
            items: list[Expr] = []
            while True:
                pt = self.peek(skip_nl=True)
                if pt.kind == "op" and pt.value == "]":
                    self.next(skip_nl=True)
                    break
                items.append(self.parse_expr())
                pt = self.peek(skip_nl=True)
                if pt.kind == "op" and pt.value == ",":
                    self.next(skip_nl=True)
            return lambda ctx: [it(ctx) for it in items]
        if t.kind == "op" and t.value == "{":
            pairs: list[tuple[Expr, Expr]] = []
            while True:
                pt = self.peek(skip_nl=True)
                if pt.kind == "op" and pt.value == "}":
                    self.next(skip_nl=True)
                    break
                kt = self.next(skip_nl=True)
                if kt.kind == "ident":
                    kexpr: Expr = lambda ctx, k=kt.value: k
                elif kt.kind == "string":
                    # interpolated keys evaluate like string values
                    compiled_key = [
                        p if isinstance(p, str) else parse_expression(p[1])
                        for p in kt.value
                    ]
                    kexpr = lambda ctx, cp=tuple(compiled_key): "".join(
                        p if isinstance(p, str) else _to_string(p(ctx))
                        for p in cp
                    )
                elif kt.kind == "op" and kt.value == "(":
                    kexpr = self.parse_expr()
                    self.expect_op(")", skip_nl=True)
                else:
                    raise HCLError("expected object key", kt.line, kt.col)
                sep = self.next(skip_nl=True)
                if sep.kind != "op" or sep.value not in ("=", ":"):
                    raise HCLError("expected '=' or ':'", sep.line, sep.col)
                vexpr = self.parse_expr()
                pairs.append((kexpr, vexpr))
                pt = self.peek(skip_nl=True)
                if pt.kind == "op" and pt.value == ",":
                    self.next(skip_nl=True)
            return lambda ctx: {k(ctx): v(ctx) for k, v in pairs}
        raise HCLError(f"unexpected token {t.value!r}", t.line, t.col)


def _traverse(obj: Any, key: str, tok: Token) -> Any:
    if isinstance(obj, dict):
        if key not in obj:
            raise HCLError(f"unknown attribute {key!r}", tok.line, tok.col)
        return obj[key]
    if hasattr(obj, key):
        return getattr(obj, key)
    raise HCLError(f"cannot traverse into {type(obj).__name__}", tok.line, tok.col)


def _index(obj: Any, idx: Any) -> Any:
    if isinstance(obj, dict):
        return obj[idx]
    return obj[int(idx)]


def _call(ctx: EvalContext, name: str, args: tuple, spread: bool, tok: Token) -> Any:
    if name == "try":
        # first argument that evaluates without error
        for a in args:
            try:
                return a(ctx)
            except (HCLError, IndexError, KeyError, TypeError):
                continue
        raise HCLError("try(): no argument evaluated successfully", tok.line, tok.col)
    if name == "can":
        try:
            args[0](ctx) if args else None
            return True
        except (HCLError, IndexError, KeyError, TypeError):
            return False
    fn = ctx.functions.get(name)
    if fn is None:
        raise HCLError(f"unknown function {name!r}", tok.line, tok.col)
    vals = [a(ctx) for a in args]
    if spread and vals:
        last = vals.pop()
        vals.extend(last)
    return fn(*vals)


def _lookup_var(ctx: EvalContext, name: str, tok: Token) -> Any:
    if name in ctx.variables:
        return ctx.variables[name]
    raise HCLError(f"unknown variable {name!r}", tok.line, tok.col)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def parse(src: str) -> Body:
    """Parse an HCL document into a Body AST."""
    p = _Parser(tokenize(src))
    return p.parse_body(until=None)


def parse_expression(src: str) -> Expr:
    """Parse a standalone expression (used for ${...} interpolations)."""
    p = _Parser(tokenize(src))
    expr = p.parse_expr()
    t = p.peek(skip_nl=True)
    if t.kind != "eof":
        raise HCLError(f"trailing tokens after expression: {t.value!r}", t.line, t.col)
    return expr


def evaluate(expr: Expr, ctx: Optional[EvalContext] = None) -> Any:
    return expr(ctx or EvalContext())


def body_to_value(body: Body, ctx: Optional[EvalContext] = None) -> dict:
    """Evaluate a Body into plain dicts: attrs become keys; blocks become
    ``{type: [ {labels..., body...} ]}`` lists. Handy for tests/tools."""
    ctx = ctx or EvalContext()
    out: dict[str, Any] = {name: a.expr(ctx) for name, a in body.attrs.items()}
    for b in body.blocks:
        entry: dict[str, Any] = body_to_value(b.body, ctx)
        for lbl in reversed(b.labels):
            entry = {lbl: entry}
        out.setdefault(b.type, []).append(entry)
    return out
