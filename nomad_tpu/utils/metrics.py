"""In-process metrics registry.

Reference: armon/go-metrics gauges/timers used throughout the reference
(`nomad.worker.*` worker.go:461,495,553; `nomad.plan.*` plan_apply.go:185)
surfaced at /v1/metrics (http.go:333). Counters, gauges and timing
samples with mean/max, zero dependencies.
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._samples: dict[str, list[float]] = {}

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def measure(self, name: str, seconds: float) -> None:
        with self._lock:
            buf = self._samples.setdefault(name, [])
            buf.append(seconds)
            if len(buf) > 8192:
                del buf[: len(buf) - 8192]

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.measure(name, time.perf_counter() - t0)

    @staticmethod
    def _pct(sorted_buf: list[float], q: float) -> float:
        if not sorted_buf:
            return 0.0
        i = min(len(sorted_buf) - 1, int(round(q * (len(sorted_buf) - 1))))
        return sorted_buf[i]

    def snapshot(self) -> dict:
        # copy under the lock, sort outside it: percentile recomputation
        # over up to 8192 samples per key is O(n log n) per series, and
        # holding the registry lock through it would stall every
        # measure()/incr() on the worker hot path while /v1/metrics renders
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            buffers = {name: list(buf) for name, buf in self._samples.items()}
        samples = {}
        for name, buf in buffers.items():
            s = sorted(buf)
            samples[name] = {
                "count": len(buf),
                "mean_ms": (sum(buf) / len(buf)) * 1000 if buf else 0.0,
                "p50_ms": self._pct(s, 0.50) * 1000,
                "p95_ms": self._pct(s, 0.95) * 1000,
                "p99_ms": self._pct(s, 0.99) * 1000,
                "max_ms": s[-1] * 1000 if s else 0.0,
            }
        return {
            "counters": counters,
            "gauges": gauges,
            "samples": samples,
        }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._samples.clear()


global_metrics = Metrics()

_swallow_log = logging.getLogger("nomad_tpu.swallowed")


def count_swallowed(component: str, exc: BaseException | None = None) -> None:
    """Account an intentionally-swallowed exception: bumps the
    ``<component>.swallowed_errors`` counter and logs at debug. Every
    ``except`` that deliberately eats an error in server/broker/state
    code calls this (or logs outright) — the NTA003 lint rule rejects
    handlers that do neither, so swallows stay visible on the metrics
    surface instead of silently zeroing throughput. Each swallow also
    lands in the flight recorder's error ring (/v1/agent/trace).

    Faults injected by nomad_tpu.chaos carry ``nta_chaos_fault``; a
    swallow site that absorbs one is additionally tallied under
    ``nomad.chaos.swallowed_faults`` and the fault object is marked
    accounted, so the chaos tests can prove no swallow site absorbs an
    injected fault invisibly."""
    global_metrics.incr(f"{component}.swallowed_errors")
    if exc is not None and getattr(exc, "nta_chaos_fault", False):
        global_metrics.incr("nomad.chaos.swallowed_faults")
        exc.accounted = True
    _swallow_log.debug(
        "%s: swallowed %s: %s", component, type(exc).__name__ if exc else
        "error", exc, exc_info=exc is not None,
    )
    from ..obs.recorder import flight_recorder

    flight_recorder.record_error(
        component, repr(exc) if exc is not None else "error"
    )
