"""In-process metrics registry.

Reference: armon/go-metrics gauges/timers used throughout the reference
(`nomad.worker.*` worker.go:461,495,553; `nomad.plan.*` plan_apply.go:185)
surfaced at /v1/metrics (http.go:333). Counters, gauges and timing
samples with mean/max, zero dependencies.

Timing series are held as bounded :class:`~nomad_tpu.utils.hist.LogHistogram`
buckets — O(buckets) memory per key no matter how many samples are
recorded, so a minutes-long soak can't grow the registry. Percentiles
read from bucket counts land within one ~7%-wide bucket of the exact
sorted-list answer; count/mean/max stay exact.
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager

from .hist import LogHistogram, pct_nearest_rank


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._samples: dict[str, LogHistogram] = {}

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def measure(self, name: str, seconds: float) -> None:
        with self._lock:
            hist = self._samples.get(name)
            if hist is None:
                hist = self._samples[name] = LogHistogram()
            hist.record(seconds)

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.measure(name, time.perf_counter() - t0)

    @staticmethod
    def _pct(sorted_buf: list[float], q: float) -> float:
        return pct_nearest_rank(sorted_buf, q)

    def histograms(self) -> dict[str, LogHistogram]:
        """Point-in-time copies of every timing series, for callers
        (the SLO collector) that want to window-diff bucket counts."""
        with self._lock:
            return {name: h.copy() for name, h in self._samples.items()}

    def snapshot(self) -> dict:
        # copy under the lock, summarize outside it: a percentile read
        # walks every bucket per series, and holding the registry lock
        # through it would stall every measure()/incr() on the worker
        # hot path while /v1/metrics renders
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {name: h.copy() for name, h in self._samples.items()}
        samples = {name: h.snapshot() for name, h in hists.items()}
        return {
            "counters": counters,
            "gauges": gauges,
            "samples": samples,
        }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._samples.clear()


global_metrics = Metrics()

_swallow_log = logging.getLogger("nomad_tpu.swallowed")


def count_swallowed(component: str, exc: BaseException | None = None) -> None:
    """Account an intentionally-swallowed exception: bumps the
    ``<component>.swallowed_errors`` counter and logs at debug. Every
    ``except`` that deliberately eats an error in server/broker/state
    code calls this (or logs outright) — the NTA003 lint rule rejects
    handlers that do neither, so swallows stay visible on the metrics
    surface instead of silently zeroing throughput. Each swallow also
    lands in the flight recorder's error ring (/v1/agent/trace).

    Faults injected by nomad_tpu.chaos carry ``nta_chaos_fault``; a
    swallow site that absorbs one is additionally tallied under
    ``nomad.chaos.swallowed_faults`` and the fault object is marked
    accounted, so the chaos tests can prove no swallow site absorbs an
    injected fault invisibly."""
    global_metrics.incr(f"{component}.swallowed_errors")
    if exc is not None and getattr(exc, "nta_chaos_fault", False):
        global_metrics.incr("nomad.chaos.swallowed_faults")
        exc.accounted = True
    _swallow_log.debug(
        "%s: swallowed %s: %s", component, type(exc).__name__ if exc else
        "error", exc, exc_info=exc is not None,
    )
    from ..obs.recorder import flight_recorder

    flight_recorder.record_error(
        component, repr(exc) if exc is not None else "error"
    )
