"""In-process metrics registry.

Reference: armon/go-metrics gauges/timers used throughout the reference
(`nomad.worker.*` worker.go:461,495,553; `nomad.plan.*` plan_apply.go:185)
surfaced at /v1/metrics (http.go:333). Counters, gauges and timing
samples with mean/max, zero dependencies.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._samples: dict[str, list[float]] = {}

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def measure(self, name: str, seconds: float) -> None:
        with self._lock:
            buf = self._samples.setdefault(name, [])
            buf.append(seconds)
            if len(buf) > 1024:
                del buf[: len(buf) - 1024]

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.measure(name, time.perf_counter() - t0)

    def snapshot(self) -> dict:
        with self._lock:
            samples = {
                name: {
                    "count": len(buf),
                    "mean_ms": (sum(buf) / len(buf)) * 1000 if buf else 0.0,
                    "max_ms": max(buf) * 1000 if buf else 0.0,
                }
                for name, buf in self._samples.items()
            }
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "samples": samples,
            }


global_metrics = Metrics()
