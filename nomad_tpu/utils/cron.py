"""Minimal 5-field cron evaluator (minute hour dom month dow).

Backs the periodic dispatcher (the reference uses gorhill/cronexpr via
nomad/periodic.go). Supports: ``*``, lists ``a,b``, ranges ``a-b``, and
steps ``*/n`` / ``a-b/n``. All times UTC.
"""

from __future__ import annotations

import calendar
from datetime import datetime, timedelta, timezone

_FIELDS = (
    ("minute", 0, 59),
    ("hour", 0, 23),
    ("dom", 1, 31),
    ("month", 1, 12),
    ("dow", 0, 6),  # 0 = Sunday
)


class CronParseError(ValueError):
    pass


def _parse_field(expr: str, lo: int, hi: int) -> frozenset[int]:
    out: set[int] = set()
    for part in expr.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            try:
                step = int(step_s)
            except ValueError:
                raise CronParseError(f"bad step {step_s!r}") from None
            if step <= 0:
                raise CronParseError("step must be positive")
        if part in ("*", ""):
            lo2, hi2 = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            try:
                lo2, hi2 = int(a), int(b)
            except ValueError:
                raise CronParseError(f"bad range {part!r}") from None
        else:
            try:
                lo2 = hi2 = int(part)
            except ValueError:
                raise CronParseError(f"bad value {part!r}") from None
        if lo2 < lo or hi2 > hi or lo2 > hi2:
            raise CronParseError(f"value out of range: {part!r}")
        out.update(range(lo2, hi2 + 1, step))
    return frozenset(out)


class Cron:
    def __init__(self, spec: str):
        fields = spec.split()
        if len(fields) != 5:
            raise CronParseError(
                f"cron spec needs 5 fields, got {len(fields)}: {spec!r}"
            )
        self.minute = _parse_field(fields[0], 0, 59)
        self.hour = _parse_field(fields[1], 0, 23)
        self.dom = _parse_field(fields[2], 1, 31)
        self.month = _parse_field(fields[3], 1, 12)
        self.dow = _parse_field(fields[4], 0, 6)
        self._dom_wild = fields[2] == "*"
        self._dow_wild = fields[4] == "*"

    def _day_match(self, dt: datetime) -> bool:
        dom_ok = dt.day in self.dom
        dow_ok = ((dt.weekday() + 1) % 7) in self.dow  # python Mon=0 → cron Sun=0
        if self._dom_wild and self._dow_wild:
            return True
        if self._dom_wild:
            return dow_ok
        if self._dow_wild:
            return dom_ok
        return dom_ok or dow_ok  # vixie-cron OR semantics

    def next_after(self, after: float) -> float:
        """Next firing (unix seconds) strictly after ``after``."""
        dt = datetime.fromtimestamp(after, tz=timezone.utc).replace(
            second=0, microsecond=0
        ) + timedelta(minutes=1)
        for _ in range(366 * 24 * 60):  # bounded search: one year of minutes
            if (
                dt.month in self.month
                and self._day_match(dt)
                and dt.hour in self.hour
                and dt.minute in self.minute
            ):
                return dt.timestamp()
            dt += timedelta(minutes=1)
        raise CronParseError("no firing within a year")
