"""Profiling / self-diagnosis surface — the pprof analog.

Reference: Go pprof is first-class in the agent
(command/agent/http.go:331 `/v1/agent/pprof/*`, command/agent/pprof/) and
`nomad operator debug` captures a support bundle of pprof + logs + state
(command/operator_debug.go:54). Python equivalents:

- goroutine profile → thread dump via sys._current_frames();
- CPU profile      → sampling profiler over the same frame table
  (collapsed-stack counts, flamegraph-ready);
- heap profile     → tracemalloc top allocations (enabled on demand);
- operator debug   → one JSON bundle of metrics, broker/raft/worker
  stats, and the thread dump.
"""

from __future__ import annotations

import threading
import time
import traceback
import sys
from collections import Counter


def thread_dump() -> dict:
    """pprof/goroutine analog: every thread's current stack."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        stack = traceback.format_stack(frame)
        out[f"{names.get(ident, 'unknown')}-{ident}"] = [
            line.strip() for line in stack
        ]
    return out


def sample_profile(seconds: float = 1.0, hz: int = 100) -> dict:
    """pprof/profile analog: sample all threads' stacks at ``hz`` for
    ``seconds``; returns collapsed stacks (semicolon-joined frames →
    sample count), ready for flamegraph tooling."""
    samples: Counter = Counter()
    interval = 1.0 / max(hz, 1)
    deadline = time.monotonic() + seconds
    me = threading.get_ident()
    n = 0
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            frames = []
            f = frame
            while f is not None:
                code = f.f_code
                frames.append(f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}")
                f = f.f_back
            samples[";".join(reversed(frames))] += 1
        n += 1
        time.sleep(interval)
    return {
        "duration_s": seconds,
        "samples": n,
        "collapsed": dict(samples.most_common(200)),
    }


def heap_profile(top: int = 50) -> dict:
    """pprof/heap analog via tracemalloc; starts tracing on first call
    (subsequent calls diff against a warm tracer)."""
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return {"started": True, "note": "tracing enabled; call again for stats"}
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:top]
    return {
        "started": False,
        "total_kb": sum(s.size for s in stats) // 1024,
        "top": [
            {
                "site": str(s.traceback[0]) if s.traceback else "?",
                "size_kb": s.size // 1024,
                "count": s.count,
            }
            for s in stats
        ],
    }


def debug_bundle(server) -> dict:
    """`nomad operator debug` analog (command/operator_debug.go:54): one
    self-contained diagnostic capture of the server's moving parts."""
    from .metrics import global_metrics

    bundle: dict = {
        "captured_at": time.time(),
        "metrics": global_metrics.snapshot(),
        "threads": thread_dump(),
    }
    try:
        broker = server.eval_broker
        bundle["eval_broker"] = {
            **dict(getattr(broker, "stats", {}) or {}),
            "ready": broker.ready_count(),
            "unacked": len(broker._unack),
        }
    except Exception:
        pass
    try:
        bundle["blocked_evals"] = dict(server.blocked_evals.stats)
    except Exception:
        pass
    try:
        bundle["workers"] = [dict(w.stats) for w in server.workers]
    except Exception:
        pass
    try:
        bundle["device_cache"] = {
            "full_flattens": server.device_cache.full_flattens,
            "incremental_refreshes": server.device_cache.incremental_refreshes,
            "hits": server.device_cache.hits,
            "stale_builds": server.device_cache.stale_builds,
        }
    except Exception:
        pass
    raft = getattr(server, "raft", None)
    if raft is not None:
        try:
            bundle["raft"] = raft.stats()
        except Exception:
            pass
    return bundle
