"""Cross-cutting helpers (metrics, ids)."""

from .metrics import Metrics, global_metrics

__all__ = ["Metrics", "global_metrics"]
