"""Bounded telemetry primitives: log-bucketed histograms and
per-second time-series rings.

The original metrics registry kept a raw ``list[float]`` per sample key
and re-sorted it on every snapshot — fine for a drain bench, unusable
over a minutes-long soak where a single hot series records hundreds of
samples per second. Both structures here are O(1) per record and hold a
fixed amount of memory regardless of how many samples pass through:

* :class:`LogHistogram` — geometric buckets over ``[lo, hi)`` with
  ~7% relative width, so any percentile read is within one bucket
  (≤ ~3.5% relative error) of the exact sorted-list answer while count,
  sum, min and max stay exact.
* :class:`TimeSeriesRing` — a fixed number of per-second slots for
  "what did queue depth / arrival rate look like over the last N
  seconds", overwriting the oldest second as the clock advances.

Everything here is plain Python with no locking: callers (the metrics
registry, the SLO collector) serialize access with their own locks.
"""

from __future__ import annotations

import math


def pct_nearest_rank(sorted_buf: list[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted buffer — the one
    formula used repo-wide (metrics snapshots, trace phase breakdowns,
    histogram reads all agree on it)."""
    if not sorted_buf:
        return 0.0
    i = min(len(sorted_buf) - 1, int(round(q * (len(sorted_buf) - 1))))
    return sorted_buf[i]


class LogHistogram:
    """Fixed-memory histogram with geometrically-spaced buckets.

    Values are clamped into ``[lo, hi)``; bucket ``i`` covers
    ``[lo * growth**i, lo * growth**(i+1))``. With the defaults
    (1 microsecond .. 1 hour, 7% growth) that is ~325 buckets — a few
    KB per series, forever, versus an unbounded sample list.
    """

    __slots__ = (
        "lo", "hi", "growth", "_log_growth", "_log_lo",
        "counts", "count", "total", "min", "max",
    )

    def __init__(
        self, lo: float = 1e-6, hi: float = 3600.0, growth: float = 1.07
    ):
        if not (lo > 0 and hi > lo and growth > 1.0):
            raise ValueError("need lo > 0, hi > lo, growth > 1")
        self.lo = lo
        self.hi = hi
        self.growth = growth
        self._log_growth = math.log(growth)
        self._log_lo = math.log(lo)
        n = int(math.ceil((math.log(hi) - self._log_lo) / self._log_growth))
        self.counts = [0] * n
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf

    def _index(self, value: float) -> int:
        if value < self.lo:
            return 0
        i = int((math.log(value) - self._log_lo) // self._log_growth)
        return min(i, len(self.counts) - 1)

    def record(self, value: float) -> None:
        self.counts[self._index(value)] += 1
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def merge(self, other: "LogHistogram") -> None:
        if len(other.counts) != len(self.counts):
            raise ValueError("histogram geometry mismatch")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.total += other.total
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)

    def _bucket_value(self, i: int) -> float:
        # geometric midpoint of the bucket, clamped to the observed
        # range so p0/p100 reads never invent values outside it
        mid = self.lo * self.growth ** (i + 0.5)
        return min(max(mid, self.min), self.max)

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile, same rank formula as
        :func:`pct_nearest_rank`, answered from bucket counts — the
        result lands inside the true sample's bucket, i.e. within one
        bucket width of the exact sorted-list answer."""
        if self.count == 0:
            return 0.0
        rank = min(self.count - 1, int(round(q * (self.count - 1))))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen > rank:
                return self._bucket_value(i)
        return self.max

    def diff(self, base: "LogHistogram") -> "LogHistogram":
        """Windowed view: this histogram minus an earlier snapshot of
        the same series. Bucket counts, count and total subtract
        exactly; min/max can't be un-merged, so the window keeps the
        lifetime extremes (documented approximation — percentile reads
        only use them to clamp bucket midpoints)."""
        if len(base.counts) != len(self.counts):
            raise ValueError("histogram geometry mismatch")
        h = self.copy()
        for i, c in enumerate(base.counts):
            h.counts[i] -= c
        h.count -= base.count
        h.total -= base.total
        return h

    def copy(self) -> "LogHistogram":
        h = LogHistogram.__new__(LogHistogram)
        h.lo = self.lo
        h.hi = self.hi
        h.growth = self.growth
        h._log_growth = self._log_growth
        h._log_lo = self._log_lo
        h.counts = list(self.counts)
        h.count = self.count
        h.total = self.total
        h.min = self.min
        h.max = self.max
        return h

    def snapshot(self) -> dict:
        """The registry's sample shape: count/mean/max exact,
        percentiles within one bucket of exact."""
        if self.count == 0:
            return {
                "count": 0, "mean_ms": 0.0, "p50_ms": 0.0,
                "p95_ms": 0.0, "p99_ms": 0.0, "max_ms": 0.0,
            }
        return {
            "count": self.count,
            "mean_ms": (self.total / self.count) * 1000,
            "p50_ms": self.percentile(0.50) * 1000,
            "p95_ms": self.percentile(0.95) * 1000,
            "p99_ms": self.percentile(0.99) * 1000,
            "max_ms": self.max * 1000,
        }


class TimeSeriesRing:
    """Per-second slots over a sliding window of ``seconds``.

    ``observe(t, value)`` records a gauge-style sample into the slot for
    second ``int(t)``; ``incr(t, n)`` accumulates a counter. Advancing
    past a slot's horizon clears it, so memory is fixed at
    ``seconds`` slots no matter how long the soak runs.
    """

    __slots__ = ("seconds", "_epoch", "_counts", "_sums", "_maxes", "_events")

    def __init__(self, seconds: int = 600):
        if seconds <= 0:
            raise ValueError("seconds must be positive")
        self.seconds = seconds
        self._epoch = [-1] * seconds   # which absolute second owns the slot
        self._counts = [0] * seconds   # gauge samples in the slot
        self._sums = [0.0] * seconds
        self._maxes = [0.0] * seconds
        self._events = [0.0] * seconds  # counter accumulation

    def _slot(self, t: float) -> int:
        sec = int(t)
        i = sec % self.seconds
        if self._epoch[i] != sec:
            self._epoch[i] = sec
            self._counts[i] = 0
            self._sums[i] = 0.0
            self._maxes[i] = 0.0
            self._events[i] = 0.0
        return i

    def observe(self, t: float, value: float) -> None:
        i = self._slot(t)
        self._counts[i] += 1
        self._sums[i] += value
        if self._counts[i] == 1 or value > self._maxes[i]:
            self._maxes[i] = value

    def incr(self, t: float, n: float = 1.0) -> None:
        self._events[self._slot(t)] += n

    def _live(self, now: float) -> list[int]:
        horizon = int(now) - self.seconds
        return [
            i for i in range(self.seconds)
            if self._epoch[i] > horizon and self._epoch[i] >= 0
        ]

    def series(self, now: float) -> list[tuple[int, float, float, float]]:
        """(second, mean, max, events) rows for live slots, oldest
        first — the raw per-second trajectory for a report."""
        rows = []
        for i in self._live(now):
            n = self._counts[i]
            rows.append((
                self._epoch[i],
                self._sums[i] / n if n else 0.0,
                self._maxes[i],
                self._events[i],
            ))
        rows.sort()
        return rows

    def stats(self, now: float) -> dict:
        """Aggregate over live slots: mean-of-means, global max, total
        events, events/sec over the covered span."""
        rows = self.series(now)
        if not rows:
            return {"seconds": 0, "mean": 0.0, "max": 0.0,
                    "events": 0.0, "events_per_s": 0.0}
        span = len(rows)
        sampled = [r for r in rows if r[1] or r[2]]
        mean = (
            sum(r[1] for r in sampled) / len(sampled) if sampled else 0.0
        )
        events = sum(r[3] for r in rows)
        return {
            "seconds": span,
            "mean": mean,
            "max": max(r[2] for r in rows),
            "events": events,
            "events_per_s": events / span,
        }
