"""Backend liveness probe + CPU-fallback env construction.

The axon TPU plugin can hang ``jax.devices()`` indefinitely when its
tunnel is down, and jax latches its platform at first init — so a process
that needs a different backend (or a virtual multi-device CPU mesh) must
decide *before* touching jax, or delegate to a child process with the
right env. Both bench.py and __graft_entry__.dryrun_multichip share this
hazard; this module is the single copy of the workaround.
"""

import os
import threading


def probe_device_count(timeout_s: float = 90.0) -> int:
    """Return ``len(jax.devices())``, or 0 if init fails or hangs past
    ``timeout_s`` (probe runs in a daemon thread so a hung PJRT plugin
    cannot wedge the caller)."""
    found: list[int] = []

    def probe():
        try:
            import jax

            found.append(len(jax.devices()))
        except Exception:
            found.append(0)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    return found[0] if found else 0


def cpu_fallback_env(n_devices: int | None = None) -> dict:
    """A copy of os.environ steered to the CPU backend: JAX_PLATFORMS=cpu,
    the axon sitecustomize stripped from PYTHONPATH, and (optionally) a
    virtual ``n_devices``-device host platform via XLA_FLAGS."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":") if ".axon_site" not in p
    )
    if n_devices is not None:
        flags = env.get("XLA_FLAGS", "")
        flags = " ".join(
            f
            for f in flags.split()
            if "xla_force_host_platform_device_count" not in f
        )
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    return env
