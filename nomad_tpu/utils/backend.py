"""Backend liveness probe + CPU-fallback env construction.

The axon TPU plugin can hang ``jax.devices()`` indefinitely when its
tunnel is down, and jax latches its platform at first init — so a process
that needs a different backend (or a virtual multi-device CPU mesh) must
decide *before* touching jax, or delegate to a child process with the
right env. Both bench.py and __graft_entry__.dryrun_multichip share this
hazard; this module is the single copy of the workaround.
"""

import functools
import os
import threading

# -- jit trace accounting ----------------------------------------------------
#
# ``traced_jit`` is the seam the retrace budget checker
# (nomad_tpu.analysis.retrace) reads: it wraps a kernel's Python body with
# a counter bump BEFORE handing it to jax.jit, so the counter increments
# exactly once per XLA trace (jit only re-executes the Python body on a
# cache miss) and never on a cached dispatch. A hot-path kernel that
# silently retraces per call — a dropped shape bucket, a static arg that
# became dynamic — shows up as a counter marching in lockstep with the
# call count instead of plateauing at the handful of shape buckets its
# declared budget allows.

_trace_lock = threading.Lock()
_trace_counts: dict[str, int] = {}
_trace_budgets: dict[str, int] = {}


def record_trace(name: str) -> None:
    with _trace_lock:
        _trace_counts[name] = _trace_counts.get(name, 0) + 1


def trace_counts() -> dict[str, int]:
    with _trace_lock:
        return dict(_trace_counts)


def trace_budgets() -> dict[str, int]:
    with _trace_lock:
        return dict(_trace_budgets)


def reset_trace_counts() -> None:
    with _trace_lock:
        for k in _trace_counts:
            _trace_counts[k] = 0


def traced_jit(fn=None, *, trace_name=None, retrace_budget=None, **jit_kwargs):
    """Drop-in ``jax.jit`` replacement that counts traces per callable and
    (optionally) declares a retrace budget for the analysis checker::

        @functools.partial(traced_jit, retrace_budget=16,
                           static_argnames=("max_j", "k"))
        def place_kernel(...): ...

    jax is imported lazily at decoration time, so importing this module
    stays safe in jax-free contexts."""
    if fn is None:
        return functools.partial(
            traced_jit,
            trace_name=trace_name,
            retrace_budget=retrace_budget,
            **jit_kwargs,
        )
    import jax

    name = trace_name or f"{fn.__module__}.{fn.__qualname__}"
    with _trace_lock:
        _trace_counts.setdefault(name, 0)
        if retrace_budget is not None:
            _trace_budgets[name] = retrace_budget

    @functools.wraps(fn)
    def _counted(*args, **kwargs):
        record_trace(name)
        return fn(*args, **kwargs)

    return jax.jit(_counted, **jit_kwargs)


def probe_device_count(timeout_s: float = 90.0) -> int:
    """Return ``len(jax.devices())``, or 0 if init fails or hangs past
    ``timeout_s`` (probe runs in a daemon thread so a hung PJRT plugin
    cannot wedge the caller)."""
    found: list[int] = []

    def probe():
        try:
            import jax

            found.append(len(jax.devices()))
        except Exception:
            found.append(0)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    return found[0] if found else 0


def cpu_fallback_env(n_devices: int | None = None) -> dict:
    """A copy of os.environ steered to the CPU backend: JAX_PLATFORMS=cpu,
    the axon sitecustomize stripped from PYTHONPATH, and (optionally) a
    virtual ``n_devices``-device host platform via XLA_FLAGS."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":") if ".axon_site" not in p
    )
    if n_devices is not None:
        flags = env.get("XLA_FLAGS", "")
        flags = " ".join(
            f
            for f in flags.split()
            if "xla_force_host_platform_device_count" not in f
        )
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    return env
