"""Backend liveness probe + CPU-fallback env construction.

The axon TPU plugin can hang ``jax.devices()`` indefinitely when its
tunnel is down, and jax latches its platform at first init — so a process
that needs a different backend (or a virtual multi-device CPU mesh) must
decide *before* touching jax, or delegate to a child process with the
right env. Both bench.py and __graft_entry__.dryrun_multichip share this
hazard; this module is the single copy of the workaround.
"""

import functools
import os
import threading
import time

# -- jit trace accounting ----------------------------------------------------
#
# ``traced_jit`` is the seam the retrace budget checker
# (nomad_tpu.analysis.retrace) reads: it wraps a kernel's Python body with
# a counter bump BEFORE handing it to jax.jit, so the counter increments
# exactly once per XLA trace (jit only re-executes the Python body on a
# cache miss) and never on a cached dispatch. A hot-path kernel that
# silently retraces per call — a dropped shape bucket, a static arg that
# became dynamic — shows up as a counter marching in lockstep with the
# call count instead of plateauing at the handful of shape buckets its
# declared budget allows.

_trace_lock = threading.Lock()
_trace_counts: dict[str, int] = {}
_trace_budgets: dict[str, int] = {}

# -- kernel registry (nomad_tpu.analysis.jaxlint) -----------------------------
#
# Every ``traced_jit`` decoration registers a ``KernelEntry``: the
# ORIGINAL un-jitted body, the jit kwargs (static_argnames included),
# and — recorded at trace time, when the dynamic args are tracers
# carrying avals and the static args are plain Python values — the
# last-seen abstract call specs. The jaxpr analyzer re-traces each
# registered kernel from these specs with ``jax.make_jaxpr`` and walks
# the resulting ClosedJaxpr, so purity/dtype/determinism/fingerprint
# invariants are checked against the *traced program*, not the Python
# source.

_KERNEL_SPECS_MAX = 8  # distinct abstract call specs kept per kernel


class KernelEntry:
    """One registered device kernel: identity, jit config, and the
    abstract call specs seen so far (newest last)."""

    __slots__ = ("name", "short", "fn", "jit_kwargs", "retrace_budget",
                 "specs")

    def __init__(self, name, short, fn, jit_kwargs, retrace_budget):
        self.name = name
        self.short = short
        self.fn = fn
        self.jit_kwargs = dict(jit_kwargs)
        self.retrace_budget = retrace_budget
        # sig string -> {"args": [spec...], "kwargs": {name: spec}};
        # insertion-ordered, bounded to _KERNEL_SPECS_MAX (oldest evicted)
        self.specs: dict[str, dict] = {}

    @property
    def static_argnames(self) -> tuple:
        sa = self.jit_kwargs.get("static_argnames", ())
        return (sa,) if isinstance(sa, str) else tuple(sa)

    def last_spec(self):
        """Newest recorded abstract call spec, or None if never traced."""
        if not self.specs:
            return None
        return next(reversed(self.specs.values()))

    def describe(self) -> dict:
        return {
            "name": self.name,
            "short": self.short,
            "module": self.fn.__module__,
            "qualname": self.fn.__qualname__,
            "static_argnames": list(self.static_argnames),
            "retrace_budget": self.retrace_budget,
            "specs": list(self.specs),
        }


_kernel_registry: dict[str, KernelEntry] = {}


def _arg_spec(a):
    """Abstract spec of one kernel argument, built at trace time.

    Dynamic args are tracers -> ("aval", shape, dtype, weak_type);
    static args are plain Python values -> ("static", value); anything
    the analyzer cannot reconstruct -> ("opaque", type name)."""
    aval = getattr(a, "aval", None)
    if aval is not None and hasattr(aval, "shape"):
        return ("aval", tuple(int(d) for d in aval.shape),
                str(aval.dtype), bool(getattr(aval, "weak_type", False)))
    if a is None or isinstance(a, (bool, int, float, str)):
        return ("static", a)
    if hasattr(a, "shape") and hasattr(a, "dtype"):  # concrete array
        return ("aval", tuple(int(d) for d in a.shape),
                str(a.dtype), False)
    return ("opaque", type(a).__name__)


def _record_kernel_spec(name: str, sig: str, args, kwargs) -> None:
    """Record the abstract call spec under ``sig`` (called from the
    trace-time counter, so once per XLA trace, never per dispatch)."""
    entry = _kernel_registry.get(name)
    if entry is None:
        return
    spec = {
        "args": [_arg_spec(a) for a in args],
        "kwargs": {k: _arg_spec(v) for k, v in sorted(kwargs.items())},
    }
    entry.specs.pop(sig, None)
    entry.specs[sig] = spec
    while len(entry.specs) > _KERNEL_SPECS_MAX:
        entry.specs.pop(next(iter(entry.specs)))


def kernel_registry() -> dict[str, KernelEntry]:
    """Snapshot of the registered kernel fleet (name -> KernelEntry).
    Entries are live objects — the analyzer reads, never mutates."""
    with _trace_lock:
        return dict(_kernel_registry)

# -- kernel profiling (nomad_tpu.obs) ----------------------------------------
#
# Per-kernel call/compile accounting behind the same lock: every
# traced_jit call records its dispatch wall time; calls that triggered an
# XLA trace additionally record the abstract batch shape that caused it
# and land in a bounded recent-events list. Caveat, stated honestly:
# dispatch wall time UNDERESTIMATES device execute time under jax's
# async dispatch (we deliberately do not block_until_ready — profiling
# must not change the pipeline), while a trace-triggering call's wall
# time INCLUDES trace+compile, which is why those are exported as a
# separate ``.compile`` sample series.

_KERNEL_TRACE_EVENTS = 32  # recent trace events kept per kernel

_kernel_stats: dict[str, dict] = {}
_kernel_traces: dict[str, list[dict]] = {}
_last_trace_shape: dict[str, str] = {}

_obs_tracer = None  # lazily bound nomad_tpu.obs.trace.global_tracer


def record_trace(name: str) -> None:
    with _trace_lock:
        _trace_counts[name] = _trace_counts.get(name, 0) + 1


def _shape_sig(args, kwargs) -> str:
    """Abstract signature of a kernel call — built only at trace time,
    when the positional args are jax tracers carrying shape/dtype."""
    parts = []
    for a in list(args) + [v for _, v in sorted(kwargs.items())]:
        shp = getattr(a, "shape", None)
        if shp is not None:
            dt = getattr(getattr(a, "dtype", None), "name", "?")
            parts.append(f"{dt}[{','.join(str(d) for d in shp)}]")
        elif isinstance(a, (bool, int, float, str)):
            parts.append(repr(a))
    return " ".join(parts)[:256]


def _record_kernel_call(
    name: str, short: str, seconds: float, traced: bool
) -> None:
    with _trace_lock:
        st = _kernel_stats.setdefault(
            name, {"calls": 0, "traces": 0, "total_s": 0.0}
        )
        st["calls"] += 1
        st["total_s"] += seconds
        shape = _last_trace_shape.get(name, "")
        if traced:
            st["traces"] += 1
            events = _kernel_traces.setdefault(name, [])
            events.append({"shape": shape, "wall_s": round(seconds, 6)})
            del events[:-_KERNEL_TRACE_EVENTS]
    from .metrics import global_metrics

    global_metrics.measure(
        f"nomad.kernel.{short}.compile" if traced
        else f"nomad.kernel.{short}.execute",
        seconds,
    )
    global _obs_tracer
    if _obs_tracer is None:
        from ..obs.trace import global_tracer

        _obs_tracer = global_tracer
    _obs_tracer.record_kernel(
        short, seconds, traced=traced, shape=shape if traced else None
    )


def kernel_profile() -> dict:
    """Per-kernel profile snapshot: call/trace counts, cumulative wall
    time, the last shapes that triggered traces (the /v1/agent/trace
    ``kernels`` section and the retrace post-mortem companion)."""
    with _trace_lock:
        out = {}
        for name, st in _kernel_stats.items():
            out[name] = {
                "calls": st["calls"],
                "traces": st["traces"],
                "total_ms": round(st["total_s"] * 1000.0, 3),
                "last_trace_shape": _last_trace_shape.get(name, ""),
                "recent_traces": list(_kernel_traces.get(name, ())),
            }
        return out


def reset_kernel_profile() -> None:
    with _trace_lock:
        _kernel_stats.clear()
        _kernel_traces.clear()
        _last_trace_shape.clear()


def trace_counts() -> dict[str, int]:
    with _trace_lock:
        return dict(_trace_counts)


def trace_budgets() -> dict[str, int]:
    with _trace_lock:
        return dict(_trace_budgets)


def reset_trace_counts() -> None:
    with _trace_lock:
        for k in _trace_counts:
            _trace_counts[k] = 0


def traced_jit(fn=None, *, trace_name=None, retrace_budget=None, **jit_kwargs):
    """Drop-in ``jax.jit`` replacement that counts traces per callable and
    (optionally) declares a retrace budget for the analysis checker::

        @functools.partial(traced_jit, retrace_budget=16,
                           static_argnames=("max_j", "k"))
        def place_kernel(...): ...

    jax is imported lazily at decoration time, so importing this module
    stays safe in jax-free contexts."""
    if fn is None:
        return functools.partial(
            traced_jit,
            trace_name=trace_name,
            retrace_budget=retrace_budget,
            **jit_kwargs,
        )
    import jax

    name = trace_name or f"{fn.__module__}.{fn.__qualname__}"
    short = name.rsplit(".", 1)[-1]
    with _trace_lock:
        _trace_counts.setdefault(name, 0)
        if retrace_budget is not None:
            _trace_budgets[name] = retrace_budget
        _kernel_registry[name] = KernelEntry(
            name, short, fn, jit_kwargs, retrace_budget
        )

    @functools.wraps(fn)
    def _counted(*args, **kwargs):
        record_trace(name)
        sig = _shape_sig(args, kwargs)
        with _trace_lock:
            _last_trace_shape[name] = sig
            _record_kernel_spec(name, sig, args, kwargs)
        return fn(*args, **kwargs)

    jitted = jax.jit(_counted, **jit_kwargs)
    watchdog_on = os.environ.get("NOMAD_TPU_KERNEL_WATCHDOG", "1") != "0"

    def _reference_call(args, kwargs):
        """The exact CPU/reference path: the ORIGINAL un-jitted body,
        op by op, inputs pulled to host and computation pinned to the
        CPU backend so a sick device is never consulted. Eager jax ops
        and the jitted program compute the same values; with the whole
        pass on this path the placements are byte-identical to a
        from-scratch CPU run."""
        from .metrics import global_metrics

        t0 = time.perf_counter()
        try:
            cpu = jax.local_devices(backend="cpu")[0]
        except Exception:
            cpu = None

        def _host(x):
            if hasattr(x, "shape") and hasattr(x, "dtype") and hasattr(
                x, "__array__"
            ):
                try:
                    import numpy as np

                    return np.asarray(x)
                except Exception:
                    return x
            return x

        args = tuple(_host(a) for a in args)
        kwargs = {k: _host(v) for k, v in kwargs.items()}
        if cpu is not None:
            with jax.default_device(cpu):
                out = fn(*args, **kwargs)
        else:
            out = fn(*args, **kwargs)
        global_metrics.incr("nomad.resilience.fallback_calls")
        global_metrics.measure(
            f"nomad.kernel.{short}.fallback", time.perf_counter() - t0
        )
        return out

    @functools.wraps(fn)
    def _profiled(*args, **kwargs):
        from ..chaos.plane import chaos_site
        from ..resilience.breaker import breaker_for
        from ..resilience.errors import KernelDeadlineExceeded

        # nested kernel: when an outer traced_jit kernel is being traced
        # and calls this one, the args are tracers bound to the caller's
        # thread-local trace — shipping them to the watchdog thread leaks
        # them. The outer call's breaker/watchdog already covers the
        # whole fused computation, so just inline.
        if not jax.core.trace_state_clean():
            return jitted(*args, **kwargs)
        br = breaker_for(name)
        if not br.allow():
            return _reference_call(args, kwargs)
        # a raise here models a device-side failure (OOM, preempted
        # TPU); the worker's batch path falls back to single-eval runs
        try:
            chaos_site("kernel.execute")
        except Exception as e:
            br.record_failure(e)
            raise
        before = _trace_counts.get(name, 0)

        def _thunk():
            # a hang here models a wedged PJRT call — only the watchdog
            # deadline gets the caller's thread back
            chaos_site("kernel.hang")
            return jitted(*args, **kwargs)

        t0 = time.perf_counter()
        try:
            if watchdog_on and br.execute_deadline > 0:
                from ..resilience.watchdog import global_executor

                out = global_executor.run(
                    _thunk,
                    name=name,
                    deadline_s=br.execute_deadline,
                    extend_deadline_s=br.compile_deadline,
                    extend_probe=(
                        lambda: _trace_counts.get(name, 0) > before
                    ),
                )
            else:
                out = _thunk()
        except KernelDeadlineExceeded as e:
            br.record_timeout(e)
            # finish THIS call on the reference path: a mid-batch trip
            # must not fail sibling members of the merged commit
            return _reference_call(args, kwargs)
        except Exception as e:
            br.record_failure(e)
            raise
        br.record_success()
        dt = time.perf_counter() - t0
        _record_kernel_call(name, short, dt, _trace_counts.get(name, 0) > before)
        return out

    _profiled.jitted = jitted  # escape hatch: the raw jax.jit object
    return _profiled


def probe_device_count(timeout_s: float = 90.0) -> int:
    """Return ``len(jax.devices())``, or 0 if init fails or hangs past
    ``timeout_s`` (probe runs in a daemon thread so a hung PJRT plugin
    cannot wedge the caller)."""
    found: list[int] = []

    def probe():
        try:
            import jax

            found.append(len(jax.devices()))
        except Exception:
            found.append(0)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    return found[0] if found else 0


def probe_device_count_cached(
    timeout_s: float = 90.0,
    cache_path: str | None = None,
    ttl_s: float = 300.0,
) -> tuple[int, dict]:
    """One probe per process *family*: a dead backend's negative result
    is cached in the file named by ``NOMAD_TPU_BACKEND_PROBE_CACHE`` (or
    ``cache_path``), so follow-on processes within ``ttl_s`` skip
    straight to CPU fallback instead of each paying another timeout.
    A live probe result removes the cache entry. Returns
    ``(devices, diag)`` — bench emits ``diag`` as ``probe_diag``."""
    import json as _json

    if cache_path is None:
        cache_path = os.environ.get("NOMAD_TPU_BACKEND_PROBE_CACHE", "")
    diag: dict = {
        "timeout_s": timeout_s,
        "cached": False,
        "cache_path": cache_path or None,
    }
    if cache_path and os.path.exists(cache_path):
        try:
            with open(cache_path) as f:
                entry = _json.load(f)
            age = time.time() - float(entry.get("at_unix", 0))
            if entry.get("devices", 1) == 0 and 0 <= age < ttl_s:
                diag.update(
                    cached=True, devices=0,
                    cache_age_s=round(age, 1), took_s=0.0,
                )
                return 0, diag
        except (OSError, ValueError, TypeError):
            pass
    t0 = time.monotonic()
    n = probe_device_count(timeout_s)
    took = time.monotonic() - t0
    diag.update(devices=n, took_s=round(took, 2))
    if cache_path:
        try:
            if n == 0:
                with open(cache_path, "w") as f:
                    _json.dump(
                        {"devices": 0, "at_unix": time.time(),
                         "took_s": round(took, 2)},
                        f,
                    )
            elif os.path.exists(cache_path):
                os.unlink(cache_path)
        except OSError:
            pass
    return n, diag


# -- mesh sharding seam -------------------------------------------------------
#
# The ONE place the repo constructs a jax Mesh / NamedSharding and calls
# jax.device_put on pipeline tensors (NTA015 bans it elsewhere in
# device/ and scheduler/). Axis names match tests/test_mesh_sharding.py:
# "groups" is data-parallel over the eval/group axis, "nodes" shards the
# node axis region-major. The degenerate 1x1 mesh keeps get_mesh()
# callable everywhere while leaving the single-device jaxpr — and thus
# placements — bit-identical.

_MESH_ENV = "NOMAD_TPU_MESH"

_mesh_lock = threading.Lock()
_mesh_config = None  # cached MeshConfig | None (None = not resolved yet)


class MeshConfig:
    """Resolved mesh decision. ``mesh`` is a ``jax.sharding.Mesh`` when
    ``active``, else None; ``dp``/``mp`` are the groups/nodes axis sizes
    (1,1 when degenerate)."""

    __slots__ = ("mesh", "dp", "mp", "source")

    def __init__(self, mesh, dp: int, mp: int, source: str):
        self.mesh = mesh
        self.dp = int(dp)
        self.mp = int(mp)
        self.source = source

    @property
    def active(self) -> bool:
        return self.mesh is not None

    @property
    def n_node_shards(self) -> int:
        return self.mp if self.mesh is not None else 1

    def describe(self) -> dict:
        """The self-describing ``mesh`` block bench.py embeds in every
        JSON record (per-shard node counts are filled in by the caller
        that knows the padded bucket)."""
        return {
            "active": self.active,
            "shape": [self.dp, self.mp],
            "axis_names": ["groups", "nodes"],
            "source": self.source,
        }


def parse_mesh_spec(spec: str):
    """``NOMAD_TPU_MESH`` grammar: ``off``/``0`` (degenerate), ``auto``
    (shape from all visible devices), or ``dp,mp``. Returns "off",
    "auto", or an (dp, mp) int tuple; raises ValueError on junk."""
    s = (spec or "").strip().lower()
    if s in ("off", "0", "none"):
        return "off"
    if s == "auto":
        return "auto"
    parts = s.split(",")
    if len(parts) != 2:
        raise ValueError(
            f"bad {_MESH_ENV}={spec!r}: expected 'dp,mp', 'auto', or 'off'"
        )
    dp, mp = int(parts[0]), int(parts[1])
    if dp < 1 or mp < 1:
        raise ValueError(f"bad {_MESH_ENV}={spec!r}: axes must be >= 1")
    if mp & (mp - 1):
        raise ValueError(
            f"bad {_MESH_ENV}={spec!r}: nodes axis must be a power of two "
            "(it must divide the padded node bucket)"
        )
    return (dp, mp)


def auto_mesh_shape(n_devices: int) -> tuple[int, int]:
    """Shape rule for ``auto``: use the largest power-of-two device
    count, cap the node axis at 8 (the minimum node bucket), put the
    rest on the groups axis. 8 devices -> (2, 4)."""
    total = 1
    while total * 2 <= n_devices:
        total *= 2
    if total <= 1:
        return (1, 1)
    mp = min(8, total // 2) if total > 2 else total
    dp = total // mp
    return (dp, mp)


def _resolve_mesh() -> "MeshConfig":
    spec = os.environ.get(_MESH_ENV)
    if spec is None:
        # Unset: activate automatically only on a real accelerator
        # backend with >1 device — the production default. The CPU test
        # rig (8 virtual host devices) stays degenerate unless a test
        # opts in, so the single-device jaxpr suite is undisturbed.
        import jax

        if jax.default_backend() == "cpu" or len(jax.devices()) <= 1:
            return MeshConfig(None, 1, 1, "default-off")
        parsed = "auto"
        source = "auto-detected"
    else:
        parsed = parse_mesh_spec(spec)
        source = f"env:{spec.strip()}"
    if parsed == "off":
        return MeshConfig(None, 1, 1, source)
    import jax

    devices = jax.devices()
    if parsed == "auto":
        dp, mp = auto_mesh_shape(len(devices))
    else:
        dp, mp = parsed
    if dp * mp > len(devices):
        raise ValueError(
            f"{_MESH_ENV} asks for {dp}x{mp}={dp * mp} devices but only "
            f"{len(devices)} are visible"
        )
    if dp * mp == 1:
        return MeshConfig(None, 1, 1, source)
    import numpy as _np
    from jax.sharding import Mesh

    grid = _np.array(devices[: dp * mp]).reshape(dp, mp)
    return MeshConfig(Mesh(grid, ("groups", "nodes")), dp, mp, source)


def get_mesh() -> "MeshConfig":
    """The process-wide mesh decision, resolved once from
    ``NOMAD_TPU_MESH`` (see ``_resolve_mesh``). Call ``reset_mesh()``
    after changing the env in tests."""
    global _mesh_config
    cfg = _mesh_config
    if cfg is not None:
        return cfg
    with _mesh_lock:
        if _mesh_config is None:
            _mesh_config = _resolve_mesh()
        return _mesh_config


def reset_mesh() -> None:
    global _mesh_config
    with _mesh_lock:
        _mesh_config = None


def shard_put(x, axes, cfg: "MeshConfig | None" = None):
    """Place ``x`` on the mesh with PartitionSpec(*axes); the sanctioned
    device_put seam. ``axes`` entries are "groups"/"nodes"/None, one per
    array dim (trailing Nones may be omitted). Degenerate mesh or an
    axis size that does not divide the corresponding dim -> plain
    jnp.asarray (full replication semantics, unchanged jaxpr)."""
    import jax.numpy as jnp

    if cfg is None:
        cfg = get_mesh()
    if not cfg.active:
        return jnp.asarray(x)
    shape = getattr(x, "shape", None)
    if shape is None:
        x = jnp.asarray(x)
        shape = x.shape
    sizes = {"groups": cfg.dp, "nodes": cfg.mp}
    use = []
    for i, ax in enumerate(axes):
        if ax is None or i >= len(shape) or shape[i] % sizes[ax] != 0:
            use.append(None)
        else:
            use.append(ax)
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.device_put(x, NamedSharding(cfg.mesh, PartitionSpec(*use)))


# -- incremental score-state seam ---------------------------------------------
#
# ``NOMAD_TPU_INCREMENTAL`` gates the DeviceStateCache's score-state
# persistence (device/cache.py): with it on, the per-pass ``used``
# tensor stays device-resident across passes and only dirty slices
# re-upload. Resolved once like the mesh spec; the gate is PYTHON-level
# (the resident buffer has the same aval as a fresh ``shard_put``), so
# flipping it can never change a traced program — the jaxlint differ
# (analysis/jaxlint/diff.py: prove_incremental_invariance) pins that.

_INCR_ENV = "NOMAD_TPU_INCREMENTAL"

_incr_lock = threading.Lock()
_incr_enabled = None  # cached bool | None (None = not resolved yet)


def incremental_enabled() -> bool:
    """The process-wide incremental-rescoring decision, resolved once
    from ``NOMAD_TPU_INCREMENTAL`` (``on``/``1``/``true`` enable; unset
    or anything else is off — the from-scratch reference path). Call
    ``reset_incremental()`` after changing the env in tests."""
    global _incr_enabled
    val = _incr_enabled
    if val is not None:
        return val
    with _incr_lock:
        if _incr_enabled is None:
            spec = os.environ.get(_INCR_ENV, "")
            _incr_enabled = spec.strip().lower() in ("on", "1", "true")
        return _incr_enabled


def reset_incremental() -> None:
    global _incr_enabled
    with _incr_lock:
        _incr_enabled = None


def transfer_fence(*arrays) -> None:
    """The ONE sanctioned ``jax.block_until_ready`` fence of the
    pipelined device loop. ``shard_put``/per-shard patch uploads
    dispatch asynchronously; the double-buffered score-state generations
    swap on commit, and THIS is where the swap synchronizes — never
    inside the upload path, or the overlap the pipeline exists to win
    is serialized away."""
    import jax

    for a in arrays:
        if a is not None:
            jax.block_until_ready(a)


def cpu_fallback_env(n_devices: int | None = None) -> dict:
    """A copy of os.environ steered to the CPU backend: JAX_PLATFORMS=cpu,
    the axon sitecustomize stripped from PYTHONPATH, and (optionally) a
    virtual ``n_devices``-device host platform via XLA_FLAGS."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":") if ".axon_site" not in p
    )
    if n_devices is not None:
        flags = env.get("XLA_FLAGS", "")
        flags = " ".join(
            f
            for f in flags.split()
            if "xla_force_host_platform_device_count" not in f
        )
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    return env
