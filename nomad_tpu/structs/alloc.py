"""Allocation model + per-placement explainability metrics.

Reference: structs.Allocation (nomad/structs/structs.go ~:8700),
structs.AllocMetric (:10034-10079 — nodes evaluated/filtered/exhausted and
per-node score breakdown surfaced by ``alloc status``), RescheduleTracker.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from .job import Job, ReschedulePolicy
from .resources import ComparableResources

ALLOC_DESIRED_RUN = "run"
ALLOC_DESIRED_STOP = "stop"
ALLOC_DESIRED_EVICT = "evict"

ALLOC_CLIENT_PENDING = "pending"
ALLOC_CLIENT_RUNNING = "running"
ALLOC_CLIENT_COMPLETE = "complete"
ALLOC_CLIENT_FAILED = "failed"
ALLOC_CLIENT_LOST = "lost"

TERMINAL_CLIENT_STATUSES = frozenset(
    {ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_LOST}
)


@dataclass(slots=True)
class NodeScoreMeta:
    """Per-node score breakdown recorded into AllocMetric.ScoreMetaData."""

    node_id: str = ""
    scores: dict[str, float] = field(default_factory=dict)
    norm_score: float = 0.0


@dataclass(slots=True)
class AllocMetric:
    """Why an allocation landed where it did (or why placement failed).
    Reference: structs.AllocMetric (structs.go:10034-10079)."""

    nodes_evaluated: int = 0
    nodes_filtered: int = 0
    nodes_available: dict[str, int] = field(default_factory=dict)  # dc → count
    class_filtered: dict[str, int] = field(default_factory=dict)
    constraint_filtered: dict[str, int] = field(default_factory=dict)
    nodes_exhausted: int = 0
    class_exhausted: dict[str, int] = field(default_factory=dict)
    dimension_exhausted: dict[str, int] = field(default_factory=dict)
    quota_exhausted: list[str] = field(default_factory=list)
    scores: dict[str, float] = field(default_factory=dict)
    score_meta: list[NodeScoreMeta] = field(default_factory=list)
    allocation_time_ns: int = 0
    coalesced_failures: int = 0
    # structured feasibility-rejection histogram from the explain seam
    # (obs/explain.py): reason key → node count, e.g. "exhausted:cpu",
    # "class-infeasible", "penalty-excluded". Finer-grained than the
    # reference's DimensionExhausted strings; rides blocked evals so
    # `eval status` can say what to drain or resize.
    rejections: dict[str, int] = field(default_factory=dict)

    def exhausted_node(self, node_id: str, dimension: str) -> None:
        self.nodes_exhausted += 1
        if dimension:
            self.dimension_exhausted[dimension] = (
                self.dimension_exhausted.get(dimension, 0) + 1
            )

    def filter_node(self, constraint: str) -> None:
        self.nodes_filtered += 1
        if constraint:
            self.constraint_filtered[constraint] = (
                self.constraint_filtered.get(constraint, 0) + 1
            )


@dataclass(slots=True)
class RescheduleEvent:
    reschedule_time_ns: int = 0
    prev_alloc_id: str = ""
    prev_node_id: str = ""
    delay_s: float = 0.0


@dataclass(slots=True)
class RescheduleTracker:
    events: list[RescheduleEvent] = field(default_factory=list)


@dataclass(slots=True)
class DesiredTransition:
    migrate: bool = False
    reschedule: bool = False
    force_reschedule: bool = False


@dataclass(slots=True)
class Allocation:
    """An instance of a task group placed on a node."""

    id: str = ""
    namespace: str = "default"
    eval_id: str = ""
    name: str = ""  # "<job>.<group>[<index>]"
    node_id: str = ""
    node_name: str = ""
    job_id: str = ""
    job: Optional[Job] = None
    job_version: int = 0
    task_group: str = ""
    resources: ComparableResources = field(default_factory=ComparableResources)
    # Concrete port/bandwidth assignments made by the plan applier's
    # NetworkIndex (list of structs.network.AllocatedNetwork).
    allocated_networks: list = field(default_factory=list)
    # Concrete device instances assigned by the scheduler's device
    # allocator (list of resources.AllocatedDeviceResource).
    allocated_devices: list = field(default_factory=list)
    desired_status: str = ALLOC_DESIRED_RUN
    desired_description: str = ""
    desired_transition: DesiredTransition = field(default_factory=DesiredTransition)
    client_status: str = ALLOC_CLIENT_PENDING
    client_description: str = ""
    task_states: dict[str, object] = field(default_factory=dict)
    deployment_id: str = ""
    deployment_status: Optional[object] = None
    canary: bool = False
    previous_allocation: str = ""
    next_allocation: str = ""
    reschedule_tracker: Optional[RescheduleTracker] = None
    followup_eval_id: str = ""
    preempted_by_allocation: str = ""
    preempted_allocations: list[str] = field(default_factory=list)
    metrics: AllocMetric = field(default_factory=AllocMetric)
    create_time_ns: int = 0
    modify_time_ns: int = 0
    create_index: int = 0
    modify_index: int = 0
    alloc_modify_index: int = 0

    def comparable_resources(self) -> ComparableResources:
        return self.resources

    def device_asks(self) -> dict[str, int]:
        """device id → requested instance count. Prefers the concrete
        assignment made at placement (full vendor/type/name ids); falls
        back to the attached job's asks (possibly partial ids)."""
        if self.allocated_devices:
            out: dict[str, int] = {}
            for ad in self.allocated_devices:
                out[ad.id()] = out.get(ad.id(), 0) + len(ad.device_ids)
            return out
        tg = self.job.lookup_task_group(self.task_group) if self.job else None
        if tg is None:
            return {}
        out = {}
        for t in tg.tasks:
            for d in t.resources.devices:
                out[d.name] = out.get(d.name, 0) + d.count
        return out

    def device_instance_ids(self) -> dict[str, set]:
        """device full-id → concrete instance ids held by this alloc."""
        out: dict[str, set] = {}
        for ad in self.allocated_devices:
            out.setdefault(ad.id(), set()).update(ad.device_ids)
        return out

    def terminal_status(self) -> bool:
        """Desired-or-actual terminal — structs.Allocation.TerminalStatus."""
        if self.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            return True
        return self.client_terminal_status()

    def client_terminal_status(self) -> bool:
        return self.client_status in TERMINAL_CLIENT_STATUSES

    def index(self) -> int:
        """Alloc name index: "job.group[3]" → 3."""
        lb = self.name.rfind("[")
        rb = self.name.rfind("]")
        if lb == -1 or rb == -1:
            return -1
        try:
            return int(self.name[lb + 1 : rb])
        except ValueError:
            return -1

    def job_namespaced_id(self) -> tuple[str, str]:
        return (self.namespace, self.job_id)

    def should_reschedule(
        self, policy: Optional[ReschedulePolicy], now_ns: Optional[int] = None
    ) -> bool:
        """Eligibility for replacement on another node after failure.
        Mirrors structs.Allocation.ShouldReschedule + RescheduleEligible."""
        if self.desired_status != ALLOC_DESIRED_RUN:
            return False
        if self.client_status not in (ALLOC_CLIENT_FAILED, ALLOC_CLIENT_LOST):
            return False
        if policy is None or (policy.attempts == 0 and not policy.unlimited):
            return False
        if policy.unlimited:
            return True
        now_ns = now_ns if now_ns is not None else time.time_ns()
        window_start = now_ns - int(policy.interval_s * 1e9)
        attempted = 0
        if self.reschedule_tracker:
            attempted = sum(
                1
                for ev in self.reschedule_tracker.events
                if ev.reschedule_time_ns >= window_start
            )
        return attempted < policy.attempts

    def next_reschedule_delay(self, policy: ReschedulePolicy) -> float:
        """Backoff delay for the followup eval (constant/exponential/fib).
        Mirrors structs.Allocation.NextDelay."""
        n = len(self.reschedule_tracker.events) if self.reschedule_tracker else 0
        base = policy.delay_s
        if policy.delay_function == "constant":
            delay = base
        elif policy.delay_function == "exponential":
            delay = base * (2**n)
        elif policy.delay_function == "fibonacci":
            a, b = base, base
            for _ in range(n):
                a, b = b, a + b
            delay = a
        else:
            delay = base
        if policy.max_delay_s > 0:
            delay = min(delay, policy.max_delay_s)
        return delay

    def copy_for_update(self) -> "Allocation":
        import copy

        return copy.copy(self)
