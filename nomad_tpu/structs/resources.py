"""Resource model and the fit/score kernels' host reference semantics.

This is the semantic ground truth the device kernels in
``nomad_tpu.device.score`` are validated against. Reference behavior:
nomad/structs/funcs.go:147-274 (AllocsFit, ScoreFitBinPack, ScoreFitSpread,
computeFreePercentage) and nomad/structs/structs.go (Resources,
NodeResources, ComparableResources).

Design note (TPU-first): every resource bundle can be flattened to a fixed
``float32[NUM_DIMS]`` vector via :meth:`ComparableResources.to_vector`, so
that cluster-wide fit checks and scores are dense tensor ops. The dim order
is the module-level ``RESOURCE_DIMS`` tuple and must stay stable — device
arrays, checkpoints, and the plan applier all index by it.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Iterable

import numpy as np

# Canonical dense resource dimensions. CPU in MHz, memory/disk in MiB,
# bandwidth in Mbits. Mirrors the axes AllocsFit checks in funcs.go:147-210.
RESOURCE_DIMS: tuple[str, ...] = ("cpu", "memory_mb", "disk_mb", "bandwidth_mbits")
NUM_DIMS = len(RESOURCE_DIMS)

# ScoreFitBinPack constants — nomad/structs/funcs.go:236-256. The score is
# ``20 - 10^freeCpuFrac - 10^freeMemFrac`` clamped to [0, 18] ("BestFit v3"
# from Google's Borg-adjacent work), later normalized by /18 in the ranker
# (scheduler/rank.go:513-516).
BINPACK_MAX_SCORE = 18.0


@dataclass(slots=True)
class NetworkResource:
    """A requested or fingerprinted network. Port accounting itself is
    host-side (see nomad_tpu.structs.network); scores use MBits only."""

    mode: str = "host"
    device: str = ""
    ip: str = ""
    mbits: int = 0
    reserved_ports: list[int] = field(default_factory=list)
    dynamic_ports: list[str] = field(default_factory=list)  # labels


@dataclass(slots=True)
class RequestedDevice:
    """A device ask, e.g. ``gpu`` / ``nvidia/gpu/k80`` with count.
    Reference: structs.RequestedDevice (nomad/structs/structs.go)."""

    name: str = ""
    count: int = 1
    constraints: list = field(default_factory=list)
    affinities: list = field(default_factory=list)


@dataclass(slots=True)
class Resources:
    """A task's resource ask. Reference: structs.Resources."""

    cpu: int = 100
    memory_mb: int = 300
    disk_mb: int = 0
    networks: list[NetworkResource] = field(default_factory=list)
    devices: list[RequestedDevice] = field(default_factory=list)

    def bandwidth_mbits(self) -> int:
        return sum(n.mbits for n in self.networks)

    def add(self, other: "Resources") -> None:
        self.cpu += other.cpu
        self.memory_mb += other.memory_mb
        self.disk_mb += other.disk_mb

    def to_vector(self) -> np.ndarray:
        return np.array(
            [self.cpu, self.memory_mb, self.disk_mb, self.bandwidth_mbits()],
            dtype=np.float32,
        )


@dataclass(slots=True)
class NodeReservedResources:
    """Resources carved out of a node for the OS/agent.
    Reference: structs.NodeReservedResources."""

    cpu: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    reserved_ports: list[int] = field(default_factory=list)


@dataclass(slots=True)
class NodeDeviceInstance:
    id: str = ""
    healthy: bool = True


@dataclass(slots=True)
class NodeDeviceResource:
    """One device group on a node (vendor/type/name with instances).
    Reference: structs.NodeDeviceResource."""

    vendor: str = ""
    type: str = ""
    name: str = ""
    instances: list[NodeDeviceInstance] = field(default_factory=list)
    attributes: dict[str, object] = field(default_factory=dict)

    def id(self) -> str:
        return f"{self.vendor}/{self.type}/{self.name}"

    def matches(self, ask: RequestedDevice) -> bool:
        """Device name matching per nomad/scheduler/device.go:32-131:
        the ask may be ``type``, ``vendor/type``, or ``vendor/type/name``."""
        parts = ask.name.split("/")
        if len(parts) == 1:
            return parts[0] == self.type
        if len(parts) == 2:
            return parts[0] == self.vendor and parts[1] == self.type
        return (
            parts[0] == self.vendor
            and parts[1] == self.type
            and parts[2] == self.name
        )


@dataclass(slots=True)
class AllocatedDeviceResource:
    """Concrete device instances assigned to an allocation.
    Reference: structs.AllocatedDeviceResource (nomad/structs/structs.go)."""

    vendor: str = ""
    type: str = ""
    name: str = ""
    device_ids: list[str] = field(default_factory=list)

    def id(self) -> str:
        return f"{self.vendor}/{self.type}/{self.name}"


@dataclass(slots=True)
class NodeResources:
    """A node's fingerprinted capacity. Reference: structs.NodeResources."""

    cpu: int = 4000
    memory_mb: int = 8192
    disk_mb: int = 100 * 1024
    networks: list[NetworkResource] = field(default_factory=list)
    devices: list[NodeDeviceResource] = field(default_factory=list)

    def bandwidth_mbits(self) -> int:
        return sum(n.mbits for n in self.networks) or 1000

    def to_vector(self) -> np.ndarray:
        return np.array(
            [self.cpu, self.memory_mb, self.disk_mb, self.bandwidth_mbits()],
            dtype=np.float32,
        )


@dataclass(slots=True)
class ComparableResources:
    """Flattened (summed over tasks) resources used for fit and scoring.
    Reference: structs.ComparableResources / AllocatedResources.Comparable()."""

    cpu: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    bandwidth_mbits: int = 0

    @classmethod
    def from_task_resources(cls, asks: Iterable[Resources]) -> "ComparableResources":
        out = cls()
        for r in asks:
            out.cpu += r.cpu
            out.memory_mb += r.memory_mb
            out.disk_mb += r.disk_mb
            out.bandwidth_mbits += r.bandwidth_mbits()
        return out

    def add(self, other: "ComparableResources") -> None:
        self.cpu += other.cpu
        self.memory_mb += other.memory_mb
        self.disk_mb += other.disk_mb
        self.bandwidth_mbits += other.bandwidth_mbits

    def superset(self, other: "ComparableResources") -> tuple[bool, str]:
        """Does self contain other? Mirrors ComparableResources.Superset."""
        if self.cpu < other.cpu:
            return False, "cpu"
        if self.memory_mb < other.memory_mb:
            return False, "memory"
        if self.disk_mb < other.disk_mb:
            return False, "disk"
        return True, ""

    def to_vector(self) -> np.ndarray:
        return np.array(
            [self.cpu, self.memory_mb, self.disk_mb, self.bandwidth_mbits],
            dtype=np.float32,
        )

    @classmethod
    def from_vector(cls, v) -> "ComparableResources":
        return cls(
            cpu=int(v[0]),
            memory_mb=int(v[1]),
            disk_mb=int(v[2]),
            bandwidth_mbits=int(v[3]),
        )

    def copy(self) -> "ComparableResources":
        return replace(self)


def node_comparable_capacity(node) -> ComparableResources:
    """The node's schedulable capacity: fingerprinted resources minus the
    OS/agent reserved carve-out. Mirrors Node.ComparableResources() —
    all fit checks and score denominators use this, never raw capacity."""
    cap = node.node_resources
    return ComparableResources(
        cpu=cap.cpu - node.reserved.cpu,
        memory_mb=cap.memory_mb - node.reserved.memory_mb,
        disk_mb=cap.disk_mb - node.reserved.disk_mb,
        bandwidth_mbits=cap.bandwidth_mbits(),
    )


def allocs_fit(
    node,  # structs.node.Node
    allocs,  # Iterable[has .comparable_resources()]
    *,
    check_devices: bool = False,
) -> tuple[bool, str, ComparableResources]:
    """Host reference of AllocsFit (nomad/structs/funcs.go:147-210).

    Sums the proposed allocations' comparable resources (terminal allocs
    skipped, as in the reference) and checks the node's reserved-adjusted
    capacity is a superset. Returns (fits, failure_dimension, used) where
    ``used`` excludes the reserved carve-out. Port-collision checking is
    the plan applier's job (NetworkIndex), matching the reference split
    where the scheduler guesses and the applier verifies
    (nomad/plan_apply.go:638-689).
    """
    used = ComparableResources()
    live = []
    for alloc in allocs:
        if getattr(alloc, "terminal_status", None) and alloc.terminal_status():
            continue
        live.append(alloc)
        used.add(alloc.comparable_resources())

    ok, dim = node_comparable_capacity(node).superset(used)
    if not ok:
        return False, dim, used

    if check_devices:
        ok, dim = _device_accounting_fits(node, live)
        if not ok:
            return False, dim, used

    return True, "", used


def _device_accounting_fits(node, allocs) -> tuple[bool, str]:
    """Count device instance usage vs capacity with a shared pool.
    Mirrors structs.DeviceAccounter (nomad/structs/devices.go): asks drain
    one common per-device-group pool, so overlapping partial ids (``gpu``
    and ``nvidia/gpu/k80``) cannot jointly overcommit. Most-specific asks
    are resolved first so a full-id ask isn't starved by a wildcard one."""
    cap: dict[str, int] = {}
    for dev in node.node_resources.devices:
        cap[dev.id()] = cap.get(dev.id(), 0) + sum(
            1 for i in dev.instances if i.healthy
        )
    asks: dict[str, int] = {}
    for alloc in allocs:
        for dev_id, count in getattr(alloc, "device_asks", lambda: {})().items():
            asks[dev_id] = asks.get(dev_id, 0) + count
    for dev_id in sorted(asks, key=lambda d: -d.count("/")):
        need = asks[dev_id]
        for cid in sorted(c for c in cap if _dev_id_matches(c, dev_id)):
            take = min(cap[cid], need)
            cap[cid] -= take
            need -= take
            if need == 0:
                break
        if need > 0:
            return False, f"device {dev_id}"
    return True, ""


def _dev_id_matches(full_id: str, ask_id: str) -> bool:
    vendor, typ, name = full_id.split("/")
    parts = ask_id.split("/")
    if len(parts) == 1:
        return parts[0] == typ
    if len(parts) == 2:
        return parts[:2] == [vendor, typ]
    return parts[:3] == [vendor, typ, name]


def _free_fraction(capacity: float, used: float) -> float:
    """computeFreePercentage (funcs.go:212-229): free fraction in [?, 1].
    A zero-capacity dimension counts as fully free (fraction 1)."""
    if capacity <= 0:
        return 1.0
    return (capacity - used) / capacity


def score_fit_binpack(node, used: ComparableResources) -> float:
    """ScoreFitBinPack (funcs.go:236-256): BestFit-v3.

    ``score = 20 - 10^freeCpuFrac - 10^freeMemFrac`` clamped to
    [0, BINPACK_MAX_SCORE]. Higher utilization ⇒ higher score (packing).
    ``used`` excludes the reserved carve-out; fractions are over the
    reserved-adjusted capacity (computeFreePercentage subtracts reserved
    from the denominator, funcs.go:212-229).
    """
    cap = node_comparable_capacity(node)
    free_cpu = _free_fraction(cap.cpu, used.cpu)
    free_mem = _free_fraction(cap.memory_mb, used.memory_mb)
    total = math.pow(10.0, free_cpu) + math.pow(10.0, free_mem)
    score = 20.0 - total
    return max(0.0, min(BINPACK_MAX_SCORE, score))


def score_fit_spread(node, used: ComparableResources) -> float:
    """ScoreFitSpread (funcs.go:263-274): inverse of binpack — prefer
    emptier nodes. ``score = 10^freeCpu + 10^freeMem - 2`` clamped."""
    cap = node_comparable_capacity(node)
    free_cpu = _free_fraction(cap.cpu, used.cpu)
    free_mem = _free_fraction(cap.memory_mb, used.memory_mb)
    score = math.pow(10.0, free_cpu) + math.pow(10.0, free_mem) - 2.0
    return max(0.0, min(BINPACK_MAX_SCORE, score))
