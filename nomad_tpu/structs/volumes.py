"""Volume model: host volumes and CSI volumes with claim accounting.

Reference: structs.ClientHostVolumeConfig + VolumeRequest + VolumeMount
(nomad/structs/volumes.go), structs.CSIVolume / CSIPlugin / claim modes
(nomad/structs/csi.go), checked by HostVolumeChecker
(scheduler/feasible.go:132-207) and CSIVolumeChecker (:209-339), released
by the volume watcher (nomad/volumewatcher/).

TPU note: volume feasibility is host-side per node (host volumes are node
config, CSI claims are counted state) and folds into the dense eligibility
mask like every other hard constraint (device/flatten.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

VOLUME_TYPE_HOST = "host"
VOLUME_TYPE_CSI = "csi"

# CSI access modes (structs/csi.go CSIVolumeAccessMode)
ACCESS_MODE_SINGLE_NODE_READER = "single-node-reader-only"
ACCESS_MODE_SINGLE_NODE_WRITER = "single-node-writer"
ACCESS_MODE_MULTI_NODE_READER = "multi-node-reader-only"
ACCESS_MODE_MULTI_NODE_SINGLE_WRITER = "multi-node-single-writer"
ACCESS_MODE_MULTI_NODE_MULTI_WRITER = "multi-node-multi-writer"

ATTACHMENT_MODE_FILE_SYSTEM = "file-system"
ATTACHMENT_MODE_BLOCK_DEVICE = "block-device"


@dataclass(slots=True)
class ClientHostVolumeConfig:
    """A host directory exposed by a node (client config ``host_volume``).
    Reference: structs.ClientHostVolumeConfig."""

    name: str = ""
    path: str = ""
    read_only: bool = False


@dataclass(slots=True)
class VolumeRequest:
    """A task group's ask for a volume (group ``volume`` block).
    Reference: structs.VolumeRequest."""

    name: str = ""
    type: str = VOLUME_TYPE_HOST
    source: str = ""
    read_only: bool = False
    per_alloc: bool = False
    access_mode: str = ""
    attachment_mode: str = ""


@dataclass(slots=True)
class VolumeMount:
    """A task's mount of a group volume (task ``volume_mount`` block).
    Reference: structs.VolumeMount."""

    volume: str = ""
    destination: str = ""
    read_only: bool = False
    propagation_mode: str = "private"


@dataclass(slots=True)
class CSITopology:
    segments: dict[str, str] = field(default_factory=dict)


@dataclass(slots=True)
class CSIVolume:
    """A registered CSI volume with claim state.
    Reference: structs.CSIVolume (nomad/structs/csi.go)."""

    id: str = ""
    namespace: str = "default"
    name: str = ""
    external_id: str = ""
    plugin_id: str = ""
    access_mode: str = ACCESS_MODE_SINGLE_NODE_WRITER
    attachment_mode: str = ATTACHMENT_MODE_FILE_SYSTEM
    schedulable: bool = True
    # alloc id → node id, split by claim kind
    read_claims: dict[str, str] = field(default_factory=dict)
    write_claims: dict[str, str] = field(default_factory=dict)
    # claims being detached by the volume watcher
    past_claims: dict[str, str] = field(default_factory=dict)
    # claim ids registered via the Claim API by non-alloc claimants; the
    # volume watcher must not reap these as "alloc gone"
    external_claims: set[str] = field(default_factory=set)
    topologies: list[CSITopology] = field(default_factory=list)
    context: dict[str, str] = field(default_factory=dict)
    capacity_bytes: int = 0
    create_index: int = 0
    modify_index: int = 0

    # -- claim logic (structs/csi.go CSIVolume.Claim*) --------------------
    def write_free(self) -> bool:
        if self.access_mode == ACCESS_MODE_SINGLE_NODE_WRITER:
            return len(self.write_claims) == 0
        if self.access_mode == ACCESS_MODE_MULTI_NODE_SINGLE_WRITER:
            return len(self.write_claims) == 0
        if self.access_mode == ACCESS_MODE_MULTI_NODE_MULTI_WRITER:
            return True
        return False  # reader-only modes never admit writers

    def read_free(self) -> bool:
        if self.access_mode in (
            ACCESS_MODE_SINGLE_NODE_READER,
            ACCESS_MODE_SINGLE_NODE_WRITER,
        ):
            # single-node: one claimant total
            return not self.read_claims and not self.write_claims
        return True

    def claimable(self, read_only: bool) -> bool:
        if not self.schedulable:
            return False
        return self.read_free() if read_only else self.write_free()

    def claim(self, alloc_id: str, node_id: str, read_only: bool) -> bool:
        if not self.claimable(read_only):
            return False
        (self.read_claims if read_only else self.write_claims)[alloc_id] = node_id
        return True

    def release(self, alloc_id: str) -> bool:
        found = False
        for claims in (self.read_claims, self.write_claims, self.past_claims):
            if alloc_id in claims:
                del claims[alloc_id]
                found = True
        self.external_claims.discard(alloc_id)
        return found

    def in_use(self) -> bool:
        return bool(self.read_claims or self.write_claims)


@dataclass(slots=True)
class CSIPlugin:
    """Aggregated health of a CSI plugin's controller/node instances.
    Reference: structs.CSIPlugin — derived state, updated as nodes
    fingerprint plugin instances."""

    id: str = ""
    provider: str = ""
    version: str = ""
    controller_required: bool = False
    controllers_healthy: int = 0
    nodes_healthy: int = 0
    create_index: int = 0
    modify_index: int = 0


@dataclass(slots=True)
class CSINodeInfo:
    """Per-node CSI plugin presence (node fingerprint of a running node
    plugin). Reference: structs.CSIInfo on Node.CSINodePlugins."""

    plugin_id: str = ""
    healthy: bool = True
    requires_topology: bool = False
    accessible_topology: Optional[CSITopology] = None
    max_volumes: int = 0  # 0 = unlimited
