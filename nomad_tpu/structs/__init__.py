"""Shared data model — the L5 "structs" layer (SURVEY.md §1)."""

from .resources import (
    BINPACK_MAX_SCORE,
    NUM_DIMS,
    RESOURCE_DIMS,
    ComparableResources,
    NetworkResource,
    NodeDeviceInstance,
    NodeDeviceResource,
    NodeReservedResources,
    NodeResources,
    RequestedDevice,
    Resources,
    allocs_fit,
    score_fit_binpack,
    score_fit_spread,
)
from .job import (
    DEFAULT_NAMESPACE,
    JOB_DEFAULT_PRIORITY,
    JOB_STATUS_DEAD,
    JOB_STATUS_PENDING,
    JOB_STATUS_RUNNING,
    JOB_TYPE_BATCH,
    JOB_TYPE_CORE,
    JOB_TYPE_SERVICE,
    JOB_TYPE_SYSBATCH,
    JOB_TYPE_SYSTEM,
    Affinity,
    Constraint,
    EphemeralDisk,
    Job,
    MigrateStrategy,
    ParameterizedJobConfig,
    PeriodicConfig,
    ReschedulePolicy,
    RestartPolicy,
    Spread,
    SpreadTarget,
    Task,
    TaskGroup,
    UpdateStrategy,
)
from .node import (
    NODE_SCHED_ELIGIBLE,
    NODE_SCHED_INELIGIBLE,
    NODE_STATUS_DOWN,
    NODE_STATUS_INIT,
    NODE_STATUS_READY,
    DrainStrategy,
    Node,
)
from .alloc import (
    ALLOC_CLIENT_COMPLETE,
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_LOST,
    ALLOC_CLIENT_PENDING,
    ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_EVICT,
    ALLOC_DESIRED_RUN,
    ALLOC_DESIRED_STOP,
    AllocMetric,
    Allocation,
    DesiredTransition,
    NodeScoreMeta,
    RescheduleEvent,
    RescheduleTracker,
)
from .evaluation import (
    EVAL_STATUS_BLOCKED,
    EVAL_STATUS_CANCELLED,
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
    EVAL_STATUS_PENDING,
    TRIGGER_JOB_REGISTER,
    TRIGGER_MAX_PLANS,
    TRIGGER_NODE_UPDATE,
    TRIGGER_QUEUED_ALLOCS,
    Evaluation,
    new_id,
)
from .plan import DesiredUpdates, Plan, PlanAnnotations, PlanResult
from .network import AllocatedNetwork, AllocatedPort, NetworkIndex
from .volumes import (
    CSINodeInfo,
    CSIPlugin,
    CSIVolume,
    ClientHostVolumeConfig,
    VolumeMount,
    VolumeRequest,
)

__all__ = [n for n in dir() if not n.startswith("_")]
