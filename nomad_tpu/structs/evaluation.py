"""Evaluation — the unit of scheduler work.

Reference: structs.Evaluation (nomad/structs/structs.go ~:10150) and the
trigger taxonomy. An evaluation says "something changed for job J; bring
desired and actual state back into agreement".
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

EVAL_STATUS_BLOCKED = "blocked"
EVAL_STATUS_PENDING = "pending"
EVAL_STATUS_COMPLETE = "complete"
EVAL_STATUS_FAILED = "failed"
EVAL_STATUS_CANCELLED = "canceled"

TRIGGER_JOB_REGISTER = "job-register"
TRIGGER_JOB_DEREGISTER = "job-deregister"
TRIGGER_PERIODIC_JOB = "periodic-job"
TRIGGER_NODE_DRAIN = "node-drain"
TRIGGER_NODE_UPDATE = "node-update"
TRIGGER_ALLOC_STOP = "alloc-stop"
TRIGGER_SCHEDULED = "scheduled"
TRIGGER_ROLLING_UPDATE = "rolling-update"
TRIGGER_DEPLOYMENT_WATCHER = "deployment-watcher"
TRIGGER_FAILED_FOLLOW_UP = "failed-follow-up"
TRIGGER_MAX_PLANS = "max-plan-attempts"
TRIGGER_RETRY_FAILED_ALLOC = "alloc-failure"
TRIGGER_QUEUED_ALLOCS = "queued-allocs"
TRIGGER_PREEMPTION = "preemption"
TRIGGER_JOB_SCALING = "job-scaling"

# Ack/Nack redelivery caps — nomad/structs/structs.go DeliveryLimit handling
# plus eval_broker nack timeout semantics.
EVAL_DELIVERY_LIMIT = 3


def new_id() -> str:
    """UUIDv4-formatted random id. Hand-rolled over uuid.uuid4(): the
    library constructor costs ~18µs apiece in object plumbing, and alloc
    creation mints tens of thousands per burst (profiled at 0.35s of a
    3.7s commit window); direct urandom + formatting is ~5× cheaper and
    produces the same 122-bit-random RFC-4122 shape."""
    b = bytearray(os.urandom(16))
    b[6] = (b[6] & 0x0F) | 0x40  # version 4
    b[8] = (b[8] & 0x3F) | 0x80  # variant 10
    h = b.hex()
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"


@dataclass(slots=True)
class AllocStopRequest:
    alloc_id: str = ""
    no_shutdown_delay: bool = False


@dataclass(slots=True)
class Evaluation:
    id: str = field(default_factory=new_id)
    namespace: str = "default"
    priority: int = 50
    type: str = "service"  # mirrors the job type; selects the scheduler
    triggered_by: str = TRIGGER_JOB_REGISTER
    job_id: str = ""
    job_modify_index: int = 0
    node_id: str = ""
    node_modify_index: int = 0
    deployment_id: str = ""
    status: str = EVAL_STATUS_PENDING
    status_description: str = ""
    wait_until_unix: float = 0.0
    next_eval: str = ""
    previous_eval: str = ""
    blocked_eval: str = ""
    related_evals: list[str] = field(default_factory=list)
    failed_tg_allocs: dict[str, object] = field(default_factory=dict)
    class_eligibility: dict[str, bool] = field(default_factory=dict)
    escaped_computed_class: bool = False
    quota_limit_reached: str = ""
    annotate_plan: bool = False
    queued_allocations: dict[str, int] = field(default_factory=dict)
    leader_acl: str = ""
    # worker processing-deadline expiries survived so far (resilience
    # layer); at the server's eval_attempt_limit the eval is marked
    # failed with a structured status_description instead of re-nacked
    attempts: int = 0
    snapshot_index: int = 0
    create_index: int = 0
    modify_index: int = 0
    create_time_ns: int = 0
    modify_time_ns: int = 0

    def terminal_status(self) -> bool:
        return self.status in (
            EVAL_STATUS_COMPLETE,
            EVAL_STATUS_FAILED,
            EVAL_STATUS_CANCELLED,
        )

    def should_enqueue(self) -> bool:
        return self.status == EVAL_STATUS_PENDING

    def should_block(self) -> bool:
        return self.status == EVAL_STATUS_BLOCKED

    def make_plan(self, job) -> "object":
        from .plan import Plan

        return Plan(
            eval_id=self.id,
            priority=self.priority if job is None else job.priority,
            job=job,
            all_at_once=False if job is None else job.all_at_once,
        )

    def create_blocked_eval(
        self,
        class_eligibility: dict[str, bool],
        escaped: bool,
        quota_reached: str,
        failed_tg_allocs: dict,
    ) -> "Evaluation":
        """Blocked-eval factory — structs.Evaluation.CreateBlockedEval;
        used by generic_sched.go:193-212 when placements fail."""
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=TRIGGER_QUEUED_ALLOCS,
            job_id=self.job_id,
            status=EVAL_STATUS_BLOCKED,
            previous_eval=self.id,
            class_eligibility=class_eligibility,
            escaped_computed_class=escaped,
            quota_limit_reached=quota_reached,
            failed_tg_allocs=dict(failed_tg_allocs),
        )

    def create_failed_follow_up_eval(self, wait_s: float, now: float) -> "Evaluation":
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=TRIGGER_FAILED_FOLLOW_UP,
            job_id=self.job_id,
            status=EVAL_STATUS_PENDING,
            wait_until_unix=now + wait_s,
            previous_eval=self.id,
        )
