"""Plan — a scheduler's proposed state mutation, and its applied result.

Reference: structs.Plan / structs.PlanResult (nomad/structs/structs.go
~:10400). Plans are optimistic: built against a possibly-stale snapshot,
re-verified node-by-node by the leader's serialized plan applier
(nomad/plan_apply.go:400-689) which may partially commit and hand back a
``refresh_index`` so the worker can retry the remainder on fresher state.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .alloc import (
    ALLOC_CLIENT_LOST,
    ALLOC_DESIRED_EVICT,
    ALLOC_DESIRED_STOP,
    Allocation,
)
from .job import Job


@dataclass(slots=True)
class DesiredUpdates:
    """Per-task-group annotation counts for dry-run plans
    (scheduler/annotate.go)."""

    ignore: int = 0
    place: int = 0
    migrate: int = 0
    stop: int = 0
    in_place_update: int = 0
    destructive_update: int = 0
    canary: int = 0
    preemptions: int = 0


@dataclass(slots=True)
class PlanAnnotations:
    desired_tg_updates: dict[str, DesiredUpdates] = field(default_factory=dict)
    preempted_allocs: list[Allocation] = field(default_factory=list)


@dataclass(slots=True)
class Plan:
    eval_id: str = ""
    eval_token: str = ""
    priority: int = 50
    all_at_once: bool = False
    job: Optional[Job] = None
    # node id → allocs to stop/evict on that node
    node_update: dict[str, list[Allocation]] = field(default_factory=dict)
    # node id → new/updated allocs on that node
    node_allocation: dict[str, list[Allocation]] = field(default_factory=dict)
    # node id → allocs preempted to make room
    node_preemptions: dict[str, list[Allocation]] = field(default_factory=dict)
    deployment: Optional[object] = None
    deployment_updates: list = field(default_factory=list)
    annotations: Optional[PlanAnnotations] = None
    snapshot_index: int = 0

    def append_stopped_alloc(
        self, alloc: Allocation, desired_desc: str, client_status: str = ""
    ) -> None:
        """Plan.AppendStoppedAlloc — record a stop with its reason."""
        a = alloc.copy_for_update()
        a.desired_status = ALLOC_DESIRED_STOP
        a.desired_description = desired_desc
        if client_status:
            a.client_status = client_status
        self.node_update.setdefault(alloc.node_id, []).append(a)

    def append_alloc(self, alloc: Allocation) -> None:
        self.node_allocation.setdefault(alloc.node_id, []).append(alloc)

    def append_preempted_alloc(self, alloc: Allocation, preempting_id: str) -> None:
        a = alloc.copy_for_update()
        a.desired_status = ALLOC_DESIRED_EVICT
        a.desired_description = f"Preempted by alloc ID {preempting_id}"
        a.preempted_by_allocation = preempting_id
        self.node_preemptions.setdefault(alloc.node_id, []).append(a)

    def append_lost_alloc(self, alloc: Allocation) -> None:
        self.append_stopped_alloc(
            alloc, "alloc lost since node is down", ALLOC_CLIENT_LOST
        )

    def is_no_op(self) -> bool:
        return (
            not self.node_update
            and not self.node_allocation
            and not self.node_preemptions
            and self.deployment is None
            and not self.deployment_updates
        )

    def placed_allocs(self) -> list[Allocation]:
        return [a for allocs in self.node_allocation.values() for a in allocs]

    def normalize(self) -> None:
        """Strip the heavyweight Job pointer from every alloc before
        shipping the plan over the wire — mirrors Plan.Normalize /
        Allocation.Stub to keep plan-apply payloads small."""
        for bucket in (self.node_allocation, self.node_update, self.node_preemptions):
            for allocs in bucket.values():
                for a in allocs:
                    a.job = None


@dataclass(slots=True)
class MergedPlan:
    """One batched pass's plans coalesced into a single commit unit.

    Member plans stay intact — per-eval attribution on every placement,
    update, and preemption is the member plan itself — so the applier can
    verify the UNION of touched nodes once, yet still reject (and hand a
    ``refresh_index`` retry to) exactly the member whose placements went
    stale, without failing its batch siblings. The whole merged result
    lands as ONE FSM entry and one store index bump, which is the entire
    point: a batched device pass that scored B evals in one kernel call
    no longer pays B serialized verify/commit round trips.
    """

    plans: list[Plan] = field(default_factory=list)
    # lane mode: the batching worker that built this commit. With lanes
    # active the applier ASSERTS every touched node is either owned by
    # this worker or covered by one of the attached (confirmed)
    # cross-lane claims — a violation is a structural bug, counted as
    # nomad.plan.lane_conflicts and pinned at zero by invariant law 9.
    owner_worker: int = -1
    # confirmed LaneClaim objects riding this commit. Host-side only:
    # never serialized into the raft entry (commit_merged ships results).
    claims: list = field(default_factory=list)

    @property
    def priority(self) -> int:
        """Queue priority: a merged entry sorts by its most urgent member
        (the batch dequeue already grouped by readiness, not priority)."""
        return max((p.priority for p in self.plans), default=50)

    def eval_ids(self) -> list[str]:
        return [p.eval_id for p in self.plans]

    def normalize(self) -> None:
        for p in self.plans:
            p.normalize()


@dataclass(slots=True)
class PlanResult:
    """What the applier actually committed."""

    node_update: dict[str, list[Allocation]] = field(default_factory=dict)
    node_allocation: dict[str, list[Allocation]] = field(default_factory=dict)
    node_preemptions: dict[str, list[Allocation]] = field(default_factory=dict)
    rejected_nodes: list[str] = field(default_factory=list)
    deployment: Optional[object] = None
    deployment_updates: list = field(default_factory=list)
    refresh_index: int = 0
    alloc_index: int = 0
    # set by the applier when the plan's broker token was no longer the
    # eval's outstanding token at apply time (unack-deadline redelivery
    # handed the eval to another worker) — nothing was committed and the
    # submitter must NOT retry: the redelivered copy owns the eval now
    token_stale: bool = False

    def is_no_op(self) -> bool:
        return (
            not self.node_update
            and not self.node_allocation
            and not self.node_preemptions
        )

    def full_commit(self, plan: Plan) -> tuple[bool, int, int]:
        """Did every proposed alloc commit? Returns (full, expected, actual).
        Mirrors PlanResult.FullCommit (used at generic_sched.go:317-324)."""
        expected = sum(len(v) for v in plan.node_allocation.values())
        actual = sum(len(v) for v in self.node_allocation.values())
        return expected == actual, expected, actual
