"""Node model: fingerprinted attributes, capacity, drain/eligibility state.

Reference: structs.Node (nomad/structs/structs.go ~:1900), computed node
class (nomad/structs/node_class.go) — the memoization key that lets
feasibility be evaluated once per *class* instead of once per node
(scheduler/feasible.go:1029-1153). In the TPU design the computed class is
also the unit at which host-side regex/semver constraints are pre-evaluated
before being broadcast into the device eligibility mask.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Optional

from .resources import NodeReservedResources, NodeResources

NODE_STATUS_INIT = "initializing"
NODE_STATUS_READY = "ready"
NODE_STATUS_DOWN = "down"

NODE_SCHED_ELIGIBLE = "eligible"
NODE_SCHED_INELIGIBLE = "ineligible"


@dataclass(slots=True)
class DrainStrategy:
    """Reference: structs.DrainStrategy."""

    deadline_s: float = 0.0  # <0: force drain now; 0: no deadline
    ignore_system_jobs: bool = False
    force_deadline_unix: float = 0.0


@dataclass(slots=True)
class Node:
    id: str = ""
    name: str = ""
    datacenter: str = "dc1"
    node_class: str = ""
    # accelerator class for heterogeneity-aware scheduling (Gavel-style):
    # e.g. "tpu-v5e", "tpu-v4", "gpu-a100", "cpu". "" means class-less —
    # the node participates in scheduling exactly as before this field
    # existed (throughput coefficient 1.0 for every job).
    device_class: str = ""
    # physical placement coordinates for gang/topology-aware scheduling:
    # level → id, e.g. {"rack": "r03", "pod": "p1", "ici": "2.1"}. Empty
    # means topology-less — the node participates in scheduling exactly
    # as before this field existed (every topology term contributes 0).
    topology: dict[str, str] = field(default_factory=dict)
    attributes: dict[str, str] = field(default_factory=dict)
    meta: dict[str, str] = field(default_factory=dict)
    links: dict[str, str] = field(default_factory=dict)
    node_resources: NodeResources = field(default_factory=NodeResources)
    reserved: NodeReservedResources = field(default_factory=NodeReservedResources)
    drivers: dict[str, bool] = field(default_factory=dict)  # driver → healthy
    # name → ClientHostVolumeConfig (client config host_volume blocks)
    host_volumes: dict[str, object] = field(default_factory=dict)
    # plugin id → CSINodeInfo (fingerprinted running node plugins)
    csi_node_plugins: dict[str, object] = field(default_factory=dict)
    status: str = NODE_STATUS_INIT
    status_description: str = ""
    scheduling_eligibility: str = NODE_SCHED_ELIGIBLE
    drain: Optional[DrainStrategy] = None
    computed_class: str = ""
    status_updated_at: float = 0.0
    create_index: int = 0
    modify_index: int = 0

    def ready(self) -> bool:
        """Node can accept new work — structs.Node.Ready()."""
        return (
            self.status == NODE_STATUS_READY
            and self.drain is None
            and self.scheduling_eligibility == NODE_SCHED_ELIGIBLE
        )

    def terminal_status(self) -> bool:
        return self.status == NODE_STATUS_DOWN

    def compute_class(self) -> None:
        """Hash scheduling-relevant fields into ``computed_class``.
        Mirrors structs.Node.ComputeClass (node_class.go): nodes with equal
        hashes are interchangeable for feasibility, enabling per-class
        memoization and, here, per-class host pre-evaluation of constraint
        operators the device can't run (regex/version)."""
        h = hashlib.blake2b(digest_size=8)
        h.update(self.datacenter.encode())
        h.update(self.node_class.encode())
        for k in sorted(self.attributes):
            if k.startswith("unique."):
                continue
            h.update(k.encode())
            h.update(str(self.attributes[k]).encode())
        for k in sorted(self.meta):
            if k.startswith("unique."):
                continue
            h.update(k.encode())
            h.update(str(self.meta[k]).encode())
        for d in sorted(self.drivers):
            if self.drivers[d]:
                h.update(d.encode())
        for name in sorted(self.host_volumes):
            hv = self.host_volumes[name]
            h.update(f"hv:{name}:{getattr(hv, 'read_only', False)}".encode())
        h.update(self.node_resources.to_vector().tobytes())
        # device_class participates unconditionally: two nodes differing
        # only in accelerator class must never share a computed class, or
        # the per-class feasibility memo (and the device cache keyed on
        # it) silently treats a v5e and a CPU box as interchangeable.
        h.update(b"dev:")
        h.update(self.device_class.encode())
        # topology participates for the same reason: a rack/pod flip must
        # flip the computed class so the device cache (keyed on the class
        # hash) rebuilds its topology id columns.
        h.update(b"topo:")
        for k in sorted(self.topology):
            h.update(k.encode())
            h.update(str(self.topology[k]).encode())
        self.computed_class = "v2:" + h.hexdigest()

    def lookup_attribute(self, target: str) -> Optional[str]:
        """Resolve a constraint LTarget like ``${attr.kernel.name}``,
        ``${node.datacenter}``, ``${meta.rack}`` against this node.
        Mirrors scheduler/feasible.go:748-781 (resolveTarget)."""
        t = target
        if t.startswith("${") and t.endswith("}"):
            t = t[2:-1]
        if t == "node.unique.id":
            return self.id
        if t == "node.unique.name":
            return self.name
        if t == "node.datacenter":
            return self.datacenter
        if t == "node.region":
            return self.attributes.get("platform.region", "global")
        if t == "node.class":
            return self.node_class
        if t == "node.device_class":
            return self.device_class
        if t.startswith("node.topology."):
            return self.topology.get(t[len("node.topology."):])
        if t.startswith("attr."):
            return self.attributes.get(t[len("attr."):])
        if t.startswith("meta."):
            return self.meta.get(t[len("meta."):])
        if t.startswith("node.attr."):
            return self.attributes.get(t[len("node.attr."):])
        if t.startswith("node.meta."):
            return self.meta.get(t[len("node.meta."):])
        return None
