"""Deployment model — tracks the rollout of one job version.

Reference: structs.Deployment / DeploymentState / AllocDeploymentStatus
(nomad/structs/structs.go ~:9200) driven by the deployment watcher
(nomad/deploymentwatcher/). A deployment exists per (job, version) while a
rolling update / canary release is in flight; per-group state carries the
canary and health bookkeeping the reconciler gates on.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Optional

DEPLOYMENT_STATUS_RUNNING = "running"
DEPLOYMENT_STATUS_PAUSED = "paused"
DEPLOYMENT_STATUS_FAILED = "failed"
DEPLOYMENT_STATUS_SUCCESSFUL = "successful"
DEPLOYMENT_STATUS_CANCELLED = "cancelled"

TERMINAL_DEPLOYMENT_STATUSES = frozenset(
    {
        DEPLOYMENT_STATUS_FAILED,
        DEPLOYMENT_STATUS_SUCCESSFUL,
        DEPLOYMENT_STATUS_CANCELLED,
    }
)

DESC_PROGRESS_DEADLINE = "Failed due to progress deadline"
DESC_UNHEALTHY_ALLOCS = "Failed due to unhealthy allocations"
DESC_AUTO_REVERT = "Failed; auto-reverting to previous stable version"
DESC_SUCCESSFUL = "Deployment completed successfully"
DESC_NEW_VERSION = "Cancelled due to newer version of job"


@dataclass(slots=True)
class DeploymentState:
    """Per task-group rollout state (structs.DeploymentState)."""

    auto_revert: bool = False
    auto_promote: bool = False
    promoted: bool = False
    placed_canaries: list[str] = field(default_factory=list)  # alloc ids
    desired_canaries: int = 0
    desired_total: int = 0
    placed_allocs: int = 0
    healthy_allocs: int = 0
    unhealthy_allocs: int = 0
    progress_deadline_s: float = 600.0
    require_progress_by_unix: float = 0.0


@dataclass(slots=True)
class AllocDeploymentStatus:
    """Health verdict for one alloc within a deployment
    (structs.AllocDeploymentStatus)."""

    healthy: Optional[bool] = None
    timestamp_unix: float = 0.0
    canary: bool = False

    def is_healthy(self) -> bool:
        return self.healthy is True

    def is_unhealthy(self) -> bool:
        return self.healthy is False


@dataclass(slots=True)
class Deployment:
    id: str = field(default_factory=lambda: str(uuid.uuid4()))
    namespace: str = "default"
    job_id: str = ""
    job_version: int = 0
    job_modify_index: int = 0
    task_groups: dict[str, DeploymentState] = field(default_factory=dict)
    status: str = DEPLOYMENT_STATUS_RUNNING
    status_description: str = "Deployment is running"
    is_multiregion: bool = False
    create_index: int = 0
    modify_index: int = 0

    def active(self) -> bool:
        return self.status in (DEPLOYMENT_STATUS_RUNNING, DEPLOYMENT_STATUS_PAUSED)

    def requires_promotion(self) -> bool:
        return any(
            s.desired_canaries > 0 and not s.promoted
            for s in self.task_groups.values()
        )

    def healthy_by_group(self) -> dict[str, int]:
        return {name: s.healthy_allocs for name, s in self.task_groups.items()}
