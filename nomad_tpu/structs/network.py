"""NetworkIndex — per-node port/bandwidth accounting.

Reference: nomad/structs/network.go:37-360. Inherently sequential bitmap
allocation per node, so it stays host-side: the device score pass uses
aggregate bandwidth/port-count as a fit proxy and the plan applier runs
this exact check before commit (the reference has the same guess-then-
verify split — scheduler guesses in rank.go:210-323, applier verifies in
plan_apply.go:638-689).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from .resources import NetworkResource

MIN_DYNAMIC_PORT = 20000
MAX_DYNAMIC_PORT = 32000
MAX_RAND_PORT_ATTEMPTS = 20


@dataclass(slots=True)
class AllocatedPort:
    label: str
    value: int
    to: int = 0


@dataclass(slots=True)
class AllocatedNetwork:
    device: str = ""
    ip: str = ""
    mbits: int = 0
    reserved_ports: list[AllocatedPort] = field(default_factory=list)
    dynamic_ports: list[AllocatedPort] = field(default_factory=list)


class NetworkIndex:
    """Tracks used ports and bandwidth on one node."""

    def __init__(self, node=None):
        self.avail_bandwidth: int = 0
        self.used_bandwidth: int = 0
        self.used_ports: set[int] = set()
        if node is not None:
            self.set_node(node)

    def set_node(self, node) -> None:
        self.avail_bandwidth = node.node_resources.bandwidth_mbits()
        for p in node.reserved.reserved_ports:
            self.used_ports.add(p)

    def add_allocs(self, allocs) -> bool:
        """Account every non-terminal alloc's network usage. Returns False
        on a (pre-existing) collision, matching NetworkIndex.AddAllocs."""
        collide = False
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            for net in getattr(alloc, "allocated_networks", []) or []:
                if not self.add_reserved_network(net):
                    collide = True
        return not collide

    def add_reserved_network(self, net: AllocatedNetwork) -> bool:
        ok = True
        for p in net.reserved_ports + net.dynamic_ports:
            if p.value in self.used_ports:
                ok = False
            self.used_ports.add(p.value)
        self.used_bandwidth += net.mbits
        return ok

    def assign_network(
        self, ask: NetworkResource, rng: random.Random | None = None
    ) -> tuple[AllocatedNetwork | None, str]:
        """Fit an ask: bandwidth check, reserved-port collision check, then
        dynamic port selection (random probe then linear scan — mirrors
        network.go:270-340). Returns (offer, failure_reason)."""
        if ask.mbits and self.used_bandwidth + ask.mbits > self.avail_bandwidth:
            return None, "bandwidth exceeded"
        offer = AllocatedNetwork(mbits=ask.mbits)
        for p in ask.reserved_ports:
            if p in self.used_ports:
                return None, f"reserved port {p} already in use"
            offer.reserved_ports.append(AllocatedPort(label=str(p), value=p))
        rng = rng or random
        taken = {p.value for p in offer.reserved_ports} | self.used_ports
        for label in ask.dynamic_ports:
            port = self._pick_dynamic_port(taken, rng)
            if port < 0:
                return None, "dynamic port selection failed"
            taken.add(port)
            offer.dynamic_ports.append(AllocatedPort(label=label, value=port))
        return offer, ""

    def _pick_dynamic_port(self, taken: set[int], rng) -> int:
        for _ in range(MAX_RAND_PORT_ATTEMPTS):
            p = rng.randint(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT)
            if p not in taken:
                return p
        for p in range(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT + 1):
            if p not in taken:
                return p
        return -1

    def commit(self, offer: AllocatedNetwork) -> None:
        self.add_reserved_network(offer)
