"""Job / TaskGroup / Task model with constraints, affinities and spreads.

Reference shapes: nomad/structs/structs.go (Job ~:3900, TaskGroup ~:5610,
Task ~:6090, Constraint ~:7600, Affinity ~:7700, Spread ~:7800). Only the
scheduling-relevant surface is modeled; service discovery, vault/consul
blocks, and template hooks are client-side concerns added in later layers.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional

from .resources import Resources

# Job types — structs.go JobTypeService/Batch/System/SysBatch + core GC jobs.
JOB_TYPE_SERVICE = "service"
JOB_TYPE_BATCH = "batch"
JOB_TYPE_SYSTEM = "system"
JOB_TYPE_SYSBATCH = "sysbatch"
JOB_TYPE_CORE = "_core"

JOB_STATUS_PENDING = "pending"
JOB_STATUS_RUNNING = "running"
JOB_STATUS_DEAD = "dead"

JOB_DEFAULT_PRIORITY = 50
JOB_MIN_PRIORITY = 1
JOB_MAX_PRIORITY = 100

DEFAULT_NAMESPACE = "default"

# Constraint operands — scheduler/feasible.go:785-820 checkConstraint dispatch.
CONSTRAINT_DISTINCT_HOSTS = "distinct_hosts"
CONSTRAINT_DISTINCT_PROPERTY = "distinct_property"
CONSTRAINT_REGEX = "regexp"
CONSTRAINT_VERSION = "version"
CONSTRAINT_SEMVER = "semver"
CONSTRAINT_SET_CONTAINS = "set_contains"
CONSTRAINT_SET_CONTAINS_ALL = "set_contains_all"
CONSTRAINT_SET_CONTAINS_ANY = "set_contains_any"
CONSTRAINT_ATTRIBUTE_IS_SET = "is_set"
CONSTRAINT_ATTRIBUTE_IS_NOT_SET = "is_not_set"

COMPARISON_OPERANDS = ("=", "==", "is", "!=", "not", "<", "<=", ">", ">=")


@dataclass(slots=True)
class Constraint:
    """Hard placement constraint. Reference: structs.Constraint."""

    l_target: str = ""
    r_target: str = ""
    operand: str = "="

    def key(self) -> tuple:
        return (self.l_target, self.r_target, self.operand)


@dataclass(slots=True)
class Affinity:
    """Soft placement preference with weight in [-100, 100].
    Reference: structs.Affinity; scored in scheduler/rank.go:650-737."""

    l_target: str = ""
    r_target: str = ""
    operand: str = "="
    weight: int = 50


@dataclass(slots=True)
class SpreadTarget:
    value: str = ""
    percent: int = 0


@dataclass(slots=True)
class Spread:
    """Spread allocations over values of an attribute, optionally with
    per-value target percentages. Reference: structs.Spread; scored in
    scheduler/spread.go."""

    attribute: str = ""
    weight: int = 50
    targets: list[SpreadTarget] = field(default_factory=list)


@dataclass(slots=True)
class RestartPolicy:
    attempts: int = 2
    interval_s: float = 1800.0
    delay_s: float = 15.0
    mode: str = "fail"  # fail | delay


@dataclass(slots=True)
class ReschedulePolicy:
    """Controls replacement of failed allocs on new nodes.
    Reference: structs.ReschedulePolicy; consumed by the reconciler and
    generic_sched.go:718-753 (followup evals with backoff)."""

    attempts: int = 0
    interval_s: float = 0.0
    delay_s: float = 30.0
    delay_function: str = "exponential"  # constant | exponential | fibonacci
    max_delay_s: float = 3600.0
    unlimited: bool = True


@dataclass(slots=True)
class MigrateStrategy:
    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time_s: float = 10.0
    healthy_deadline_s: float = 300.0


@dataclass(slots=True)
class UpdateStrategy:
    """Deployment/rolling-update knobs. Reference: structs.UpdateStrategy;
    consumed by the reconciler's deployment logic (scheduler/reconcile.go)."""

    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time_s: float = 10.0
    healthy_deadline_s: float = 300.0
    progress_deadline_s: float = 600.0
    auto_revert: bool = False
    auto_promote: bool = False
    canary: int = 0
    stagger_s: float = 30.0

    def rolling(self) -> bool:
        return self.max_parallel > 0


@dataclass(slots=True)
class EphemeralDisk:
    size_mb: int = 300
    sticky: bool = False
    migrate: bool = False


@dataclass(slots=True)
class PeriodicConfig:
    """Cron-style launch config. Reference: structs.PeriodicConfig;
    driven by the leader's periodic dispatcher (nomad/periodic.go)."""

    enabled: bool = True
    spec: str = ""
    spec_type: str = "cron"
    prohibit_overlap: bool = False
    time_zone: str = "UTC"


@dataclass(slots=True)
class ParameterizedJobConfig:
    payload: str = "optional"
    meta_required: list[str] = field(default_factory=list)
    meta_optional: list[str] = field(default_factory=list)


@dataclass(slots=True)
class LogConfig:
    """Per-task log retention (structs.LogConfig, DefaultLogConfig:
    10 files × 10 MiB) — consumed by the client's logmon rotation."""

    max_files: int = 10
    max_file_size_mb: int = 10


@dataclass(slots=True)
class ServiceCheck:
    """One health check on a service. Reference: structs.ServiceCheck
    (consumed by client/allochealth via the check watcher; the reference
    registers these in Consul — this build evaluates them client-side)."""

    name: str = ""
    type: str = "tcp"  # tcp | http | script
    path: str = "/"  # http only
    port: int = 0  # literal port (the reference resolves port labels)
    address: str = "127.0.0.1"
    command: str = ""  # script only
    args: list = field(default_factory=list)
    interval_s: float = 1.0
    timeout_s: float = 2.0


@dataclass(slots=True)
class Service:
    """A service advertised by a task. Reference: structs.Service —
    trimmed to the health-check role (no Consul registration)."""

    name: str = ""
    port: int = 0
    checks: list = field(default_factory=list)  # [ServiceCheck]


@dataclass(slots=True)
class Task:
    """One process under a driver. Reference: structs.Task."""

    name: str = "task"
    driver: str = "exec"
    user: str = ""
    config: dict = field(default_factory=dict)
    env: dict[str, str] = field(default_factory=dict)
    resources: Resources = field(default_factory=Resources)
    constraints: list[Constraint] = field(default_factory=list)
    affinities: list[Affinity] = field(default_factory=list)
    meta: dict[str, str] = field(default_factory=dict)
    leader: bool = False
    kill_timeout_s: float = 5.0
    lifecycle_hook: str = ""  # "" (main) | prestart | poststart | poststop
    lifecycle_sidecar: bool = False
    artifacts: list[dict] = field(default_factory=list)
    templates: list[dict] = field(default_factory=list)
    kind: str = ""
    log_config: LogConfig = field(default_factory=LogConfig)
    # volume name → structs.volumes.VolumeMount
    volume_mounts: list = field(default_factory=list)
    # advertised services with health checks (structs.Task.Services)
    services: list = field(default_factory=list)


@dataclass(slots=True)
class ScalingPolicy:
    """Horizontal group scaling bounds + autoscaler policy document.
    Reference: structs.ScalingPolicy (nomad/structs/structs.go; jobspec
    ``scaling`` block on a task group)."""

    min: int = 0
    max: int = 0
    enabled: bool = True
    # opaque autoscaler policy document (passed through verbatim)
    policy: dict = field(default_factory=dict)


@dataclass(slots=True)
class Namespace:
    """Reference: structs.Namespace (nomad/structs/namespace)."""

    name: str = ""
    description: str = ""
    create_index: int = 0
    modify_index: int = 0


@dataclass(slots=True)
class TaskGroup:
    """A co-scheduled set of tasks; the unit of placement.
    Reference: structs.TaskGroup."""

    name: str = "group"
    count: int = 1
    tasks: list[Task] = field(default_factory=list)
    constraints: list[Constraint] = field(default_factory=list)
    affinities: list[Affinity] = field(default_factory=list)
    spreads: list[Spread] = field(default_factory=list)
    restart_policy: RestartPolicy = field(default_factory=RestartPolicy)
    reschedule_policy: Optional[ReschedulePolicy] = None
    ephemeral_disk: EphemeralDisk = field(default_factory=EphemeralDisk)
    update: Optional[UpdateStrategy] = None
    migrate: Optional[MigrateStrategy] = None
    networks: list = field(default_factory=list)
    stop_after_client_disconnect_s: Optional[float] = None
    meta: dict[str, str] = field(default_factory=dict)
    # volume name → structs.volumes.VolumeRequest (group volume blocks)
    volumes: dict[str, object] = field(default_factory=dict)
    scaling: Optional[ScalingPolicy] = None

    def combined_resources(self) -> Resources:
        """Sum of task asks + ephemeral disk, the group's placement ask."""
        out = Resources(cpu=0, memory_mb=0, disk_mb=self.ephemeral_disk.size_mb)
        for t in self.tasks:
            out.cpu += t.resources.cpu
            out.memory_mb += t.resources.memory_mb
            out.networks.extend(t.resources.networks)
            out.devices.extend(t.resources.devices)
        out.networks = list(out.networks) + list(self.networks)
        return out


@dataclass(slots=True)
class Job:
    """Reference: structs.Job. ``version`` increments on every mutating
    registration; the reconciler compares alloc.job_version to decide
    in-place vs destructive updates."""

    id: str = ""
    name: str = ""
    namespace: str = DEFAULT_NAMESPACE
    type: str = JOB_TYPE_SERVICE
    priority: int = JOB_DEFAULT_PRIORITY
    region: str = "global"
    datacenters: list[str] = field(default_factory=lambda: ["dc1"])
    all_at_once: bool = False
    constraints: list[Constraint] = field(default_factory=list)
    affinities: list[Affinity] = field(default_factory=list)
    spreads: list[Spread] = field(default_factory=list)
    task_groups: list[TaskGroup] = field(default_factory=list)
    periodic: Optional[PeriodicConfig] = None
    parameterized: Optional[ParameterizedJobConfig] = None
    parent_id: str = ""
    payload: bytes = b""
    # per-device-class throughput coefficients (Gavel-style heterogeneity):
    # device_class → relative rate this job achieves on that class. A
    # class absent from the map runs at the default 1.0; an empty map
    # means the job is throughput-agnostic and hetero policies treat
    # every class identically. Values must be finite and >= 0 (0 = the
    # job cannot make progress on that class).
    throughputs: dict[str, float] = field(default_factory=dict)
    # gang scheduling stanza: {"groups": [group names placed all-or-
    # nothing], "colocate": {"level": "rack"|"pod", "weight": > 0},
    # "spread": {...}}. colocate/spread are optional topology terms; an
    # empty dict means no gang and the job schedules exactly as before
    # this field existed. Validated by validate_gang.
    gang: dict = field(default_factory=dict)
    meta: dict[str, str] = field(default_factory=dict)
    status: str = JOB_STATUS_PENDING
    stop: bool = False
    stable: bool = False
    version: int = 0
    submit_time_ns: int = 0
    create_index: int = 0
    modify_index: int = 0
    job_modify_index: int = 0

    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.task_groups:
            if tg.name == name:
                return tg
        return None

    def is_periodic(self) -> bool:
        return self.periodic is not None

    def is_parameterized(self) -> bool:
        return self.parameterized is not None

    def stopped(self) -> bool:
        return self.stop

    def terminal(self) -> bool:
        return self.stop and self.status == JOB_STATUS_DEAD

    def required_allocs(self) -> dict[str, int]:
        """group name → desired count (0 when the job is stopped)."""
        if self.stop:
            return {tg.name: 0 for tg in self.task_groups}
        return {tg.name: tg.count for tg in self.task_groups}

    def constraints_for_group(self, tg: TaskGroup) -> list[Constraint]:
        """Job + group + per-task constraints, the full hard-constraint set
        for a placement (mirrors how the stack layers ConstraintCheckers
        across job/group/task scopes). Implicit driver constraints are
        added separately by the feasibility layer."""
        out = list(itertools.chain(self.constraints, tg.constraints))
        for t in tg.tasks:
            out.extend(t.constraints)
        return out

    def affinities_for_group(self, tg: TaskGroup) -> list[Affinity]:
        out = list(itertools.chain(self.affinities, tg.affinities))
        for t in tg.tasks:
            out.extend(t.affinities)
        return out

    def spreads_for_group(self, tg: TaskGroup) -> list[Spread]:
        return list(itertools.chain(self.spreads, tg.spreads))

    def namespaced_id(self) -> tuple[str, str]:
        return (self.namespace, self.id)

    def throughput_for(self, device_class: str) -> float:
        """Relative rate this job achieves on ``device_class`` (1.0 when
        the class is unmapped or class-less)."""
        if not device_class:
            return 1.0
        return float(self.throughputs.get(device_class, 1.0))


class JobValidationError(ValueError):
    pass


def validate_throughputs(throughputs: dict) -> list[str]:
    """Validate a per-device-class throughput map, returning structured
    problem strings (empty = valid). Shared by jobspec parse and job
    admission so NaN/negative/garbage coefficients are rejected before
    they can reach the scoring kernels."""
    problems: list[str] = []
    if not isinstance(throughputs, dict):
        return [f"throughput must be a mapping, got {type(throughputs).__name__}"]
    for key, value in throughputs.items():
        if not isinstance(key, str) or not key:
            problems.append(f"throughput class name must be a non-empty string, got {key!r}")
            continue
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            problems.append(
                f"throughput[{key!r}] must be a number, got {type(value).__name__}"
            )
            continue
        v = float(value)
        if v != v:  # NaN
            problems.append(f"throughput[{key!r}] is NaN")
        elif v in (float("inf"), float("-inf")):
            problems.append(f"throughput[{key!r}] must be finite, got {v}")
        elif v < 0:
            problems.append(f"throughput[{key!r}] must be >= 0, got {v}")
    return problems


GANG_TOPOLOGY_LEVELS = ("rack", "pod", "ici")


def validate_gang(gang: dict, group_names=None) -> list[str]:
    """Validate a gang stanza, returning structured problem strings
    (empty = valid). Shared by jobspec parse and job admission.
    ``group_names`` (when given) checks member references against the
    job's real task groups."""
    problems: list[str] = []
    if not isinstance(gang, dict):
        return [f"gang must be a mapping, got {type(gang).__name__}"]
    if not gang:
        return problems
    unknown = set(gang) - {"groups", "colocate", "spread"}
    for key in sorted(unknown):
        problems.append(f"gang has unknown key {key!r}")
    groups = gang.get("groups")
    if not isinstance(groups, list) or not groups:
        problems.append("gang.groups must be a non-empty list of group names")
        groups = []
    seen = set()
    for name in groups:
        if not isinstance(name, str) or not name:
            problems.append(
                f"gang.groups entries must be non-empty strings, got {name!r}"
            )
            continue
        if name in seen:
            problems.append(f"gang.groups lists {name!r} twice")
        seen.add(name)
        if group_names is not None and name not in group_names:
            problems.append(f"gang.groups references unknown group {name!r}")
    levels_used = {}
    for stanza in ("colocate", "spread"):
        term = gang.get(stanza)
        if term is None:
            continue
        if not isinstance(term, dict):
            problems.append(
                f"gang.{stanza} must be a mapping, got {type(term).__name__}"
            )
            continue
        level = term.get("level")
        if level not in GANG_TOPOLOGY_LEVELS:
            problems.append(
                f"gang.{stanza}.level must be one of "
                f"{'/'.join(GANG_TOPOLOGY_LEVELS)}, got {level!r}"
            )
        elif level in levels_used:
            problems.append(
                f"gang.colocate and gang.spread both target level {level!r}"
            )
        else:
            levels_used[level] = stanza
        weight = term.get("weight", 1.0)
        if isinstance(weight, bool) or not isinstance(weight, (int, float)):
            problems.append(
                f"gang.{stanza}.weight must be a number, "
                f"got {type(weight).__name__}"
            )
        else:
            w = float(weight)
            if w != w or w in (float("inf"), float("-inf")):
                problems.append(f"gang.{stanza}.weight must be finite, got {w}")
            elif w <= 0:
                problems.append(f"gang.{stanza}.weight must be > 0, got {w}")
    return problems


def validate_job(job: Job) -> None:
    """Admission validation — the high-value subset of structs.Job.Validate
    + jobspec semantic checks (nomad/structs/structs.go Job.Validate,
    TaskGroup.Validate):

    - id/name/datacenters present, known type, non-negative counts
    - unique group names, unique task names per group, groups non-empty
    - every task volume_mount references a declared group volume
    - a non-per_alloc single-writer CSI volume can't serve count > 1
    """
    if not job.id:
        raise JobValidationError("missing job ID")
    if not job.name:
        raise JobValidationError("missing job name")
    if not job.datacenters:
        raise JobValidationError("job must specify at least one datacenter")
    if job.type not in ("service", "batch", "system", "sysbatch"):
        raise JobValidationError(f"invalid job type: {job.type!r}")
    if not job.task_groups:
        raise JobValidationError("job must have at least one task group")
    for problem in validate_throughputs(job.throughputs):
        raise JobValidationError(problem)
    group_names = {tg.name for tg in job.task_groups}
    for problem in validate_gang(job.gang, group_names):
        raise JobValidationError(problem)
    seen_groups = set()
    for tg in job.task_groups:
        if tg.name in seen_groups:
            raise JobValidationError(f"duplicate task group {tg.name!r}")
        seen_groups.add(tg.name)
        if tg.count < 0:
            raise JobValidationError(f"group {tg.name!r} count must be >= 0")
        if not tg.tasks:
            raise JobValidationError(f"group {tg.name!r} has no tasks")
        seen_tasks = set()
        for t in tg.tasks:
            if t.name in seen_tasks:
                raise JobValidationError(
                    f"duplicate task {t.name!r} in group {tg.name!r}"
                )
            seen_tasks.add(t.name)
            for vm in t.volume_mounts:
                if vm.volume not in tg.volumes:
                    raise JobValidationError(
                        f"task {t.name!r} mounts undeclared volume "
                        f"{vm.volume!r}"
                    )
        for name, req in tg.volumes.items():
            if req.type == "csi" and not req.source:
                raise JobValidationError(
                    f"volume {name!r} requires a source"
                )
            single_writer = req.type == "csi" and not req.read_only and (
                req.access_mode
                in ("", "single-node-writer", "multi-node-single-writer")
            )
            if single_writer and tg.count > 1 and not req.per_alloc:
                raise JobValidationError(
                    f"volume {name!r} is single-writer but group "
                    f"{tg.name!r} has count {tg.count}; use per_alloc"
                )
