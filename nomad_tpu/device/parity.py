"""Placement-score parity harness — the BASELINE ≤0.5% clause.

BASELINE.md's acceptance bar is "≤0.5% placement-score regression vs the
Go binpacker" (scheduler/benchmarks/benchmarks_test.go:71-124 shapes,
scored per the AllocMetric breakdown nomad/structs/structs.go:
10034-10079). The component vectors (tests/test_rank_vectors.py etc.) pin
each scoring term in isolation; this module closes the corpus-level gap:
it drives a seeded PLAN STREAM through (a) the device placement kernels
and (b) a reference-faithful host oracle — ``_rescore_pick``, the exact
NumPy implementation of the same component semantics, applied stepwise-
greedily exactly like the reference's iterator chain walks one placement
at a time (scheduler/rank.go:193-527, stack.go:343-438) — and reports
the aggregate normalized-score delta plus per-placement divergence.

The oracle and the kernels intentionally share scoring SEMANTICS but not
mechanism: the kernels place via closed-form top-k / chunked scans over
[N, J] planes (approximating stepwise greedy with a monotone clamp and
frozen-boost chunks), so a nonzero delta here measures exactly the
approximation the ≤0.5% clause bounds.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .score import PlacementKernel, _rescore_pick


@dataclass
class ParityResult:
    config: str
    n_placements: int = 0
    device_total: float = 0.0
    oracle_total: float = 0.0
    node_mismatches: int = 0  # chosen node differs (ties excluded)
    score_mismatches: int = 0  # |device − oracle| > tol at same step
    failed_device: int = 0  # device failed where oracle placed
    failed_oracle: int = 0  # oracle failed where device placed

    @property
    def score_delta_pct(self) -> float:
        """Aggregate regression of device vs oracle total score, in %.
        Positive = device scored WORSE (a regression); negative = device
        scored better than stepwise greedy (possible: greedy is not
        optimal)."""
        if self.oracle_total == 0:
            return 0.0
        return round(
            (self.oracle_total - self.device_total)
            / abs(self.oracle_total)
            * 100.0,
            4,
        )

    def to_dict(self) -> dict:
        return {
            "config": self.config,
            "placements": self.n_placements,
            "device_total_score": round(self.device_total, 3),
            "oracle_total_score": round(self.oracle_total, 3),
            "score_delta_pct": self.score_delta_pct,
            "node_mismatches": self.node_mismatches,
            "score_mismatches": self.score_mismatches,
            "failed_device": self.failed_device,
            "failed_oracle": self.failed_oracle,
        }


def oracle_place(capacity, used, ask, count: int, algorithm_spread=False):
    """Reference-faithful stepwise greedy: one exact argmax per placement
    (the Go iterator chain's semantics), mutating a local overlay.
    Returns (rows i32[count], scores f32[count], used') — used' includes
    the placements."""
    used = used.copy()
    placed = np.zeros(capacity.shape[0], dtype=np.float32)
    counts = ask.blocks.counts0.copy() if ask.blocks is not None else None
    rows = np.full(count, -1, dtype=np.int32)
    scores = np.full(count, -np.inf, dtype=np.float32)
    for i in range(count):
        row, sc = _rescore_pick(
            capacity, used, ask, placed, counts, algorithm_spread
        )
        if row < 0:
            break
        rows[i] = row
        scores[i] = sc
        used[row] += ask.ask
        placed[row] += 1
        if ask.blocks is not None:
            for b in range(ask.blocks.num_blocks):
                v = ask.blocks.value_ids[b, row]
                if v >= 0:
                    counts[b, v] += 1
    return rows, scores, used


def run_parity_stream(
    cluster,
    asks: list,
    config_name: str,
    algorithm: str = "binpack",
    tol: float = 1e-3,
) -> ParityResult:
    """Drive one seeded ask stream through the device kernels and the
    host oracle SEQUENTIALLY (each eval's placements are committed into
    the shared usage before the next eval, both sides in the same order —
    the corpus drifts identically, so per-step comparisons stay
    meaningful)."""
    kernel = PlacementKernel(algorithm)
    res = ParityResult(config=config_name)
    capacity = np.asarray(cluster.capacity)
    used_dev = np.asarray(cluster.used).copy()
    used_ora = np.asarray(cluster.used).copy()
    spread = algorithm == "spread"
    for a in asks:
        [r] = kernel.place(cluster, [a], used_override=used_dev)
        o_rows, o_scores, used_ora = oracle_place(
            capacity, used_ora, a, a.count, algorithm_spread=spread
        )
        d_rows = r.node_rows
        d_scores = r.scores
        for i in range(a.count):
            d_ok = i < d_rows.shape[0] and d_rows[i] >= 0
            o_ok = o_rows[i] >= 0
            if d_ok and o_ok:
                res.n_placements += 1
                res.device_total += float(d_scores[i])
                res.oracle_total += float(o_scores[i])
                if abs(float(d_scores[i]) - float(o_scores[i])) > tol:
                    res.score_mismatches += 1
                    if d_rows[i] != o_rows[i]:
                        res.node_mismatches += 1
            elif o_ok and not d_ok:
                res.failed_device += 1
                res.oracle_total += float(o_scores[i])
                res.n_placements += 1
            elif d_ok and not o_ok:
                res.failed_oracle += 1
            # commit device placements into the device stream's usage
            if d_ok:
                used_dev[d_rows[i]] += a.ask
    return res


# -- seeded corpus builders (BASELINE graded-config shapes) ------------------


def _cluster(n_nodes: int, seed: int, load: float = 0.35):
    """Synthetic heterogeneous cluster, same recipe as bench.build_cluster
    (4/8/16-core classes, 0..load pre-existing usage)."""
    from .flatten import ClusterTensors, node_bucket

    rng = np.random.default_rng(seed)
    pn = node_bucket(n_nodes)
    classes = rng.integers(0, 3, size=n_nodes)
    cpu = np.choose(classes, [4000, 8000, 16000]).astype(np.float32)
    mem = np.choose(classes, [8192, 16384, 32768]).astype(np.float32)
    capacity = np.zeros((pn, 4), dtype=np.float32)
    capacity[:n_nodes, 0] = cpu
    capacity[:n_nodes, 1] = mem
    capacity[:n_nodes, 2] = 100 * 1024
    capacity[:n_nodes, 3] = 1000
    used = np.zeros_like(capacity)
    lf = rng.uniform(0.0, load, size=(n_nodes, 1)).astype(np.float32)
    used[:n_nodes, :2] = capacity[:n_nodes, :2] * lf
    ready = np.zeros(pn, dtype=bool)
    ready[:n_nodes] = True
    return ClusterTensors(
        node_ids=[f"node-{i}" for i in range(n_nodes)],
        index=1,
        num_nodes=n_nodes,
        capacity=capacity,
        used=used,
        ready=ready,
        dc_ids=np.pad(rng.integers(0, 3, n_nodes).astype(np.int32), (0, pn - n_nodes)),
        class_ids=np.pad(classes.astype(np.int32), (0, pn - n_nodes)),
        dc_vocab={"dc1": 0, "dc2": 1, "dc3": 2},
        class_vocab={"small": 0, "medium": 1, "large": 2},
        class_rep=[0, 1, 2],
        node_row={f"node-{i}": i for i in range(n_nodes)},
    )


def _ask(ct, job: str, count: int, cpu: float, mem: float, **kw):
    from .flatten import GroupAsk

    pn = ct.padded_n
    return GroupAsk(
        job_id=job,
        tg_name="web",
        count=count,
        desired_total=count,
        ask=np.array([cpu, mem, 300.0, 0.0], dtype=np.float32),
        eligible=ct.ready.copy(),
        job_counts=np.zeros(pn, dtype=np.int32),
        penalty_nodes=np.zeros(pn, dtype=bool),
        affinity_scores=np.zeros(pn, dtype=np.float32),
        has_affinities=False,
        distinct_hosts=False,
        **kw,
    )


def build_config2(n_nodes=1000, n_jobs=20, count=250, seed=11):
    """BASELINE config 2: homogeneous service binpack (cpu+mem only)."""
    ct = _cluster(n_nodes, seed)
    rng = np.random.default_rng(seed + 1)
    asks = [
        _ask(
            ct,
            f"c2-{j}",
            count,
            float(rng.choice([250, 500, 1000])),
            float(rng.choice([256, 512, 1024])),
        )
        for j in range(n_jobs)
    ]
    return ct, asks


def build_config3(n_nodes=5000, n_jobs=10, count=250, racks=25, seed=13):
    """BASELINE config 3 shape: spread + affinity scoring."""
    from .flatten import ValueBlocks
    from .score import BLOCK_EVEN_SPREAD

    ct = _cluster(n_nodes, seed)
    pn = ct.padded_n
    rng = np.random.default_rng(seed + 1)
    rack_ids = np.pad(
        (np.arange(n_nodes) % racks).astype(np.int32),
        (0, pn - n_nodes),
        constant_values=-1,
    )
    asks = []
    for j in range(n_jobs):
        a = _ask(
            ct,
            f"c3-{j}",
            count,
            float(rng.choice([250, 500])),
            float(rng.choice([256, 512])),
        )
        a.blocks = ValueBlocks(
            value_ids=rack_ids[None, :],
            counts0=np.zeros((1, racks), dtype=np.float32),
            desired=np.full((1, racks), -1.0, dtype=np.float32),
            caps=np.full((1, racks), np.inf, dtype=np.float32),
            weights=np.ones(1, dtype=np.float32),
            kinds=np.array([BLOCK_EVEN_SPREAD], dtype=np.int32),
        )
        # ssd affinity on every 4th node (the config-3 bench shape)
        a.has_affinities = True
        a.affinity_scores = np.where(
            np.arange(pn) % 4 == 0, 0.5, -0.5
        ).astype(np.float32) * ct.ready
        asks.append(a)
    return ct, asks


def build_config4(n_nodes=5000, n_jobs=10, count=200, seed=17):
    """BASELINE config 4 shape: anti-affinity pressure (existing job
    allocs on some nodes) + distinct_property caps + target spread."""
    from .flatten import ValueBlocks
    from .score import BLOCK_DISTINCT_CAP, BLOCK_TARGET_SPREAD

    ct = _cluster(n_nodes, seed)
    pn = ct.padded_n
    rng = np.random.default_rng(seed + 1)
    dcs = 3
    dc_ids = np.pad(
        (np.arange(n_nodes) % dcs).astype(np.int32),
        (0, pn - n_nodes),
        constant_values=-1,
    )
    asks = []
    for j in range(n_jobs):
        a = _ask(
            ct,
            f"c4-{j}",
            count,
            float(rng.choice([500, 1000])),
            float(rng.choice([512, 1024])),
        )
        # anti-affinity: pretend 1/8 of nodes already run an alloc of
        # this job (rank.go:536-604 JobAntiAffinity)
        a.job_counts = (
            (rng.random(pn) < 0.125) & ct.ready
        ).astype(np.int32)
        # reschedule penalty on a few nodes (rank.go:606-648)
        a.penalty_nodes = (rng.random(pn) < 0.02) & ct.ready
        # dc target spread 50/30/20 + per-dc distinct cap
        weights = np.array([0.7, 0.3], dtype=np.float32)
        desired = np.stack(
            [
                np.array(
                    [count * 0.5, count * 0.3, count * 0.2], dtype=np.float32
                ),
                np.full(dcs, -1.0, dtype=np.float32),
            ]
        )
        caps = np.stack(
            [
                np.full(dcs, np.inf, dtype=np.float32),
                np.full(dcs, count * 0.6, dtype=np.float32),
            ]
        )
        a.blocks = ValueBlocks(
            value_ids=np.stack([dc_ids, dc_ids]),
            counts0=np.zeros((2, dcs), dtype=np.float32),
            desired=desired,
            caps=caps,
            weights=weights,
            kinds=np.array(
                [BLOCK_TARGET_SPREAD, BLOCK_DISTINCT_CAP], dtype=np.int32
            ),
        )
        asks.append(a)
    return ct, asks


def run_parity_suite(small: bool = False) -> dict:
    """The published corpus: one ParityResult per graded config. ``small``
    shrinks shapes for CI."""
    shrink = 5 if small else 1
    c2 = build_config2(
        n_nodes=1000 // shrink, n_jobs=max(20 // shrink, 3),
        count=max(250 // shrink, 40),
    )
    c3 = build_config3(
        n_nodes=5000 // shrink, n_jobs=max(10 // shrink, 2),
        count=max(250 // shrink, 40),
    )
    c4 = build_config4(
        n_nodes=5000 // shrink, n_jobs=max(10 // shrink, 2),
        count=max(200 // shrink, 40),
    )
    out = {}
    for name, (ct, asks) in (
        ("config2_binpack", c2),
        ("config3_spread_affinity", c3),
        ("config4_antiaffinity_caps", c4),
    ):
        out[name] = run_parity_stream(ct, asks, name).to_dict()
    return out
