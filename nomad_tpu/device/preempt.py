"""Vectorized preemption — the knapsack relaxation of the reference's
greedy victim search.

Reference semantics (scheduler/preemption.go):
- Eligibility: victim priority ≤ job priority − 10
  (filterAndGroupPreemptibleAllocs :663-697).
- Victim choice per node: group by priority ascending, then nearest
  resource distance first (PreemptForTaskGroup :198-265,
  basicResourceDistance :608-624) — take victims until the ask fits.
- Redundancy: drop victims whose removal isn't needed (filterSuperset
  :702-733).
- Scoring: preempting options are down-ranked by a logistic of the summed
  victim priorities, inflection at net priority 2048
  (rank.go:775-844 PreemptionScoringIterator / preemptionScore).

TPU reformulation (SURVEY.md §7 step 6): all nodes evaluated at once.
Victims are padded to ``[N, V]``; one vectorized pass does

    order   = argsort by (priority, resource-distance)      # segmented sort
    prefix  = cumsum of victim resources in that order      # prefix scan
    k[n]    = first prefix index where used − prefix + ask ≤ capacity
    net[n]  = sum of the first k victims' priorities
    score   = base_score(n) · logistic(net)                 # preemption penalty

The reference's superset filter falls out for free: taking the *minimal
feasible prefix* of the sorted order never includes a redundant victim in
the single-resource-direction sense the greedy covers.
"""

from __future__ import annotations

import functools

import jax  # noqa: F401 — kernels trace through traced_jit
import jax.numpy as jnp
import numpy as np

from ..utils.backend import traced_jit

# Priority delta a preemptor must have over its victims
# (preemption.go:673: delta ≥ 10).
PREEMPTION_PRIORITY_DELTA = 10
# Logistic inflection point for the net-priority penalty (rank.go:842).
NET_PRIORITY_INFLECTION = 2048.0


def preemption_score(net_priority):
    """Down-weight for preempting options: ≈1 for cheap preemptions, →0 as
    summed victim priority passes the inflection (rank.go:834-844)."""
    return 1.0 / (1.0 + jnp.exp((net_priority - NET_PRIORITY_INFLECTION) / 256.0))


def resource_distance(ask, victim):
    """basicResourceDistance (preemption.go:608-624): L2 over the relative
    per-dimension deltas — closer victims waste less."""
    rel = (victim - ask) / jnp.maximum(ask, 1.0)
    return jnp.sqrt(jnp.sum(rel * rel, axis=-1))


@functools.partial(traced_jit, retrace_budget=8)
def find_preemption_kernel(
    capacity,  # f32[N, D]
    used,  # f32[N, D] (incl. victims)
    ask,  # f32[D]
    eligible,  # bool[N] (constraint/dc mask, ignoring resource fit)
    victim_res,  # f32[N, V, D] resources per candidate victim
    victim_prio,  # i32[N, V] victim priorities (already delta-filtered)
    victim_mask,  # bool[N, V] real victims vs padding
):
    """For every node, the minimal sorted victim prefix that frees room.

    Returns (feasible bool[N], k i32[N] victims needed, net_priority f32[N],
    order i32[N, V] victim index order). Host maps (node, order[:k]) back to
    allocation ids with the same deterministic key.
    """
    n, v, d = victim_res.shape
    big = jnp.float32(1e9)

    dist = resource_distance(ask[None, None, :], victim_res)  # [N, V]
    # sort key: priority major, distance minor; padding last
    key = victim_prio.astype(jnp.float32) * 1e4 + jnp.minimum(dist, 9e3)
    key = jnp.where(victim_mask, key, big)
    order = jnp.argsort(key, axis=1)  # [N, V]

    sorted_res = jnp.take_along_axis(victim_res, order[:, :, None], axis=1)
    sorted_prio = jnp.take_along_axis(
        jnp.where(victim_mask, victim_prio, 0), order, axis=1
    )
    sorted_mask = jnp.take_along_axis(victim_mask, order, axis=1)

    freed = jnp.cumsum(
        jnp.where(sorted_mask[:, :, None], sorted_res, 0.0), axis=1
    )  # [N, V, D]
    # after freeing the first (i+1) victims, does the ask fit?
    fits_after = jnp.all(
        used[:, None, :] - freed + ask[None, None, :] <= capacity[:, None, :],
        axis=-1,
    ) & sorted_mask  # [N, V]

    any_fit = jnp.any(fits_after, axis=1) & eligible
    k = jnp.argmax(fits_after, axis=1) + 1  # victims needed (first hit)
    k = jnp.where(any_fit, k, 0)

    prio_prefix = jnp.cumsum(sorted_prio * sorted_mask, axis=1)  # [N, V]
    net = jnp.where(
        any_fit,
        jnp.take_along_axis(
            prio_prefix, jnp.maximum(k - 1, 0)[:, None], axis=1
        )[:, 0].astype(jnp.float32),
        0.0,
    )
    return any_fit, k.astype(jnp.int32), net, order.astype(jnp.int32)


@functools.partial(traced_jit, retrace_budget=8)
def choose_preemption_node_kernel(
    capacity,
    used,
    ask,
    eligible,
    victim_res,
    victim_prio,
    victim_mask,
):
    """Pick the best node to preempt on: binpack fit score (post-placement)
    scaled by the preemption penalty. Returns (best i32, feasible bool[N],
    k, net, order)."""
    from .score import _pow10

    feasible, k, net, order = find_preemption_kernel(
        capacity, used, ask, eligible, victim_res, victim_prio, victim_mask
    )
    # fit score after preempting + placing (approximate: fully-freed victims)
    freed = jnp.sum(
        jnp.where(victim_mask[:, :, None], victim_res, 0.0), axis=1
    )
    proposed = used - freed + ask
    free_frac = jnp.where(
        capacity > 0, (capacity - proposed) / jnp.maximum(capacity, 1e-9), 1.0
    )
    fit = jnp.clip(
        20.0 - _pow10(free_frac[:, 0]) - _pow10(free_frac[:, 1]), 0.0, 18.0
    ) / 18.0
    score = fit * preemption_score(net)
    score = jnp.where(feasible, score, -jnp.inf)
    best = jnp.argmax(score)
    return best, feasible, k, net, order, score


def _victim_bucket(n: int) -> int:
    """Pad the victim axis to a power of two so victim-count churn doesn't
    retrigger XLA compilation (same policy as score._steps_bucket)."""
    b = 1
    while b < n:
        b <<= 1
    return b


def build_victim_tensors(ct, snap, job, exclude_ids=frozenset()):
    """Flatten preemption candidates: for every node row, the allocs whose
    priority is ≤ job.priority − 10 (preemption.go:663-697), padded to a
    power-of-two victim bucket. ``exclude_ids`` drops allocs already
    preempted by the in-flight plan (their capacity is freed once, not
    twice). Returns (victim_res, victim_prio, victim_mask,
    victim_ids[list per node])."""
    pn = ct.padded_n
    max_prio = job.priority - PREEMPTION_PRIORITY_DELTA
    per_node: list[list] = [[] for _ in range(pn)]
    for row, node_id in enumerate(ct.node_ids):
        for a in snap.allocs_by_node(node_id):
            if a.terminal_status() or a.id in exclude_ids:
                continue
            prio = a.job.priority if a.job is not None else 50
            if prio <= max_prio:
                per_node[row].append((a, prio))
    v = _victim_bucket(max((len(x) for x in per_node), default=1) or 1)
    victim_res = np.zeros((pn, v, 4), dtype=np.float32)
    victim_prio = np.zeros((pn, v), dtype=np.int32)
    victim_mask = np.zeros((pn, v), dtype=bool)
    victim_ids: list[list[str]] = [[] for _ in range(pn)]
    for row, cands in enumerate(per_node):
        for j, (a, prio) in enumerate(cands):
            victim_res[row, j] = a.comparable_resources().to_vector()
            victim_prio[row, j] = prio
            victim_mask[row, j] = True
            victim_ids[row].append(a.id)
    return victim_res, victim_prio, victim_mask, victim_ids


def rank_preemption_nodes(
    ct, snap, job, ask_vec, eligible, exclude_ids=frozenset(), top: int = 16
):
    """One [N, V] device pass ranking every node by post-preemption fit ×
    preemption penalty; returns up to ``top`` feasible node rows, best
    first. The exact victim set per node is then chosen host-side by
    scheduler/preempt_host.select_victims (reference-exact greedy with
    maxParallel/ports/devices) — the kernel narrows 10k nodes to a
    shortlist, the host pays exactness only on the shortlist."""
    victim_res, victim_prio, victim_mask, _ids = build_victim_tensors(
        ct, snap, job, exclude_ids=exclude_ids
    )
    if not victim_mask.any():
        return []
    _best, feasible, _k, _net, _order, score = choose_preemption_node_kernel(
        jnp.asarray(ct.capacity),
        jnp.asarray(ct.used),
        jnp.asarray(ask_vec),
        jnp.asarray(eligible),
        jnp.asarray(victim_res),
        jnp.asarray(victim_prio),
        jnp.asarray(victim_mask),
    )
    feasible = np.asarray(feasible)
    score = np.asarray(score)
    rows = np.flatnonzero(feasible)
    if rows.size == 0:
        return []
    return rows[np.argsort(-score[rows], kind="stable")][:top].tolist()


def find_preemptions(ct, snap, job, ask_vec, eligible, exclude_ids=frozenset()):
    """Host driver: one device pass, then map the chosen node's sorted
    victim prefix back to allocation ids. Returns (node_row, [alloc ids])
    or (None, [])."""
    victim_res, victim_prio, victim_mask, victim_ids = build_victim_tensors(
        ct, snap, job, exclude_ids=exclude_ids
    )
    if not victim_mask.any():
        return None, []
    best, feasible, k, net, order, _score = choose_preemption_node_kernel(
        jnp.asarray(ct.capacity),
        jnp.asarray(ct.used),
        jnp.asarray(ask_vec),
        jnp.asarray(eligible),
        jnp.asarray(victim_res),
        jnp.asarray(victim_prio),
        jnp.asarray(victim_mask),
    )
    best = int(best)
    if not bool(np.asarray(feasible)[best]):
        return None, []
    kk = int(np.asarray(k)[best])
    node_order = np.asarray(order)[best]
    ids = []
    for idx in node_order[:kk]:
        if idx < len(victim_ids[best]):
            ids.append(victim_ids[best][idx])
    return best, ids
