"""DeviceStateCache — resident cluster tensors refreshed incrementally.

SURVEY.md §7 "latency floor": the device arrays are a *derived cache* of
the state store's node/alloc tables, refreshed by state-index watermark
(the ``SnapshotMinIndex`` analog, nomad/worker.go:536-549) — NOT rebuilt
per evaluation. The store's ChangeJournal (state/store.py) records which
node rows were touched; the cache patches exactly those rows.

Generational copy-on-write: a refresh builds new arrays (cheap — O(N·D)
numpy copies) and swaps the generation, so evals holding the previous
``ClusterTensors`` keep reading frozen state — the same MVCC discipline
the store itself uses.

Full rebuilds happen only when the journal can't cover the interval, a
node disappears or changes class/datacenter (representative-node
semantics would go stale), or the padded node bucket overflows.
"""

from __future__ import annotations

import threading
from dataclasses import replace

import numpy as np

from ..structs.resources import node_comparable_capacity
from .flatten import ClusterTensors, flatten_cluster


def _node_used(snap, node_id: str, dims: int) -> np.ndarray:
    vec = np.zeros(dims, dtype=np.float32)
    for a in snap.allocs_by_node(node_id):
        if not a.terminal_status():
            vec += a.comparable_resources().to_vector()
    return vec


class ScoreState:
    """One generation of the persisted device-resident score view.

    The score planes every placement kernel computes are pure functions
    of ``(capacity, used, ask)``; capacity is already device-resident
    (``_device_capacity_locked``) and the asks are per-pass, so the
    persisted half of the score state is ``used`` — the alloc-churn-hot
    tensor that the from-scratch path re-uploads whole every pass. A
    generation is immutable once built (jax buffers are, and the host
    mirror is a private copy): the double-buffered pipeline hands the
    previous generation to an in-flight pass while the next one is
    staged, and ``score_commit`` swaps staged → committed at the merge
    point. ``used_host`` is the exact bytes on device — the dirty-row
    diff and ``verify_score_view`` both compare against it bitwise."""

    __slots__ = ("used_dev", "used_host", "layout_gen", "gen")

    def __init__(self, used_dev, used_host, layout_gen: int, gen: int):
        self.used_dev = used_dev
        self.used_host = used_host
        self.layout_gen = layout_gen
        self.gen = gen


class DeviceStateCache:
    """One per server/harness; thread-safe. ``tensors(snap)`` returns a
    ClusterTensors at exactly ``snap.index`` whose ``used`` array is a
    private copy (schedulers overlay in-plan stops/preemptions onto it)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ct: ClusterTensors | None = None
        # instrumentation: test_device_cache asserts full_flattens stays 1
        # across eval storms; metrics surface these (nomad.worker.* analog)
        self.full_flattens = 0
        self.incremental_refreshes = 0
        self.hits = 0
        self.stale_builds = 0  # older-than-resident snapshots (transient)
        # mesh sharding: device-resident capacity, refreshed per shard.
        # Dirty-REGION tracking (region ids are stable across incremental
        # refreshes; only a full reflatten may re-sort rows) maps journal
        # changes to the node-axis shards that must re-upload; clean
        # shards keep their existing device buffers.
        self._dev_capacity = None  # committed sharded jax.Array | None
        self._dev_layout_gen = 0
        self._dirty_regions: set[int] = set()
        self.shard_uploads = 0  # per-shard (partial) device refreshes
        self.full_uploads = 0  # whole-tensor device uploads
        # score-state persistence (NOMAD_TPU_INCREMENTAL): double-
        # buffered device-resident ``used`` generations. ``_score`` is
        # the committed generation; ``score_view`` stages the next one
        # (dirty rows diffed bitwise against the newest mirror, clean
        # shards keep their buffers) and ``score_commit`` swaps it in
        # from the worker's commit path. Dirty detection is an exact
        # host compare rather than journal bookkeeping: overlay
        # overrides and partially-landed commits self-heal on the next
        # pass because ANY divergence from the mirror re-uploads.
        self._score: ScoreState | None = None  # committed generation
        self._score_staged: ScoreState | None = None
        self.score_rows_rescored = 0  # rows re-uploaded (score inputs changed)
        self.score_rows_reused = 0  # rows served from the resident buffer
        self.score_patch_uploads = 0  # partial (dirty-slice) refreshes
        self.score_full_rebuilds = 0  # whole-tensor score-state uploads
        self.score_swaps = 0  # staged → committed generation swaps
        self.pipeline_overlap_ms = 0.0  # commit time hidden behind passes

    # -- public -----------------------------------------------------------
    def tensors(self, snap) -> ClusterTensors:
        from ..utils.backend import get_mesh, incremental_enabled

        with self._lock:
            ct = self._refresh_locked(snap)
            out = replace(ct, used=ct.used.copy())
            cfg = get_mesh()
            if cfg.active:
                out.device_capacity = self._device_capacity_locked(ct, cfg)
            if incremental_enabled():
                # the incremental seam the kernels read (device/score.py
                # used_device): present ⇒ the pass's ``used`` upload may
                # be served from the persisted score state. Off-mode
                # tensors carry None and take the from-scratch path
                # untouched — the Python-level gate the jaxpr-identity
                # pin depends on.
                out.score_cache = self
            return out

    def invalidate(self) -> None:
        with self._lock:
            self._ct = None
            self._dev_capacity = None
            self._dirty_regions.clear()
            self._score = None
            self._score_staged = None

    def device_counters(self) -> dict:
        with self._lock:
            state = self._score_staged or self._score
            return {
                "shard_uploads": self.shard_uploads,
                "full_uploads": self.full_uploads,
                "dirty_regions": len(self._dirty_regions),
                "score_rows_rescored": self.score_rows_rescored,
                "score_rows_reused": self.score_rows_reused,
                "score_patch_uploads": self.score_patch_uploads,
                "score_full_rebuilds": self.score_full_rebuilds,
                "score_swaps": self.score_swaps,
                "score_gen": 0 if state is None else state.gen,
                "pipeline_overlap_ms": round(self.pipeline_overlap_ms, 3),
            }

    def note_overlap(self, ms: float) -> None:
        """Worker-reported pipeline overlap: wall-clock the commit
        thread ran underneath the NEXT pass's prepare + device work."""
        with self._lock:
            self.pipeline_overlap_ms += max(0.0, float(ms))

    def verify_device_view(self) -> list[str] | None:
        """Invariant law 12 (shard_consistency) probe: re-gather every
        device-resident capacity shard to host and compare *bitwise*
        against the resident generation's store-derived capacity.
        Returns None when no device view is materialized (mesh off, or
        never accessed); else a list of mismatch details (empty ==
        consistent). Pending dirty regions are fine — they re-upload on
        the next access — but a shard that claims to be clean must
        match."""
        with self._lock:
            ct = self._ct
            arr = self._dev_capacity
            if ct is None or arr is None:
                return None
            if self._dirty_regions:
                # flush pending per-shard refreshes so the comparison
                # sees what the next eval would read
                from ..utils.backend import get_mesh

                cfg = get_mesh()
                if cfg.active:
                    arr = self._device_capacity_locked(ct, cfg)
            problems: list[str] = []
            ref = np.asarray(ct.capacity)
            for sh in arr.addressable_shards:
                host = np.asarray(sh.data)
                want = ref[sh.index]
                if host.shape != want.shape or not np.array_equal(
                    host, want
                ):
                    start = sh.index[0].start or 0
                    problems.append(
                        f"rows[{start}:{start + host.shape[0]}] on "
                        f"{sh.device} diverge from store-derived capacity"
                    )
            return problems

    # -- score-state persistence (incremental rescoring) -------------------
    def score_view(self, ct, used0: np.ndarray, cfg=None):
        """Device-resident ``used`` for one scoring pass, bitwise equal
        to ``used0`` — or None when the incremental path is inactive
        (callers ``shard_put`` from scratch, exactly the off-mode path).

        Stages the next score-state generation: rows whose bytes differ
        from the newest mirror re-upload (per dirty shard under a mesh,
        whole-tensor when degenerate or chaos-dropped); clean shards
        keep their existing device buffers and their per-shard top-k
        heads are recomputed from resident data — the hierarchical
        merge in device/score.py (``_topk_nodes``) runs unchanged, so
        the traced program is identical to from-scratch and only the
        host→device traffic scales with the dirt. The staged generation
        becomes committed at ``score_commit`` (worker commit path)."""
        from ..utils.backend import get_mesh, incremental_enabled

        if not incremental_enabled():
            return None
        if cfg is None:
            cfg = get_mesh()
        used0 = np.asarray(used0, dtype=np.float32)
        layout_gen = getattr(ct, "layout_gen", 0)
        with self._lock:
            base = self._score_staged or self._score
            n_rows = int(used0.shape[0])
            if (
                base is None
                or base.layout_gen != layout_gen
                or base.used_host.shape != used0.shape
            ):
                # first access, layout change (full reflatten re-sorts
                # rows: every cached partial is row-misaligned), or a
                # shape flip — rebuild the whole score state
                return self._score_rebuild_locked(used0, layout_gen, cfg)
            dirty = np.flatnonzero(
                np.any(base.used_host != used0, axis=1)
            )
            if dirty.size == 0:
                self.score_rows_reused += n_rows
                self._score_staged = ScoreState(
                    base.used_dev, base.used_host, layout_gen, base.gen
                )
                return base.used_dev
            from ..chaos.plane import chaos_site

            if chaos_site("cache.score_refresh_drop") == "drop":
                # a dropped dirty-slice refresh must never serve stale
                # score inputs: recovery is a whole-tensor re-upload on
                # this access (mesh.shard_refresh_drop discipline)
                return self._score_rebuild_locked(used0, layout_gen, cfg)
            self.score_rows_rescored += int(dirty.size)
            self.score_rows_reused += n_rows - int(dirty.size)
            dev = self._score_patch_locked(base, used0, dirty, cfg)
            self._score_staged = ScoreState(
                dev, used0.copy(), layout_gen, base.gen + 1
            )
            self.score_patch_uploads += 1
            return dev

    def _score_rebuild_locked(self, used0, layout_gen: int, cfg):
        from ..utils.backend import shard_put

        # upload from a PRIVATE copy: on the CPU backend device_put may
        # alias the host numpy buffer zero-copy, and a buffer aliasing
        # the caller's live ``used`` array would mutate under alloc
        # churn — the generation must hold the exact bytes it was built
        # from. The copy doubles as the mirror.
        host = used0.copy()
        dev = shard_put(host, ("nodes",), cfg)
        base = self._score_staged or self._score
        gen = 1 if base is None else base.gen + 1
        self._score_staged = ScoreState(
            dev, host, layout_gen, gen
        )
        self.score_full_rebuilds += 1
        self.score_rows_rescored += int(used0.shape[0])
        return dev

    def _score_patch_locked(self, base: ScoreState, used0, dirty, cfg):
        """New device buffer for ``used0``: under a mesh whose node axis
        divides the rows, re-upload only the shards containing dirty
        rows and reassemble around the clean shards' existing buffers
        (the capacity protocol); degenerate single-device falls back to
        a whole-tensor upload — there is no partial-placement primitive
        for an unsharded buffer, and the reuse win there is the
        zero-dirty case above."""
        from ..utils.backend import shard_put

        mp = cfg.n_node_shards
        n_rows = int(used0.shape[0])
        arr = base.used_dev
        if (
            mp <= 1
            or n_rows % mp != 0
            or getattr(arr, "sharding", None) is None
        ):
            # .copy() for the same aliasing reason as the shard path
            return shard_put(used0.copy(), ("nodes",), cfg)
        import jax

        seg = n_rows // mp
        dirty_shards = {int(r) // seg for r in dirty}
        bufs = []
        for sh in arr.addressable_shards:
            start = sh.index[0].start or 0
            if start // seg in dirty_shards:
                # .copy(): CPU device_put may alias host memory (see
                # _score_rebuild_locked) — a dirty-slice buffer must
                # not track the caller's live ``used`` rows
                bufs.append(
                    jax.device_put(
                        used0[start : start + seg].copy(), sh.device
                    )
                )
            else:
                bufs.append(sh.data)
        return jax.make_array_from_single_device_arrays(
            used0.shape, arr.sharding, bufs
        )

    def score_commit(self) -> None:
        """Swap the staged score-state generation in as committed — the
        double buffer's merge point, called from the worker's commit
        path. The ONE ``jax.block_until_ready`` fence of the pipeline
        lives here: patch uploads dispatch async and overlap the
        previous pass's verify/commit; by swap time they must be real
        buffers, never in-flight transfers a holder could stall on."""
        from ..utils.backend import transfer_fence

        with self._lock:
            staged = self._score_staged
            if staged is None:
                return
            self._score_staged = None
            if self._score is not None and staged.gen == self._score.gen:
                return  # zero-dirty pass: same generation, no swap
            self._score = staged
            self.score_swaps += 1
        transfer_fence(staged.used_dev)

    def score_abort(self) -> None:
        """Drop the staged generation (a pass that died before commit);
        the next pass diffs against the committed mirror and re-uploads
        whatever the aborted pass had staged — correctness never
        depends on an abort being observed."""
        with self._lock:
            self._score_staged = None

    def verify_score_view(self) -> list[str] | None:
        """Invariant law 12 (shard_consistency), score half: re-gather
        every device-resident ``used`` shard of the newest score-state
        generation and compare *bitwise* against its host mirror — the
        ``verify_device_view`` analog for the incremental path. Returns
        None when no score state is materialized (incremental off, or
        never accessed); else a list of mismatch details (empty ==
        consistent)."""
        with self._lock:
            state = self._score_staged or self._score
            if state is None:
                return None
            problems: list[str] = []
            ref = state.used_host
            for sh in state.used_dev.addressable_shards:
                host = np.asarray(sh.data)
                want = ref[sh.index]
                if host.shape != want.shape or (
                    host.tobytes() != want.tobytes()
                ):
                    start = sh.index[0].start or 0
                    problems.append(
                        f"score rows[{start}:{start + host.shape[0]}] on "
                        f"{sh.device} diverge bitwise from the gen-"
                        f"{state.gen} mirror"
                    )
            return problems

    # -- device view (mesh sharding) ---------------------------------------
    def _device_capacity_locked(self, ct: ClusterTensors, cfg):
        """Sharded device-resident capacity for the resident generation.
        Steady-state node updates re-upload ONLY the shards whose regions
        went dirty; layout changes (full reflatten) or a chaos-dropped
        shard refresh fall back to a whole-tensor upload. Returns None
        when the mesh doesn't divide the bucket (callers shard on the
        fly)."""
        import jax

        from ..chaos.plane import chaos_site
        from ..utils.backend import shard_put

        mp = cfg.n_node_shards
        pn = ct.padded_n
        if mp <= 1 or pn % mp != 0 or ct.region_ids is None:
            return None
        if (
            self._dev_capacity is None
            or self._dev_layout_gen != ct.layout_gen
            or self._dev_capacity.shape != ct.capacity.shape
        ):
            self._dev_capacity = shard_put(ct.capacity, ("nodes",), cfg)
            self._dev_layout_gen = ct.layout_gen
            self._dirty_regions.clear()
            self.full_uploads += 1
            return self._dev_capacity
        if not self._dirty_regions:
            return self._dev_capacity
        if chaos_site("mesh.shard_refresh_drop") == "drop":
            # a dropped shard upload must never serve stale capacity:
            # recovery is a whole-tensor re-upload on this access
            self._dev_capacity = shard_put(ct.capacity, ("nodes",), cfg)
            self._dirty_regions.clear()
            self.full_uploads += 1
            return self._dev_capacity
        seg = pn // mp
        rows = np.flatnonzero(
            np.isin(ct.region_ids, list(self._dirty_regions))
        )
        dirty_shards = {int(r) // seg for r in rows}
        arr = self._dev_capacity
        bufs = []
        for sh in arr.addressable_shards:
            start = sh.index[0].start or 0
            if start // seg in dirty_shards:
                bufs.append(
                    jax.device_put(
                        ct.capacity[start : start + seg], sh.device
                    )
                )
            else:
                bufs.append(sh.data)
        self._dev_capacity = jax.make_array_from_single_device_arrays(
            ct.capacity.shape, arr.sharding, bufs
        )
        self._dirty_regions.clear()
        self.shard_uploads += 1
        return self._dev_capacity

    # -- refresh machinery -------------------------------------------------
    def _rebuild_locked(self, snap) -> ClusterTensors:
        self.full_flattens += 1
        self._ct = replace(
            flatten_cluster(snap), layout_gen=self.full_flattens
        )
        return self._ct

    def _refresh_locked(self, snap) -> ClusterTensors:
        ct = self._ct
        if ct is not None and snap.index < ct.index:
            # A worker holding an older snapshot than the resident
            # generation: serve the RESIDENT build. Its usage is newer
            # than the snapshot — strictly MORE accurate for optimistic
            # placement (it already includes commits the snapshot
            # missed); the plan applier re-checks against live state
            # either way. The alternative (a transient rebuild from the
            # old snapshot) is quadratically worse under pipelined
            # workers: it is a full reflatten per pass, its row order
            # differs from the resident layout (layout_gen 0) so the
            # shared optimistic overlay gets dropped, and its usage
            # EXCLUDES the other workers' in-flight commits — measured
            # as >90% applier rejection of whole passes.
            self.stale_builds += 1
            return ct
        if ct is None:
            return self._rebuild_locked(snap)
        if snap.index == ct.index:
            self.hits += 1
            return ct
        journal = getattr(snap, "journal", None)
        if journal is None:
            return self._rebuild_locked(snap)
        changes = journal.since(ct.index, snap.index)
        if changes is None:
            return self._rebuild_locked(snap)
        node_keys = changes.get("nodes", set())
        alloc_nodes = changes.get("node_allocs", set())
        if not node_keys and not alloc_nodes:
            # index advanced without touching schedulable state
            self._ct = replace(ct, index=snap.index)
            self.hits += 1
            return self._ct

        new_nodes: list = []
        for nid in node_keys:
            node = snap.node_by_id(nid)
            if node is None:
                return self._rebuild_locked(snap)  # node removed
            row = ct.node_row.get(nid)
            if row is None:
                new_nodes.append(node)
                continue
            # class/dc changes invalidate representative-node memoization.
            # device_class folds into computed_class (structs/node.py), so
            # an accelerator-class flip always lands here and forces the
            # rebuild — the cache can never serve a stale class column.
            cid = ct.class_vocab.get(node.computed_class or "")
            if cid is None or cid != ct.class_ids[row]:
                return self._rebuild_locked(snap)
            did = ct.dc_vocab.get(node.datacenter)
            if did is None or did != ct.dc_ids[row]:
                return self._rebuild_locked(snap)
            # belt-and-braces for hand-mutated nodes that skipped
            # compute_class(): a raw device_class change alone still
            # invalidates the heterogeneity column
            dcid = ct.device_class_vocab.get(
                getattr(node, "device_class", "")
            )
            dcol = ct.device_class_ids
            if dcid is None or (
                dcol is not None and dcid != dcol[row]
            ):
                return self._rebuild_locked(snap)
        if ct.num_nodes + len(new_nodes) > ct.padded_n:
            return self._rebuild_locked(snap)  # bucket overflow

        self.incremental_refreshes += 1
        dims = ct.capacity.shape[1]
        capacity = ct.capacity.copy()
        used = ct.used.copy()
        ready = ct.ready.copy()
        dc_ids = ct.dc_ids.copy()
        class_ids = ct.class_ids.copy()
        region_ids = (
            ct.region_ids.copy() if ct.region_ids is not None else None
        )
        region_vocab = dict(ct.region_vocab)
        node_ids = list(ct.node_ids)
        nodes = list(ct.nodes)
        node_row = dict(ct.node_row)
        dc_vocab = dict(ct.dc_vocab)
        class_vocab = dict(ct.class_vocab)
        class_rep = list(ct.class_rep)
        device_class_ids, _ = ct.device_class_column()
        device_class_ids = device_class_ids.copy()
        device_class_vocab = dict(ct.device_class_vocab)
        num_nodes = ct.num_nodes
        # attribute columns referencing changed nodes go stale; drop them
        # (recomputed lazily — node attribute changes are rare next to
        # alloc churn, which never touches these)
        attr_cache = dict(ct.attr_cache) if not node_keys else {}

        for node in new_nodes:
            row = num_nodes
            num_nodes += 1
            node_row[node.id] = row
            node_ids.append(node.id)
            nodes.append(node)
            if not node.computed_class:
                node.compute_class()
            cid = class_vocab.setdefault(node.computed_class, len(class_vocab))
            if cid == len(class_rep):
                class_rep.append(row)
            class_ids[row] = cid
            dc_ids[row] = dc_vocab.setdefault(node.datacenter, len(dc_vocab))
            device_class_ids[row] = device_class_vocab.setdefault(
                getattr(node, "device_class", ""), len(device_class_vocab)
            )
            capacity[row] = node_comparable_capacity(node).to_vector()
            ready[row] = node.ready()
            used[row] = _node_used(snap, node.id, dims)
            if region_ids is not None:
                # appended rows break strict region-major contiguity
                # until the next full reflatten re-sorts; sharding
                # correctness (hierarchical top-k) never depends on
                # contiguity — only shard-locality of the prefilters does
                from .flatten import _region_name, region_key

                region_ids[row] = region_vocab.setdefault(
                    _region_name(region_key(node)), len(region_vocab)
                )
                self._dirty_regions.add(int(region_ids[row]))

        for nid in node_keys:
            row = node_row[nid]
            if row >= ct.num_nodes:
                continue  # appended above
            node = snap.node_by_id(nid)
            nodes[row] = node
            capacity[row] = node_comparable_capacity(node).to_vector()
            ready[row] = node.ready()
            used[row] = _node_used(snap, nid, dims)
            if region_ids is not None:
                self._dirty_regions.add(int(region_ids[row]))

        for nid in alloc_nodes:
            if nid in node_keys:
                continue  # already recomputed
            row = node_row.get(nid)
            if row is None:
                continue  # alloc on an unknown node — nothing resident
            used[row] = _node_used(snap, nid, dims)

        self._ct = ClusterTensors(
            node_ids=node_ids,
            index=snap.index,
            num_nodes=num_nodes,
            capacity=capacity,
            used=used,
            ready=ready,
            dc_ids=dc_ids,
            class_ids=class_ids,
            dc_vocab=dc_vocab,
            class_vocab=class_vocab,
            class_rep=class_rep,
            node_row=node_row,
            nodes=nodes,
            attr_cache=attr_cache,
            device_class_ids=device_class_ids,
            device_class_vocab=device_class_vocab,
            region_ids=region_ids,
            region_vocab=region_vocab,
            # incremental refresh never reorders existing rows (new nodes
            # append) — row-indexed overlays stay valid
            layout_gen=ct.layout_gen,
        )
        return self._ct
