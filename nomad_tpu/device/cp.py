"""Batched joint placement as an assignment relaxation solved on device.

The CP/ILP job-dispatcher line (PAPERS.md: arxiv 2009.10348, constraint-
based pod packing arxiv 2511.08373) models dispatch as one assignment
problem: variables = (group-slot × node), constraints = per-node
capacity over every resource dim, distinct_hosts, cross-group coupling,
priority tiers. This module is that formulation over the dense score
matrix (device/score.py finals), solved by **iterated proportional
rounding** — an auction-flavored price loop:

  1. price the matrix: ``u[g, n] = score[g, n] − λ[n] − anti·sib[g, n]``
     (λ = per-node congestion price, sib = OTHER same-job groups'
     instances already rounded onto the node this pass — the in-batch
     anti-affinity coupling the per-group kernels cannot see; a group's
     own instances are priced only by λ and blocked only by
     distinct_hosts, so piling a group on its best node stays free);
  2. every unfinished group claims its argmax-feasible node (the
     proportional assignment, rounded to its most-confident row);
  3. each contested node admits ONE claimant — highest priority tier
     first, then highest priced utility (first index on ties) — and
     commits exactly one instance, so per-node capacity is re-checked
     against the committed ``used`` and can never be exceeded;
  4. λ rises on every node with leftover claimants (the capacity-
     violation price update of the relaxation: demand beyond the one
     slot a node can absorb per round) and RELAXES on nodes nobody
     claims — congestion pricing, not a ratchet, so a node priced up
     during an early contested phase recovers once demand moves on —
     and the loop repeats until a round commits nothing.

Up to min(G, N) instances commit per round, against the slot-at-a-time
greedy kernels' one — the same generalization device/preempt.py made
for victim selection, now for whole-batch placement.

Byte-parity discipline (scheduler/hetero.py's contract): the jitted
kernel (``lax.while_loop``) and the NumPy host oracle share one round's
math through the ``_cp_*`` helpers; every carried value is f32/i32,
every op is elementwise/argmax/integer-sum (no transcendentals, no
float reductions — XLA's ``exp`` and sum orders are not bitwise
NumPy's, so prices update from exact integer claim counts scaled by a
power of two), and ties break on the first index in both argmax
implementations. The parity tests compare uint32 views.

Only ``scheduler/cp.py`` and the algorithm registry may call into this
module — lint rule NTA016 (SolverSeamDiscipline).
"""

from __future__ import annotations

import functools

import numpy as np

from ..utils.backend import traced_jit

import jax
import jax.numpy as jnp

# Price step per leftover claimant: a power of two, so the f32 multiply
# is exact and host/device prices agree bitwise.
ETA = np.float32(0.125)
# In-batch same-job co-location penalty (soft anti-affinity across task
# groups of one job). Also a power of two for exact f32 scaling.
ANTI = np.float32(0.0625)

_NEG_INF = np.float32(-np.inf)


def _steps_bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


# -- shared round math (np and jnp, identical op order) ----------------------


def _cp_feasible(capacity, used, asks, eligible, job_counts, assigned_sib,
                 distinct):
    """bool[G, N]: capacity room for one more instance ∧ eligible ∧
    distinct_hosts honored against existing allocs AND same-job
    instances rounded earlier in this pass."""
    xp = np if isinstance(capacity, np.ndarray) else jnp
    proposed = used[None, :, :] + asks[:, None, :]  # [G, N, D]
    fits = xp.all(proposed <= capacity[None, :, :], axis=-1)
    taken = (job_counts + assigned_sib) > 0
    return fits & eligible & ~(distinct[:, None] & taken)


def _cp_siblings(jobgrp, assigned):
    """Two i32[G, N] views of same-job commits this pass (integer matmul
    — exact and order-free): ``sib_all`` counts every same-job instance
    (what distinct_hosts must honor), ``sib_other`` excludes the group's
    own instances (what the anti-affinity price charges — a group never
    repels itself off its best node)."""
    xp = np if isinstance(assigned, np.ndarray) else jnp
    same = (jobgrp[:, None] == jobgrp[None, :]).astype(xp.int32)
    sib_all = same @ assigned
    return sib_all, sib_all - assigned


def _cp_priced(scores, lam, sib):
    """f32[G, N] priced utilities (all elementwise — bitwise portable)."""
    xp = np if isinstance(scores, np.ndarray) else jnp
    return scores - lam[None, :] - ANTI * sib.astype(xp.float32)


def _cp_winners(umask, feas, active, prio, arange_g, arange_n):
    """One auction round's selection. Every unfinished group claims its
    argmax feasible node; each claimed node admits the claimant with the
    highest (priority, priced utility) — lexicographic via two masked
    maxes, no magnitude mixing. Returns (claim i32[G], claimable bool[G],
    won bool[G], win i32[N], has bool[N], claims i32[N])."""
    xp = np if isinstance(prio, np.ndarray) else jnp
    claim = xp.argmax(umask, axis=1).astype(xp.int32)
    claimable = active & xp.any(feas, axis=1)
    claim_m = claimable[:, None] & (claim[:, None] == arange_n[None, :])
    neg = xp.float32(_NEG_INF)
    prio_m = xp.where(claim_m, prio[:, None], neg)
    maxprio = prio_m.max(axis=0)  # f32[N]
    uclaim = umask[arange_g, claim]  # f32[G], finite where claimable
    conf_ok = claim_m & (prio[:, None] == maxprio[None, :])
    conf_m = xp.where(conf_ok, uclaim[:, None], neg)
    win = xp.argmax(conf_m, axis=0).astype(xp.int32)
    has = xp.any(claim_m, axis=0)
    won = claimable & has[claim] & (win[claim] == arange_g)
    claims = claim_m.astype(xp.int32).sum(axis=0)  # exact integer sum
    return claim, claimable, won, win, has, claims


@functools.partial(
    traced_jit, retrace_budget=16, static_argnames=("steps", "max_c")
)
def cp_place_kernel(
    capacity,  # f32[N, D]
    used0,  # f32[N, D]
    asks,  # f32[G, D]
    counts,  # i32[G]
    eligible,  # bool[G, N]
    scores,  # f32[G, N] dense score matrix (registry score_group finals)
    prio,  # f32[G] job priority (exact small ints)
    job_counts,  # i32[G, N] existing same-job allocs per node
    distinct,  # bool[G] distinct_hosts groups
    jobgrp,  # i32[G] job grouping codes (same job → same code)
    lam0,  # f32[N] initial prices (zeros; chaos perturbs)
    steps: int,
    max_c: int,
):
    """Iterated proportional rounding on device. Returns (choices
    i32[G, C], choice_scores f32[G, C], used f32[N, D], rounds i32,
    lam f32[N]) — C = max_c, -1 = unfilled, rounds = committing rounds."""
    g, n = scores.shape
    arange_g = jnp.arange(g)
    arange_n = jnp.arange(n)

    def cond(carry):
        it, progress = carry[0], carry[1]
        return (it < steps) & progress

    def body(carry):
        it, _, rounds, used, placed, assigned, choices, choice_scores, lam \
            = carry
        sib_all, sib_other = _cp_siblings(jobgrp, assigned)
        feas = _cp_feasible(
            capacity, used, asks, eligible, job_counts, sib_all, distinct
        )
        active = placed < counts
        umask = jnp.where(
            feas, _cp_priced(scores, lam, sib_other), _NEG_INF
        )
        claim, claimable, won, win, has, claims = _cp_winners(
            umask, feas, active, prio, arange_g, arange_n
        )
        # commit: ≤1 instance per group (its claim) and ≤1 per node (the
        # winner) per round — injective both ways, so the single-instance
        # fit check in `feas` is exactly the capacity invariant
        delta = jnp.where(has[:, None], asks[win], jnp.float32(0.0))
        used = used + delta
        slot = jnp.minimum(placed, max_c - 1)
        old_c = choices[arange_g, slot]
        old_s = choice_scores[arange_g, slot]
        choices = choices.at[arange_g, slot].set(
            jnp.where(won, claim, old_c)
        )
        choice_scores = choice_scores.at[arange_g, slot].set(
            jnp.where(won, scores[arange_g, claim], old_s)
        )
        onehot = (won[:, None] & (claim[:, None] == arange_n[None, :]))
        assigned = assigned + onehot.astype(jnp.int32)
        placed = placed + won.astype(jnp.int32)
        # capacity-violation price update: demand beyond the one slot a
        # node absorbed this round (exact integer count × power of two);
        # unclaimed nodes decay back toward 0 so stale congestion never
        # permanently repels demand from a node with room
        lam = lam + ETA * jnp.maximum(claims - 1, 0).astype(jnp.float32)
        lam = jnp.where(
            claims == 0, jnp.maximum(lam - ETA, jnp.float32(0.0)), lam
        )
        progress = jnp.any(claimable)
        rounds = rounds + progress.astype(jnp.int32)
        return (it + 1, progress, rounds, used, placed, assigned,
                choices, choice_scores, lam)

    carry = (
        jnp.int32(0),
        jnp.bool_(True),
        jnp.int32(0),
        used0,
        jnp.zeros(g, dtype=jnp.int32),
        jnp.zeros((g, n), dtype=jnp.int32),
        jnp.full((g, max_c), -1, dtype=jnp.int32),
        jnp.zeros((g, max_c), dtype=jnp.float32),
        lam0,
    )
    out = jax.lax.while_loop(cond, body, carry)
    _, _, rounds, used, _, _, choices, choice_scores, lam = out
    return choices, choice_scores, used, rounds, lam


def oracle_cp_place(
    capacity: np.ndarray,
    used0: np.ndarray,
    asks: np.ndarray,
    counts: np.ndarray,
    eligible: np.ndarray,
    scores: np.ndarray,
    prio: np.ndarray,
    job_counts: np.ndarray,
    distinct: np.ndarray,
    jobgrp: np.ndarray,
    lam0: np.ndarray,
    steps: int,
    max_c: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, int, np.ndarray]:
    """Pure-NumPy host oracle: the same round math as the device kernel,
    stepwise. Byte-identical output is the contract (tests/test_cp.py
    pins uint32 views across seeds, like hetero's oracle)."""
    g, n = scores.shape
    arange_g = np.arange(g)
    arange_n = np.arange(n)
    used = used0.astype(np.float32).copy()
    placed = np.zeros(g, dtype=np.int32)
    assigned = np.zeros((g, n), dtype=np.int32)
    choices = np.full((g, max_c), -1, dtype=np.int32)
    choice_scores = np.zeros((g, max_c), dtype=np.float32)
    lam = lam0.astype(np.float32).copy()
    counts = counts.astype(np.int32)
    it = 0
    rounds = 0
    progress = True
    while it < steps and progress:
        sib_all, sib_other = _cp_siblings(jobgrp, assigned)
        feas = _cp_feasible(
            capacity, used, asks, eligible, job_counts, sib_all, distinct
        )
        active = placed < counts
        umask = np.where(
            feas, _cp_priced(scores, lam, sib_other), _NEG_INF
        )
        claim, claimable, won, win, has, claims = _cp_winners(
            umask, feas, active, prio, arange_g, arange_n
        )
        delta = np.where(has[:, None], asks[win], np.float32(0.0))
        used = used + delta
        slot = np.minimum(placed, max_c - 1)
        old_c = choices[arange_g, slot]
        old_s = choice_scores[arange_g, slot]
        choices[arange_g, slot] = np.where(won, claim, old_c)
        choice_scores[arange_g, slot] = np.where(
            won, scores[arange_g, claim], old_s
        )
        onehot = won[:, None] & (claim[:, None] == arange_n[None, :])
        assigned = assigned + onehot.astype(np.int32)
        placed = placed + won.astype(np.int32)
        lam = lam + ETA * np.maximum(claims - 1, 0).astype(np.float32)
        lam = np.where(
            claims == 0, np.maximum(lam - ETA, np.float32(0.0)), lam
        )
        progress = bool(claimable.any())
        rounds += int(progress)
        it += 1
    return choices, choice_scores, used, rounds, lam


# -- gang/topology extension (separate kernel: zero added retraces and
# guaranteed bit-identity on the gang-less path, which never enters here) ----


def topo_onehot(ids: np.ndarray, width: int) -> np.ndarray:
    """i32[N, W] one-hot of per-node topology level ids with id 0 (the
    coordinate-less "") zeroed out: a node without a coordinate is
    adjacent to nothing, not to every other bare node. ``width`` is the
    bucket-padded vocab size (static kernel dim)."""
    n = ids.shape[0]
    oh = np.zeros((n, width), dtype=np.int32)
    mask = ids > 0
    oh[np.arange(n)[mask], ids[mask]] = 1
    return oh


def _cp_gang_same(gang):
    """i32[G, G] gang co-membership, INCLUDING self (a member's own
    instances attract/repel each other too — the ICI-adjacent-slice
    case). Gang id 0 = not in any gang."""
    xp = np if isinstance(gang, np.ndarray) else jnp
    return (
        (gang[:, None] == gang[None, :]) & (gang[:, None] > 0)
    ).astype(xp.int32)


def _cp_topo_mates(same_gang, assigned, level_oh):
    """i32[G, N]: for each group row, how many gang-mate instances are
    already committed on nodes sharing each node's coordinate at one
    topology level. Three integer matmuls — exact and order-free:
    per-node mate counts → per-coordinate totals → broadcast back."""
    per_node = same_gang @ assigned  # i32[G, N]
    per_level = per_node @ level_oh  # i32[G, W]
    return per_level @ level_oh.T  # i32[G, N]


def _cp_gang_priced(scores, lam, sib, topo):
    """f32[G, N] priced utilities with the signed topology term added
    (elementwise, fixed order — bitwise portable)."""
    xp = np if isinstance(scores, np.ndarray) else jnp
    return scores - lam[None, :] - ANTI * sib.astype(xp.float32) + topo


# topology weights quantize to this binary grid so the weighted mate
# sum accumulates in i32 (exact, fusion-proof — an f32 a*w1 + b*w2
# leaves XLA free to contract into an FMA, and whether it does varies
# with the sharding, a 1-ulp portability leak) and rescales by an
# exact power of two
TOPO_WEIGHT_SCALE = 256


def _cp_topo_quant(w):
    """i32[G] topology weights on the 1/256 grid (round-half-even,
    matching np.round/jnp.round on both hosts)."""
    xp = np if isinstance(w, np.ndarray) else jnp
    return xp.round(w * TOPO_WEIGHT_SCALE).astype(xp.int32)


def _cp_topo_term(q_rack, q_pod, q_ici, mates_rack, mates_pod, mates_ici):
    """f32[G, N] signed topology term: all-integer weighted sum over the
    three levels (rack, pod, ici — the normalized ICI-hop-distance
    coordinate), then one exact power-of-two rescale — bitwise identical
    under any mesh partitioning."""
    xp = np if isinstance(mates_rack, np.ndarray) else jnp
    acc = (
        q_rack[:, None] * mates_rack
        + q_pod[:, None] * mates_pod
        + q_ici[:, None] * mates_ici
    )
    return acc.astype(xp.float32) * xp.float32(1.0 / TOPO_WEIGHT_SCALE)


@functools.partial(
    traced_jit, retrace_budget=16, static_argnames=("steps", "max_c")
)
def cp_gang_place_kernel(
    capacity,  # f32[N, D]
    used0,  # f32[N, D]
    asks,  # f32[G, D]
    counts,  # i32[G]
    eligible,  # bool[G, N]
    scores,  # f32[G, N]
    prio,  # f32[G]
    job_counts,  # i32[G, N]
    distinct,  # bool[G]
    jobgrp,  # i32[G]
    gang,  # i32[G] gang ids (0 = not ganged)
    w_rack,  # f32[G] signed rack-level topology weight (+colocate/−spread)
    w_pod,  # f32[G] signed pod-level topology weight
    w_ici,  # f32[G] signed ici-level topology weight (hop-distance slice)
    rack_oh,  # i32[N, R] one-hot rack ids (col 0 zeroed)
    pod_oh,  # i32[N, P] one-hot pod ids (col 0 zeroed)
    ici_oh,  # i32[N, I] one-hot ici slice ids (col 0 zeroed)
    lam0,  # f32[N]
    steps: int,
    max_c: int,
):
    """cp_place_kernel + gang topology pricing + reservation holds.

    Two additions to the round: (1) priced utility gains a signed
    topology term — gang-mate instances already reserved on same-rack/
    same-pod nodes attract (colocate, +w) or repel (spread, −w) further
    members, so the first member to land seeds the rack the rest of the
    gang follows into; (2) a gang member's wins are RESERVATIONS, not
    final placements — they hold capacity inside the loop (feasibility
    stays exact) but a gang whose members cannot all reach their counts
    releases every member's reservations in the host post-pass
    (``release_incomplete_gangs``), with the λ prices carrying out of
    the pass untouched. Committing per-round only when every member won
    simultaneously would deadlock: members of one gang share identical
    score rows, claim the same argmax node, and at most one can win any
    round. Returns the cp_place_kernel tuple plus ``waits`` i32[G]:
    rounds a group was active and claimable but lost its node (the
    explain release_rounds provenance)."""
    g, n = scores.shape
    arange_g = jnp.arange(g)
    arange_n = jnp.arange(n)
    same_gang = _cp_gang_same(gang)
    q_rack = _cp_topo_quant(w_rack)
    q_pod = _cp_topo_quant(w_pod)
    q_ici = _cp_topo_quant(w_ici)

    def cond(carry):
        it, progress = carry[0], carry[1]
        return (it < steps) & progress

    def body(carry):
        (it, _, rounds, used, placed, assigned, choices, choice_scores,
         lam, waits) = carry
        sib_all, sib_other = _cp_siblings(jobgrp, assigned)
        feas = _cp_feasible(
            capacity, used, asks, eligible, job_counts, sib_all, distinct
        )
        active = placed < counts
        mates_rack = _cp_topo_mates(same_gang, assigned, rack_oh)
        mates_pod = _cp_topo_mates(same_gang, assigned, pod_oh)
        mates_ici = _cp_topo_mates(same_gang, assigned, ici_oh)
        topo = _cp_topo_term(
            q_rack, q_pod, q_ici, mates_rack, mates_pod, mates_ici
        )
        umask = jnp.where(
            feas, _cp_gang_priced(scores, lam, sib_other, topo), _NEG_INF
        )
        claim, claimable, won, win, has, claims = _cp_winners(
            umask, feas, active, prio, arange_g, arange_n
        )
        waits = waits + (claimable & ~won).astype(jnp.int32)
        delta = jnp.where(has[:, None], asks[win], jnp.float32(0.0))
        used = used + delta
        slot = jnp.minimum(placed, max_c - 1)
        old_c = choices[arange_g, slot]
        old_s = choice_scores[arange_g, slot]
        choices = choices.at[arange_g, slot].set(
            jnp.where(won, claim, old_c)
        )
        choice_scores = choice_scores.at[arange_g, slot].set(
            jnp.where(won, scores[arange_g, claim], old_s)
        )
        onehot = (won[:, None] & (claim[:, None] == arange_n[None, :]))
        assigned = assigned + onehot.astype(jnp.int32)
        placed = placed + won.astype(jnp.int32)
        lam = lam + ETA * jnp.maximum(claims - 1, 0).astype(jnp.float32)
        lam = jnp.where(
            claims == 0, jnp.maximum(lam - ETA, jnp.float32(0.0)), lam
        )
        progress = jnp.any(claimable)
        rounds = rounds + progress.astype(jnp.int32)
        return (it + 1, progress, rounds, used, placed, assigned,
                choices, choice_scores, lam, waits)

    carry = (
        jnp.int32(0),
        jnp.bool_(True),
        jnp.int32(0),
        used0,
        jnp.zeros(g, dtype=jnp.int32),
        jnp.zeros((g, n), dtype=jnp.int32),
        jnp.full((g, max_c), -1, dtype=jnp.int32),
        jnp.zeros((g, max_c), dtype=jnp.float32),
        lam0,
        jnp.zeros(g, dtype=jnp.int32),
    )
    out = jax.lax.while_loop(cond, body, carry)
    _, _, rounds, used, _, _, choices, choice_scores, lam, waits = out
    return choices, choice_scores, used, rounds, lam, waits


def oracle_cp_gang_place(
    capacity: np.ndarray,
    used0: np.ndarray,
    asks: np.ndarray,
    counts: np.ndarray,
    eligible: np.ndarray,
    scores: np.ndarray,
    prio: np.ndarray,
    job_counts: np.ndarray,
    distinct: np.ndarray,
    jobgrp: np.ndarray,
    gang: np.ndarray,
    w_rack: np.ndarray,
    w_pod: np.ndarray,
    w_ici: np.ndarray,
    rack_oh: np.ndarray,
    pod_oh: np.ndarray,
    ici_oh: np.ndarray,
    lam0: np.ndarray,
    steps: int,
    max_c: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, int, np.ndarray, np.ndarray]:
    """Pure-NumPy host oracle for cp_gang_place_kernel — same round
    math, stepwise, byte-identical outputs (uint32-view pinned)."""
    g, n = scores.shape
    arange_g = np.arange(g)
    arange_n = np.arange(n)
    same_gang = _cp_gang_same(gang)
    q_rack = _cp_topo_quant(w_rack)
    q_pod = _cp_topo_quant(w_pod)
    q_ici = _cp_topo_quant(w_ici)
    used = used0.astype(np.float32).copy()
    placed = np.zeros(g, dtype=np.int32)
    assigned = np.zeros((g, n), dtype=np.int32)
    choices = np.full((g, max_c), -1, dtype=np.int32)
    choice_scores = np.zeros((g, max_c), dtype=np.float32)
    lam = lam0.astype(np.float32).copy()
    waits = np.zeros(g, dtype=np.int32)
    counts = counts.astype(np.int32)
    it = 0
    rounds = 0
    progress = True
    while it < steps and progress:
        sib_all, sib_other = _cp_siblings(jobgrp, assigned)
        feas = _cp_feasible(
            capacity, used, asks, eligible, job_counts, sib_all, distinct
        )
        active = placed < counts
        mates_rack = _cp_topo_mates(same_gang, assigned, rack_oh)
        mates_pod = _cp_topo_mates(same_gang, assigned, pod_oh)
        mates_ici = _cp_topo_mates(same_gang, assigned, ici_oh)
        topo = _cp_topo_term(
            q_rack, q_pod, q_ici, mates_rack, mates_pod, mates_ici
        )
        umask = np.where(
            feas, _cp_gang_priced(scores, lam, sib_other, topo), _NEG_INF
        )
        claim, claimable, won, win, has, claims = _cp_winners(
            umask, feas, active, prio, arange_g, arange_n
        )
        waits = waits + (claimable & ~won).astype(np.int32)
        delta = np.where(has[:, None], asks[win], np.float32(0.0))
        used = used + delta
        slot = np.minimum(placed, max_c - 1)
        old_c = choices[arange_g, slot]
        old_s = choice_scores[arange_g, slot]
        choices[arange_g, slot] = np.where(won, claim, old_c)
        choice_scores[arange_g, slot] = np.where(
            won, scores[arange_g, claim], old_s
        )
        onehot = won[:, None] & (claim[:, None] == arange_n[None, :])
        assigned = assigned + onehot.astype(np.int32)
        placed = placed + won.astype(np.int32)
        lam = lam + ETA * np.maximum(claims - 1, 0).astype(np.float32)
        lam = np.where(
            claims == 0, np.maximum(lam - ETA, np.float32(0.0)), lam
        )
        progress = bool(claimable.any())
        rounds += int(progress)
        it += 1
    return choices, choice_scores, used, rounds, lam, waits


def release_incomplete_gangs(
    choices: np.ndarray,
    choice_scores: np.ndarray,
    used: np.ndarray,
    asks: np.ndarray,
    counts: np.ndarray,
    gang: np.ndarray,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, list[int]]:
    """Host post-pass over RAW kernel outputs (parity is pinned before
    this runs): any gang with a member short of its count releases every
    member's placements — capacity back to ``used``, choices to -1 —
    so a partially-placed gang can never leave the solver layer.
    Returns (choices, choice_scores, used, released_gang_ids)."""
    choices = choices.copy()
    choice_scores = choice_scores.copy()
    used = used.copy()
    released: list[int] = []
    placed = (choices >= 0).sum(axis=1).astype(np.int32)
    for gid in np.unique(gang[gang > 0]):
        members = np.flatnonzero(gang == gid)
        if bool(np.all(placed[members] >= counts[members])):
            continue
        released.append(int(gid))
        for g in members:
            for slot in range(choices.shape[1]):
                node = int(choices[g, slot])
                if node >= 0:
                    used[node] -= asks[g]
            choices[g, :] = -1
            choice_scores[g, :] = np.float32(0.0)
    return choices, choice_scores, used, released
