"""The batched placement kernel — the TPU replacement for the reference's
iterator-chain inner loop.

What the reference does per placement (scheduler/stack.go:343-438 chain,
scheduler/rank.go:193-527 BinPackIterator.Next): walk up to ``limit`` nodes
through ~10 iterator stages, computing fit and score sequentially in Go.
O(allocs × limit × stages), single-threaded per eval.

What this module does instead: ONE fully-parallel scoring pass per group
batch. For a group placing ``count`` identical asks, every candidate
"place the (j+1)-th instance of this group on node n" has a closed-form
score — usage is used0 + (j+1)·ask, collisions are jc0 + j — so the whole
candidate space is a dense [N, J] plane computed in one shot
(``_score_planes``). Two selection paths consume the planes:

- **Closed-form top-k** (groups with no cross-node coupling): per-node
  score columns are made monotone by a running-min clamp, which turns
  greedy placement into a single ``lax.top_k`` over the flattened plane.
  One parallel pass replaces ``count`` sequential argmax steps.

- **Gather-scan** (groups whose spread blocks / distinct_property caps
  couple nodes through global per-value counts): a ``lax.scan`` over
  placement steps that does only O(N) *gather* work per step — the heads
  of each node's precomputed column plus a [B, V] per-value boost table —
  instead of rescoring every node against every resource dim. Exact
  stepwise-greedy semantics at a fraction of the serial cost.

Batch dimension = concurrent evals/groups, replacing Nomad's worker-per-
core optimistic concurrency (nomad/worker.go:85): every group in a batch
scores against the same snapshot, and conflicts are resolved host-side by
``repair_batch_conflicts`` (using each lane's overflow candidates) before
the plan applier's authoritative re-check.

Scoring component semantics (each cites its reference):
- binpack/spread fit: nomad/structs/funcs.go:236-274, normalized /18
  (rank.go:513-516).
- job anti-affinity: −(collisions+1)/desired_count for nodes already
  holding collisions > 0 allocs of the job (rank.go:536-604).
- reschedule penalty: −1 on the node a failed alloc is being replaced
  from (rank.go:606-648).
- node affinity: weight-normalized Σ w·match / Σ|w| (rank.go:650-737),
  precomputed per node host-side (string matching ≪ scoring cost).
- spread (scheduler/spread.go:110-228): one component summing per-block
  boosts. Target mode: (desired − used−1)/desired × weight/Σweights, −1
  for untargeted values; even mode: the min/max-delta boost
  (spread.go:178-228). The component joins the normalization mean only
  when the total boost is nonzero (spread.go:168-171).
- distinct_property (feasible.go:604-707): not a score — a dynamic
  per-value cap carried through the scan's count state.
- normalization: mean over *contributing* components
  (rank.go:740-767 ScoreNormalizationIterator).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.trace import global_tracer as _tracer
from ..structs.resources import BINPACK_MAX_SCORE
from ..utils.backend import get_mesh, shard_put, traced_jit

# Retrace budgets (nomad_tpu.analysis.retrace): the per-kernel trace
# count a representative bench batch may reach. Every dynamic dimension
# is bucketed (nodes/victims/steps to powers of two, k to the overflow
# grid), so distinct static-arg combos — not calls — bound compiles; a
# kernel that blows its budget has lost a shape bucket or a static arg.
RETRACE_BUDGET = 16

_LN10 = 2.302585092994046

# value-block kinds (ValueBlocks.kinds; see flatten.py)
BLOCK_TARGET_SPREAD = 0
BLOCK_EVEN_SPREAD = 1
BLOCK_DISTINCT_CAP = 2
BLOCK_INACTIVE = -1

# extra greedy candidates emitted beyond ``count`` per lane, consumed by
# repair_batch_conflicts when optimistic batch lanes collide on a node
OVERFLOW_CANDIDATES = 16

# exact stepwise scan only for small groups; larger spread groups place in
# chunks (boost tables frozen for CHUNK placements — spread counts move
# slowly, and the host repair walk re-verifies every placement anyway)
EXACT_SCAN_MAX_COUNT = 32
CHUNK = 16


def _pow10(x):
    # KNOWN 1-ulp portability leak: XLA's exp expansion is not
    # bit-stable across shardings (fmuladd/vector-width decisions shift
    # with the per-shard loop bounds), so scores built under an active
    # mesh can differ from degenerate ones in the last bit. Solver
    # kernels stay byte-portable on FIXED inputs (tests pin that); the
    # scoring stack's cross-mesh stability is input-dependent.
    return jnp.exp(_LN10 * x)


def _topk_nodes(flat, k: int, n_shards: int = 1):
    """Top-k over the flattened node-major [N*J] plane, hierarchically
    when the node axis is sharded: per-shard local top-k, then one
    cross-shard merge over the [S·k'] candidates. BIT-IDENTICAL to the
    global ``lax.top_k`` by construction — ``lax.top_k`` orders by
    (value desc, index asc), each shard forwards a prefix of its own such
    order (min(k, seg) entries always covers the global winners, ties
    included), and candidates are concatenated shard-major so the merge's
    lowest-candidate-position tie-break IS the lowest-global-index
    tie-break. ``n_shards`` is static; 1 (or a non-dividing length)
    Python-gates to the plain global top_k, leaving the single-device
    jaxpr untouched."""
    if n_shards <= 1 or flat.shape[0] % n_shards != 0:
        return jax.lax.top_k(flat, k)
    seg = flat.shape[0] // n_shards
    k_local = min(k, seg)
    lv, li = jax.lax.top_k(flat.reshape(n_shards, seg), k_local)
    gi = li + (jnp.arange(n_shards, dtype=li.dtype) * seg)[:, None]
    mv, mpos = jax.lax.top_k(lv.reshape(-1), k)
    return mv, gi.reshape(-1)[mpos]


def _unpack_mask(packed, n: int):
    """Device-side unpack of a host np.packbits mask: u8[..., n/8] →
    bool[..., n]. Per-lane masks dominate the host→device transfer for
    big clusters (the axon tunnel moves ~35 MB/s; a dense [128, 16k]
    bool batch alone is 2 MB), so bools ride packed 8×."""
    bits = (
        packed[..., :, None]
        >> jnp.arange(7, -1, -1, dtype=packed.dtype)[None, :]
    ) & 1
    return bits.reshape(*packed.shape[:-1], -1)[..., :n].astype(bool)


def _unpack_lane_inputs(capacity, eligible, job_counts, penalty_nodes):
    """Normalize slim per-lane encodings at kernel entry (static on
    dtype/shape at trace time): packed masks unpack to [G, N]; degenerate
    [G, 1] arrays stay and broadcast through the score math."""
    n = capacity.shape[0]
    if eligible.dtype == jnp.uint8:
        eligible = _unpack_mask(eligible, n)
    if penalty_nodes.dtype == jnp.uint8:
        penalty_nodes = _unpack_mask(penalty_nodes, n)
    return eligible, job_counts.astype(jnp.int32), penalty_nodes


def component_scores(
    capacity,  # f32[N, D]
    used,  # f32[N, D] current proposed usage
    ask,  # f32[D]
    eligible,  # bool[N]
    job_counts,  # i32[N]
    desired_total,  # f32[] anti-affinity denominator
    penalty_nodes,  # bool[N]
    affinity_scores,  # f32[N]
    has_affinities,  # bool[]
    spread_boost,  # f32[N] (precomputed for this step)
    has_spreads,  # bool[]
    distinct_hosts,  # bool[]
    algorithm_spread,  # bool[] scheduler algorithm: binpack vs spread fit
    throughputs=None,  # f32[N] normalized [0, 1] class-throughput share
):
    """Per-node normalized score for placing one instance of ``ask``.
    Returns (final_score f32[N] with -inf infeasible, fits bool[N]).
    Used by the dense [G, N] score-matrix path (annotation, system
    scheduler); the placement paths use the [N, J] planes instead.

    ``throughputs`` is the heterogeneity axis: the job's per-device-class
    coefficient gathered per node and normalized by the job's best class
    (scheduler/hetero.py). When given it joins the component average like
    affinity does, and zero-throughput nodes (the job cannot progress on
    that class) become infeasible. The gate is Python-level ``None`` —
    class-less callers trace the exact same jaxpr as before the axis
    existed, which is what keeps binpack/spread bit-identical."""
    proposed = used + ask  # [N, D]
    fits = jnp.all(proposed <= capacity, axis=-1) & eligible
    fits &= jnp.where(distinct_hosts, job_counts == 0, True)
    if throughputs is not None:
        fits &= throughputs > 0.0

    free_frac = jnp.where(
        capacity > 0, (capacity - proposed) / jnp.maximum(capacity, 1e-9), 1.0
    )
    pow_sum = _pow10(free_frac[:, 0]) + _pow10(free_frac[:, 1])  # cpu, mem
    binpack = jnp.clip(20.0 - pow_sum, 0.0, BINPACK_MAX_SCORE)
    spread_fit = jnp.clip(pow_sum - 2.0, 0.0, BINPACK_MAX_SCORE)
    fit_score = jnp.where(algorithm_spread, spread_fit, binpack) / BINPACK_MAX_SCORE

    collisions = job_counts.astype(jnp.float32)
    anti = jnp.where(
        job_counts > 0, -(collisions + 1.0) / jnp.maximum(desired_total, 1.0), 0.0
    )
    resched = jnp.where(penalty_nodes, -1.0, 0.0)
    aff = jnp.where(has_affinities, affinity_scores, 0.0)
    spread_on = has_spreads & (spread_boost != 0.0)
    spread_c = jnp.where(spread_on, spread_boost, 0.0)

    n_comp = (
        1.0
        + (job_counts > 0)
        + penalty_nodes
        + jnp.where(has_affinities, 1.0, 0.0)
        + jnp.where(spread_on, 1.0, 0.0)
    )
    total = fit_score + anti + resched + aff + spread_c
    if throughputs is not None:
        total = total + throughputs
        n_comp = n_comp + 1.0
    final = total / n_comp
    return jnp.where(fits, final, -jnp.inf), fits


def _score_planes(
    capacity,  # f32[N, D]
    used0,  # f32[N, D]
    ask,  # f32[D]
    elig,  # bool[N]
    jc0,  # i32[N]
    dt,  # f32[] anti-affinity denominator
    pen,  # bool[N]
    aff,  # f32[N]
    has_aff,  # bool[]
    dh,  # bool[] distinct_hosts
    caps,  # f32[N] per-node device-slot caps
    algorithm_spread,  # bool[]
    max_j: int,
    jitter=None,  # f32[N] tie-break noise (decorrelated batch passes)
):
    """The shared [N, J] candidate planes: numerator (sum of non-spread
    components), denominator (contributing-component count, spread
    excluded — the scan adds it dynamically), and feasibility. Work in
    [N, J] planes only — a [N, J, D] temp is N·J·D·4 bytes and OOMs at
    40k-node scale; the D axis is tiny and static, so unroll it."""
    js = jnp.arange(max_j, dtype=jnp.float32)  # [J]
    mult = js[None, :] + 1.0  # [1, J]
    # Closed-form per-node feasible-column bound instead of D separate
    # [N, J] comparison planes (the r3 regression suspect): used0 +
    # (j+1)·ask ≤ cap for all dims ⇔ j < min_d floor((cap−used0)/ask).
    # The 1e-6 nudge absorbs float division round-down on exact fits.
    free0 = capacity - used0  # [N, D]
    per_dim = jnp.where(
        ask[None, :] > 0,
        jnp.floor(free0 / jnp.maximum(ask[None, :], 1e-9) + 1e-6),
        jnp.inf,
    )
    jmax = jnp.min(per_dim, axis=1)  # [N] feasible instances of this ask
    jmax = jnp.where(elig, jmax, 0.0)
    jmax = jnp.minimum(jmax, caps)  # device-slot caps
    # distinct_hosts ⇒ only j=0 and only where no existing collision
    jmax = jnp.where(
        dh,
        jnp.where(jc0 == 0, jnp.minimum(jmax, 1.0), 0.0),
        jmax,
    )
    fits = js[None, :] < jmax[:, None]  # [N, J]

    pow_sum = jnp.zeros_like(fits, dtype=jnp.float32)
    for d in (0, 1):  # cpu, mem drive the fit score
        cap_d = capacity[:, d : d + 1]
        prop_d = used0[:, d : d + 1] + mult * ask[d]
        free_d = jnp.where(
            cap_d > 0, (cap_d - prop_d) / jnp.maximum(cap_d, 1e-9), 1.0
        )
        pow_sum = pow_sum + _pow10(free_d)
    binpack = jnp.clip(20.0 - pow_sum, 0.0, BINPACK_MAX_SCORE)
    spread_fit = jnp.clip(pow_sum - 2.0, 0.0, BINPACK_MAX_SCORE)
    fit_score = (
        jnp.where(algorithm_spread, spread_fit, binpack) / BINPACK_MAX_SCORE
    )

    coll = jc0[:, None].astype(jnp.float32) + js[None, :]  # after j placed
    has_coll = coll > 0
    anti = jnp.where(has_coll, -(coll + 1.0) / jnp.maximum(dt, 1.0), 0.0)
    resched = jnp.where(pen[:, None], -1.0, 0.0)
    aff_c = jnp.where(has_aff, aff[:, None], 0.0)
    num = fit_score + anti + resched + aff_c  # [N, J]
    if jitter is not None:
        # per-call deterministic tie-break noise (~1e-5 ≪ any meaningful
        # score difference): the vector analog of the reference's
        # per-worker node shuffle (stack.go:74-90) — without it every
        # concurrent batch fills an empty homogeneous cluster in the
        # same node order and the applier bounces the later plans
        num = num + jitter[:, None]
    den = 1.0 + has_coll + pen[:, None] + jnp.where(has_aff, 1.0, 0.0)
    # slim [1]-shaped lane inputs leave den rank-deficient; the gather
    # paths index it per node, so materialize the broadcast
    num = jnp.broadcast_to(num, fits.shape)
    den = jnp.broadcast_to(den, fits.shape)
    return num, den, fits


# -- closed-form greedy (the TPU-shaped fast path) ---------------------------
#
# For one group placing ``count`` IDENTICAL asks with no per-value
# coupling, node scores are independent and the per-node score sequence
# s[n, j] is monotone non-increasing in j after a running-min clamp
# (binpack worsens with usage, anti-affinity grows; the single
# non-monotone corner — a rising best-fit head — is flattened by the
# clamp, under which top-k fills nodes in descending initial-score order,
# exactly what stepwise greedy does with rising heads). Greedy placement
# then equals a plain top-k over the flattened [N, J] matrix.
#
# This is the "batched dense score matrix" BASELINE.json names as the
# north-star replacement for the reference's per-placement iterator walk
# (scheduler/rank.go:193-527): O(N·J) parallel work, O(log) depth.


@functools.partial(traced_jit, retrace_budget=RETRACE_BUDGET,
                   static_argnames=("max_j", "k", "n_shards"))
def place_closed_form_kernel(
    capacity,  # f32[N, D] shared
    used0,  # f32[N, D] shared snapshot usage
    asks,  # f32[G, D]
    eligible,  # bool[G, N]
    job_counts,  # i32[G, N]
    desired_totals,  # f32[G]
    penalty_nodes,  # bool[G, N]
    affinity_scores,  # f32[G, N]
    has_affinities,  # bool[G]
    distinct_hosts,  # bool[G]
    slot_caps,  # f32[G, N]
    algorithm_spread,  # bool[]
    counts,  # i32[G]
    max_j: int,  # static: max instances of one group per node
    k: int,  # static: top-k width (≥ max count in batch + overflow)
    jitter=None,  # f32[N] tie-break noise, shared across lanes
    n_shards: int = 1,  # static: node-axis mesh shards (hierarchical top-k)
):
    """Returns (choices i32[G, k], scores f32[G, k]) in greedy order.
    Entries past a lane's feasible candidates are −1/−inf; entries in
    [count, k) are valid *overflow* candidates for conflict repair."""

    eligible, job_counts, penalty_nodes = _unpack_lane_inputs(
        capacity, eligible, job_counts, penalty_nodes
    )

    def one_group(ask, elig, jc0, dt, pen, aff, has_aff, dh, caps, count):
        num, den, fits = _score_planes(
            capacity, used0, ask, elig, jc0, dt, pen, aff, has_aff, dh,
            caps, algorithm_spread, max_j, jitter=jitter,
        )
        s_raw = jnp.where(fits, num / den, -jnp.inf)
        # Selection runs on the running-min clamp: it restores the prefix
        # rule "(n,j) requires (n,j-1)" that plain top-k needs.
        s_sel = jax.lax.associative_scan(jnp.minimum, s_raw, axis=1)

        flat_sel = s_sel.reshape(-1)  # [N*J]
        flat_raw = s_raw.reshape(-1)
        k_eff = min(k, flat_sel.shape[0])  # tiny clusters: < k slots total
        # node-major flattening keeps each shard's rows contiguous in
        # flat index space, so the hierarchical reduction applies as-is
        top_sel, top_idx = _topk_nodes(flat_sel, k_eff, n_shards)
        if k_eff < k:
            pad = k - k_eff
            top_sel = jnp.concatenate(
                [top_sel, jnp.full(pad, -jnp.inf, top_sel.dtype)]
            )
            top_idx = jnp.concatenate([top_idx, jnp.zeros(pad, top_idx.dtype)])
        # report the TRUE (unclamped) score of each chosen (n, j) — the
        # AllocMetric the oracle would have recorded for that placement
        top_raw = flat_raw[top_idx]
        node_rows = (top_idx // max_j).astype(jnp.int32)
        ok = top_sel > -jnp.inf  # caller slices [:count] vs overflow
        return jnp.where(ok, node_rows, -1), jnp.where(ok, top_raw, -jnp.inf)

    choices, scores = jax.vmap(one_group)(
        asks, eligible, job_counts, desired_totals, penalty_nodes,
        affinity_scores, has_affinities, distinct_hosts, slot_caps, counts,
    )
    # one fused [G, 2k] i32 result: the tunnel-attached TPU pays a full
    # round trip per fetched array, so scores ride bitcast alongside rows
    return jnp.concatenate(
        [choices, jax.lax.bitcast_convert_type(scores, jnp.int32)], axis=1
    )


# -- gather-scan (spread / distinct_property groups) -------------------------


def _block_tables(c, desired, caps, weights, kinds):
    """Per-(block, value) boost + allowance tables from the current count
    state ``c`` [B, V].

    Target mode (spread.go:110-174): boost[v] = (desired − (c+1))/desired
    × weight, where weight is already weight/Σweights; desired < 0 marks a
    value with no explicit or implicit target → flat −1 (unweighted,
    spread.go:145-152).

    Even mode (spread.go:178-228 evenSpreadScoreBoost): boosts derive
    from the min/max of *positive* counts. (The reference computes min
    over a Go map that may contain cleared-to-zero entries, making the
    min==0 branch order-dependent; we define min over positive counts,
    which matches the deterministic reading.)

    Distinct caps (feasible.go:604): allow[v] = c[v] < cap[v].
    """
    # target
    t_boost = jnp.where(
        desired > 0,
        (desired - (c + 1.0)) / jnp.maximum(desired, 1e-9) * weights[:, None],
        -1.0,
    )
    # even
    pos = c > 0
    any_pos = jnp.any(pos, axis=1, keepdims=True)  # [B, 1]
    minc = jnp.min(jnp.where(pos, c, jnp.inf), axis=1, keepdims=True)
    maxc = jnp.max(jnp.where(pos, c, -jnp.inf), axis=1, keepdims=True)
    at_min = c == minc
    e_boost = jnp.where(
        at_min,
        jnp.where(minc == maxc, -1.0, (maxc - minc) / jnp.maximum(minc, 1e-9)),
        (minc - c) / jnp.maximum(minc, 1e-9),
    )
    e_boost = jnp.where(any_pos, e_boost, 0.0)

    boost = jnp.where(
        (kinds == BLOCK_TARGET_SPREAD)[:, None],
        t_boost,
        jnp.where((kinds == BLOCK_EVEN_SPREAD)[:, None], e_boost, 0.0),
    )
    allow = jnp.where((kinds == BLOCK_DISTINCT_CAP)[:, None], c < caps, True)
    return boost, allow


@functools.partial(traced_jit, retrace_budget=RETRACE_BUDGET,
                   static_argnames=("max_j", "max_steps"))
def place_value_scan_kernel(
    capacity,  # f32[N, D] shared
    used0,  # f32[N, D] shared snapshot usage
    asks,  # f32[G, D]
    eligible,  # bool[G, N]
    job_counts,  # i32[G, N]
    desired_totals,  # f32[G]
    penalty_nodes,  # bool[G, N]
    affinity_scores,  # f32[G, N]
    has_affinities,  # bool[G]
    distinct_hosts,  # bool[G]
    slot_caps,  # f32[G, N]
    block_value_ids,  # i32[G, B, N] (−1 = node has no value)
    block_counts0,  # f32[G, B, V]
    block_desired,  # f32[G, B, V]
    block_caps,  # f32[G, B, V]
    block_weights,  # f32[G, B]
    block_kinds,  # i32[G, B]
    algorithm_spread,  # bool[]
    counts,  # i32[G] placements to emit (incl. overflow slots)
    max_j: int,
    max_steps: int,
    jitter=None,  # f32[N] tie-break noise
):
    """Greedy sequential placement with per-value count coupling.

    All heavy scoring is hoisted into the parallel [N, J] plane
    precompute; each scan step gathers per-node column heads, adds the
    per-value boost/allowance tables, and argmaxes — the device-resident
    analog of re-running SpreadIterator + DistinctPropertyIterator per
    placement (scheduler/spread.go:110, feasible.go:645), at O(N) gather
    cost per step instead of O(N·D·stages) rescoring.
    """

    eligible, job_counts, penalty_nodes = _unpack_lane_inputs(
        capacity, eligible, job_counts, penalty_nodes
    )

    def one_group(
        ask, elig, jc0, dt, pen, aff, has_aff, dh, caps,
        vids, c0, desired, vcaps, weights, kinds, count,
    ):
        num, den, fits = _score_planes(
            capacity, used0, ask, elig, jc0, dt, pen, aff, has_aff, dh,
            caps, algorithm_spread, max_j, jitter=jitter,
        )
        n = num.shape[0]
        is_spread = (kinds == BLOCK_TARGET_SPREAD) | (kinds == BLOCK_EVEN_SPREAD)
        has_spread_any = jnp.any(is_spread)
        safe_vids = jnp.maximum(vids, 0)  # [B, N]

        def step(state, i):
            jn, c = state  # jn i32[N] next column per node; c f32[B, V]
            head_j = jnp.minimum(jn, max_j - 1)
            gather = lambda plane: jnp.take_along_axis(
                plane, head_j[:, None], axis=1
            )[:, 0]
            head_num = gather(num)
            head_den = gather(den)
            head_fit = gather(fits) & (jn < max_j)

            tbl, allow = _block_tables(c, desired, vcaps, weights, kinds)
            per_block = jnp.take_along_axis(tbl, safe_vids, axis=1)  # [B, N]
            contrib = jnp.where(vids >= 0, per_block, -1.0)
            boost = jnp.sum(
                jnp.where(is_spread[:, None], contrib, 0.0), axis=0
            )  # [N]
            allow_pb = jnp.take_along_axis(allow, safe_vids, axis=1)
            allowed = jnp.all(
                jnp.where(
                    (kinds == BLOCK_DISTINCT_CAP)[:, None] & (vids >= 0),
                    allow_pb,
                    True,
                ),
                axis=0,
            )  # [N]

            spread_on = has_spread_any & (boost != 0.0)
            den_t = head_den + jnp.where(spread_on, 1.0, 0.0)
            score = (head_num + jnp.where(spread_on, boost, 0.0)) / den_t
            score = jnp.where(head_fit & allowed, score, -jnp.inf)

            best = jnp.argmax(score)
            ok = (score[best] > -jnp.inf) & (i < count)
            onehot = (jnp.arange(n) == best) & ok
            jn = jn + onehot.astype(jn.dtype)
            bumped = vids[:, best]  # [B] value per block at the chosen node
            c = c + jnp.where(
                (ok & (bumped >= 0))[:, None],
                jax.nn.one_hot(
                    jnp.maximum(bumped, 0), c.shape[1], dtype=c.dtype
                ),
                0.0,
            )
            return (jn, c), (
                jnp.where(ok, best, -1).astype(jnp.int32),
                jnp.where(ok, score[best], -jnp.inf).astype(jnp.float32),
            )

        state0 = (jnp.zeros(n, dtype=jnp.int32), c0)
        _, (choices, scores) = jax.lax.scan(
            step, state0, jnp.arange(max_steps)
        )
        return choices, scores

    return jax.vmap(one_group)(
        asks, eligible, job_counts, desired_totals, penalty_nodes,
        affinity_scores, has_affinities, distinct_hosts, slot_caps,
        block_value_ids, block_counts0, block_desired, block_caps,
        block_weights, block_kinds, counts,
    )


@functools.partial(traced_jit, retrace_budget=RETRACE_BUDGET,
                   static_argnames=("max_j", "chunk", "n_chunks", "n_shards"))
def place_spread_chunked_kernel(
    capacity,  # f32[N, D] shared
    used0,  # f32[N, D] shared snapshot usage
    asks,  # f32[G, D]
    eligible,  # bool[G, N]
    job_counts,  # i32[G, N]
    desired_totals,  # f32[G]
    penalty_nodes,  # bool[G, N]
    affinity_scores,  # f32[G, N]
    has_affinities,  # bool[G]
    distinct_hosts,  # bool[G]
    slot_caps,  # f32[G, N]
    block_value_ids,  # i32[G, B, N] (−1 = node has no value)
    block_counts0,  # f32[G, B, V]
    block_desired,  # f32[G, B, V]
    block_caps,  # f32[G, B, V]
    block_weights,  # f32[G, B]
    block_kinds,  # i32[G, B]
    algorithm_spread,  # bool[]
    counts,  # i32[G] placements to emit (incl. overflow slots)
    max_j: int,
    chunk: int,
    n_chunks: int,
    jitter=None,  # f32[N] tie-break noise
    n_shards: int = 1,  # static: node-axis mesh shards (hierarchical top-k)
):
    """Chunked greedy placement for large spread-coupled groups.

    The exact gather-scan (place_value_scan_kernel) pays one sequential
    ``lax.scan`` step per placement — 250-instance groups compile to
    512-deep scans whose per-step work is a trivial gather+argmax, the
    exact wrong shape for a TPU (the r3 e2e p99 of 11.6 s lives here).
    This kernel instead freezes the per-value boost/allowance tables for
    ``chunk`` placements at a time and selects each chunk with the same
    running-min-clamp + top-k used by the closed-form path, so a
    250-instance group runs ~16 wide parallel steps instead of 512
    narrow ones. Spread counts move by at most ``chunk`` between table
    refreshes; the resulting boost staleness is bounded and verified
    against the stepwise oracle in tests (test_value_scan.py). Caps
    (distinct_property) can overshoot within a chunk, so groups with cap
    blocks stay on the exact scan — see PlacementKernel.place routing.

    Reference seam: scheduler/spread.go:110-228 recomputes boosts per
    placement; the reference tolerates far coarser approximation in the
    other direction by score-sampling only ≥100 nodes (stack.go:165-174).
    """

    eligible, job_counts, penalty_nodes = _unpack_lane_inputs(
        capacity, eligible, job_counts, penalty_nodes
    )

    def one_group(
        ask, elig, jc0, dt, pen, aff, has_aff, dh, caps,
        vids, c0, desired, vcaps, weights, kinds, count,
    ):
        num, den, fits = _score_planes(
            capacity, used0, ask, elig, jc0, dt, pen, aff, has_aff, dh,
            caps, algorithm_spread, max_j, jitter=jitter,
        )
        n = num.shape[0]
        nb = vids.shape[0]
        is_spread = (kinds == BLOCK_TARGET_SPREAD) | (kinds == BLOCK_EVEN_SPREAD)
        has_spread_any = jnp.any(is_spread)
        safe_vids = jnp.maximum(vids, 0)  # [B, N]
        js_row = jnp.arange(max_j, dtype=jnp.int32)[None, :]  # [1, J]

        def step(state, _):
            jn, c, n_placed = state  # i32[N], f32[B, V], i32[]
            tbl, allow = _block_tables(c, desired, vcaps, weights, kinds)
            per_block = jnp.take_along_axis(tbl, safe_vids, axis=1)  # [B, N]
            contrib = jnp.where(vids >= 0, per_block, -1.0)
            boost = jnp.sum(
                jnp.where(is_spread[:, None], contrib, 0.0), axis=0
            )  # [N]
            allow_pb = jnp.take_along_axis(allow, safe_vids, axis=1)
            allowed = jnp.all(
                jnp.where(
                    (kinds == BLOCK_DISTINCT_CAP)[:, None] & (vids >= 0),
                    allow_pb,
                    True,
                ),
                axis=0,
            )  # [N]

            spread_on = has_spread_any & (boost != 0.0)  # [N]
            den_t = den + jnp.where(spread_on, 1.0, 0.0)[:, None]
            s_raw = (num + jnp.where(spread_on, boost, 0.0)[:, None]) / den_t
            feas = fits & allowed[:, None] & (js_row >= jn[:, None])
            # consumed columns (j < jn) must not poison the running-min
            s_for_min = jnp.where(
                js_row < jn[:, None],
                jnp.inf,
                jnp.where(feas, s_raw, -jnp.inf),
            )
            s_sel = jax.lax.associative_scan(jnp.minimum, s_for_min, axis=1)
            s_sel = jnp.where(feas, s_sel, -jnp.inf)

            vals, idx = _topk_nodes(s_sel.reshape(-1), chunk, n_shards)
            take = (jnp.arange(chunk) + n_placed < count) & (vals > -jnp.inf)
            rows = (idx // max_j).astype(jnp.int32)
            true_scores = s_raw.reshape(-1)[idx]

            # dense masked updates — TPU scatters serialize
            jn = jn + jnp.sum(
                (jnp.arange(n)[None, :] == rows[:, None])
                & take[:, None],
                axis=0,
            ).astype(jnp.int32)
            picked_vals = vids[:, rows]  # [B, chunk]
            upd = take[None, :] & (picked_vals >= 0)
            c = c + jnp.sum(
                jnp.where(
                    upd[:, :, None],
                    picked_vals[:, :, None]
                    == jnp.arange(c.shape[1])[None, None, :],
                    False,
                ).astype(c.dtype),
                axis=1,
            )
            n_placed = n_placed + jnp.sum(take.astype(jnp.int32))
            return (jn, c, n_placed), (
                jnp.where(take, rows, -1),
                jnp.where(take, true_scores, -jnp.inf).astype(jnp.float32),
            )

        state0 = (
            jnp.zeros(n, dtype=jnp.int32),
            c0,
            jnp.zeros((), dtype=jnp.int32),
        )
        _, (choices, scores) = jax.lax.scan(
            step, state0, None, length=n_chunks
        )
        return choices.reshape(-1), scores.reshape(-1)

    return jax.vmap(one_group)(
        asks, eligible, job_counts, desired_totals, penalty_nodes,
        affinity_scores, has_affinities, distinct_hosts, slot_caps,
        block_value_ids, block_counts0, block_desired, block_caps,
        block_weights, block_kinds, counts,
    )


@functools.partial(traced_jit, retrace_budget=RETRACE_BUDGET,
                   static_argnames=("max_j", "k_seg", "n_chunks"))
def place_spread_opv_kernel(
    capacity,  # f32[N, D] shared
    used0,  # f32[N, D] shared snapshot usage
    asks,  # f32[G, D]
    eligible,  # bool[G, N]
    job_counts,  # i32[G, N]
    desired_totals,  # f32[G]
    penalty_nodes,  # bool[G, N]
    affinity_scores,  # f32[G, N]
    has_affinities,  # bool[G]
    distinct_hosts,  # bool[G]
    slot_caps,  # f32[G, N]
    block_value_ids,  # i32[G, B, N]
    block_counts0,  # f32[G, B, V]
    block_desired,  # f32[G, B, V]
    block_caps,  # f32[G, B, V]
    block_weights,  # f32[G, B]
    block_kinds,  # i32[G, B]
    enforce_idx,  # i32[G] block whose values are one-per-chunk
    algorithm_spread,  # bool[]
    counts,  # i32[G] placements to emit (incl. overflow slots)
    max_j: int,
    k_seg: int,  # picks per step = min(CHUNK, V+1)
    n_chunks: int,
    jitter=None,  # f32[N] tie-break noise
):
    """One-per-value chunked placement for even-mode spread groups.

    Even-spread boosts (spread.go:178-228) jump discontinuously as a
    value stops being the min — freezing the boost table for a plain
    CHUNK-sized step dumps the whole chunk onto the currently-min values
    and oscillates. But stepwise greedy under even-spread naturally
    *rotates* values (placing on the min value usually removes it from
    the min set), so restricting each step to at most ONE placement per
    value of the dominant even block recovers stepwise-like behavior
    while still placing up to min(CHUNK, V) instances per sequential
    step: per-value segment-max of the head scores, then top-k over the
    [V+1] segment maxima (the +1 segment holds value-less nodes).
    Depth count/min(CHUNK, V) instead of count — for the BASELINE
    config-3 shape (250 instances × 25 racks) that is 18 steps vs 512.
    """

    eligible, job_counts, penalty_nodes = _unpack_lane_inputs(
        capacity, eligible, job_counts, penalty_nodes
    )

    def one_group(
        ask, elig, jc0, dt, pen, aff, has_aff, dh, caps,
        vids, c0, desired, vcaps, weights, kinds, eidx, count,
    ):
        num, den, fits = _score_planes(
            capacity, used0, ask, elig, jc0, dt, pen, aff, has_aff, dh,
            caps, algorithm_spread, max_j, jitter=jitter,
        )
        n = num.shape[0]
        nb = vids.shape[0]
        nv = c0.shape[1]
        is_spread = (kinds == BLOCK_TARGET_SPREAD) | (kinds == BLOCK_EVEN_SPREAD)
        has_spread_any = jnp.any(is_spread)
        safe_vids = jnp.maximum(vids, 0)  # [B, N]
        evids = jnp.take(vids, eidx, axis=0)  # [N] enforce-block values
        seg = jnp.where(evids >= 0, evids, nv)  # [N]; nv = no-value segment
        # which enforce-block values actually exist on an eligible node:
        # V is padded to a power of two, and a phantom value with count 0
        # must not read as "empty" to the rotation guard (it would lock
        # the rotation onto unreachable segments and starve the chunk)
        present_v = jnp.any(
            (evids[None, :] == jnp.arange(nv)[:, None]) & elig[None, :],
            axis=1,
        )  # [V]

        def node_scores(head_num, head_den, head_ok, c):
            tbl, allow = _block_tables(c, desired, vcaps, weights, kinds)
            per_block = jnp.take_along_axis(tbl, safe_vids, axis=1)
            contrib = jnp.where(vids >= 0, per_block, -1.0)
            boost = jnp.sum(
                jnp.where(is_spread[:, None], contrib, 0.0), axis=0
            )
            allow_pb = jnp.take_along_axis(allow, safe_vids, axis=1)
            allowed = jnp.all(
                jnp.where(
                    (kinds == BLOCK_DISTINCT_CAP)[:, None] & (vids >= 0),
                    allow_pb,
                    True,
                ),
                axis=0,
            )
            spread_on = has_spread_any & (boost != 0.0)
            den_t = head_den + jnp.where(spread_on, 1.0, 0.0)
            score = (head_num + jnp.where(spread_on, boost, 0.0)) / den_t
            return jnp.where(head_ok & allowed, score, -jnp.inf)

        def step(state, _):
            jn, c, n_placed = state
            head_j = jnp.minimum(jn, max_j - 1)
            gather = lambda plane: jnp.take_along_axis(
                plane, head_j[:, None], axis=1
            )[:, 0]
            head_num = gather(num)
            head_den = gather(den)
            head_fit = gather(fits) & (jn < max_j)

            # Two-phase chunk: spread counts sit at symmetric states (all
            # values even ⇒ every even-boost −1) at chunk boundaries, and
            # under a negative frozen total the component-count divisor
            # inverts within-value ordering — the whole chunk would
            # re-pick already-filled nodes. One placement breaks the
            # symmetry exactly as stepwise greedy experiences it, so:
            # pick 1 with the frozen table, bump its value, re-derive the
            # table, then pick the remaining k−1 one-per-value.
            score0 = node_scores(head_num, head_den, head_fit, c)
            first = jnp.argmax(score0).astype(jnp.int32)
            ok0 = (score0[first] > -jnp.inf) & (n_placed < count)
            v_first = seg[first]  # segment (nv = value-less)
            first_vals = vids[:, first]  # [B]
            c1 = c + jnp.where(
                (ok0 & (first_vals >= 0))[:, None],
                jax.nn.one_hot(
                    jnp.maximum(first_vals, 0), nv, dtype=c.dtype
                ),
                0.0,
            )

            score1 = node_scores(head_num, head_den, head_fit, c1)
            score1 = jnp.where(seg == v_first, -jnp.inf, score1)
            # Rotation guard: stepwise greedy only places on values at
            # the (positive) minimum count — or still empty — of the
            # dominant even block; each placement removes that value from
            # the min set. A chunk that keeps taking beyond the min set
            # pays the symmetric-state −1 boost for its tail picks and
            # diverges from greedy (measured 11% corpus score loss at
            # config-3). Restrict the one-per-value picks to the rotating
            # set; the chunk under-fills and later chunks (or the host
            # repair re-score) finish the remainder exactly.
            ecounts = c1[eidx]  # [V] enforce-block counts after the bump
            pos1 = ecounts > 0
            minc1 = jnp.min(jnp.where(pos1, ecounts, jnp.inf))
            maxc1 = jnp.max(jnp.where(pos1, ecounts, -jnp.inf))
            empty_v = (~pos1) & present_v  # reachable and still unused
            no_empty = ~jnp.any(empty_v)
            # greedy's rotation set under even spread: empty values while
            # any exist (+1 boost beats every filled value's); otherwise
            # the at-min values — but only once the bump broke symmetry
            # (minc==maxc ⇒ every value scores the −1 symmetric boost;
            # greedy pays that once per ROUND, not once per pick — the
            # chunk's single first-pick is that once, and the next
            # chunk's re-derived table continues from the broken state)
            rotate_ok = jnp.where(
                no_empty,
                pos1 & (ecounts <= minc1) & (maxc1 > minc1),
                empty_v,
            )
            is_even_enforce = (
                jnp.take(kinds, eidx) == BLOCK_EVEN_SPREAD
            )
            seg_allowed = jnp.concatenate(
                [
                    jnp.where(is_even_enforce, rotate_ok, True),
                    jnp.ones(1, dtype=bool),  # value-less segment
                ]
            )
            # dense masked segment-max — TPU scatters serialize, masked
            # compare+reduce rides the VPU ([V+1, N] is small)
            seg_plane = seg[None, :] == jnp.arange(nv + 1)[:, None]
            seg_max = jnp.max(
                jnp.where(seg_plane, score1[None, :], -jnp.inf), axis=1
            )
            seg_max = jnp.where(seg_allowed, seg_max, -jnp.inf)
            vals, vsel = jax.lax.top_k(seg_max, k_seg - 1)
            take_r = (
                jnp.arange(k_seg - 1) + n_placed + ok0.astype(jnp.int32)
                < count
            ) & (vals > -jnp.inf) & ok0
            in_seg = seg[None, :] == vsel[:, None]  # [k_seg-1, N]
            rows_r = jnp.argmax(
                jnp.where(in_seg, score1[None, :], -jnp.inf), axis=1
            ).astype(jnp.int32)

            rows = jnp.concatenate([first[None], rows_r])
            take = jnp.concatenate([ok0[None], take_r])
            vals_all = jnp.concatenate([score0[first][None], vals])

            jn = jn + jnp.sum(
                (jnp.arange(n)[None, :] == rows[:, None])
                & take[:, None],
                axis=0,
            ).astype(jnp.int32)
            picked_vals = vids[:, rows_r]  # [B, k_seg-1]
            upd = take_r[None, :] & (picked_vals >= 0)
            c = c1 + jnp.sum(
                jnp.where(
                    upd[:, :, None],
                    picked_vals[:, :, None]
                    == jnp.arange(c.shape[1])[None, None, :],
                    False,
                ).astype(c.dtype),
                axis=1,
            )
            # ok0 False ⇒ c1 == c and nothing was taken
            n_placed = n_placed + jnp.sum(take.astype(jnp.int32))
            return (jn, c, n_placed), (
                jnp.where(take, rows, -1),
                jnp.where(take, vals_all, -jnp.inf).astype(jnp.float32),
            )

        state0 = (
            jnp.zeros(n, dtype=jnp.int32),
            c0,
            jnp.zeros((), dtype=jnp.int32),
        )
        _, (choices, scores) = jax.lax.scan(
            step, state0, None, length=n_chunks
        )
        return choices.reshape(-1), scores.reshape(-1)

    return jax.vmap(one_group)(
        asks, eligible, job_counts, desired_totals, penalty_nodes,
        affinity_scores, has_affinities, distinct_hosts, slot_caps,
        block_value_ids, block_counts0, block_desired, block_caps,
        block_weights, block_kinds, enforce_idx, counts,
    )


@functools.partial(traced_jit, retrace_budget=RETRACE_BUDGET)
def score_matrix_kernel(
    capacity,
    used,
    asks,  # f32[G, D]
    eligible,  # bool[G, N]
    job_counts,  # i32[G, N]
    desired_totals,
    penalty_nodes,
    affinity_scores,
    has_affinities,
    distinct_hosts,
    algorithm_spread,
    throughputs=None,  # f32[G, N] normalized class-throughput shares
):
    """The dense evals×nodes score matrix (no sequential state) — used for
    dry-run annotation, the system scheduler, and benchmarks. The optional
    class axis (``throughputs``) is Python-gated on None, so class-less
    callers compile and run the pre-heterogeneity program unchanged."""
    zero_boost = jnp.zeros(capacity.shape[0], dtype=jnp.float32)

    if throughputs is None:

        def one(a, e, jc, dt, pn, af, ha, dh):
            final, fits = component_scores(
                capacity, used, a, e, jc, dt, pn, af, ha,
                zero_boost, jnp.asarray(False), dh, algorithm_spread,
            )
            return final, fits

        return jax.vmap(one)(
            asks,
            eligible,
            job_counts,
            desired_totals,
            penalty_nodes,
            affinity_scores,
            has_affinities,
            distinct_hosts,
        )

    def one_tp(a, e, jc, dt, pn, af, ha, dh, tp):
        final, fits = component_scores(
            capacity, used, a, e, jc, dt, pn, af, ha,
            zero_boost, jnp.asarray(False), dh, algorithm_spread,
            throughputs=tp,
        )
        return final, fits

    return jax.vmap(one_tp)(
        asks,
        eligible,
        job_counts,
        desired_totals,
        penalty_nodes,
        affinity_scores,
        has_affinities,
        distinct_hosts,
        throughputs,
    )


def _steps_bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


def _dummy_ask(pn: int):
    """Zero-count padding lane for the group axis: eligible nowhere, so
    the kernel places nothing and its lane is dropped on unpack. Keeps
    the compiled G dimension bucketed (recompiles are the real cost of a
    varying batch size, not the padded FLOPs)."""
    from .flatten import GroupAsk

    return GroupAsk(
        job_id="",
        tg_name="",
        count=0,
        desired_total=1,
        ask=np.zeros(4, dtype=np.float32),
        eligible=np.zeros(pn, dtype=bool),
        job_counts=np.zeros(pn, dtype=np.int32),
        penalty_nodes=np.zeros(pn, dtype=bool),
        affinity_scores=np.zeros(pn, dtype=np.float32),
        has_affinities=False,
        distinct_hosts=False,
    )


def _pad_group_axis(asks: list, pn: int) -> list:
    """Pad the ask list so the compiled G dimension takes only two small
    values: 1 (single-eval path) or a power-of-two ≥ 16 (batched path).
    Collapsing 2..16 asks onto one 16-lane executable costs padded vmap
    lanes but avoids a recompile per distinct batch size."""
    n = len(asks)
    g = 1 if n == 1 else max(16, _steps_bucket(n))
    if g == n:
        return asks
    dummy = _dummy_ask(pn)
    return asks + [dummy] * (g - n)


def _shared_batch(asks: list, pn: int) -> dict:
    """Host-side assembly of the kernel inputs common to all placement
    paths (the value-block fields are added by the coupled paths).

    Transfer-slimmed for the tunnel-attached TPU (uploads were 3× the
    kernel's own runtime at 10k nodes): eligibility/penalty masks ride
    bit-packed (u8, 8×), and per-lane arrays that are degenerate across
    the whole batch (no job allocs yet, no penalties, no affinities, no
    device asks — the common case for fresh registrations) collapse to
    [G, 1] broadcasts instead of [G, N] uploads."""
    g = len(asks)
    jc = np.stack([a.job_counts for a in asks])
    if not jc.any():
        jc = np.zeros((g, 1), dtype=np.int32)
    pen = np.stack([a.penalty_nodes for a in asks])
    pen = (
        np.packbits(pen, axis=1)
        if pen.any()
        else np.zeros((g, 1), dtype=bool)
    )
    if any(a.has_affinities for a in asks):
        aff = np.stack([a.affinity_scores for a in asks])
    else:
        aff = np.zeros((g, 1), dtype=np.float32)
    if any(a.slot_caps is not None for a in asks):
        caps = np.stack(
            [
                a.slot_caps
                if a.slot_caps is not None
                else np.full(pn, np.inf, dtype=np.float32)
                for a in asks
            ]
        )
    else:
        caps = np.full((g, 1), np.inf, dtype=np.float32)
    return dict(
        asks=np.stack([a.ask for a in asks]),
        eligible=np.packbits(
            np.stack([a.eligible for a in asks]), axis=1
        ),
        job_counts=jc,
        desired_totals=np.array(
            [a.desired_total for a in asks], dtype=np.float32
        ),
        penalty_nodes=pen,
        affinity_scores=aff,
        has_affinities=np.array([a.has_affinities for a in asks]),
        distinct_hosts=np.array([a.distinct_hosts for a in asks]),
        slot_caps=caps,
        counts=np.array([a.count for a in asks], dtype=np.int32),
    )


# PartitionSpec axes per batch tensor (mesh sharding seam): groups ride
# data-parallel, dense per-node columns shard on the node axis. Packed u8
# masks and [G, 1] degenerate broadcasts keep their trailing axes
# replicated (shard_put skips any axis the mesh size doesn't divide).
_BATCH_SPECS = {
    "asks": ("groups",),
    "eligible": ("groups",),
    "job_counts": ("groups", "nodes"),
    "desired_totals": ("groups",),
    "penalty_nodes": ("groups",),
    "affinity_scores": ("groups", "nodes"),
    "has_affinities": ("groups",),
    "distinct_hosts": ("groups",),
    "slot_caps": ("groups", "nodes"),
    "counts": ("groups",),
    "block_value_ids": ("groups", None, "nodes"),
    "block_counts0": ("groups",),
    "block_desired": ("groups",),
    "block_caps": ("groups",),
    "block_weights": ("groups",),
    "block_kinds": ("groups",),
    "throughputs": ("groups", "nodes"),
}


def used_device(cluster, used0, cfg=None):
    """The one seam every kernel's per-pass ``used`` upload routes
    through. With incremental rescoring on (the tensors carry a
    ``score_cache``), the DeviceStateCache serves a device-resident
    buffer bitwise equal to ``used0`` — only dirty slices travelled;
    otherwise (or when the cache declines) the from-scratch
    ``shard_put``, byte for byte the pre-incremental upload. The
    returned array has the same aval either way, so the traced program
    is one and the same — the jaxpr-identity pin of the incremental
    path (analysis/jaxlint/diff.py)."""
    if cfg is None:
        cfg = get_mesh()
    cache = getattr(cluster, "score_cache", None)
    if cache is not None:
        dev = cache.score_view(cluster, used0, cfg)
        if dev is not None:
            return dev
    return shard_put(used0, ("nodes",), cfg)


def _device_batch(batch: dict, cfg=None) -> dict:
    """Upload a host batch dict through the sharding seam: NamedSharding
    placement when a mesh is active, plain jnp.asarray otherwise (the
    degenerate path is byte-for-byte the pre-mesh upload)."""
    if cfg is None:
        cfg = get_mesh()
    if not cfg.active:
        return {k: jnp.asarray(v) for k, v in batch.items()}
    return {
        k: shard_put(v, _BATCH_SPECS.get(k, ()), cfg)
        for k, v in batch.items()
    }


@dataclass
class PlacementResult:
    """Host-side result for one group: chosen node rows (−1 = failed) and
    their normalized scores, in placement order; plus overflow candidates
    (the next entries greedy would have taken) for conflict repair."""

    node_rows: np.ndarray
    scores: np.ndarray
    overflow_rows: np.ndarray = field(
        default_factory=lambda: np.empty(0, dtype=np.int32)
    )
    overflow_scores: np.ndarray = field(
        default_factory=lambda: np.empty(0, dtype=np.float32)
    )
    # score provenance (obs/explain.PlacementExplanation), attached only
    # when the pass ran with explain=True; purely observational — never
    # consulted by repair or the schedulers' placement decisions
    explanation: Optional[object] = None


class PlacementKernel:
    """Host wrapper: pads a list of GroupAsks into batch tensors, runs the
    compiled kernel, unpacks results. Shape-bucketed so node churn and
    varying batch sizes hit a small set of compiled programs."""

    def __init__(
        self,
        algorithm: str = "binpack",
        force_scan: bool = False,
        mesh=None,  # utils.backend.MeshConfig override; None = process mesh
    ):
        self.algorithm = algorithm
        self.algorithm_spread = algorithm == "spread"
        self.force_scan = force_scan  # parity testing: disable the fast path
        self._mesh = mesh

    def mesh_cfg(self):
        return self._mesh if self._mesh is not None else get_mesh()

    def _n_shards(self, pn: int) -> int:
        """Static node-axis shard count for the hierarchical top-k; 1
        unless the mesh is active AND divides the padded bucket (pn is a
        power of two ≥ 8 and mp is a power of two, so a non-dividing mp
        means mp > pn — a tiny cluster on a big mesh)."""
        cfg = self.mesh_cfg()
        mp = cfg.n_node_shards
        return mp if mp > 1 and pn % mp == 0 else 1

    @staticmethod
    def _capacity_dev(cluster, cfg):
        """The DeviceStateCache's per-shard-refreshed capacity buffer
        when one rode along on the tensors; else upload via the seam."""
        dev = getattr(cluster, "device_capacity", None)
        if dev is not None and cfg.active:
            return dev
        return shard_put(cluster.capacity, ("nodes",), cfg)

    def place(
        self,
        cluster,
        asks: list,
        *,
        overflow: int = OVERFLOW_CANDIDATES,
        decorrelate: bool = False,
        decorrelate_salt: int = 0,
        decorrelate_workers: int = 1,  # concurrent batching workers
        used_override=None,  # [pn, D] optimistic usage (pipelined passes)
        explain: bool = False,  # attach score provenance (obs/explain)
    ) -> list[PlacementResult]:
        """``overflow`` = extra greedy candidates emitted per lane for
        conflict repair. ``decorrelate``: stripe each lane onto a disjoint
        node partition so concurrent-eval lanes stop argmaxing onto the
        same nodes — the vector analog of the reference's per-worker
        shuffle sampling (stack.go:74-90); repair re-scores any shortfall
        against the full node set, so partitioning is purely an
        optimization. ``decorrelate_salt`` (worker id) permutes the
        stripes so CONCURRENT WORKERS' batches collide at ~1/stripes
        instead of stripe-for-stripe."""
        if not asks:
            return []
        from ..resilience.breaker import degraded

        if degraded():
            # one tick per scoring pass executed while any kernel breaker
            # is open / forced open — the pass runs on the reference path
            from ..utils.metrics import global_metrics as _metrics

            _metrics.incr("nomad.resilience.fallback_passes")
        used0 = (
            np.asarray(cluster.used)
            if used_override is None
            else np.asarray(used_override)
        )
        work = asks
        jitter = None
        if decorrelate:
            work = _decorrelate_lanes(
                cluster, asks, salt=decorrelate_salt, used0=used0,
                n_workers=decorrelate_workers,
            )
            rows = np.arange(cluster.padded_n, dtype=np.int64)
            h = (rows * 2654435761 + (decorrelate_salt + 1) * 40503) & 0xFFFFFFFF
            jitter = ((h % 65536).astype(np.float32) / 65536.0) * 2e-5
        # routing: uncoupled groups → closed-form top-k; large
        # spread-coupled groups → chunked (one-per-value variant when an
        # even block is present); small / capped groups → exact scan
        fast, chunked, opv, scan = [], [], [], []
        for i, a in enumerate(work):
            coupled = a.blocks is not None and a.blocks.num_blocks > 0
            if self.force_scan or (coupled and self._needs_exact_scan(a)):
                scan.append(i)
            elif coupled:
                if bool((a.blocks.kinds == BLOCK_EVEN_SPREAD).any()):
                    opv.append(i)
                else:
                    chunked.append(i)
            else:
                fast.append(i)
        out: list[Optional[PlacementResult]] = [None] * len(asks)
        # the span carries the routing split so a trace shows WHICH
        # kernel family scored each pass (jit-level detail — compile
        # events, shapes — attaches underneath via traced_jit's hooks)
        with _tracer.span(
            "kernel.place",
            tags={
                "lanes": len(asks),
                "fast": len(fast),
                "chunked": len(chunked),
                "opv": len(opv),
                "scan": len(scan),
            },
        ):
            for idxs, fn in (
                (fast, self._place_closed_form),
                (chunked, self._place_spread_chunked),
                (opv, self._place_spread_opv),
                (scan, self._place_scan_batch),
            ):
                if idxs:
                    for i, r in zip(
                        idxs,
                        fn(
                            cluster, [work[i] for i in idxs], overflow,
                            jitter, used0,
                        ),
                    ):
                        out[i] = r
        if explain:
            # Python-level gate, exactly like the hetero ``None`` gate:
            # explain-off passes run the identical code above (no new
            # traced program exists in either mode) and place
            # bit-for-bit. Explanations are built host-side against the
            # ORIGINAL asks and the pass's base usage — decorrelation
            # stripes/jitter are a placement optimization repair undoes,
            # not part of the score semantics being explained.
            from ..obs.explain import explain_group

            sharded = self.mesh_cfg().n_node_shards > 1
            for a, res in zip(asks, out):
                if res is not None:
                    cand = None
                    if sharded:
                        # node axis sharded: rank only the candidate
                        # columns the kernel actually surfaced (primary +
                        # overflow) instead of gathering full score rows
                        # back to host — the per-shard top-k union
                        # provably contains every global winner
                        cand = np.unique(
                            np.concatenate(
                                [res.node_rows, res.overflow_rows]
                            )
                        )
                        cand = cand[cand >= 0]
                    res.explanation = explain_group(
                        cluster, a, used0,
                        algorithm=self.algorithm,
                        algorithm_spread=self.algorithm_spread,
                        candidate_rows=cand,
                    )
        return out

    @staticmethod
    def _needs_exact_scan(a) -> bool:
        """Cap (distinct_property) blocks can overshoot a per-value
        budget within one chunk, and small groups compile to short exact
        scans anyway — both stay on the stepwise path."""
        if a.count <= EXACT_SCAN_MAX_COUNT:
            return True
        return bool((a.blocks.kinds == BLOCK_DISTINCT_CAP).any())

    @staticmethod
    def _j_bucket(n: int) -> int:
        """Multiples of 16 up to 128, then multiples of 64. The r4
        coarsening ({16,24,32,48,64,96,...}) cost a measured 1.6× on the
        headline CPU kernel (J=96 where 80 suffices — plane work scales
        with J and the padding waste is pure overhead); multiples of 16
        keep padding ≤ 20% at the shapes that matter while a typical
        workload still touches only 1-2 compiled variants (~30 s each
        over the tunnel)."""
        if n <= 16:
            return 16
        if n <= 24:
            return 24  # the spread-opv J cap (n_chunks+1) lives here
        if n <= 128:
            return -(-n // 16) * 16
        return -(-n // 64) * 64

    def _max_j(self, cluster, asks: list) -> int:
        """J bound: most instances of one identical ask any node could
        hold, bucketed (see _j_bucket)."""
        cap_max = np.asarray(cluster.capacity).max(axis=0)  # [D]
        max_j = 1
        for a in asks:
            pos = a.ask > 0
            if pos.any():
                j = int(np.floor(np.min(cap_max[pos] / a.ask[pos]))) + 1
            else:
                j = a.count
            max_j = max(max_j, min(j, a.count))
        return self._j_bucket(max_j)

    def _place_closed_form(
        self, cluster, asks: list, overflow: int = OVERFLOW_CANDIDATES,
        jitter=None, used0=None,
    ) -> list[PlacementResult]:
        if used0 is None:
            used0 = np.asarray(cluster.used)
        pn = cluster.padded_n
        max_count = max(a.count for a in asks)
        k = _steps_bucket(max(max_count + overflow, 1))
        max_j = self._max_j(cluster, asks)

        # chunk the group axis so the [chunk, N, J] planes stay within an
        # HBM budget (~4 GB of live f32 planes on a 16 GB v5e chip);
        # splitting a pass costs an extra tunnel round trip, so the
        # budget errs large
        bytes_per_lane = pn * max_j * 4 * 4
        chunk = max(1, int((4 << 30) // max(bytes_per_lane, 1)))
        if len(asks) > chunk:
            out: list[PlacementResult] = []
            for i in range(0, len(asks), chunk):
                out.extend(
                    self._place_closed_form(
                        cluster, asks[i:i + chunk], overflow, jitter, used0
                    )
                )
            return out

        real_n = len(asks)
        asks = _pad_group_axis(asks, pn)
        batch = _shared_batch(asks, pn)
        cfg = self.mesh_cfg()
        fused = np.array(
            place_closed_form_kernel(
                self._capacity_dev(cluster, cfg),
                used_device(cluster, used0, cfg),
                **_device_batch(batch, cfg),
                algorithm_spread=jnp.asarray(self.algorithm_spread),
                max_j=max_j,
                k=k,
                jitter=None
                if jitter is None
                else shard_put(jitter, ("nodes",), cfg),
                n_shards=self._n_shards(pn),
            )
        )
        choices = fused[:, :k]  # writable copies: repair mutates rows
        scores = fused[:, k:].view(np.float32)
        return [
            PlacementResult(
                node_rows=choices[gi, : a.count],
                scores=scores[gi, : a.count],
                overflow_rows=choices[gi, a.count :],
                overflow_scores=scores[gi, a.count :],
            )
            for gi, a in enumerate(asks[:real_n])
        ]

    def _place_scan_batch(
        self, cluster, asks: list, overflow: int = OVERFLOW_CANDIDATES,
        jitter=None, used0=None,
    ) -> list[PlacementResult]:
        if used0 is None:
            used0 = np.asarray(cluster.used)
        from .flatten import pad_value_blocks

        pn = cluster.padded_n
        real_n = len(asks)
        asks = _pad_group_axis(asks, pn)
        max_count = max(a.count for a in asks)
        max_steps = _steps_bucket(max(max_count + overflow, 1))
        max_j = self._max_j(cluster, asks)

        batch = _shared_batch(asks, pn)
        # emit overflow candidates past each lane's primary count
        batch["counts"] = np.minimum(
            batch["counts"] + overflow, max_steps
        ).astype(np.int32)
        # zero-count padding lanes stay inert (eligible nowhere)
        batch["counts"] = np.where(
            np.array([a.count for a in asks]) > 0, batch["counts"], 0
        ).astype(np.int32)
        batch.update(pad_value_blocks([a.blocks for a in asks], pn))
        cfg = self.mesh_cfg()
        choices, scores = place_value_scan_kernel(
            self._capacity_dev(cluster, cfg),
            used_device(cluster, used0, cfg),
            **_device_batch(batch, cfg),
            algorithm_spread=jnp.asarray(self.algorithm_spread),
            max_j=max_j,
            max_steps=max_steps,
            jitter=None
            if jitter is None
            else shard_put(jitter, ("nodes",), cfg),
        )
        return self._unpack_coupled(choices, scores, asks[:real_n], overflow)

    def _place_spread_chunked(
        self, cluster, asks: list, overflow: int = OVERFLOW_CANDIDATES,
        jitter=None, used0=None,
    ) -> list[PlacementResult]:
        if used0 is None:
            used0 = np.asarray(cluster.used)
        from .flatten import pad_value_blocks

        pn = cluster.padded_n
        real_n = len(asks)
        asks = _pad_group_axis(asks, pn)
        max_count = max(a.count for a in asks)
        max_j = self._max_j(cluster, asks)
        # round chunk count to a multiple of 4, not a power of two — the
        # sequential depth is the dominant cost and 2× overshoot is real
        # wall-clock; a handful of extra compile variants is not
        n_chunks = max(4, -(-max(-(-(max_count + overflow) // CHUNK), 1) // 4) * 4)

        batch = _shared_batch(asks, pn)
        batch["counts"] = np.minimum(
            batch["counts"] + overflow, n_chunks * CHUNK
        ).astype(np.int32)
        batch["counts"] = np.where(
            np.array([a.count for a in asks]) > 0, batch["counts"], 0
        ).astype(np.int32)
        batch.update(pad_value_blocks([a.blocks for a in asks], pn))
        cfg = self.mesh_cfg()
        choices, scores = place_spread_chunked_kernel(
            self._capacity_dev(cluster, cfg),
            used_device(cluster, used0, cfg),
            **_device_batch(batch, cfg),
            algorithm_spread=jnp.asarray(self.algorithm_spread),
            max_j=max_j,
            chunk=CHUNK,
            n_chunks=n_chunks,
            jitter=None
            if jitter is None
            else shard_put(jitter, ("nodes",), cfg),
            n_shards=self._n_shards(pn),
        )
        return self._unpack_coupled(choices, scores, asks[:real_n], overflow)

    def _place_spread_opv(
        self, cluster, asks: list, overflow: int = OVERFLOW_CANDIDATES,
        jitter=None, used0=None,
    ) -> list[PlacementResult]:
        if used0 is None:
            used0 = np.asarray(cluster.used)
        from .flatten import pad_value_blocks

        pn = cluster.padded_n
        real_n = len(asks)
        asks = _pad_group_axis(asks, pn)
        max_j = self._max_j(cluster, asks)

        batch = _shared_batch(asks, pn)
        blocks_list = [a.blocks for a in asks]
        batch.update(pad_value_blocks(blocks_list, pn))
        nv = batch["block_counts0"].shape[2]
        k_seg = min(CHUNK, nv + 1)

        # per-lane: dominant even block + how many picks one chunk can
        # actually yield (active values of that block, +1 for value-less
        # nodes) — lanes with few values need more sequential chunks
        enforce_idx = np.zeros(len(asks), dtype=np.int32)
        lane_steps = 1
        for gi, a in enumerate(asks):
            b = a.blocks
            if b is None or a.count <= 0:
                continue
            even = np.flatnonzero(b.kinds == BLOCK_EVEN_SPREAD)
            if even.size:
                enforce_idx[gi] = even[np.argmax(b.weights[even])]
            ev = b.value_ids[enforce_idx[gi]]
            # a step can only yield picks from segments that hold at
            # least one ELIGIBLE node (pad rows and unreachable values
            # yield nothing — counting them under-provisions n_chunks
            # and truncates the lane's placements)
            elig = a.eligible
            v_act = len(np.unique(ev[(ev >= 0) & elig])) + int(
                ((ev < 0) & elig).any()
            )
            per_chunk = max(1, min(k_seg, v_act))
            lane_steps = max(
                lane_steps, -(-(a.count + overflow) // per_chunk)
            )
        # multiple-of-4 rounding, not power-of-two (sequential depth is
        # the dominant cost; see _place_spread_chunked). +2 slack chunks:
        # the rotation guard makes a chunk starting from uneven counts
        # yield fewer than v_act picks; the host repair re-score rescues
        # any residue, but slack keeps that path cold.
        n_chunks = max(4, -(-(lane_steps + 2) // 4) * 4)
        # J bound tightened by the kernel's own structure: each chunk
        # step picks DISTINCT nodes (the first pick and the one-per-value
        # segment picks are disjoint), so one node gains at most one
        # instance per step — head_j never exceeds n_chunks. At the
        # config-3 shape this cuts the [N, J] planes ~3× (J 80 → 24):
        # plane construction dominates the pass, so it's ~linear
        # wall-clock.
        max_j = min(max_j, self._j_bucket(n_chunks + 1))

        batch["counts"] = np.minimum(
            batch["counts"] + overflow, n_chunks * k_seg
        ).astype(np.int32)
        batch["counts"] = np.where(
            np.array([a.count for a in asks]) > 0, batch["counts"], 0
        ).astype(np.int32)
        cfg = self.mesh_cfg()
        choices, scores = place_spread_opv_kernel(
            self._capacity_dev(cluster, cfg),
            used_device(cluster, used0, cfg),
            **_device_batch(batch, cfg),
            enforce_idx=jnp.asarray(enforce_idx),
            algorithm_spread=jnp.asarray(self.algorithm_spread),
            max_j=max_j,
            k_seg=k_seg,
            n_chunks=n_chunks,
            jitter=None
            if jitter is None
            else shard_put(jitter, ("nodes",), cfg),
        )
        return self._unpack_coupled(choices, scores, asks[:real_n], overflow)

    @staticmethod
    def _unpack_coupled(choices, scores, asks, overflow):
        """Compact each lane's valid picks (greedy emission order) into
        count primary + overflow slots. The one-per-value kernel can
        intersperse empty slots between chunks (a chunk is capped at one
        pick per value, not by feasibility), so valid picks are compacted
        rather than sliced positionally."""
        choices = np.array(choices)
        scores = np.array(scores)
        out = []
        for gi, a in enumerate(asks):
            row = choices[gi]
            valid = row >= 0
            vrows = row[valid]
            vscores = scores[gi][valid]
            node_rows = np.full(a.count, -1, dtype=np.int32)
            sc = np.full(a.count, -np.inf, dtype=np.float32)
            n_primary = min(a.count, vrows.shape[0])
            node_rows[:n_primary] = vrows[:n_primary]
            sc[:n_primary] = vscores[:n_primary]
            of_rows = np.full(overflow, -1, dtype=np.int32)
            of_sc = np.full(overflow, -np.inf, dtype=np.float32)
            n_of = min(overflow, max(0, vrows.shape[0] - a.count))
            of_rows[:n_of] = vrows[a.count : a.count + n_of]
            of_sc[:n_of] = vscores[a.count : a.count + n_of]
            out.append(
                PlacementResult(
                    node_rows=node_rows,
                    scores=sc,
                    overflow_rows=of_rows,
                    overflow_scores=of_sc,
                )
            )
        return out


def _decorrelate_lanes(
    cluster, asks: list, salt: int = 0, used0=None, n_workers: int = 1
) -> list:
    """Stripe each batch lane onto a disjoint subset of node rows
    (row % n_lanes == lane). Concurrent lanes scoring the same snapshot
    otherwise compute near-identical greedy sequences and pile onto the
    same nodes — the r3 bench measured a 92.9% conflict-fallback rate.
    The reference decorrelates its parallel workers by per-worker node
    shuffling + limit sampling (stack.go:74-90); a 1/L stripe of a 10k
    cluster still offers each lane more candidates than the reference's
    ≥100-node sample. Lanes whose stripe leaves thin headroom (or whose
    constraints concentrate eligibility) keep the full node set — repair
    resolves whatever conflicts remain."""
    from dataclasses import replace

    n_lanes = len(asks)
    if n_lanes < 2:
        return asks
    pn = cluster.padded_n
    # stripes decorrelate lanes WITHIN one batch; concurrent workers are
    # decorrelated by the score jitter (mod-l permutations of the row
    # index only relabel the same congruence classes, so salting the
    # stripe math cross-worker is a no-op — the salt instead rotates
    # which lane gets which class, and seeds the jitter in place())
    rows = np.arange(pn)
    # Stripe on a HASHED row index, not the raw row: raw `rows % l_eff`
    # interacts arithmetically with any attribute laid out periodically
    # over rows (racks assigned round-robin: rack = row % n_racks). When
    # gcd(l_eff, n_racks) > 1 each stripe reaches only n_racks/gcd of the
    # rack values, the reachability guard below rejects every lane, and
    # the whole batch falls back to the full node set — measured as a
    # 34× repair blow-up at 64 lanes × 25 racks. A multiplicative hash
    # de-correlates stripe membership from any row-periodic attribute, so
    # each stripe samples all values ~uniformly.
    row_hash = (rows.astype(np.uint64) * np.uint64(2654435761)) & np.uint64(
        0xFFFFFFFF
    )
    # CONCURRENT batching workers must not share stripes at all: the salt
    # only rotates lane→stripe assignment within the same congruence
    # classes, so two workers' passes land one lane from each on every
    # stripe and argmax the same best nodes (measured 0.83+ conflict at
    # 2×32 deep). Partition the node universe by worker FIRST (a second,
    # independent hash so it doesn't alias the lane stripes), then stripe
    # within each worker's slice.
    worker_universe = None
    if n_workers > 1:
        h2 = (rows.astype(np.uint64) * np.uint64(0x9E3779B1)) & np.uint64(
            0xFFFFFFFF
        )
        worker_universe = (h2 % np.uint64(n_workers)).astype(np.int64) == (
            salt % n_workers
        )
    free = np.asarray(cluster.capacity) - (
        np.asarray(cluster.used) if used0 is None else np.asarray(used0)
    )  # [pn, D]
    out = []
    for i, a in enumerate(asks):
        if a.count <= 0:
            out.append(a)
            continue
        # Widest stripe count that still leaves this lane comfortable
        # headroom, measured in feasible INSTANCE SLOTS (Σ per-node jmax),
        # not node count — a node holds many instances of one ask, and
        # sizing by nodes (the old 2×count heuristic) capped l_eff at
        # ~N/(2·count), forcing lanes to share stripes and collide (the
        # measured 11.7 s repair blow-up at 64 lanes). When even the
        # slot-based 1/n_lanes stripe is too thin, lanes SHARE coarser
        # stripes (conflicts only within a stripe group) instead of
        # abandoning decorrelation entirely.
        pos = a.ask > 0
        if pos.any():
            jn = np.floor(
                np.min(free[:, pos] / a.ask[pos], axis=1)
            ).clip(min=0)
        else:
            jn = np.full(pn, float(a.count))
        jn = np.where(a.eligible, jn, 0.0)

        # full-set value vocabulary per block, computed ONCE per ask —
        # the reachability closure runs up to twice per lane in the hot
        # decorrelation path
        full_vals_per_block = (
            [
                np.unique(
                    a.blocks.value_ids[b][
                        (a.blocks.value_ids[b] >= 0) & a.eligible
                    ]
                ).shape[0]
                for b in range(a.blocks.num_blocks)
            ]
            if a.blocks is not None
            else []
        )

        def values_reachable(mask) -> bool:
            # a node subset must not silently amputate spread/cap values:
            # every value reachable from the full eligible set must stay
            # reachable from the subset (rack-contiguous row orderings
            # with racks smaller than the lane count would otherwise skew
            # the spread with no error surfaced)
            if a.blocks is None:
                return True
            for b in range(a.blocks.num_blocks):
                vids = a.blocks.value_ids[b]
                sub_vals = np.unique(vids[(vids >= 0) & mask])
                if full_vals_per_block[b] != sub_vals.shape[0]:
                    return False
            return True

        # this worker's node slice first (cross-worker disjointness),
        # provided it still holds the lane's ask comfortably — else fall
        # back to the full set and let repair/applier arbitrate
        from ..utils.metrics import global_metrics as _metrics

        base_elig = a.eligible
        if worker_universe is not None:
            wu_elig = a.eligible & worker_universe
            if (
                float(jn[wu_elig].sum()) >= 2 * a.count
                and int(wu_elig.sum()) >= 8
                and values_reachable(wu_elig)
            ):
                base_elig = wu_elig
                _metrics.incr("nomad.kernel.lane_universe_applied")
            else:
                _metrics.incr("nomad.kernel.lane_universe_skipped")
        jn_w = np.where(base_elig, jn, 0.0)
        total_elig = int(base_elig.sum())
        slots = float(jn_w.sum())
        l_eff = min(
            n_lanes,
            max(1, min(
                int(slots // max(4 * a.count, 1)), total_elig // 8
            )),
        )
        if l_eff < 2:
            out.append(
                replace(a, eligible=base_elig)
                if base_elig is not a.eligible
                else a
            )
            continue
        in_stripe = (
            (row_hash % np.uint64(l_eff)).astype(np.int64)
            == ((i + salt) % l_eff)
        )
        elig = base_elig & in_stripe
        # the stripe must still hold 2× the lane's ask in feasible slots
        ok = float(jn_w[elig].sum()) >= 2 * a.count and int(
            elig.sum()
        ) >= 8
        if ok:
            ok = values_reachable(elig)
        if ok:
            _metrics.incr("nomad.kernel.lane_striped")
            out.append(replace(a, eligible=elig))
        elif base_elig is not a.eligible:
            # stripe rejected but the worker slice is viable: keep
            # cross-worker disjointness at least
            _metrics.incr("nomad.kernel.lane_universe_only")
            out.append(replace(a, eligible=base_elig))
        else:
            _metrics.incr("nomad.kernel.lane_full_set")
            out.append(a)
    return out


def _host_block_tables(c, blocks):
    """NumPy mirror of _block_tables for one lane's [B, V] count state."""
    boost = np.zeros_like(c)
    allow = np.ones_like(c, dtype=bool)
    for b in range(blocks.num_blocks):
        kind = blocks.kinds[b]
        if kind == BLOCK_TARGET_SPREAD:
            d = blocks.desired[b]
            boost[b] = np.where(
                d > 0,
                (d - (c[b] + 1.0)) / np.maximum(d, 1e-9) * blocks.weights[b],
                -1.0,
            )
        elif kind == BLOCK_EVEN_SPREAD:
            pos = c[b] > 0
            if pos.any():
                minc = float(c[b][pos].min())
                maxc = float(c[b][pos].max())
                at_min = c[b] == minc
                boost[b] = np.where(
                    at_min,
                    -1.0 if minc == maxc else (maxc - minc) / max(minc, 1e-9),
                    (minc - c[b]) / max(minc, 1e-9),
                )
        elif kind == BLOCK_DISTINCT_CAP:
            allow[b] = c[b] < blocks.caps[b]
    return boost, allow


def _rescore_pick(capacity, used, a, placed_on_node, counts, algorithm_spread):
    """Exact host-side argmax for one additional placement of ``a``
    against a usage overlay — the same component semantics as the device
    kernels (see module docstring), in one vectorized NumPy pass. Used by
    repair when a lane's precomputed overflow candidates run out, so a
    conflicted placement is re-placed instead of aborting the whole eval.
    Returns (row, score) with row −1 when nothing fits."""
    prop = used + a.ask[None, :]
    fits = np.all(prop <= capacity, axis=1) & a.eligible
    jc = a.job_counts + placed_on_node
    if a.distinct_hosts:
        fits &= jc == 0
    if a.slot_caps is not None:
        fits &= placed_on_node < a.slot_caps
    blocks = a.blocks
    boost = np.zeros(capacity.shape[0], dtype=np.float32)
    has_spread_any = False
    if blocks is not None:
        tbl_boost, tbl_allow = _host_block_tables(counts, blocks)
        for b in range(blocks.num_blocks):
            vids = blocks.value_ids[b]
            safe = np.maximum(vids, 0)
            if blocks.kinds[b] == BLOCK_DISTINCT_CAP:
                fits &= np.where(vids >= 0, tbl_allow[b][safe], True)
            elif blocks.kinds[b] in (BLOCK_TARGET_SPREAD, BLOCK_EVEN_SPREAD):
                has_spread_any = True
                boost += np.where(vids >= 0, tbl_boost[b][safe], -1.0)
    if not fits.any():
        return -1, -np.inf
    free = np.where(
        capacity > 0, (capacity - prop) / np.maximum(capacity, 1e-9), 1.0
    )
    pow_sum = 10.0 ** free[:, 0] + 10.0 ** free[:, 1]
    binpack = np.clip(20.0 - pow_sum, 0.0, BINPACK_MAX_SCORE)
    spread_fit = np.clip(pow_sum - 2.0, 0.0, BINPACK_MAX_SCORE)
    fit_score = (spread_fit if algorithm_spread else binpack) / BINPACK_MAX_SCORE
    coll = jc.astype(np.float32)
    anti = np.where(jc > 0, -(coll + 1.0) / max(a.desired_total, 1.0), 0.0)
    resched = np.where(a.penalty_nodes, -1.0, 0.0)
    aff = a.affinity_scores if a.has_affinities else 0.0
    spread_on = has_spread_any & (boost != 0.0)
    num = fit_score + anti + resched + aff + np.where(spread_on, boost, 0.0)
    den = (
        1.0
        + (jc > 0)
        + a.penalty_nodes
        + (1.0 if a.has_affinities else 0.0)
        + spread_on
    )
    score = np.where(fits, num / den, -np.inf)
    row = int(np.argmax(score))
    return row, float(score[row])


def repair_batch_conflicts(
    cluster,
    asks: list,
    results: list,
    algorithm_spread: bool = False,
    fail_on_contention: bool = False,
    lane_groups: Optional[list] = None,
    used_override=None,  # [pn, D] optimistic base usage (pipelined passes)
) -> list[bool]:
    """Host-side optimistic-conflict resolution for one batched pass.

    Every lane scored against the same snapshot ``used0``, so lanes can
    pile onto the same best nodes (true argmax removes the decorrelation
    the reference gets from per-worker shuffle sampling, stack.go:74-90;
    _decorrelate_lanes removes most of the correlation up front). Walk
    the lanes in order with a usage overlay: placements that no longer
    fit move to the lane's next overflow candidate, and when overflow
    runs out an exact NumPy re-score places them directly — only the
    *conflicted placement* is re-placed, never the whole eval. Kernel
    failures (row −1, e.g. a lane whose stripe ran dry) get the same
    re-score. The plan applier's per-node AllocsFit re-check
    (plan_apply.go:638-689) remains the authority.

    Mutates each PlacementResult in place. Returns per-lane ``ok`` —
    False only when a placement is unplaceable under the batch overlay
    but WOULD fit without the other lanes' placements (true cross-eval
    contention): that eval should re-run individually against fresh
    state, where preemption and retries apply. Intrinsically infeasible
    placements (caps exhausted, cluster full even alone) stay −1 with
    ok=True — they'd fail individually too, and become blocked evals.

    ``lane_groups`` (optional, parallel to ``asks``) marks lanes that
    belong to one EVAL (a multi-task-group eval spans several lanes and
    the caller discards the whole eval when any lane fails): a contention
    failure releases the overlay reservations of EVERY processed lane in
    the group and skips its remaining lanes — sibling placements of a
    discarded plan must not stay reserved against later lanes.
    """
    capacity = np.asarray(cluster.capacity)
    used0 = (
        np.asarray(cluster.used)
        if used_override is None
        else np.asarray(used_override)
    )
    used = used0.copy()
    ok_lanes: list[bool] = []
    # group id -> [(placed_on_node, ask), ...] commit journal for rollback
    group_commits: dict = {}
    failed_groups: set = set()
    for lane_idx, (a, res) in enumerate(zip(asks, results)):
        group = lane_groups[lane_idx] if lane_groups is not None else lane_idx
        if group in failed_groups:
            # a sibling lane of this eval already hit contention: the
            # whole eval re-runs individually, so don't reserve anything
            ok_lanes.append(False)
            continue
        ok = True
        # within-lane placements per node (distinct_hosts, slot caps,
        # anti-affinity collisions all key off it)
        placed_on_node: dict[int, int] = {}
        blocks = a.blocks
        counts = blocks.counts0.copy() if blocks is not None else None
        overflow = list(
            zip(res.overflow_rows.tolist(), res.overflow_scores.tolist())
        )
        of_idx = 0
        dead = False  # lane-intrinsic infeasibility: stop re-scoring

        def commit(row: int) -> None:
            used[row] += a.ask
            placed_on_node[row] = placed_on_node.get(row, 0) + 1
            if blocks is not None:
                for b in range(blocks.num_blocks):
                    v = blocks.value_ids[b, row]
                    if v >= 0:
                        counts[b, v] += 1

        def acceptable(row: int) -> bool:
            if row < 0:
                return False
            if not np.all(used[row] + a.ask <= capacity[row]):
                return False
            mine = placed_on_node.get(row, 0)
            if a.distinct_hosts and (a.job_counts[row] + mine) > 0:
                return False
            if a.slot_caps is not None and mine >= a.slot_caps[row]:
                return False
            if blocks is not None:
                for b in range(blocks.num_blocks):
                    if blocks.kinds[b] != BLOCK_DISTINCT_CAP:
                        continue
                    v = blocks.value_ids[b, row]
                    if v >= 0 and counts[b, v] >= blocks.caps[b, v]:
                        return False
            return True

        def rescore(i: int) -> str:
            """Exact re-place of placement ``i``. Returns 'placed',
            'contention' (fits alone, not under the overlay), or
            'intrinsic'."""
            pm = np.zeros(capacity.shape[0], dtype=np.float32)
            for r, m in placed_on_node.items():
                pm[r] = m
            row, sc = _rescore_pick(
                capacity, used, a, pm, counts, algorithm_spread
            )
            if row >= 0:
                res.node_rows[i] = row
                res.scores[i] = sc
                commit(row)
                return "placed"
            # would it fit with only this lane's own placements applied?
            lane_used = used0 + pm[:, None] * a.ask[None, :]
            row, _sc = _rescore_pick(
                capacity, lane_used, a, pm, counts, algorithm_spread
            )
            return "contention" if row >= 0 else "intrinsic"

        for i, row in enumerate(res.node_rows.tolist()):
            if row >= 0 and acceptable(row):
                commit(row)
                continue
            if dead:
                res.node_rows[i] = -1
                res.scores[i] = -np.inf
                continue
            # conflicted or unplaced: advance through overflow candidates
            repl = -1
            while of_idx < len(overflow):
                cand, sc = overflow[of_idx]
                of_idx += 1
                if acceptable(cand):
                    repl = cand
                    res.node_rows[i] = cand
                    res.scores[i] = sc
                    commit(cand)
                    break
            if repl >= 0:
                continue
            outcome = rescore(i)
            if outcome == "contention" and not fail_on_contention:
                # this eval re-runs individually on fresh state — its plan
                # is NOT submitted, so its already-committed placements
                # must not stay reserved in the shared overlay (phantom
                # reservations would cascade later lanes into serial
                # fallbacks a fresh-state rerun would avoid). Release this
                # lane AND every processed sibling lane of the same eval.
                for r, m in placed_on_node.items():
                    used[r] -= m * a.ask
                for sib_placed, sib_ask in group_commits.get(group, ()):
                    for r, m in sib_placed.items():
                        used[r] -= m * sib_ask
                failed_groups.add(group)
                ok = False
                break
            if outcome in ("intrinsic", "contention"):
                # fail_on_contention (single-eval path): there is no
                # fresher state to retry against, so an unplaceable
                # placement becomes a recorded failure instead of a
                # shipped-overcommitted row the applier would bounce
                res.node_rows[i] = -1
                res.scores[i] = -np.inf
                dead = True
        if ok and lane_groups is not None:
            group_commits.setdefault(group, []).append(
                (placed_on_node, a.ask)
            )
        ok_lanes.append(ok)
    return ok_lanes
