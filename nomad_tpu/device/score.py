"""The batched placement kernel — the TPU replacement for the reference's
iterator-chain inner loop.

What the reference does per placement (scheduler/stack.go:343-438 chain,
scheduler/rank.go:193-527 BinPackIterator.Next): walk up to ``limit`` nodes
through ~10 iterator stages, computing fit and score sequentially in Go.
O(allocs × limit × stages), single-threaded per eval.

What this module does instead: ONE fully-parallel scoring pass per group
batch. For a group placing ``count`` identical asks, every candidate
"place the (j+1)-th instance of this group on node n" has a closed-form
score — usage is used0 + (j+1)·ask, collisions are jc0 + j — so the whole
candidate space is a dense [N, J] plane computed in one shot
(``_score_planes``). Two selection paths consume the planes:

- **Closed-form top-k** (groups with no cross-node coupling): per-node
  score columns are made monotone by a running-min clamp, which turns
  greedy placement into a single ``lax.top_k`` over the flattened plane.
  One parallel pass replaces ``count`` sequential argmax steps.

- **Gather-scan** (groups whose spread blocks / distinct_property caps
  couple nodes through global per-value counts): a ``lax.scan`` over
  placement steps that does only O(N) *gather* work per step — the heads
  of each node's precomputed column plus a [B, V] per-value boost table —
  instead of rescoring every node against every resource dim. Exact
  stepwise-greedy semantics at a fraction of the serial cost.

Batch dimension = concurrent evals/groups, replacing Nomad's worker-per-
core optimistic concurrency (nomad/worker.go:85): every group in a batch
scores against the same snapshot, and conflicts are resolved host-side by
``repair_batch_conflicts`` (using each lane's overflow candidates) before
the plan applier's authoritative re-check.

Scoring component semantics (each cites its reference):
- binpack/spread fit: nomad/structs/funcs.go:236-274, normalized /18
  (rank.go:513-516).
- job anti-affinity: −(collisions+1)/desired_count for nodes already
  holding collisions > 0 allocs of the job (rank.go:536-604).
- reschedule penalty: −1 on the node a failed alloc is being replaced
  from (rank.go:606-648).
- node affinity: weight-normalized Σ w·match / Σ|w| (rank.go:650-737),
  precomputed per node host-side (string matching ≪ scoring cost).
- spread (scheduler/spread.go:110-228): one component summing per-block
  boosts. Target mode: (desired − used−1)/desired × weight/Σweights, −1
  for untargeted values; even mode: the min/max-delta boost
  (spread.go:178-228). The component joins the normalization mean only
  when the total boost is nonzero (spread.go:168-171).
- distinct_property (feasible.go:604-707): not a score — a dynamic
  per-value cap carried through the scan's count state.
- normalization: mean over *contributing* components
  (rank.go:740-767 ScoreNormalizationIterator).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..structs.resources import BINPACK_MAX_SCORE

_LN10 = 2.302585092994046

# value-block kinds (ValueBlocks.kinds; see flatten.py)
BLOCK_TARGET_SPREAD = 0
BLOCK_EVEN_SPREAD = 1
BLOCK_DISTINCT_CAP = 2
BLOCK_INACTIVE = -1

# extra greedy candidates emitted beyond ``count`` per lane, consumed by
# repair_batch_conflicts when optimistic batch lanes collide on a node
OVERFLOW_CANDIDATES = 16


def _pow10(x):
    return jnp.exp(_LN10 * x)


def component_scores(
    capacity,  # f32[N, D]
    used,  # f32[N, D] current proposed usage
    ask,  # f32[D]
    eligible,  # bool[N]
    job_counts,  # i32[N]
    desired_total,  # f32[] anti-affinity denominator
    penalty_nodes,  # bool[N]
    affinity_scores,  # f32[N]
    has_affinities,  # bool[]
    spread_boost,  # f32[N] (precomputed for this step)
    has_spreads,  # bool[]
    distinct_hosts,  # bool[]
    algorithm_spread,  # bool[] scheduler algorithm: binpack vs spread fit
):
    """Per-node normalized score for placing one instance of ``ask``.
    Returns (final_score f32[N] with -inf infeasible, fits bool[N]).
    Used by the dense [G, N] score-matrix path (annotation, system
    scheduler); the placement paths use the [N, J] planes instead."""
    proposed = used + ask  # [N, D]
    fits = jnp.all(proposed <= capacity, axis=-1) & eligible
    fits &= jnp.where(distinct_hosts, job_counts == 0, True)

    free_frac = jnp.where(
        capacity > 0, (capacity - proposed) / jnp.maximum(capacity, 1e-9), 1.0
    )
    pow_sum = _pow10(free_frac[:, 0]) + _pow10(free_frac[:, 1])  # cpu, mem
    binpack = jnp.clip(20.0 - pow_sum, 0.0, BINPACK_MAX_SCORE)
    spread_fit = jnp.clip(pow_sum - 2.0, 0.0, BINPACK_MAX_SCORE)
    fit_score = jnp.where(algorithm_spread, spread_fit, binpack) / BINPACK_MAX_SCORE

    collisions = job_counts.astype(jnp.float32)
    anti = jnp.where(
        job_counts > 0, -(collisions + 1.0) / jnp.maximum(desired_total, 1.0), 0.0
    )
    resched = jnp.where(penalty_nodes, -1.0, 0.0)
    aff = jnp.where(has_affinities, affinity_scores, 0.0)
    spread_on = has_spreads & (spread_boost != 0.0)
    spread_c = jnp.where(spread_on, spread_boost, 0.0)

    n_comp = (
        1.0
        + (job_counts > 0)
        + penalty_nodes
        + jnp.where(has_affinities, 1.0, 0.0)
        + jnp.where(spread_on, 1.0, 0.0)
    )
    total = fit_score + anti + resched + aff + spread_c
    final = total / n_comp
    return jnp.where(fits, final, -jnp.inf), fits


def _score_planes(
    capacity,  # f32[N, D]
    used0,  # f32[N, D]
    ask,  # f32[D]
    elig,  # bool[N]
    jc0,  # i32[N]
    dt,  # f32[] anti-affinity denominator
    pen,  # bool[N]
    aff,  # f32[N]
    has_aff,  # bool[]
    dh,  # bool[] distinct_hosts
    caps,  # f32[N] per-node device-slot caps
    algorithm_spread,  # bool[]
    max_j: int,
):
    """The shared [N, J] candidate planes: numerator (sum of non-spread
    components), denominator (contributing-component count, spread
    excluded — the scan adds it dynamically), and feasibility. Work in
    [N, J] planes only — a [N, J, D] temp is N·J·D·4 bytes and OOMs at
    40k-node scale; the D axis is tiny and static, so unroll it."""
    js = jnp.arange(max_j, dtype=jnp.float32)  # [J]
    mult = js[None, :] + 1.0  # [1, J]
    fits = elig[:, None] & jnp.ones((1, max_j), dtype=bool)
    for d in range(capacity.shape[1]):
        prop_d = used0[:, d : d + 1] + mult * ask[d]
        fits &= prop_d <= capacity[:, d : d + 1]
    # distinct_hosts ⇒ only j=0 and only where no existing collision
    dh_mask = jnp.where(dh, (js[None, :] == 0) & (jc0[:, None] == 0), True)
    fits &= dh_mask
    fits &= js[None, :] < caps[:, None]  # device-slot caps

    pow_sum = jnp.zeros_like(fits, dtype=jnp.float32)
    for d in (0, 1):  # cpu, mem drive the fit score
        cap_d = capacity[:, d : d + 1]
        prop_d = used0[:, d : d + 1] + mult * ask[d]
        free_d = jnp.where(
            cap_d > 0, (cap_d - prop_d) / jnp.maximum(cap_d, 1e-9), 1.0
        )
        pow_sum = pow_sum + _pow10(free_d)
    binpack = jnp.clip(20.0 - pow_sum, 0.0, BINPACK_MAX_SCORE)
    spread_fit = jnp.clip(pow_sum - 2.0, 0.0, BINPACK_MAX_SCORE)
    fit_score = (
        jnp.where(algorithm_spread, spread_fit, binpack) / BINPACK_MAX_SCORE
    )

    coll = jc0[:, None].astype(jnp.float32) + js[None, :]  # after j placed
    has_coll = coll > 0
    anti = jnp.where(has_coll, -(coll + 1.0) / jnp.maximum(dt, 1.0), 0.0)
    resched = jnp.where(pen[:, None], -1.0, 0.0)
    aff_c = jnp.where(has_aff, aff[:, None], 0.0)
    num = fit_score + anti + resched + aff_c  # [N, J]
    den = 1.0 + has_coll + pen[:, None] + jnp.where(has_aff, 1.0, 0.0)
    return num, den, fits


# -- closed-form greedy (the TPU-shaped fast path) ---------------------------
#
# For one group placing ``count`` IDENTICAL asks with no per-value
# coupling, node scores are independent and the per-node score sequence
# s[n, j] is monotone non-increasing in j after a running-min clamp
# (binpack worsens with usage, anti-affinity grows; the single
# non-monotone corner — a rising best-fit head — is flattened by the
# clamp, under which top-k fills nodes in descending initial-score order,
# exactly what stepwise greedy does with rising heads). Greedy placement
# then equals a plain top-k over the flattened [N, J] matrix.
#
# This is the "batched dense score matrix" BASELINE.json names as the
# north-star replacement for the reference's per-placement iterator walk
# (scheduler/rank.go:193-527): O(N·J) parallel work, O(log) depth.


@functools.partial(jax.jit, static_argnames=("max_j", "k"))
def place_closed_form_kernel(
    capacity,  # f32[N, D] shared
    used0,  # f32[N, D] shared snapshot usage
    asks,  # f32[G, D]
    eligible,  # bool[G, N]
    job_counts,  # i32[G, N]
    desired_totals,  # f32[G]
    penalty_nodes,  # bool[G, N]
    affinity_scores,  # f32[G, N]
    has_affinities,  # bool[G]
    distinct_hosts,  # bool[G]
    slot_caps,  # f32[G, N]
    algorithm_spread,  # bool[]
    counts,  # i32[G]
    max_j: int,  # static: max instances of one group per node
    k: int,  # static: top-k width (≥ max count in batch + overflow)
):
    """Returns (choices i32[G, k], scores f32[G, k]) in greedy order.
    Entries past a lane's feasible candidates are −1/−inf; entries in
    [count, k) are valid *overflow* candidates for conflict repair."""

    def one_group(ask, elig, jc0, dt, pen, aff, has_aff, dh, caps, count):
        num, den, fits = _score_planes(
            capacity, used0, ask, elig, jc0, dt, pen, aff, has_aff, dh,
            caps, algorithm_spread, max_j,
        )
        s_raw = jnp.where(fits, num / den, -jnp.inf)
        # Selection runs on the running-min clamp: it restores the prefix
        # rule "(n,j) requires (n,j-1)" that plain top-k needs.
        s_sel = jax.lax.associative_scan(jnp.minimum, s_raw, axis=1)

        flat_sel = s_sel.reshape(-1)  # [N*J]
        flat_raw = s_raw.reshape(-1)
        k_eff = min(k, flat_sel.shape[0])  # tiny clusters: < k slots total
        top_sel, top_idx = jax.lax.top_k(flat_sel, k_eff)
        if k_eff < k:
            pad = k - k_eff
            top_sel = jnp.concatenate(
                [top_sel, jnp.full(pad, -jnp.inf, top_sel.dtype)]
            )
            top_idx = jnp.concatenate([top_idx, jnp.zeros(pad, top_idx.dtype)])
        # report the TRUE (unclamped) score of each chosen (n, j) — the
        # AllocMetric the oracle would have recorded for that placement
        top_raw = flat_raw[top_idx]
        node_rows = (top_idx // max_j).astype(jnp.int32)
        ok = top_sel > -jnp.inf  # caller slices [:count] vs overflow
        return jnp.where(ok, node_rows, -1), jnp.where(ok, top_raw, -jnp.inf)

    return jax.vmap(one_group)(
        asks, eligible, job_counts, desired_totals, penalty_nodes,
        affinity_scores, has_affinities, distinct_hosts, slot_caps, counts,
    )


# -- gather-scan (spread / distinct_property groups) -------------------------


def _block_tables(c, desired, caps, weights, kinds):
    """Per-(block, value) boost + allowance tables from the current count
    state ``c`` [B, V].

    Target mode (spread.go:110-174): boost[v] = (desired − (c+1))/desired
    × weight, where weight is already weight/Σweights; desired < 0 marks a
    value with no explicit or implicit target → flat −1 (unweighted,
    spread.go:145-152).

    Even mode (spread.go:178-228 evenSpreadScoreBoost): boosts derive
    from the min/max of *positive* counts. (The reference computes min
    over a Go map that may contain cleared-to-zero entries, making the
    min==0 branch order-dependent; we define min over positive counts,
    which matches the deterministic reading.)

    Distinct caps (feasible.go:604): allow[v] = c[v] < cap[v].
    """
    # target
    t_boost = jnp.where(
        desired > 0,
        (desired - (c + 1.0)) / jnp.maximum(desired, 1e-9) * weights[:, None],
        -1.0,
    )
    # even
    pos = c > 0
    any_pos = jnp.any(pos, axis=1, keepdims=True)  # [B, 1]
    minc = jnp.min(jnp.where(pos, c, jnp.inf), axis=1, keepdims=True)
    maxc = jnp.max(jnp.where(pos, c, -jnp.inf), axis=1, keepdims=True)
    at_min = c == minc
    e_boost = jnp.where(
        at_min,
        jnp.where(minc == maxc, -1.0, (maxc - minc) / jnp.maximum(minc, 1e-9)),
        (minc - c) / jnp.maximum(minc, 1e-9),
    )
    e_boost = jnp.where(any_pos, e_boost, 0.0)

    boost = jnp.where(
        (kinds == BLOCK_TARGET_SPREAD)[:, None],
        t_boost,
        jnp.where((kinds == BLOCK_EVEN_SPREAD)[:, None], e_boost, 0.0),
    )
    allow = jnp.where((kinds == BLOCK_DISTINCT_CAP)[:, None], c < caps, True)
    return boost, allow


@functools.partial(jax.jit, static_argnames=("max_j", "max_steps"))
def place_value_scan_kernel(
    capacity,  # f32[N, D] shared
    used0,  # f32[N, D] shared snapshot usage
    asks,  # f32[G, D]
    eligible,  # bool[G, N]
    job_counts,  # i32[G, N]
    desired_totals,  # f32[G]
    penalty_nodes,  # bool[G, N]
    affinity_scores,  # f32[G, N]
    has_affinities,  # bool[G]
    distinct_hosts,  # bool[G]
    slot_caps,  # f32[G, N]
    block_value_ids,  # i32[G, B, N] (−1 = node has no value)
    block_counts0,  # f32[G, B, V]
    block_desired,  # f32[G, B, V]
    block_caps,  # f32[G, B, V]
    block_weights,  # f32[G, B]
    block_kinds,  # i32[G, B]
    algorithm_spread,  # bool[]
    counts,  # i32[G] placements to emit (incl. overflow slots)
    max_j: int,
    max_steps: int,
):
    """Greedy sequential placement with per-value count coupling.

    All heavy scoring is hoisted into the parallel [N, J] plane
    precompute; each scan step gathers per-node column heads, adds the
    per-value boost/allowance tables, and argmaxes — the device-resident
    analog of re-running SpreadIterator + DistinctPropertyIterator per
    placement (scheduler/spread.go:110, feasible.go:645), at O(N) gather
    cost per step instead of O(N·D·stages) rescoring.
    """

    def one_group(
        ask, elig, jc0, dt, pen, aff, has_aff, dh, caps,
        vids, c0, desired, vcaps, weights, kinds, count,
    ):
        num, den, fits = _score_planes(
            capacity, used0, ask, elig, jc0, dt, pen, aff, has_aff, dh,
            caps, algorithm_spread, max_j,
        )
        n = num.shape[0]
        is_spread = (kinds == BLOCK_TARGET_SPREAD) | (kinds == BLOCK_EVEN_SPREAD)
        has_spread_any = jnp.any(is_spread)
        safe_vids = jnp.maximum(vids, 0)  # [B, N]

        def step(state, i):
            jn, c = state  # jn i32[N] next column per node; c f32[B, V]
            head_j = jnp.minimum(jn, max_j - 1)
            gather = lambda plane: jnp.take_along_axis(
                plane, head_j[:, None], axis=1
            )[:, 0]
            head_num = gather(num)
            head_den = gather(den)
            head_fit = gather(fits) & (jn < max_j)

            tbl, allow = _block_tables(c, desired, vcaps, weights, kinds)
            per_block = jnp.take_along_axis(tbl, safe_vids, axis=1)  # [B, N]
            contrib = jnp.where(vids >= 0, per_block, -1.0)
            boost = jnp.sum(
                jnp.where(is_spread[:, None], contrib, 0.0), axis=0
            )  # [N]
            allow_pb = jnp.take_along_axis(allow, safe_vids, axis=1)
            allowed = jnp.all(
                jnp.where(
                    (kinds == BLOCK_DISTINCT_CAP)[:, None] & (vids >= 0),
                    allow_pb,
                    True,
                ),
                axis=0,
            )  # [N]

            spread_on = has_spread_any & (boost != 0.0)
            den_t = head_den + jnp.where(spread_on, 1.0, 0.0)
            score = (head_num + jnp.where(spread_on, boost, 0.0)) / den_t
            score = jnp.where(head_fit & allowed, score, -jnp.inf)

            best = jnp.argmax(score)
            ok = (score[best] > -jnp.inf) & (i < count)
            onehot = (jnp.arange(n) == best) & ok
            jn = jn + onehot.astype(jn.dtype)
            bumped = vids[:, best]  # [B] value per block at the chosen node
            c = c + jnp.where(
                (ok & (bumped >= 0))[:, None],
                jax.nn.one_hot(
                    jnp.maximum(bumped, 0), c.shape[1], dtype=c.dtype
                ),
                0.0,
            )
            return (jn, c), (
                jnp.where(ok, best, -1).astype(jnp.int32),
                jnp.where(ok, score[best], -jnp.inf).astype(jnp.float32),
            )

        state0 = (jnp.zeros(n, dtype=jnp.int32), c0)
        _, (choices, scores) = jax.lax.scan(
            step, state0, jnp.arange(max_steps)
        )
        return choices, scores

    return jax.vmap(one_group)(
        asks, eligible, job_counts, desired_totals, penalty_nodes,
        affinity_scores, has_affinities, distinct_hosts, slot_caps,
        block_value_ids, block_counts0, block_desired, block_caps,
        block_weights, block_kinds, counts,
    )


@jax.jit
def score_matrix_kernel(
    capacity,
    used,
    asks,  # f32[G, D]
    eligible,  # bool[G, N]
    job_counts,  # i32[G, N]
    desired_totals,
    penalty_nodes,
    affinity_scores,
    has_affinities,
    distinct_hosts,
    algorithm_spread,
):
    """The dense evals×nodes score matrix (no sequential state) — used for
    dry-run annotation, the system scheduler, and benchmarks."""
    zero_boost = jnp.zeros(capacity.shape[0], dtype=jnp.float32)

    def one(a, e, jc, dt, pn, af, ha, dh):
        final, fits = component_scores(
            capacity, used, a, e, jc, dt, pn, af, ha,
            zero_boost, jnp.asarray(False), dh, algorithm_spread,
        )
        return final, fits

    return jax.vmap(one)(
        asks,
        eligible,
        job_counts,
        desired_totals,
        penalty_nodes,
        affinity_scores,
        has_affinities,
        distinct_hosts,
    )


def _steps_bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


def _dummy_ask(pn: int):
    """Zero-count padding lane for the group axis: eligible nowhere, so
    the kernel places nothing and its lane is dropped on unpack. Keeps
    the compiled G dimension bucketed (recompiles are the real cost of a
    varying batch size, not the padded FLOPs)."""
    from .flatten import GroupAsk

    return GroupAsk(
        job_id="",
        tg_name="",
        count=0,
        desired_total=1,
        ask=np.zeros(4, dtype=np.float32),
        eligible=np.zeros(pn, dtype=bool),
        job_counts=np.zeros(pn, dtype=np.int32),
        penalty_nodes=np.zeros(pn, dtype=bool),
        affinity_scores=np.zeros(pn, dtype=np.float32),
        has_affinities=False,
        distinct_hosts=False,
    )


def _pad_group_axis(asks: list, pn: int) -> list:
    """Pad the ask list so the compiled G dimension takes only two small
    values: 1 (single-eval path) or a power-of-two ≥ 16 (batched path).
    Collapsing 2..16 asks onto one 16-lane executable costs padded vmap
    lanes but avoids a recompile per distinct batch size."""
    n = len(asks)
    g = 1 if n == 1 else max(16, _steps_bucket(n))
    if g == n:
        return asks
    dummy = _dummy_ask(pn)
    return asks + [dummy] * (g - n)


def _shared_batch(asks: list, pn: int) -> dict:
    """Host-side assembly of the kernel inputs common to both placement
    paths (the value-block fields are added by the scan path)."""
    return dict(
        asks=np.stack([a.ask for a in asks]),
        eligible=np.stack([a.eligible for a in asks]),
        job_counts=np.stack([a.job_counts for a in asks]),
        desired_totals=np.array(
            [a.desired_total for a in asks], dtype=np.float32
        ),
        penalty_nodes=np.stack([a.penalty_nodes for a in asks]),
        affinity_scores=np.stack([a.affinity_scores for a in asks]),
        has_affinities=np.array([a.has_affinities for a in asks]),
        distinct_hosts=np.array([a.distinct_hosts for a in asks]),
        slot_caps=np.stack(
            [
                a.slot_caps
                if a.slot_caps is not None
                else np.full(pn, np.inf, dtype=np.float32)
                for a in asks
            ]
        ),
        counts=np.array([a.count for a in asks], dtype=np.int32),
    )


@dataclass
class PlacementResult:
    """Host-side result for one group: chosen node rows (−1 = failed) and
    their normalized scores, in placement order; plus overflow candidates
    (the next entries greedy would have taken) for conflict repair."""

    node_rows: np.ndarray
    scores: np.ndarray
    overflow_rows: np.ndarray = field(
        default_factory=lambda: np.empty(0, dtype=np.int32)
    )
    overflow_scores: np.ndarray = field(
        default_factory=lambda: np.empty(0, dtype=np.float32)
    )


class PlacementKernel:
    """Host wrapper: pads a list of GroupAsks into batch tensors, runs the
    compiled kernel, unpacks results. Shape-bucketed so node churn and
    varying batch sizes hit a small set of compiled programs."""

    def __init__(self, algorithm: str = "binpack", force_scan: bool = False):
        self.algorithm_spread = algorithm == "spread"
        self.force_scan = force_scan  # parity testing: disable the fast path

    def place(self, cluster, asks: list) -> list[PlacementResult]:
        if not asks:
            return []
        # split: uncoupled groups take the closed-form top-k fast path;
        # spread blocks / distinct_property caps couple nodes through
        # global per-value counts and take the gather-scan
        fast, slow = [], []
        for i, a in enumerate(asks):
            coupled = a.blocks is not None and a.blocks.num_blocks > 0
            (slow if (coupled or self.force_scan) else fast).append(i)
        out: list[Optional[PlacementResult]] = [None] * len(asks)
        if fast:
            for i, r in zip(fast, self._place_closed_form(
                cluster, [asks[i] for i in fast]
            )):
                out[i] = r
        if slow:
            for i, r in zip(slow, self._place_scan_batch(
                cluster, [asks[i] for i in slow]
            )):
                out[i] = r
        return out

    def _max_j(self, cluster, asks: list) -> int:
        """J bound: most instances of one identical ask any node could
        hold, bucketed to multiples of 16."""
        cap_max = np.asarray(cluster.capacity).max(axis=0)  # [D]
        max_j = 1
        for a in asks:
            pos = a.ask > 0
            if pos.any():
                j = int(np.floor(np.min(cap_max[pos] / a.ask[pos]))) + 1
            else:
                j = a.count
            max_j = max(max_j, min(j, a.count))
        return max(16, -(-max_j // 16) * 16)

    def _place_closed_form(self, cluster, asks: list) -> list[PlacementResult]:
        pn = cluster.padded_n
        max_count = max(a.count for a in asks)
        k = _steps_bucket(max(max_count + OVERFLOW_CANDIDATES, 1))
        max_j = self._max_j(cluster, asks)

        # chunk the group axis so the [chunk, N, J] planes stay within an
        # HBM budget (~2 GB of live f32 planes)
        bytes_per_lane = pn * max_j * 4 * 4
        chunk = max(1, int((2 << 30) // max(bytes_per_lane, 1)))
        if len(asks) > chunk:
            out: list[PlacementResult] = []
            for i in range(0, len(asks), chunk):
                out.extend(
                    self._place_closed_form(cluster, asks[i:i + chunk])
                )
            return out

        real_n = len(asks)
        asks = _pad_group_axis(asks, pn)
        batch = _shared_batch(asks, pn)
        choices, scores = place_closed_form_kernel(
            jnp.asarray(cluster.capacity),
            jnp.asarray(cluster.used),
            **{kk: jnp.asarray(v) for kk, v in batch.items()},
            algorithm_spread=jnp.asarray(self.algorithm_spread),
            max_j=max_j,
            k=k,
        )
        choices = np.array(choices)  # writable copy: repair mutates rows
        scores = np.array(scores)
        return [
            PlacementResult(
                node_rows=choices[gi, : a.count],
                scores=scores[gi, : a.count],
                overflow_rows=choices[gi, a.count :],
                overflow_scores=scores[gi, a.count :],
            )
            for gi, a in enumerate(asks[:real_n])
        ]

    def _place_scan_batch(self, cluster, asks: list) -> list[PlacementResult]:
        from .flatten import pad_value_blocks

        pn = cluster.padded_n
        real_n = len(asks)
        asks = _pad_group_axis(asks, pn)
        max_count = max(a.count for a in asks)
        max_steps = _steps_bucket(max(max_count + OVERFLOW_CANDIDATES, 1))
        max_j = self._max_j(cluster, asks)

        batch = _shared_batch(asks, pn)
        # emit overflow candidates past each lane's primary count
        batch["counts"] = np.minimum(
            batch["counts"] + OVERFLOW_CANDIDATES, max_steps
        ).astype(np.int32)
        # zero-count padding lanes stay inert (eligible nowhere)
        batch["counts"] = np.where(
            np.array([a.count for a in asks]) > 0, batch["counts"], 0
        ).astype(np.int32)
        batch.update(pad_value_blocks([a.blocks for a in asks], pn))
        choices, scores = place_value_scan_kernel(
            jnp.asarray(cluster.capacity),
            jnp.asarray(cluster.used),
            **{k: jnp.asarray(v) for k, v in batch.items()},
            algorithm_spread=jnp.asarray(self.algorithm_spread),
            max_j=max_j,
            max_steps=max_steps,
        )
        choices = np.array(choices)  # writable copy: repair mutates rows
        scores = np.array(scores)
        out = []
        for gi, a in enumerate(asks[:real_n]):
            out.append(
                PlacementResult(
                    node_rows=choices[gi, : a.count],
                    scores=scores[gi, : a.count],
                    overflow_rows=choices[
                        gi, a.count : a.count + OVERFLOW_CANDIDATES
                    ],
                    overflow_scores=scores[
                        gi, a.count : a.count + OVERFLOW_CANDIDATES
                    ],
                )
            )
        return out


def repair_batch_conflicts(cluster, asks: list, results: list) -> list[bool]:
    """Host-side optimistic-conflict resolution for one batched pass.

    Every lane scored against the same snapshot ``used0``, so lanes can
    pile onto the same best nodes (true argmax removes the decorrelation
    the reference gets from per-worker shuffle sampling, stack.go:74-90).
    Rather than letting the plan applier partially reject and re-running
    whole evals, walk the lanes in order with a usage overlay: placements
    that no longer fit are moved to the lane's next overflow candidate
    that does. The plan applier's per-node AllocsFit re-check
    (plan_apply.go:638-689) remains the authority.

    Mutates each PlacementResult in place. Returns per-lane ``ok`` —
    False when a conflicted placement had no usable overflow candidate
    (caller should fall back to the individual path for that eval).
    """
    capacity = np.asarray(cluster.capacity)
    used = np.asarray(cluster.used).copy()
    ok_lanes: list[bool] = []
    for a, res in zip(asks, results):
        ok = True
        taken_rows = set()  # rows this lane committed (distinct_hosts)
        # per-(block, value) counts for distinct_property caps
        blocks = a.blocks
        counts = blocks.counts0.copy() if blocks is not None else None
        overflow = list(
            zip(res.overflow_rows.tolist(), res.overflow_scores.tolist())
        )
        of_idx = 0

        def commit(row: int) -> None:
            used[row] += a.ask
            taken_rows.add(row)
            if blocks is not None:
                for b in range(blocks.num_blocks):
                    v = blocks.value_ids[b, row]
                    if v >= 0:
                        counts[b, v] += 1

        def acceptable(row: int) -> bool:
            if row < 0:
                return False
            if not np.all(used[row] + a.ask <= capacity[row]):
                return False
            if a.distinct_hosts and row in taken_rows:
                return False
            if blocks is not None:
                for b in range(blocks.num_blocks):
                    if blocks.kinds[b] != BLOCK_DISTINCT_CAP:
                        continue
                    v = blocks.value_ids[b, row]
                    if v >= 0 and counts[b, v] >= blocks.caps[b, v]:
                        return False
            return True

        for i, row in enumerate(res.node_rows.tolist()):
            if row < 0:
                continue
            if acceptable(row):
                commit(row)
                continue
            # conflicted: advance through overflow candidates
            repl = -1
            while of_idx < len(overflow):
                cand, sc = overflow[of_idx]
                of_idx += 1
                if acceptable(cand):
                    repl = cand
                    res.node_rows[i] = cand
                    res.scores[i] = sc
                    commit(cand)
                    break
            if repl < 0:
                ok = False
                break
        ok_lanes.append(ok)
    return ok_lanes
