"""The batched placement kernel — the TPU replacement for the reference's
iterator-chain inner loop.

What the reference does per placement (scheduler/stack.go:343-438 chain,
scheduler/rank.go:193-527 BinPackIterator.Next): walk up to ``limit`` nodes
through ~10 iterator stages, computing fit and score sequentially in Go.
O(allocs × limit × stages), single-threaded per eval.

What this module does instead: one compiled XLA program per shape bucket
computing, for a *batch* of task groups at once::

    scores[g, n] = mean(binpack, anti_affinity, resched_penalty,
                        affinity, spread)[g, n]        (masked -inf infeasible)

and a greedy placement *scan*: ``lax.scan`` over placement steps, each step
argmax-ing the live score vector and updating the proposed-usage state on
device — the exact greedy semantics of pulling the iterator chain to
completion with limit = ∞ (the dense pass computes the true argmax, which
the reference only approximates by sampling log₂(n) nodes; see SURVEY.md
§7 "hard parts": parity metric is placement-score, not identity).

Batch dimension = concurrent evals/groups, replacing Nomad's worker-per-
core optimistic concurrency (nomad/worker.go:85): every group in a batch
scores against the same snapshot, and conflicts are resolved by the plan
applier exactly as for concurrent Go workers.

Scoring component semantics (each cites its reference):
- binpack/spread fit: nomad/structs/funcs.go:236-274, normalized /18
  (rank.go:513-516).
- job anti-affinity: −(collisions+1)/desired_count for nodes already
  holding collisions > 0 allocs of the job (rank.go:536-604).
- reschedule penalty: −1 on the node a failed alloc is being replaced
  from (rank.go:606-648).
- node affinity: weight-normalized Σ w·match / Σ|w| (rank.go:650-737),
  precomputed per node host-side (string matching ≪ scoring cost).
- spread: (desired − used−1)/desired × weight/100 for the node's value of
  the spread attribute (scheduler/spread.go:110-228).
- normalization: mean over *contributing* components
  (rank.go:740-767 ScoreNormalizationIterator).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..structs.resources import BINPACK_MAX_SCORE

_LN10 = 2.302585092994046


def _pow10(x):
    return jnp.exp(_LN10 * x)


def component_scores(
    capacity,  # f32[N, D]
    used,  # f32[N, D] current proposed usage
    ask,  # f32[D]
    eligible,  # bool[N]
    job_counts,  # i32[N]
    desired_total,  # f32[] anti-affinity denominator
    penalty_nodes,  # bool[N]
    affinity_scores,  # f32[N]
    has_affinities,  # bool[]
    spread_boost,  # f32[N] (precomputed for this step)
    has_spreads,  # bool[]
    distinct_hosts,  # bool[]
    algorithm_spread,  # bool[] scheduler algorithm: binpack vs spread fit
):
    """Per-node normalized score for placing one instance of ``ask``.
    Returns (final_score f32[N] with -inf infeasible, fits bool[N])."""
    proposed = used + ask  # [N, D]
    fits = jnp.all(proposed <= capacity, axis=-1) & eligible
    fits &= jnp.where(distinct_hosts, job_counts == 0, True)

    free_frac = jnp.where(
        capacity > 0, (capacity - proposed) / jnp.maximum(capacity, 1e-9), 1.0
    )
    pow_sum = _pow10(free_frac[:, 0]) + _pow10(free_frac[:, 1])  # cpu, mem
    binpack = jnp.clip(20.0 - pow_sum, 0.0, BINPACK_MAX_SCORE)
    spread_fit = jnp.clip(pow_sum - 2.0, 0.0, BINPACK_MAX_SCORE)
    fit_score = jnp.where(algorithm_spread, spread_fit, binpack) / BINPACK_MAX_SCORE

    collisions = job_counts.astype(jnp.float32)
    anti = jnp.where(
        job_counts > 0, -(collisions + 1.0) / jnp.maximum(desired_total, 1.0), 0.0
    )
    resched = jnp.where(penalty_nodes, -1.0, 0.0)
    aff = jnp.where(has_affinities, affinity_scores, 0.0)
    spread_c = jnp.where(has_spreads, spread_boost, 0.0)

    n_comp = (
        1.0
        + (job_counts > 0)
        + penalty_nodes
        + jnp.where(has_affinities, 1.0, 0.0)
        + jnp.where(has_spreads, 1.0, 0.0)
    )
    total = fit_score + anti + resched + aff + spread_c
    final = total / n_comp
    return jnp.where(fits, final, -jnp.inf), fits


def _spread_boost(spread_value_ids, spread_desired, spread_counts, spread_weight):
    """Boost for adding one alloc to each node, given current per-value
    counts. Nodes with no value for the attribute get 0."""
    has_value = spread_value_ids >= 0
    vid = jnp.maximum(spread_value_ids, 0)
    desired = spread_desired[vid]
    after = spread_counts[vid] + 1.0
    boost = jnp.where(
        desired > 0, (desired - after) / jnp.maximum(desired, 1.0), -1.0
    ) * spread_weight
    return jnp.where(has_value, boost, 0.0)


def _place_scan(
    capacity,
    used0,
    ask,
    eligible,
    job_counts0,
    desired_total,
    penalty_nodes,
    affinity_scores,
    has_affinities,
    spread_value_ids,
    spread_desired,
    spread_counts0,
    spread_weight,
    has_spreads,
    distinct_hosts,
    slot_caps,  # f32[N] max additional placements per node (device sets)
    algorithm_spread,
    count,  # i32[] actual placements wanted (≤ max_steps)
    max_steps: int,
):
    """Greedy sequential placement of ``count`` identical asks.

    Each step scores all nodes against the *current* proposed usage (the
    device-resident analog of ProposedAllocs, scheduler/context.go:120-157),
    picks the argmax, and folds the placement into the state. Steps past
    ``count`` (or with no feasible node) emit choice −1. ``slot_caps``
    bounds per-node placements of *this* group — the dense form of the
    DeviceChecker/DeviceAccounter limit (scheduler/device.py).
    """

    def step(state, i):
        used, job_counts, spread_counts, placed = state
        boost = _spread_boost(
            spread_value_ids, spread_desired, spread_counts, spread_weight
        )
        final, _ = component_scores(
            capacity,
            used,
            ask,
            eligible & (placed < slot_caps),
            job_counts,
            desired_total,
            penalty_nodes,
            affinity_scores,
            has_affinities,
            boost,
            has_spreads,
            distinct_hosts,
            algorithm_spread,
        )
        best = jnp.argmax(final)
        best_score = final[best]
        ok = (best_score > -jnp.inf) & (i < count)
        choice = jnp.where(ok, best, -1)
        onehot = (jnp.arange(used.shape[0]) == best) & ok
        used = used + jnp.where(onehot[:, None], ask[None, :], 0.0)
        job_counts = job_counts + onehot.astype(job_counts.dtype)
        placed = placed + onehot.astype(placed.dtype)
        vid = jnp.maximum(spread_value_ids[best], 0)
        bump = ok & (spread_value_ids[best] >= 0)
        spread_counts = spread_counts.at[vid].add(jnp.where(bump, 1.0, 0.0))
        return (used, job_counts, spread_counts, placed), (
            choice.astype(jnp.int32),
            jnp.where(ok, best_score, -jnp.inf).astype(jnp.float32),
        )

    placed0 = jnp.zeros(used0.shape[0], dtype=jnp.float32)
    state0 = (used0, job_counts0, spread_counts0, placed0)
    (used, job_counts, spread_counts, _placed), (choices, scores) = jax.lax.scan(
        step, state0, jnp.arange(max_steps)
    )
    return choices, scores, used


@functools.partial(jax.jit, static_argnames=("max_steps",))
def place_batch_kernel(
    capacity,  # f32[N, D] shared
    used0,  # f32[N, D] shared snapshot usage
    asks,  # f32[G, D]
    eligible,  # bool[G, N]
    job_counts,  # i32[G, N]
    desired_totals,  # f32[G]
    penalty_nodes,  # bool[G, N]
    affinity_scores,  # f32[G, N]
    has_affinities,  # bool[G]
    spread_value_ids,  # i32[G, N]
    spread_desired,  # f32[G, V]
    spread_counts,  # f32[G, V]
    spread_weights,  # f32[G]
    has_spreads,  # bool[G]
    distinct_hosts,  # bool[G]
    slot_caps,  # f32[G, N] per-node device-set caps (+inf when no devices)
    algorithm_spread,  # bool[]
    counts,  # i32[G]
    max_steps: int,
):
    """vmap of the greedy scan over the group/eval batch dimension.

    Every group scores against the same snapshot ``used0`` — optimistic
    concurrency identical to the reference's parallel workers
    (doc scheduling.mdx:71-82); the plan applier re-checks fits and
    partially rejects on conflict (nomad/plan_apply.go:439-596).
    """
    return jax.vmap(
        lambda a, e, jc, dt, pn, af, ha, svi, sd, sc, sw, hs, dh, sl, c: _place_scan(
            capacity,
            used0,
            a,
            e,
            jc,
            dt,
            pn,
            af,
            ha,
            svi,
            sd,
            sc,
            sw,
            hs,
            dh,
            sl,
            algorithm_spread,
            c,
            max_steps,
        )
    )(
        asks,
        eligible,
        job_counts,
        desired_totals,
        penalty_nodes,
        affinity_scores,
        has_affinities,
        spread_value_ids,
        spread_desired,
        spread_counts,
        spread_weights,
        has_spreads,
        distinct_hosts,
        slot_caps,
        counts,
    )


@jax.jit
def score_matrix_kernel(
    capacity,
    used,
    asks,  # f32[G, D]
    eligible,  # bool[G, N]
    job_counts,  # i32[G, N]
    desired_totals,
    penalty_nodes,
    affinity_scores,
    has_affinities,
    distinct_hosts,
    algorithm_spread,
):
    """The dense evals×nodes score matrix (no sequential state) — used for
    dry-run annotation, top-k explainability, and benchmarks."""
    zero_boost = jnp.zeros(capacity.shape[0], dtype=jnp.float32)

    def one(a, e, jc, dt, pn, af, ha, dh):
        final, fits = component_scores(
            capacity, used, a, e, jc, dt, pn, af, ha,
            zero_boost, jnp.asarray(False), dh, algorithm_spread,
        )
        return final, fits

    return jax.vmap(one)(
        asks,
        eligible,
        job_counts,
        desired_totals,
        penalty_nodes,
        affinity_scores,
        has_affinities,
        distinct_hosts,
    )


def _steps_bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


# -- closed-form greedy (the TPU-shaped fast path) ---------------------------
#
# For one group placing ``count`` IDENTICAL asks, each node's score as a
# function of j (instances of this group already placed on it) is a closed
# form: usage is used0 + j·ask, collisions are job_counts0 + j. With no
# spread block (whose boost couples nodes through global per-value counts),
# node scores are independent, and the per-node score sequence s[n, j] is
# monotone non-increasing in j (binpack worsens with usage, anti-affinity
# grows; the single non-monotone corner — a penalty term diluting the
# normalization mean at the j=0→1 component-count change — is clamped by a
# running min). Greedy placement then equals: take the ``count`` largest
# entries of the [N, J] matrix under the prefix rule "(n, j) requires
# (n, j-1)" — which monotone rows turn into a plain top-k over the
# flattened matrix. One fully-parallel scoring pass + one top_k replaces
# ``count`` sequential scan steps.
#
# This is the "batched dense score matrix" BASELINE.json names as the
# north-star replacement for the reference's per-placement iterator walk
# (scheduler/rank.go:193-527): O(N·J) parallel work, O(log) depth.


@functools.partial(jax.jit, static_argnames=("max_j", "k"))
def place_closed_form_kernel(
    capacity,  # f32[N, D] shared
    used0,  # f32[N, D] shared snapshot usage
    asks,  # f32[G, D]
    eligible,  # bool[G, N]
    job_counts,  # i32[G, N]
    desired_totals,  # f32[G]
    penalty_nodes,  # bool[G, N]
    affinity_scores,  # f32[G, N]
    has_affinities,  # bool[G]
    distinct_hosts,  # bool[G]
    slot_caps,  # f32[G, N]
    algorithm_spread,  # bool[]
    counts,  # i32[G]
    max_j: int,  # static: max instances of one group per node
    k: int,  # static: top-k width (≥ max count in batch)
):
    """Returns (choices i32[G, k], scores f32[G, k]) — node row per
    placement step in greedy order, −1 past count/capacity."""

    js = jnp.arange(max_j, dtype=jnp.float32)  # [J]

    def one_group(ask, elig, jc0, dt, pen, aff, has_aff, dh, caps, count):
        # Work in [N, J] planes only — a [N, J, D] temp is N·J·D·4 bytes
        # and OOMs at 40k-node scale; the D axis is tiny and static, so
        # unroll it (proposed usage after the (j+1)-th instance is
        # used0[:, d] + (j+1)·ask[d]).
        mult = js[None, :] + 1.0  # [1, J]
        fits = elig[:, None] & jnp.ones((1, js.shape[0]), dtype=bool)
        for d in range(capacity.shape[1]):
            prop_d = used0[:, d:d + 1] + mult * ask[d]
            fits &= prop_d <= capacity[:, d:d + 1]
        # distinct_hosts ⇒ only j=0 and only where no existing collision
        dh_mask = jnp.where(dh, (js[None, :] == 0) & (jc0[:, None] == 0), True)
        fits &= dh_mask
        fits &= js[None, :] < caps[:, None]  # device-slot caps

        pow_sum = jnp.zeros_like(fits, dtype=jnp.float32)
        for d in (0, 1):  # cpu, mem drive the fit score
            cap_d = capacity[:, d:d + 1]
            prop_d = used0[:, d:d + 1] + mult * ask[d]
            free_d = jnp.where(
                cap_d > 0, (cap_d - prop_d) / jnp.maximum(cap_d, 1e-9), 1.0
            )
            pow_sum = pow_sum + _pow10(free_d)
        binpack = jnp.clip(20.0 - pow_sum, 0.0, BINPACK_MAX_SCORE)
        spread_fit = jnp.clip(pow_sum - 2.0, 0.0, BINPACK_MAX_SCORE)
        fit_score = (
            jnp.where(algorithm_spread, spread_fit, binpack) / BINPACK_MAX_SCORE
        )

        coll = jc0[:, None].astype(jnp.float32) + js[None, :]  # after j placed
        has_coll = coll > 0
        anti = jnp.where(
            has_coll, -(coll + 1.0) / jnp.maximum(dt, 1.0), 0.0
        )
        resched = jnp.where(pen[:, None], -1.0, 0.0)
        aff_c = jnp.where(has_aff, aff[:, None], 0.0)
        n_comp = (
            1.0
            + has_coll
            + pen[:, None]
            + jnp.where(has_aff, 1.0, 0.0)
        )
        s_raw = (fit_score + anti + resched + aff_c) / n_comp  # [N, J]
        s_raw = jnp.where(fits, s_raw, -jnp.inf)
        # Selection runs on the running-min clamp: it restores the prefix
        # rule "(n,j) requires (n,j-1)" that plain top-k needs. Binpack is
        # best-fit, so per-node sequences can RISE as a node fills; the
        # clamp flattens a rising run to its initial head — top-k then
        # fills nodes in descending initial-score order, which is exactly
        # what stepwise greedy does with rising heads (a rising head stays
        # max until the node is exhausted).
        s_sel = jax.lax.associative_scan(jnp.minimum, s_raw, axis=1)

        flat_sel = s_sel.reshape(-1)  # [N*J]
        flat_raw = s_raw.reshape(-1)
        k_eff = min(k, flat_sel.shape[0])  # tiny clusters: < k slots total
        top_sel, top_idx = jax.lax.top_k(flat_sel, k_eff)
        if k_eff < k:
            pad = k - k_eff
            top_sel = jnp.concatenate(
                [top_sel, jnp.full(pad, -jnp.inf, top_sel.dtype)]
            )
            top_idx = jnp.concatenate(
                [top_idx, jnp.zeros(pad, top_idx.dtype)]
            )
        # report the TRUE (unclamped) score of each chosen (n, j) — the
        # AllocMetric the oracle would have recorded for that placement
        top_raw = flat_raw[top_idx]
        node_rows = (top_idx // max_j).astype(jnp.int32)
        step = jnp.arange(k)
        ok = (top_sel > -jnp.inf) & (step < count)
        return jnp.where(ok, node_rows, -1), jnp.where(
            ok, top_raw, -jnp.inf
        )

    return jax.vmap(one_group)(
        asks, eligible, job_counts, desired_totals, penalty_nodes,
        affinity_scores, has_affinities, distinct_hosts, slot_caps, counts,
    )


def _dummy_ask(pn: int):
    """Zero-count padding lane for the group axis: eligible nowhere, so
    the kernel places nothing and its lane is dropped on unpack. Keeps
    the compiled G dimension bucketed (recompiles are the real cost of a
    varying batch size, not the padded FLOPs)."""
    from .flatten import GroupAsk

    return GroupAsk(
        job_id="",
        tg_name="",
        count=0,
        desired_total=1,
        ask=np.zeros(4, dtype=np.float32),
        eligible=np.zeros(pn, dtype=bool),
        job_counts=np.zeros(pn, dtype=np.int32),
        penalty_nodes=np.zeros(pn, dtype=bool),
        affinity_scores=np.zeros(pn, dtype=np.float32),
        has_affinities=False,
        distinct_hosts=False,
        spread_value_ids=np.full(pn, -1, dtype=np.int32),
        spread_desired=np.zeros(1, dtype=np.float32),
        spread_initial_counts=np.zeros(1, dtype=np.float32),
        spread_weight=0.0,
        has_spreads=False,
        num_spread_values=1,
    )


def _pad_group_axis(asks: list, pn: int) -> list:
    """Pad the ask list so the compiled G dimension takes only two small
    values: 1 (single-eval path) or a power-of-two ≥ 16 (batched path).
    Collapsing 2..16 asks onto one 16-lane executable costs padded vmap
    lanes but avoids a recompile per distinct batch size."""
    n = len(asks)
    g = 1 if n == 1 else max(16, _steps_bucket(n))
    if g == n:
        return asks
    dummy = _dummy_ask(pn)
    return asks + [dummy] * (g - n)


def _shared_batch(asks: list, pn: int) -> dict:
    """Host-side assembly of the kernel inputs common to both placement
    paths (the spread-only fields are added by the scan path)."""
    return dict(
        asks=np.stack([a.ask for a in asks]),
        eligible=np.stack([a.eligible for a in asks]),
        job_counts=np.stack([a.job_counts for a in asks]),
        desired_totals=np.array(
            [a.desired_total for a in asks], dtype=np.float32
        ),
        penalty_nodes=np.stack([a.penalty_nodes for a in asks]),
        affinity_scores=np.stack([a.affinity_scores for a in asks]),
        has_affinities=np.array([a.has_affinities for a in asks]),
        distinct_hosts=np.array([a.distinct_hosts for a in asks]),
        slot_caps=np.stack(
            [
                a.slot_caps
                if a.slot_caps is not None
                else np.full(pn, np.inf, dtype=np.float32)
                for a in asks
            ]
        ),
        counts=np.array([a.count for a in asks], dtype=np.int32),
    )


@dataclass
class PlacementResult:
    """Host-side result for one group: chosen node rows (−1 = failed) and
    their normalized scores, in placement order."""

    node_rows: np.ndarray
    scores: np.ndarray


class PlacementKernel:
    """Host wrapper: pads a list of GroupAsks into batch tensors, runs the
    compiled kernel, unpacks results. Shape-bucketed so node churn and
    varying batch sizes hit a small set of compiled programs."""

    def __init__(self, algorithm: str = "binpack", force_scan: bool = False):
        self.algorithm_spread = algorithm == "spread"
        self.force_scan = force_scan  # parity testing: disable the fast path

    def place(self, cluster, asks: list) -> list[PlacementResult]:
        if not asks:
            return []
        # split: spread-free groups take the closed-form top-k fast path
        # (node scores decouple); spread blocks couple nodes through global
        # per-value counts and keep the sequential scan
        fast, slow = [], []
        for i, a in enumerate(asks):
            (slow if (a.has_spreads or self.force_scan) else fast).append(i)
        out: list[Optional[PlacementResult]] = [None] * len(asks)
        if fast:
            for i, r in zip(fast, self._place_closed_form(
                cluster, [asks[i] for i in fast]
            )):
                out[i] = r
        if slow:
            for i, r in zip(slow, self._place_scan_batch(
                cluster, [asks[i] for i in slow]
            )):
                out[i] = r
        return out

    def _place_closed_form(self, cluster, asks: list) -> list[PlacementResult]:
        pn = cluster.padded_n
        max_count = max(a.count for a in asks)
        k = _steps_bucket(max(max_count, 1))
        # J bound: most instances of one identical ask any node could hold
        cap_max = np.asarray(cluster.capacity).max(axis=0)  # [D]
        max_j = 1
        for a in asks:
            pos = a.ask > 0
            if pos.any():
                j = int(np.floor(np.min(cap_max[pos] / a.ask[pos]))) + 1
            else:
                j = a.count
            max_j = max(max_j, min(j, a.count))
        max_j = max(16, -(-max_j // 16) * 16)  # multiple-of-16 bucket

        # chunk the group axis so the [chunk, N, J] planes stay within an
        # HBM budget (~2 GB of live f32 planes)
        bytes_per_lane = pn * max_j * 4 * 4
        chunk = max(1, int((2 << 30) // max(bytes_per_lane, 1)))
        if len(asks) > chunk:
            out: list[PlacementResult] = []
            for i in range(0, len(asks), chunk):
                out.extend(
                    self._place_closed_form(cluster, asks[i:i + chunk])
                )
            return out

        real_n = len(asks)
        asks = _pad_group_axis(asks, pn)
        batch = _shared_batch(asks, pn)
        choices, scores = place_closed_form_kernel(
            jnp.asarray(cluster.capacity),
            jnp.asarray(cluster.used),
            **{kk: jnp.asarray(v) for kk, v in batch.items()},
            algorithm_spread=jnp.asarray(self.algorithm_spread),
            max_j=max_j,
            k=k,
        )
        choices = np.asarray(choices)
        scores = np.asarray(scores)
        return [
            PlacementResult(
                node_rows=choices[gi, : a.count], scores=scores[gi, : a.count]
            )
            for gi, a in enumerate(asks[:real_n])
        ]

    def _place_scan_batch(self, cluster, asks: list) -> list[PlacementResult]:
        pn = cluster.padded_n
        real_n = len(asks)
        asks = _pad_group_axis(asks, pn)
        max_count = max(a.count for a in asks)
        max_steps = _steps_bucket(max(max_count, 1))
        max_v = _steps_bucket(max(a.num_spread_values for a in asks))

        def pad_v(arr, fill=0.0):
            out = np.full(max_v, fill, dtype=np.float32)
            out[: arr.shape[0]] = arr
            return out

        batch = _shared_batch(asks, pn)
        batch.update(
            spread_value_ids=np.stack([a.spread_value_ids for a in asks]),
            spread_desired=np.stack([pad_v(a.spread_desired) for a in asks]),
            spread_counts=np.stack(
                [pad_v(a.spread_initial_counts) for a in asks]
            ),
            spread_weights=np.array(
                [a.spread_weight for a in asks], dtype=np.float32
            ),
            has_spreads=np.array([a.has_spreads for a in asks]),
        )
        choices, scores, _used = place_batch_kernel(
            jnp.asarray(cluster.capacity),
            jnp.asarray(cluster.used),
            **{k: jnp.asarray(v) for k, v in batch.items()},
            algorithm_spread=jnp.asarray(self.algorithm_spread),
            max_steps=max_steps,
        )
        choices = np.asarray(choices)
        scores = np.asarray(scores)
        out = []
        for gi, a in enumerate(asks[:real_n]):
            # scan emits [steps, ...] per lane → transpose handled by vmap:
            # choices has shape [G, steps]
            ch = choices[gi, : a.count]
            sc = scores[gi, : a.count]
            out.append(PlacementResult(node_rows=ch, scores=sc))
        return out
