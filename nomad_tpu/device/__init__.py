"""Device layer: cluster flattening + compiled placement/score kernels."""

from .flatten import (
    ClusterTensors,
    GroupAsk,
    ValueBlocks,
    flatten_cluster,
    flatten_group_ask,
)
from .score import (
    PlacementKernel,
    PlacementResult,
    place_closed_form_kernel,
    place_value_scan_kernel,
    repair_batch_conflicts,
    score_matrix_kernel,
)

__all__ = [
    "ClusterTensors",
    "GroupAsk",
    "ValueBlocks",
    "flatten_cluster",
    "flatten_group_ask",
    "PlacementKernel",
    "PlacementResult",
    "place_closed_form_kernel",
    "place_value_scan_kernel",
    "repair_batch_conflicts",
    "score_matrix_kernel",
]
