"""Device layer: cluster flattening + compiled placement/score kernels."""

from .flatten import ClusterTensors, GroupAsk, flatten_cluster, flatten_group_ask
from .score import PlacementKernel, PlacementResult, place_batch_kernel, score_matrix_kernel

__all__ = [
    "ClusterTensors",
    "GroupAsk",
    "flatten_cluster",
    "flatten_group_ask",
    "PlacementKernel",
    "PlacementResult",
    "place_batch_kernel",
    "score_matrix_kernel",
]
