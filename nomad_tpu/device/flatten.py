"""Flattening layer: snapshot state → dense device tensors.

This is the layer SURVEY.md §7 step 1 demands: `NodeResources`/`Resources`
→ dense ``float32[nodes, dims]`` arrays with a stable node-index mapping
and masks for datacenter/class/eligibility. The reference walks Go structs
per node per placement (scheduler/rank.go:193-527); we pay the struct walk
once per snapshot refresh and let every placement reuse the arrays.

Split of labor (mirrors the reference's class-memoization bet,
scheduler/feasible.go:1029-1153: classes ≪ nodes):

- **Host (here):** resolve string/regex/version constraints once per
  *computed node class* into per-class bits, then broadcast to per-node
  masks with one gather. Constraints touching ``unique.`` attributes are
  evaluated per node ("escaped class" in the reference's terms).
- **Device (score.py):** resource fit, scoring, argmax, and the greedy
  placement scan over dense arrays only.

Shapes are padded to buckets (powers of two) so XLA compiles a handful of
program shapes regardless of node churn.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..structs import NUM_DIMS, Job, TaskGroup
from ..structs.resources import node_comparable_capacity


def _check_constraint(node, c):
    # deferred import: scheduler package imports device at init time, so a
    # top-level import here would be circular
    from ..scheduler.feasible import check_constraint

    return check_constraint(node, c)

# Padding buckets for the node axis: next power of two, min 8. Keeps the
# number of distinct compiled shapes logarithmic in cluster size.
_MIN_BUCKET = 8


def node_bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


@dataclass
class ClusterTensors:
    """Dense snapshot of schedulable cluster state.

    ``node_ids[i]`` ↔ row i of every array; rows ≥ ``num_nodes`` are
    padding (``ready`` False ⇒ never selected).
    """

    node_ids: list[str]
    index: int  # state index this was built at (raft watermark analog)
    num_nodes: int
    capacity: np.ndarray  # f32[N, D] reserved-adjusted capacity
    used: np.ndarray  # f32[N, D] non-terminal alloc usage
    ready: np.ndarray  # bool[N]
    dc_ids: np.ndarray  # i32[N]
    class_ids: np.ndarray  # i32[N]
    dc_vocab: dict[str, int]
    class_vocab: dict[str, int]
    # per-class representative node index (for host-side class evaluation)
    class_rep: list[int]
    node_row: dict[str, int] = field(default_factory=dict)
    # row-ordered Node objects (nodes[i] ↔ row i); kept in sync by the
    # flattener / DeviceStateCache so host-side per-class constraint
    # evaluation never re-sorts the cluster
    nodes: list = field(default_factory=list)
    # attribute → (value_ids i32[N], vocab dict) — lazily built columns for
    # spread/property attributes, owned by the cache generation
    attr_cache: dict = field(default_factory=dict)

    @property
    def padded_n(self) -> int:
        return self.capacity.shape[0]

    def row_of(self, node_id: str) -> int:
        return self.node_row[node_id]

    def attr_column(self, attr: str) -> tuple[np.ndarray, dict[str, int]]:
        """Per-node value ids for one attribute (-1 = absent), cached.
        The vocab grows append-only so cached GroupAsk ids stay valid."""
        cached = self.attr_cache.get(attr)
        if cached is not None:
            return cached
        ids = np.full(self.padded_n, -1, dtype=np.int32)
        vocab: dict[str, int] = {}
        for i in range(self.num_nodes):
            v = self.nodes[i].lookup_attribute(attr)
            if v is not None:
                ids[i] = vocab.setdefault(str(v), len(vocab))
        self.attr_cache[attr] = (ids, vocab)
        return ids, vocab


def flatten_cluster(snap, nodes=None) -> ClusterTensors:
    """Build ClusterTensors from a StateSnapshot (or an explicit node list).

    Usage is summed from each node's non-terminal allocations — the same
    quantity ``BinPackIterator`` derives per node via ProposedAllocs
    (scheduler/context.go:120-157), minus in-flight plan deltas which the
    scheduler overlays separately (see score.py's ``used`` argument).
    """
    if nodes is None:
        nodes = sorted(snap.nodes(), key=lambda n: n.id)
    else:
        nodes = sorted(nodes, key=lambda n: n.id)
    n = len(nodes)
    pn = node_bucket(max(n, 1))

    capacity = np.zeros((pn, NUM_DIMS), dtype=np.float32)
    used = np.zeros((pn, NUM_DIMS), dtype=np.float32)
    ready = np.zeros(pn, dtype=bool)
    dc_ids = np.zeros(pn, dtype=np.int32)
    class_ids = np.zeros(pn, dtype=np.int32)
    dc_vocab: dict[str, int] = {}
    class_vocab: dict[str, int] = {}
    class_rep: list[int] = []
    node_row: dict[str, int] = {}

    for i, node in enumerate(nodes):
        node_row[node.id] = i
        capacity[i] = node_comparable_capacity(node).to_vector()
        ready[i] = node.ready()
        dc_ids[i] = dc_vocab.setdefault(node.datacenter, len(dc_vocab))
        if not node.computed_class:
            node.compute_class()
        cid = class_vocab.setdefault(node.computed_class, len(class_vocab))
        if cid == len(class_rep):
            class_rep.append(i)
        class_ids[i] = cid
        if snap is not None:
            for a in snap.allocs_by_node(node.id):
                if not a.terminal_status():
                    used[i] += a.comparable_resources().to_vector()

    return ClusterTensors(
        node_ids=[nd.id for nd in nodes],
        index=getattr(snap, "index", 0) if snap is not None else 0,
        num_nodes=n,
        capacity=capacity,
        used=used,
        ready=ready,
        dc_ids=dc_ids,
        class_ids=class_ids,
        dc_vocab=dc_vocab,
        class_vocab=class_vocab,
        class_rep=class_rep,
        node_row=node_row,
        nodes=list(nodes),
    )


@dataclass
class GroupAsk:
    """One task group's flattened placement request — everything the device
    kernel needs, with strings already resolved to masks/ids."""

    job_id: str
    tg_name: str
    count: int  # placements wanted in this pass
    desired_total: int  # tg.count — anti-affinity denominator (rank.go:589)
    ask: np.ndarray  # f32[D]
    eligible: np.ndarray  # bool[N] constraint ∧ dc ∧ ready mask
    job_counts: np.ndarray  # i32[N] existing allocs of this job per node
    penalty_nodes: np.ndarray  # bool[N] rescheduling penalty (rank.go:606)
    affinity_scores: np.ndarray  # f32[N] pre-normalized [-1, 1]
    has_affinities: bool
    distinct_hosts: bool
    # spread: node → value-id of the (single merged) spread attribute;
    # -1 where the node has no value. Multiple spread blocks are summed
    # host-side into one per-node boost-rate pair (see spread_* below).
    spread_value_ids: np.ndarray  # i32[N]
    spread_desired: np.ndarray  # f32[V] desired count per value id
    spread_initial_counts: np.ndarray  # f32[V] existing usage per value id
    spread_weight: float
    has_spreads: bool
    num_spread_values: int
    # Per-node cap on additional placements of this group, from device
    # instance accounting (scheduler/device.py feasible_sets); None when
    # the group asks for no devices (kernel substitutes +inf).
    slot_caps: np.ndarray | None = None
    # AllocMetric filter accounting (structs.go AllocMetric): populated by
    # _eligibility_for_group, surfaced on placement failures.
    filter_stats: dict = field(default_factory=dict)


def _eligibility_for_group(
    ct: ClusterTensors, nodes_sorted, job: Job, tg: TaskGroup, snap=None
) -> tuple[np.ndarray, dict]:
    """ready ∧ datacenter ∧ hard constraints, with per-class memoization.

    Constraints whose targets resolve per-node (``unique.`` attrs, node id/
    name) force per-node evaluation — the "escaped computed class" path
    (scheduler/feasible.go:1029-1153).

    Also returns filter accounting for AllocMetric explainability
    (structs.go AllocMetric.FilterNode: NodesFiltered, ConstraintFiltered
    per reason, ClassFiltered per computed class)."""
    pn = ct.padded_n
    eligible = ct.ready.copy()

    dc_ok = np.zeros(pn, dtype=bool)
    for dc in job.datacenters:
        cid = ct.dc_vocab.get(dc)
        if cid is not None:
            dc_ok |= ct.dc_ids == cid
    eligible &= dc_ok
    candidates = int(eligible[: ct.num_nodes].sum())

    constraints = job.constraints_for_group(tg)
    # implicit driver constraints: every task's driver must be healthy
    drivers = {t.driver for t in tg.tasks}

    escaped = any(
        "unique." in c.l_target or "unique." in c.r_target for c in constraints
    )
    # volume feasibility is per-node: host volumes are node config and CSI
    # claims are counted cluster state (HostVolumeChecker/CSIVolumeChecker,
    # feasible.go:132-339)
    volumes = getattr(tg, "volumes", None) or {}
    if volumes:
        from ..scheduler.feasible import (  # deferred: circular at init
            FILTER_HOST_VOLUMES,
            check_csi_volumes,
            check_host_volumes,
        )

        escaped = True
    if escaped or not constraints and not drivers:
        rows = range(ct.num_nodes)
        per_class = False
    else:
        rows = ct.class_rep
        per_class = True

    ok_rows = np.ones(len(ct.class_rep) if per_class else ct.num_nodes, dtype=bool)
    reason_rows: dict[str, list[int]] = {}
    for j, i in enumerate(rows):
        node = nodes_sorted[i]
        for d in drivers:
            if not node.drivers.get(d, False):
                ok_rows[j] = False
                reason_rows.setdefault(f"missing drivers: {d}", []).append(j)
                break
        if ok_rows[j] and volumes:
            if not check_host_volumes(node, volumes):
                ok_rows[j] = False
                reason_rows.setdefault(FILTER_HOST_VOLUMES, []).append(j)
            else:
                csi_ok, reason = check_csi_volumes(snap, node, volumes)
                if not csi_ok:
                    ok_rows[j] = False
                    reason_rows.setdefault(reason, []).append(j)
        if ok_rows[j]:
            for c in constraints:
                if c.operand in ("distinct_hosts", "distinct_property"):
                    continue  # handled dynamically / via property sets
                if not _check_constraint(node, c):
                    ok_rows[j] = False
                    reason_rows.setdefault(
                        f"{c.l_target} {c.operand} {c.r_target}".strip(), []
                    ).append(j)
                    break
    stats: dict = {"constraint_filtered": {}, "class_filtered": {}}
    if per_class:
        class_ok = ok_rows
        # a filtered class filters all its member nodes (feasible.go:1029)
        class_sizes = np.bincount(
            ct.class_ids[: ct.num_nodes][eligible[: ct.num_nodes]],
            minlength=len(ct.class_rep),
        )
        class_names = {cid: name for name, cid in ct.class_vocab.items()}
        for reason, js in reason_rows.items():
            n = int(sum(class_sizes[j] for j in js))
            if n:
                stats["constraint_filtered"][reason] = n
        for j, ok in enumerate(class_ok):
            if not ok and class_sizes[j]:
                stats["class_filtered"][class_names.get(j, str(j))] = int(
                    class_sizes[j]
                )
        eligible[: ct.num_nodes] &= class_ok[ct.class_ids[: ct.num_nodes]]
    else:
        for reason, js in reason_rows.items():
            n = sum(1 for j in js if eligible[j])
            if n:
                stats["constraint_filtered"][reason] = n
        eligible[: ct.num_nodes] &= ok_rows
    stats["nodes_filtered"] = candidates - int(eligible[: ct.num_nodes].sum())
    return eligible, stats


def _affinity_scores(ct, nodes_sorted, job: Job, tg: TaskGroup) -> tuple[np.ndarray, bool]:
    """Weight-normalized affinity score per node, in [-1, 1]
    (scheduler/rank.go:650-737: Σ w_i·match_i / Σ|w_i|).

    Class-stable affinities (no ``unique.`` target) are evaluated once per
    computed node class and broadcast — O(classes), not O(nodes), the same
    memoization bet the feasibility path makes (feasible.go:1029)."""
    affs = job.affinities_for_group(tg)
    scores = np.zeros(ct.padded_n, dtype=np.float32)
    if not affs:
        return scores, False
    from ..structs import Constraint

    n = ct.num_nodes
    total = float(sum(abs(a.weight) for a in affs)) or 1.0
    for a in affs:
        c = Constraint(l_target=a.l_target, r_target=a.r_target, operand=a.operand)
        if "unique." in c.l_target or "unique." in c.r_target:
            match = np.fromiter(
                (_check_constraint(nodes_sorted[i], c) for i in range(n)),
                dtype=bool,
                count=n,
            )
        else:
            rep_ok = np.fromiter(
                (_check_constraint(nodes_sorted[r], c) for r in ct.class_rep),
                dtype=bool,
                count=len(ct.class_rep),
            )
            match = rep_ok[ct.class_ids[:n]]
        scores[:n] += np.where(match, np.float32(a.weight), np.float32(0.0))
    return scores / total, True


def _spread_tensors(ct, nodes_sorted, job: Job, tg: TaskGroup, snap, total_desired):
    """Merge the group's spread blocks into per-node value ids + per-value
    desired counts (scheduler/spread.go:110-257). With explicit targets the
    desired count is percent×total; without, even spread over seen values."""
    spreads = job.spreads_for_group(tg)
    pn = ct.padded_n
    if not spreads:
        return (
            np.full(pn, -1, dtype=np.int32),
            np.zeros(1, dtype=np.float32),
            np.zeros(1, dtype=np.float32),
            0.0,
            False,
            1,
        )
    # Round 1: support one spread attribute (merged weight); multi-block
    # spreads are scored against the first block. TODO(round2): stack
    # value-id planes per block and sum boosts in-kernel.
    sp = spreads[0]
    node_vals, value_ids = ct.attr_column(sp.attribute)
    nv = max(len(value_ids), 1)
    desired = np.zeros(nv, dtype=np.float32)
    if sp.targets:
        for t in sp.targets:
            vid = value_ids.get(t.value)
            if vid is not None:
                desired[vid] = np.ceil(t.percent / 100.0 * total_desired)
    else:
        desired[:] = np.ceil(total_desired / nv)
    counts = np.zeros(nv, dtype=np.float32)
    if snap is not None:
        for a in snap.allocs_by_job(job.namespace, job.id):
            if a.terminal_status() or a.task_group != tg.name:
                continue
            row = ct.node_row.get(a.node_id)
            if row is not None and node_vals[row] >= 0:
                counts[node_vals[row]] += 1
    weight = float(sp.weight) / 100.0
    return node_vals, desired, counts, weight, True, nv


def _device_slot_caps(
    ct, nodes_sorted, snap, tg, count, eligible, filter_stats
):
    """Device feasibility → dense per-node slot caps + device affinity.

    Returns (slot_caps f32[N] | None, dev_aff f32[N], has_dev_aff bool).
    Nodes that can't satisfy even one set of the group's device asks are
    filtered hard (DeviceChecker, feasible.go:1173); the cap feeds the
    in-batch accounting in the placement scan.
    """
    from ..scheduler.device import (
        collect_in_use,
        feasible_sets,
        group_device_asks,
        node_device_affinity,
    )

    if not group_device_asks(tg):
        return None, np.zeros(ct.padded_n, dtype=np.float32), False

    slot_caps = np.zeros(ct.padded_n, dtype=np.float32)
    dev_aff = np.zeros(ct.padded_n, dtype=np.float32)
    has_dev_aff = False
    filtered = 0
    for i in range(ct.num_nodes):
        if not eligible[i]:
            continue
        node = nodes_sorted[i]
        in_use = (
            collect_in_use(snap.allocs_by_node(node.id))
            if snap is not None
            else {}
        )
        sets = feasible_sets(node, in_use, tg, count)
        slot_caps[i] = sets
        if sets == 0 and feasible_sets(node, {}, tg, 1) == 0:
            # no matching device *hardware* at all — hard filter
            # (DeviceChecker, feasible.go:1173). Nodes whose devices are
            # merely held by other allocs keep eligible=True with
            # slot_caps=0: the scan can't place there, but the preemption
            # fallback still may (PreemptForDevice's candidate set).
            eligible[i] = False
            filtered += 1
        elif sets > 0:
            s, has = node_device_affinity(node, tg)
            if has:
                dev_aff[i] = s
                has_dev_aff = True
    if filtered:
        cf = filter_stats.setdefault("constraint_filtered", {})
        cf["missing devices"] = cf.get("missing devices", 0) + filtered
        filter_stats["nodes_filtered"] = (
            filter_stats.get("nodes_filtered", 0) + filtered
        )
    return slot_caps, dev_aff, has_dev_aff


def flatten_group_ask(
    ct: ClusterTensors,
    snap,
    job: Job,
    tg: TaskGroup,
    count: int,
    *,
    nodes_sorted=None,
    penalty_node_ids: set[str] | None = None,
) -> GroupAsk:
    """Flatten one (job, task group, count) placement request."""
    if nodes_sorted is None:
        # row-ordered node objects from the tensors themselves; falling
        # back to a sort only for hand-built ClusterTensors without them
        nodes_sorted = ct.nodes or (
            sorted(snap.nodes(), key=lambda n: n.id) if snap is not None else []
        )
    ask_res = tg.combined_resources()
    ask = np.array(
        [
            ask_res.cpu,
            ask_res.memory_mb,
            ask_res.disk_mb,
            ask_res.bandwidth_mbits(),
        ],
        dtype=np.float32,
    )

    eligible, filter_stats = _eligibility_for_group(
        ct, nodes_sorted, job, tg, snap
    )

    job_counts = np.zeros(ct.padded_n, dtype=np.int32)
    if snap is not None:
        for a in snap.allocs_by_job(job.namespace, job.id):
            if a.terminal_status():
                continue
            row = ct.node_row.get(a.node_id)
            if row is not None:
                job_counts[row] += 1

    penalty = np.zeros(ct.padded_n, dtype=bool)
    for nid in penalty_node_ids or ():
        row = ct.node_row.get(nid)
        if row is not None:
            penalty[row] = True

    aff, has_aff = _affinity_scores(ct, nodes_sorted, job, tg)
    slot_caps, dev_aff, has_dev_aff = _device_slot_caps(
        ct, nodes_sorted, snap, tg, count, eligible, filter_stats
    )
    if has_dev_aff:
        # matched device affinity folds into the node-affinity component
        # (rank.go:388-434 adds the assignment's affinity sum to the score)
        aff = (aff + dev_aff) / (2.0 if has_aff else 1.0)
        has_aff = True
    sp_vals, sp_desired, sp_counts, sp_w, has_sp, nv = _spread_tensors(
        ct, nodes_sorted, job, tg, snap, tg.count
    )

    distinct = any(
        c.operand == "distinct_hosts" for c in job.constraints_for_group(tg)
    )

    return GroupAsk(
        job_id=job.id,
        tg_name=tg.name,
        count=count,
        desired_total=max(tg.count, 1),
        ask=ask,
        eligible=eligible,
        job_counts=job_counts,
        penalty_nodes=penalty,
        affinity_scores=aff,
        has_affinities=has_aff,
        distinct_hosts=distinct,
        spread_value_ids=sp_vals,
        spread_desired=sp_desired,
        spread_initial_counts=sp_counts,
        spread_weight=sp_w,
        has_spreads=has_sp,
        num_spread_values=nv,
        slot_caps=slot_caps,
        filter_stats=filter_stats,
    )
