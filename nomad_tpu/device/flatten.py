"""Flattening layer: snapshot state → dense device tensors.

This is the layer SURVEY.md §7 step 1 demands: `NodeResources`/`Resources`
→ dense ``float32[nodes, dims]`` arrays with a stable node-index mapping
and masks for datacenter/class/eligibility. The reference walks Go structs
per node per placement (scheduler/rank.go:193-527); we pay the struct walk
once per snapshot refresh and let every placement reuse the arrays.

Split of labor (mirrors the reference's class-memoization bet,
scheduler/feasible.go:1029-1153: classes ≪ nodes):

- **Host (here):** resolve string/regex/version constraints once per
  *computed node class* into per-class bits, then broadcast to per-node
  masks with one gather. Constraints touching ``unique.`` attributes are
  evaluated per node ("escaped class" in the reference's terms).
- **Device (score.py):** resource fit, scoring, argmax, and the greedy
  placement scan over dense arrays only.

Shapes are padded to buckets (powers of two) so XLA compiles a handful of
program shapes regardless of node churn.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..structs import NUM_DIMS, Job, TaskGroup
from ..structs.resources import node_comparable_capacity


def _check_constraint(node, c):
    # deferred import: scheduler package imports device at init time, so a
    # top-level import here would be circular
    from ..scheduler.feasible import check_constraint

    return check_constraint(node, c)

# Padding buckets for the node axis: next power of two, min 8. Keeps the
# number of distinct compiled shapes logarithmic in cluster size.
_MIN_BUCKET = 8


def region_key(node) -> tuple[str, str]:
    """The region a node belongs to: (datacenter, device_class). Rows are
    laid out region-major so a region's rows are contiguous and — with a
    mesh active — land on as few node-axis shards as possible, keeping
    per-shard feasibility prefilters local. The key is pure node identity
    (no usage state), so it is stable across incremental refreshes; only
    a full reflatten may re-sort."""
    return (node.datacenter, getattr(node, "device_class", "") or "")


def _region_name(key: tuple[str, str]) -> str:
    return f"{key[0]}/{key[1]}" if key[1] else key[0]


def node_bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


@dataclass
class ClusterTensors:
    """Dense snapshot of schedulable cluster state.

    ``node_ids[i]`` ↔ row i of every array; rows ≥ ``num_nodes`` are
    padding (``ready`` False ⇒ never selected).
    """

    node_ids: list[str]
    index: int  # state index this was built at (raft watermark analog)
    num_nodes: int
    capacity: np.ndarray  # f32[N, D] reserved-adjusted capacity
    used: np.ndarray  # f32[N, D] non-terminal alloc usage
    ready: np.ndarray  # bool[N]
    dc_ids: np.ndarray  # i32[N]
    class_ids: np.ndarray  # i32[N]
    dc_vocab: dict[str, int]
    class_vocab: dict[str, int]
    # per-class representative node index (for host-side class evaluation)
    class_rep: list[int]
    node_row: dict[str, int] = field(default_factory=dict)
    # heterogeneity axis: per-node accelerator class ids. Id 0 is always
    # the class-less "" so hand-built tensors (benchmarks, parity
    # corpora) and pre-heterogeneity snapshots behave identically without
    # declaring anything. None = never flattened with classes; the
    # device_class_column accessor synthesizes the all-classless column.
    device_class_ids: np.ndarray | None = None  # i32[N]
    device_class_vocab: dict[str, int] = field(
        default_factory=lambda: {"": 0}
    )
    # topology axis (gang scheduling): factored per-level coordinate id
    # columns. Id 0 is always the coordinate-less "" so hand-built
    # tensors and pre-topology snapshots behave identically; None =
    # never flattened with topology (topology_columns synthesizes the
    # all-zero columns).
    topo_rack_ids: np.ndarray | None = None  # i32[N]
    topo_pod_ids: np.ndarray | None = None  # i32[N]
    topo_ici_ids: np.ndarray | None = None  # i32[N]
    topo_rack_vocab: dict[str, int] = field(default_factory=lambda: {"": 0})
    topo_pod_vocab: dict[str, int] = field(default_factory=lambda: {"": 0})
    topo_ici_vocab: dict[str, int] = field(default_factory=lambda: {"": 0})
    # row-ordered Node objects (nodes[i] ↔ row i); kept in sync by the
    # flattener / DeviceStateCache so host-side per-class constraint
    # evaluation never re-sorts the cluster
    nodes: list = field(default_factory=list)
    # attribute → (value_ids i32[N], vocab dict) — lazily built columns for
    # spread/property attributes, owned by the cache generation
    attr_cache: dict = field(default_factory=dict)
    # datacenter → ready-node count, filled lazily IN PLACE by the
    # scheduler (AllocMetric.nodes_available). The dict OBJECT is shared
    # by reference across the per-call used-copy wrappers (replace()
    # copies field references), so one computation serves every eval of
    # a cache generation; refresh/rebuild construct a fresh empty dict,
    # which is exactly the staleness boundary.
    dc_ready_counts: dict = field(default_factory=dict)
    # region axis (mesh sharding): per-row region ids, nondecreasing by
    # construction (rows are sorted region-major), -1 on padding rows.
    # None = hand-built tensors that never declared regions; treat as one
    # region. region_vocab maps "dc[/device_class]" → id.
    region_ids: np.ndarray | None = None  # i32[N]
    region_vocab: dict[str, int] = field(default_factory=dict)
    # device-resident sharded capacity for this generation (filled by
    # DeviceStateCache when a mesh is active; None = shard on the fly).
    # Shared by reference across the per-call used-copy wrappers — the
    # buffer is immutable on device and regenerated per cache refresh.
    device_capacity: object = None
    # incremental-rescoring seam (NOMAD_TPU_INCREMENTAL): the owning
    # DeviceStateCache, attached by ``tensors()`` only when the
    # incremental path is on. Kernels route their per-pass ``used``
    # upload through ``cache.score_view`` when present (device/score.py
    # used_device); None ⇒ the from-scratch ``shard_put`` path, byte
    # for byte the pre-incremental upload. Mutating the cached score
    # tensors anywhere but the DeviceStateCache refresh API is banned
    # (lint rule NTA019).
    score_cache: object = None
    # row-layout generation: bumped ONLY by a full reflatten (which may
    # re-sort rows); preserved across incremental refreshes and the
    # per-call used-copy. Consumers holding row-indexed overlays (the
    # worker's pipelined usage epoch) compare this to decide whether
    # their row indices still align. 0 = transient build, never matches.
    layout_gen: int = 0

    @property
    def padded_n(self) -> int:
        return self.capacity.shape[0]

    def row_of(self, node_id: str) -> int:
        return self.node_row[node_id]

    def attr_column(self, attr: str) -> tuple[np.ndarray, dict[str, int]]:
        """Per-node value ids for one attribute (-1 = absent), cached.
        The vocab grows append-only so cached GroupAsk ids stay valid."""
        cached = self.attr_cache.get(attr)
        if cached is not None:
            return cached
        ids = np.full(self.padded_n, -1, dtype=np.int32)
        vocab: dict[str, int] = {}
        for i in range(self.num_nodes):
            v = self.nodes[i].lookup_attribute(attr)
            if v is not None:
                ids[i] = vocab.setdefault(str(v), len(vocab))
        self.attr_cache[attr] = (ids, vocab)
        return ids, vocab

    def device_class_column(self) -> tuple[np.ndarray, dict[str, int]]:
        """Per-node device-class ids + vocab (id 0 = class-less "")."""
        if self.device_class_ids is None:
            self.device_class_ids = np.zeros(self.padded_n, dtype=np.int32)
        return self.device_class_ids, self.device_class_vocab

    @property
    def has_device_classes(self) -> bool:
        """True when any node declares a non-empty device_class."""
        return len(self.device_class_vocab) > 1

    def topology_columns(
        self,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Per-node (rack_ids, pod_ids, ici_ids) i32 columns (id 0 = no
        coordinate). The factored per-level form of the topology
        distance matrix: two rows are rack-adjacent iff their rack ids
        match, pod-adjacent iff their pod ids match, ici-adjacent iff
        their normalized ICI-hop-distance slice ids match — N
        three-column entries instead of an N×N hop matrix."""
        if self.topo_rack_ids is None:
            self.topo_rack_ids = np.zeros(self.padded_n, dtype=np.int32)
        if self.topo_pod_ids is None:
            self.topo_pod_ids = np.zeros(self.padded_n, dtype=np.int32)
        if self.topo_ici_ids is None:
            self.topo_ici_ids = np.zeros(self.padded_n, dtype=np.int32)
        return self.topo_rack_ids, self.topo_pod_ids, self.topo_ici_ids

    @property
    def has_topology(self) -> bool:
        """True when any node declares rack/pod/ici coordinates."""
        return (
            len(self.topo_rack_vocab) > 1
            or len(self.topo_pod_vocab) > 1
            or len(self.topo_ici_vocab) > 1
        )


def flatten_cluster(snap, nodes=None) -> ClusterTensors:
    """Build ClusterTensors from a StateSnapshot (or an explicit node list).

    Usage is summed from each node's non-terminal allocations — the same
    quantity ``BinPackIterator`` derives per node via ProposedAllocs
    (scheduler/context.go:120-157), minus in-flight plan deltas which the
    scheduler overlays separately (see score.py's ``used`` argument).
    """
    # Region-major row order — UNCONDITIONAL, so the single-device and
    # sharded paths see the same rows in the same order and argmax
    # tie-breaks agree bit-for-bit. Within a region, by node id (the
    # pre-region order); single-dc classless clusters keep the exact
    # pre-region layout.
    if nodes is None:
        nodes = snap.nodes()
    nodes = sorted(nodes, key=lambda nd: (*region_key(nd), nd.id))
    n = len(nodes)
    pn = node_bucket(max(n, 1))

    capacity = np.zeros((pn, NUM_DIMS), dtype=np.float32)
    used = np.zeros((pn, NUM_DIMS), dtype=np.float32)
    ready = np.zeros(pn, dtype=bool)
    dc_ids = np.zeros(pn, dtype=np.int32)
    class_ids = np.zeros(pn, dtype=np.int32)
    dc_vocab: dict[str, int] = {}
    class_vocab: dict[str, int] = {}
    class_rep: list[int] = []
    node_row: dict[str, int] = {}
    device_class_ids = np.zeros(pn, dtype=np.int32)
    device_class_vocab: dict[str, int] = {"": 0}
    topo_rack_ids = np.zeros(pn, dtype=np.int32)
    topo_pod_ids = np.zeros(pn, dtype=np.int32)
    topo_ici_ids = np.zeros(pn, dtype=np.int32)
    topo_rack_vocab: dict[str, int] = {"": 0}
    topo_pod_vocab: dict[str, int] = {"": 0}
    topo_ici_vocab: dict[str, int] = {"": 0}
    region_ids = np.full(pn, -1, dtype=np.int32)
    region_vocab: dict[str, int] = {}

    for i, node in enumerate(nodes):
        node_row[node.id] = i
        capacity[i] = node_comparable_capacity(node).to_vector()
        ready[i] = node.ready()
        dc_ids[i] = dc_vocab.setdefault(node.datacenter, len(dc_vocab))
        region_ids[i] = region_vocab.setdefault(
            _region_name(region_key(node)), len(region_vocab)
        )
        device_class_ids[i] = device_class_vocab.setdefault(
            getattr(node, "device_class", ""), len(device_class_vocab)
        )
        topo = getattr(node, "topology", None) or {}
        topo_rack_ids[i] = topo_rack_vocab.setdefault(
            topo.get("rack", ""), len(topo_rack_vocab)
        )
        topo_pod_ids[i] = topo_pod_vocab.setdefault(
            topo.get("pod", ""), len(topo_pod_vocab)
        )
        topo_ici_ids[i] = topo_ici_vocab.setdefault(
            topo.get("ici", ""), len(topo_ici_vocab)
        )
        if not node.computed_class:
            node.compute_class()
        cid = class_vocab.setdefault(node.computed_class, len(class_vocab))
        if cid == len(class_rep):
            class_rep.append(i)
        class_ids[i] = cid
        if snap is not None:
            for a in snap.allocs_by_node(node.id):
                if not a.terminal_status():
                    used[i] += a.comparable_resources().to_vector()

    return ClusterTensors(
        node_ids=[nd.id for nd in nodes],
        index=getattr(snap, "index", 0) if snap is not None else 0,
        num_nodes=n,
        capacity=capacity,
        used=used,
        ready=ready,
        dc_ids=dc_ids,
        class_ids=class_ids,
        dc_vocab=dc_vocab,
        class_vocab=class_vocab,
        class_rep=class_rep,
        node_row=node_row,
        nodes=list(nodes),
        device_class_ids=device_class_ids,
        device_class_vocab=device_class_vocab,
        topo_rack_ids=topo_rack_ids,
        topo_pod_ids=topo_pod_ids,
        topo_ici_ids=topo_ici_ids,
        topo_rack_vocab=topo_rack_vocab,
        topo_pod_vocab=topo_pod_vocab,
        topo_ici_vocab=topo_ici_vocab,
        region_ids=region_ids,
        region_vocab=region_vocab,
    )


@dataclass
class ValueBlocks:
    """Stacked per-attribute-value accounting blocks for one group ask.

    Spread blocks (scored — scheduler/spread.go) and distinct_property
    blocks (capped — scheduler/feasible.go:604) share the same shape: a
    per-node value-id column plus per-value state the kernel carries
    through its placement scan. ``kinds[b]`` selects the semantics
    (score.py BLOCK_* constants)."""

    value_ids: np.ndarray  # i32[B, N]  (−1 = node has no value)
    counts0: np.ndarray  # f32[B, V] initial combined-use counts
    desired: np.ndarray  # f32[B, V] target-mode desired; −1 = untargeted
    caps: np.ndarray  # f32[B, V] distinct_property allowed-count; +inf else
    weights: np.ndarray  # f32[B] target-mode relative weight (w / Σw)
    kinds: np.ndarray  # i32[B] BLOCK_TARGET_SPREAD/EVEN_SPREAD/DISTINCT_CAP

    @property
    def num_blocks(self) -> int:
        return self.value_ids.shape[0]

    @property
    def num_values(self) -> int:
        return self.counts0.shape[1]

    @property
    def has_spreads(self) -> bool:
        from .score import BLOCK_DISTINCT_CAP

        return bool((self.kinds != BLOCK_DISTINCT_CAP).any())


def pad_value_blocks(blocks: list, pn: int) -> dict:
    """Stack per-ask ValueBlocks (or None) into the padded [G, B, N] /
    [G, B, V] kernel tensors, bucketing B and V to powers of two."""
    from .score import BLOCK_INACTIVE

    def bucket(n: int) -> int:
        b = 1
        while b < n:
            b <<= 1
        return b

    max_b = bucket(max([b.num_blocks for b in blocks if b is not None] or [1]))
    max_v = bucket(max([b.num_values for b in blocks if b is not None] or [1]))
    g = len(blocks)
    value_ids = np.full((g, max_b, pn), -1, dtype=np.int32)
    counts0 = np.zeros((g, max_b, max_v), dtype=np.float32)
    desired = np.full((g, max_b, max_v), -1.0, dtype=np.float32)
    caps = np.full((g, max_b, max_v), np.inf, dtype=np.float32)
    weights = np.zeros((g, max_b), dtype=np.float32)
    kinds = np.full((g, max_b), BLOCK_INACTIVE, dtype=np.int32)
    for gi, b in enumerate(blocks):
        if b is None:
            continue
        nb, nv = b.num_blocks, b.num_values
        value_ids[gi, :nb, : b.value_ids.shape[1]] = b.value_ids
        counts0[gi, :nb, :nv] = b.counts0
        desired[gi, :nb, :nv] = b.desired
        caps[gi, :nb, :nv] = b.caps
        weights[gi, :nb] = b.weights
        kinds[gi, :nb] = b.kinds
    return dict(
        block_value_ids=value_ids,
        block_counts0=counts0,
        block_desired=desired,
        block_caps=caps,
        block_weights=weights,
        block_kinds=kinds,
    )


@dataclass
class GroupAsk:
    """One task group's flattened placement request — everything the device
    kernel needs, with strings already resolved to masks/ids."""

    job_id: str
    tg_name: str
    count: int  # placements wanted in this pass
    desired_total: int  # tg.count — anti-affinity denominator (rank.go:589)
    ask: np.ndarray  # f32[D]
    eligible: np.ndarray  # bool[N] constraint ∧ dc ∧ ready mask
    job_counts: np.ndarray  # i32[N] existing allocs of this job per node
    penalty_nodes: np.ndarray  # bool[N] rescheduling penalty (rank.go:606)
    affinity_scores: np.ndarray  # f32[N] pre-normalized [-1, 1]
    has_affinities: bool
    distinct_hosts: bool
    # spread + distinct_property accounting blocks; None when the group
    # has neither (→ the closed-form top-k path)
    blocks: ValueBlocks | None = None
    # Per-node cap on additional placements of this group, from device
    # instance accounting (scheduler/device.py feasible_sets); None when
    # the group asks for no devices (kernel substitutes +inf).
    slot_caps: np.ndarray | None = None
    # AllocMetric filter accounting (structs.go AllocMetric): populated by
    # _eligibility_for_group, surfaced on placement failures.
    filter_stats: dict = field(default_factory=dict)
    # Heterogeneity: per-node throughput coefficient for THIS job (the
    # job's per-device-class map gathered through the fleet's class
    # column). None = class-less / throughput-agnostic — every kernel and
    # policy must treat None exactly as an all-ones vector, and the base
    # binpack/spread kernels never read it at all (bit-identity).
    throughputs: np.ndarray | None = None  # f32[N]
    has_throughputs: bool = False
    # Calibration profile key (obs/calibrate.py): the job-profile axis of
    # the ThroughputEstimator's (device_class × profile) matrix. Empty =
    # not calibratable; only the hetero kernel's learned mode reads it.
    profile: str = ""
    # Job priority (structs/job.py, 0-100). The CP dispatcher's joint
    # pass resolves contested nodes by tier before score (scheduler/
    # cp.py); the per-group kernels never read it.
    priority: int = 50
    # Gang scheduling (structs/job.py gang stanza): True when this group
    # is a member of its job's all-or-nothing gang. The signed topology
    # weights price co-location (+, colocate) or anti-location (−,
    # spread) against gang-mate assignments at each level; 0.0 = no term
    # at that level. Only the cp-gang dispatcher reads any of these —
    # the base kernels stay bit-identical.
    gang_member: bool = False
    gang_weight_rack: float = 0.0
    gang_weight_pod: float = 0.0
    gang_weight_ici: float = 0.0

    @property
    def has_spreads(self) -> bool:
        return self.blocks is not None and self.blocks.has_spreads


def job_throughput_vector(
    ct: ClusterTensors, job: Job
) -> tuple[np.ndarray | None, bool]:
    """Gather the job's per-device-class throughput coefficients into a
    per-node f32[N] vector (default 1.0 for unmapped classes). Returns
    (None, False) when the fleet is class-less or the job carries no
    coefficients — the signal every downstream consumer uses to stay on
    the pre-heterogeneity code path bit-for-bit."""
    throughputs = getattr(job, "throughputs", None)
    if not throughputs or not ct.has_device_classes:
        return None, False
    ids, vocab = ct.device_class_column()
    per_class = np.ones(len(vocab), dtype=np.float32)
    for name, cid in vocab.items():
        if name:
            per_class[cid] = np.float32(throughputs.get(name, 1.0))
    vec = per_class[ids]
    if bool(np.all(vec == np.float32(1.0))):
        return None, False
    return vec, True


def job_profile_key(job) -> str:
    """Stable calibration-profile key for a job: an explicit
    ``calibration_profile`` wins; otherwise the declared throughput map
    itself (sorted, value-normalized) names the profile, so jobs with the
    same declared shape share telemetry cells. Empty = no profile —
    learned mode leaves the job on its declared/all-ones coefficients."""
    explicit = getattr(job, "calibration_profile", "") or ""
    if explicit:
        return str(explicit)
    throughputs = getattr(job, "throughputs", None) or {}
    if not throughputs:
        return ""
    return "tp:" + ",".join(
        f"{k}={float(v):g}" for k, v in sorted(throughputs.items())
    )


def _eligibility_for_group(
    ct: ClusterTensors, nodes_sorted, job: Job, tg: TaskGroup, snap=None
) -> tuple[np.ndarray, dict]:
    """ready ∧ datacenter ∧ hard constraints, with per-class memoization.

    Constraints whose targets resolve per-node (``unique.`` attrs, node id/
    name) force per-node evaluation — the "escaped computed class" path
    (scheduler/feasible.go:1029-1153).

    Also returns filter accounting for AllocMetric explainability
    (structs.go AllocMetric.FilterNode: NodesFiltered, ConstraintFiltered
    per reason, ClassFiltered per computed class)."""
    pn = ct.padded_n
    eligible = ct.ready.copy()

    dc_ok = np.zeros(pn, dtype=bool)
    for dc in job.datacenters:
        cid = ct.dc_vocab.get(dc)
        if cid is not None:
            dc_ok |= ct.dc_ids == cid
    eligible &= dc_ok
    candidates = int(eligible[: ct.num_nodes].sum())

    constraints = job.constraints_for_group(tg)
    # implicit driver constraints: every task's driver must be healthy
    drivers = {t.driver for t in tg.tasks}

    escaped = any(
        "unique." in c.l_target or "unique." in c.r_target for c in constraints
    )
    # volume feasibility is per-node: host volumes are node config and CSI
    # claims are counted cluster state (HostVolumeChecker/CSIVolumeChecker,
    # feasible.go:132-339)
    volumes = getattr(tg, "volumes", None) or {}
    if volumes:
        from ..scheduler.feasible import (  # deferred: circular at init
            FILTER_HOST_VOLUMES,
            check_csi_volumes,
            check_host_volumes,
        )

        escaped = True
    if not constraints and not drivers and not volumes:
        # nothing to check at all — skip the walk entirely. (Rare in real
        # jobs: tasks always carry a driver, which routes through the
        # cheap per-class branch below; this covers synthetic asks.)
        rows = ()
        per_class = False
    elif escaped:
        rows = range(ct.num_nodes)
        per_class = False
    else:
        rows = ct.class_rep
        per_class = True

    ok_rows = np.ones(len(ct.class_rep) if per_class else ct.num_nodes, dtype=bool)
    reason_rows: dict[str, list[int]] = {}
    for j, i in enumerate(rows):
        node = nodes_sorted[i]
        for d in drivers:
            if not node.drivers.get(d, False):
                ok_rows[j] = False
                reason_rows.setdefault(f"missing drivers: {d}", []).append(j)
                break
        if ok_rows[j] and volumes:
            if not check_host_volumes(node, volumes):
                ok_rows[j] = False
                reason_rows.setdefault(FILTER_HOST_VOLUMES, []).append(j)
            else:
                csi_ok, reason = check_csi_volumes(snap, node, volumes)
                if not csi_ok:
                    ok_rows[j] = False
                    reason_rows.setdefault(reason, []).append(j)
        if ok_rows[j]:
            for c in constraints:
                if c.operand in ("distinct_hosts", "distinct_property"):
                    continue  # handled dynamically / via property sets
                if not _check_constraint(node, c):
                    ok_rows[j] = False
                    reason_rows.setdefault(
                        f"{c.l_target} {c.operand} {c.r_target}".strip(), []
                    ).append(j)
                    break
    stats: dict = {"constraint_filtered": {}, "class_filtered": {}}
    if per_class:
        class_ok = ok_rows
        # a filtered class filters all its member nodes (feasible.go:1029)
        class_sizes = np.bincount(
            ct.class_ids[: ct.num_nodes][eligible[: ct.num_nodes]],
            minlength=len(ct.class_rep),
        )
        class_names = {cid: name for name, cid in ct.class_vocab.items()}
        for reason, js in reason_rows.items():
            n = int(sum(class_sizes[j] for j in js))
            if n:
                stats["constraint_filtered"][reason] = n
        for j, ok in enumerate(class_ok):
            if not ok and class_sizes[j]:
                stats["class_filtered"][class_names.get(j, str(j))] = int(
                    class_sizes[j]
                )
        eligible[: ct.num_nodes] &= class_ok[ct.class_ids[: ct.num_nodes]]
    else:
        for reason, js in reason_rows.items():
            n = sum(1 for j in js if eligible[j])
            if n:
                stats["constraint_filtered"][reason] = n
        eligible[: ct.num_nodes] &= ok_rows
    stats["nodes_filtered"] = candidates - int(eligible[: ct.num_nodes].sum())
    return eligible, stats


def _affinity_scores(ct, nodes_sorted, job: Job, tg: TaskGroup) -> tuple[np.ndarray, bool]:
    """Weight-normalized affinity score per node, in [-1, 1]
    (scheduler/rank.go:650-737: Σ w_i·match_i / Σ|w_i|).

    Class-stable affinities (no ``unique.`` target) are evaluated once per
    computed node class and broadcast — O(classes), not O(nodes), the same
    memoization bet the feasibility path makes (feasible.go:1029)."""
    affs = job.affinities_for_group(tg)
    scores = np.zeros(ct.padded_n, dtype=np.float32)
    if not affs:
        return scores, False
    from ..structs import Constraint

    n = ct.num_nodes
    total = float(sum(abs(a.weight) for a in affs)) or 1.0
    for a in affs:
        c = Constraint(l_target=a.l_target, r_target=a.r_target, operand=a.operand)
        if "unique." in c.l_target or "unique." in c.r_target:
            match = np.fromiter(
                (_check_constraint(nodes_sorted[i], c) for i in range(n)),
                dtype=bool,
                count=n,
            )
        else:
            rep_ok = np.fromiter(
                (_check_constraint(nodes_sorted[r], c) for r in ct.class_rep),
                dtype=bool,
                count=len(ct.class_rep),
            )
            match = rep_ok[ct.class_ids[:n]]
        scores[:n] += np.where(match, np.float32(a.weight), np.float32(0.0))
    return scores / total, True


IMPLICIT_SPREAD_TARGET = "*"  # scheduler/spread.go:10


def _combined_counts_vector(pset, vocab):
    """Flatten a PropertySet's combined-use map onto value ids. Values
    used by allocations but carried by no current node (e.g. only on a
    removed node) get *phantom* slots appended past the node vocab so
    even-spread min/max still sees them."""
    combined = pset.combined_use()
    extra = {v: n for v, n in combined.items() if v not in vocab}
    nv = len(vocab) + len(extra)
    counts = np.zeros(max(nv, 1), dtype=np.float32)
    ids = dict(vocab)
    for v, n in combined.items():
        if v in ids:
            counts[ids[v]] = n
        else:
            ids[v] = len(ids)
            counts[ids[v]] = n
    return counts, ids


def _value_blocks(
    ct, job: Job, tg: TaskGroup, snap, plan, total_desired, eligible, filter_stats
):
    """Build the group's stacked spread + distinct_property blocks.

    Spread (scheduler/spread.go:232-257 computeSpreadInfo): per block,
    desired[v] = percent/100 x tg.count for explicit targets; the
    remaining count goes to the implicit ``*`` target when explicit
    targets cover only part of the total; values with neither get -1
    (flat penalty). Block weight is weight/sum(weights) — relative across
    blocks, 1.0 for a single block (spread.go:155-161).

    distinct_property (feasible.go:604-707): job-level constraints count
    allocs of the whole job, task-group-level only this group's; nodes
    missing the property are hard-filtered here (UsedCount errors), and
    the per-value allowed-count cap is enforced dynamically in-kernel.
    """
    from ..scheduler.propertyset import PropertySet
    from .score import (
        BLOCK_DISTINCT_CAP,
        BLOCK_EVEN_SPREAD,
        BLOCK_TARGET_SPREAD,
    )

    spreads = job.spreads_for_group(tg)
    distinct_job = [
        c for c in job.constraints if c.operand == "distinct_property"
    ]
    distinct_tg = [
        c
        for c in list(tg.constraints)
        + [c for t in tg.tasks for c in t.constraints]
        if c.operand == "distinct_property"
    ]
    if not spreads and not distinct_job and not distinct_tg:
        return None

    cols = []
    counts_l = []
    desired_l = []
    caps_l = []
    weights_l = []
    kinds_l = []

    def build_pset(attribute, scope, allowed=0):
        p = PropertySet(
            namespace=job.namespace,
            job_id=job.id,
            attribute=attribute,
            task_group=scope,
            allowed_count=allowed,
        )
        return p.populate(snap, plan) if snap is not None else p

    sum_weights = float(sum(sp.weight for sp in spreads)) or 1.0
    for sp in spreads:
        node_vals, vocab = ct.attr_column(sp.attribute)
        pset = build_pset(sp.attribute, tg.name)
        counts, ids = _combined_counts_vector(pset, vocab)
        nv = counts.shape[0]
        desired = np.full(nv, -1.0, dtype=np.float32)
        if sp.targets:
            explicit_sum = 0.0
            implicit = None
            for t in sp.targets:
                d = t.percent / 100.0 * total_desired
                explicit_sum += d
                if t.value == IMPLICIT_SPREAD_TARGET:
                    implicit = d
                    continue
                vid = ids.get(t.value)
                if vid is not None:
                    desired[vid] = d
            if 0 < explicit_sum < total_desired:
                implicit = total_desired - explicit_sum
            if implicit is not None:
                # untargeted values inherit the implicit target's desired
                # count (spread.go:145-149)
                explicit_vids = {
                    ids[t.value]
                    for t in sp.targets
                    if t.value in ids and t.value != IMPLICIT_SPREAD_TARGET
                }
                for vid in range(nv):
                    if vid not in explicit_vids:
                        desired[vid] = implicit
            kinds_l.append(BLOCK_TARGET_SPREAD)
        else:
            kinds_l.append(BLOCK_EVEN_SPREAD)
        cols.append(node_vals)
        counts_l.append(counts)
        desired_l.append(desired)
        caps_l.append(np.full(nv, np.inf, dtype=np.float32))
        weights_l.append(float(sp.weight) / sum_weights)

    for c, scope in [(c, "") for c in distinct_job] + [
        (c, tg.name) for c in distinct_tg
    ]:
        node_vals, vocab = ct.attr_column(c.l_target)
        try:
            allowed = int(c.r_target) if c.r_target else 1
        except ValueError:
            # unparsable allowed-count: constraint can never pass
            # (propertyset.go:88-95 errorBuilding)
            eligible[:] = False
            filter_stats.setdefault("constraint_filtered", {})[
                f"distinct_property: bad count {c.r_target!r}"
            ] = int(ct.num_nodes)
            continue
        pset = build_pset(c.l_target, scope, allowed)
        counts, ids = _combined_counts_vector(pset, vocab)
        nv = counts.shape[0]
        # nodes missing the property are infeasible (UsedCount error path)
        missing = (node_vals < 0) & eligible
        n_missing = int(missing[: ct.num_nodes].sum())
        if n_missing:
            eligible &= node_vals >= 0
            cf = filter_stats.setdefault("constraint_filtered", {})
            reason = f'missing property "{c.l_target}"'
            cf[reason] = cf.get(reason, 0) + n_missing
            filter_stats["nodes_filtered"] = (
                filter_stats.get("nodes_filtered", 0) + n_missing
            )
        cols.append(node_vals)
        counts_l.append(counts)
        desired_l.append(np.full(nv, -1.0, dtype=np.float32))
        caps_l.append(np.full(nv, float(allowed), dtype=np.float32))
        weights_l.append(0.0)
        kinds_l.append(BLOCK_DISTINCT_CAP)

    nb = len(cols)
    max_v = max(c.shape[0] for c in counts_l)
    value_ids = np.stack(cols)  # [B, N] — all share pn
    counts0 = np.zeros((nb, max_v), dtype=np.float32)
    desired = np.full((nb, max_v), -1.0, dtype=np.float32)
    caps = np.full((nb, max_v), np.inf, dtype=np.float32)
    for b in range(nb):
        nv = counts_l[b].shape[0]
        counts0[b, :nv] = counts_l[b]
        desired[b, :nv] = desired_l[b]
        caps[b, :nv] = caps_l[b]
    return ValueBlocks(
        value_ids=value_ids,
        counts0=counts0,
        desired=desired,
        caps=caps,
        weights=np.array(weights_l, dtype=np.float32),
        kinds=np.array(kinds_l, dtype=np.int32),
    )


def _device_slot_caps(
    ct, nodes_sorted, snap, tg, count, eligible, filter_stats
):
    """Device feasibility → dense per-node slot caps + device affinity.

    Returns (slot_caps f32[N] | None, dev_aff f32[N], has_dev_aff bool).
    Nodes that can't satisfy even one set of the group's device asks are
    filtered hard (DeviceChecker, feasible.go:1173); the cap feeds the
    in-batch accounting in the placement scan.
    """
    from ..scheduler.device import (
        collect_in_use,
        feasible_sets,
        group_device_asks,
        node_device_affinity,
    )

    if not group_device_asks(tg):
        return None, np.zeros(ct.padded_n, dtype=np.float32), False

    slot_caps = np.zeros(ct.padded_n, dtype=np.float32)
    dev_aff = np.zeros(ct.padded_n, dtype=np.float32)
    has_dev_aff = False
    filtered = 0
    for i in range(ct.num_nodes):
        if not eligible[i]:
            continue
        node = nodes_sorted[i]
        in_use = (
            collect_in_use(snap.allocs_by_node(node.id))
            if snap is not None
            else {}
        )
        sets = feasible_sets(node, in_use, tg, count)
        slot_caps[i] = sets
        if sets == 0 and feasible_sets(node, {}, tg, 1) == 0:
            # no matching device *hardware* at all — hard filter
            # (DeviceChecker, feasible.go:1173). Nodes whose devices are
            # merely held by other allocs keep eligible=True with
            # slot_caps=0: the scan can't place there, but the preemption
            # fallback still may (PreemptForDevice's candidate set).
            eligible[i] = False
            filtered += 1
        elif sets > 0:
            s, has = node_device_affinity(node, tg)
            if has:
                dev_aff[i] = s
                has_dev_aff = True
    if filtered:
        cf = filter_stats.setdefault("constraint_filtered", {})
        cf["missing devices"] = cf.get("missing devices", 0) + filtered
        filter_stats["nodes_filtered"] = (
            filter_stats.get("nodes_filtered", 0) + filtered
        )
    return slot_caps, dev_aff, has_dev_aff


def flatten_group_ask(
    ct: ClusterTensors,
    snap,
    job: Job,
    tg: TaskGroup,
    count: int,
    *,
    nodes_sorted=None,
    penalty_node_ids: set[str] | None = None,
    plan=None,
) -> GroupAsk:
    """Flatten one (job, task group, count) placement request. ``plan``
    (when given) feeds proposed/cleared allocations into the spread and
    distinct_property property sets (propertyset.go:163-208)."""
    if nodes_sorted is None:
        # row-ordered node objects from the tensors themselves; falling
        # back to a sort only for hand-built ClusterTensors without them
        nodes_sorted = ct.nodes or (
            sorted(snap.nodes(), key=lambda n: n.id) if snap is not None else []
        )
    ask_res = tg.combined_resources()
    ask = np.array(
        [
            ask_res.cpu,
            ask_res.memory_mb,
            ask_res.disk_mb,
            ask_res.bandwidth_mbits(),
        ],
        dtype=np.float32,
    )

    eligible, filter_stats = _eligibility_for_group(
        ct, nodes_sorted, job, tg, snap
    )

    job_counts = np.zeros(ct.padded_n, dtype=np.int32)
    if snap is not None:
        for a in snap.allocs_by_job(job.namespace, job.id):
            if a.terminal_status():
                continue
            row = ct.node_row.get(a.node_id)
            if row is not None:
                job_counts[row] += 1

    penalty = np.zeros(ct.padded_n, dtype=bool)
    for nid in penalty_node_ids or ():
        row = ct.node_row.get(nid)
        if row is not None:
            penalty[row] = True

    aff, has_aff = _affinity_scores(ct, nodes_sorted, job, tg)
    slot_caps, dev_aff, has_dev_aff = _device_slot_caps(
        ct, nodes_sorted, snap, tg, count, eligible, filter_stats
    )
    if has_dev_aff:
        # matched device affinity folds into the node-affinity component
        # (rank.go:388-434 adds the assignment's affinity sum to the score)
        aff = (aff + dev_aff) / (2.0 if has_aff else 1.0)
        has_aff = True
    blocks = _value_blocks(
        ct, job, tg, snap, plan, tg.count, eligible, filter_stats
    )

    distinct = any(
        c.operand == "distinct_hosts" for c in job.constraints_for_group(tg)
    )
    throughputs, has_tp = job_throughput_vector(ct, job)
    gang_member, gw_rack, gw_pod, gw_ici = gang_terms(job, tg.name)

    return GroupAsk(
        job_id=job.id,
        tg_name=tg.name,
        count=count,
        desired_total=max(tg.count, 1),
        ask=ask,
        eligible=eligible,
        job_counts=job_counts,
        penalty_nodes=penalty,
        affinity_scores=aff,
        has_affinities=has_aff,
        distinct_hosts=distinct,
        blocks=blocks,
        slot_caps=slot_caps,
        filter_stats=filter_stats,
        throughputs=throughputs,
        has_throughputs=has_tp,
        profile=job_profile_key(job),
        priority=job.priority,
        gang_member=gang_member,
        gang_weight_rack=gw_rack,
        gang_weight_pod=gw_pod,
        gang_weight_ici=gw_ici,
    )


def gang_terms(job, tg_name: str) -> tuple[bool, float, float, float]:
    """Resolve one group's gang membership + signed per-level topology
    weights from the job's gang stanza. Non-members (and gang-less jobs)
    get (False, 0.0, 0.0, 0.0) — the zero that keeps every pre-gang
    path untouched."""
    gang = getattr(job, "gang", None) or {}
    groups = gang.get("groups") or []
    if tg_name not in groups:
        return False, 0.0, 0.0, 0.0
    weights = {"rack": 0.0, "pod": 0.0, "ici": 0.0}
    colocate = gang.get("colocate") or {}
    if colocate.get("level") in weights:
        weights[colocate["level"]] = float(colocate.get("weight", 1.0))
    spread = gang.get("spread") or {}
    if spread.get("level") in weights:
        weights[spread["level"]] = -float(spread.get("weight", 1.0))
    return True, weights["rack"], weights["pod"], weights["ici"]
