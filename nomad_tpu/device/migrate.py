"""Bounded-budget migration planning on the dense (allocs × nodes) grid.

Sustained churn rots packing quality (the soak harness proves it);
production fleets recover it with live migration — Tesserae's placement
policies (PAPERS.md, arxiv 2508.04953) are explicitly migration-aware.
This module is the device half of that plane: given the dense score
matrix over CANDIDATE allocs (rows) and nodes (columns), select a
bounded set of moves maximizing score-delta gain minus a per-alloc
migration cost, with the same auction machinery as ``device/cp.py``:

  1. price the grid: ``gain[a, n] = score[a, n] − cur_score[a]
     − move_cost[a] − λ[n]`` (λ = per-node congestion price, risen by
     exact integer claim counts × a power-of-two step — bitwise
     portable, no transcendentals, no float reductions);
  2. a move is feasible only where the REPLACEMENT fits on top of the
     node's committed ``used`` — the source node is never credited back
     inside the pass (capacity conservation: during a two-phase move
     the old alloc still runs while the replacement starts, so the
     conservative "used only increases" model is exactly the mid-move
     capacity invariant the defrag controller enforces, law 16);
  3. every unmoved alloc claims its argmax positive-gain node; each
     contested node admits one claimant per round (highest priced gain,
     first index on ties — ``_cp_winners`` with a flat priority row);
  4. an exclusive integer prefix over node index caps committed moves
     at ``budget`` (a *dynamic* operand, so sweeping budgets never
     retraces); λ rises on contested nodes / decays on idle ones and
     the loop repeats until a round commits nothing or budget is spent.

Byte-parity discipline (device/cp.py's contract): the jitted kernel
(``lax.while_loop``) and the NumPy host oracle share one round's math
through the ``_mig_*``/``_cp_*`` helpers; every carried value is
f32/i32, every op elementwise/argmax/integer-sum/integer-cumsum, and
ties break on the first index in both argmax implementations. The
parity tests compare uint32 views across seeds and meshes.

Only ``server/defrag.py`` (the DefragController), ``scheduler/
migrate.py`` (batch assembly + the A/B harness), and the jaxlint
exercise fleet may call into this module — lint rule NTA021
(MigrationSeamDiscipline) polices the scheduler/server side.
"""

from __future__ import annotations

import functools

import numpy as np

from ..utils.backend import traced_jit
from .cp import _NEG_INF, ETA, _cp_winners

import jax
import jax.numpy as jnp


def _mig_feasible(capacity, used, sizes, eligible, cur, gain, arange_n):
    """bool[A, N]: replacement fits on top of committed ``used`` ∧
    eligible ∧ not the current node ∧ the move has strictly positive
    priced gain (a move that doesn't pay for itself is infeasible, not
    merely unattractive — it must never win by default)."""
    xp = np if isinstance(capacity, np.ndarray) else jnp
    proposed = used[None, :, :] + sizes[:, None, :]  # [A, N, D]
    fits = xp.all(proposed <= capacity[None, :, :], axis=-1)
    not_cur = cur[:, None] != arange_n[None, :]
    return fits & eligible & not_cur & (gain > xp.float32(0.0))


def _mig_gain(scores, cur_scores, move_cost, lam):
    """f32[A, N] priced move gain (all elementwise — bitwise portable)."""
    return scores - cur_scores[:, None] - move_cost[:, None] - lam[None, :]


def _mig_allow(has, claim, moves, budget):
    """bool[A] per-claimant budget admission: an exclusive integer
    prefix (cumsum) over node index ranks this round's winning nodes;
    only the first ``budget − moves`` of them commit. Integer cumsum is
    exact and associative — byte-portable across meshes."""
    xp = np if isinstance(claim, np.ndarray) else jnp
    has_i = has.astype(xp.int32)
    rank = xp.cumsum(has_i) - has_i  # exclusive prefix over nodes
    allow_node = (moves + rank) < budget
    return allow_node[claim]


@functools.partial(traced_jit, retrace_budget=16, static_argnames=("steps",))
def migrate_plan_kernel(
    capacity,  # f32[N, D]
    used0,  # f32[N, D] committed usage (sources NOT pre-freed)
    sizes,  # f32[A, D] per-alloc resource vectors
    cur,  # i32[A] current node row per candidate alloc
    eligible,  # bool[A, N] feasibility mask for the replacement
    scores,  # f32[A, N] dense score matrix (same finals binpack ranks by)
    cur_scores,  # f32[A] score at the alloc's current node
    move_cost,  # f32[A] per-alloc migration cost (priced against gain)
    budget,  # i32 max moves this plan (dynamic operand — no retraces)
    lam0,  # f32[N] initial prices (zeros; chaos perturbs)
    steps: int,
):
    """Auction rounds on device. Returns (dest i32[A] (-1 = stay),
    gains f32[A] (0 where staying), used f32[N, D] with every planned
    replacement committed, moves i32, rounds i32, lam f32[N])."""
    a, n = scores.shape
    arange_a = jnp.arange(a)
    arange_n = jnp.arange(n)
    prio = jnp.zeros(a, dtype=jnp.float32)  # flat: pure gain elections

    def cond(carry):
        it, progress = carry[0], carry[1]
        return (it < steps) & progress

    def body(carry):
        it, _, rounds, used, dest, gains, moves, lam = carry
        gain = _mig_gain(scores, cur_scores, move_cost, lam)
        feas = _mig_feasible(
            capacity, used, sizes, eligible, cur, gain, arange_n
        )
        active = dest < 0
        umask = jnp.where(feas, gain, _NEG_INF)
        claim, claimable, won, win, has, claims = _cp_winners(
            umask, feas, active, prio, arange_a, arange_n
        )
        allow = _mig_allow(has, claim, moves, budget)
        won = won & allow
        has_won = has & ((moves + jnp.cumsum(has.astype(jnp.int32))
                          - has.astype(jnp.int32)) < budget)
        # commit: ≤1 replacement per node per round, winners only up to
        # the budget — used only ever increases inside a pass, so every
        # planned move's replacement fits while its old alloc still runs
        delta = jnp.where(has_won[:, None], sizes[win], jnp.float32(0.0))
        used = used + delta
        dest = jnp.where(won, claim, dest)
        gains = jnp.where(won, gain[arange_a, claim], gains)
        moves = moves + won.astype(jnp.int32).sum()
        lam = lam + ETA * jnp.maximum(claims - 1, 0).astype(jnp.float32)
        lam = jnp.where(
            claims == 0, jnp.maximum(lam - ETA, jnp.float32(0.0)), lam
        )
        progress = jnp.any(claimable) & (moves < budget)
        rounds = rounds + jnp.any(claimable).astype(jnp.int32)
        return (it + 1, progress, rounds, used, dest, gains, moves, lam)

    carry = (
        jnp.int32(0),
        jnp.bool_(True),
        jnp.int32(0),
        used0,
        jnp.full(a, -1, dtype=jnp.int32),
        jnp.zeros(a, dtype=jnp.float32),
        jnp.int32(0),
        lam0,
    )
    out = jax.lax.while_loop(cond, body, carry)
    _, _, rounds, used, dest, gains, moves, lam = out
    return dest, gains, used, moves, rounds, lam


def oracle_migrate_plan(
    capacity: np.ndarray,
    used0: np.ndarray,
    sizes: np.ndarray,
    cur: np.ndarray,
    eligible: np.ndarray,
    scores: np.ndarray,
    cur_scores: np.ndarray,
    move_cost: np.ndarray,
    budget: int,
    lam0: np.ndarray,
    steps: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, int, int, np.ndarray]:
    """Pure-NumPy host oracle: the same round math as the device kernel,
    stepwise. Byte-identical output is the contract (tests/test_migrate.py
    pins uint32 views across seeds and meshes, like cp's oracle)."""
    a, n = scores.shape
    arange_a = np.arange(a)
    arange_n = np.arange(n)
    prio = np.zeros(a, dtype=np.float32)
    used = used0.astype(np.float32).copy()
    dest = np.full(a, -1, dtype=np.int32)
    gains = np.zeros(a, dtype=np.float32)
    lam = lam0.astype(np.float32).copy()
    budget = np.int32(budget)
    moves = np.int32(0)
    it = 0
    rounds = 0
    progress = True
    while it < steps and progress:
        gain = _mig_gain(scores, cur_scores, move_cost, lam)
        feas = _mig_feasible(
            capacity, used, sizes, eligible, cur, gain, arange_n
        )
        active = dest < 0
        umask = np.where(feas, gain, _NEG_INF)
        claim, claimable, won, win, has, claims = _cp_winners(
            umask, feas, active, prio, arange_a, arange_n
        )
        allow = _mig_allow(has, claim, moves, budget)
        won = won & allow
        has_won = has & ((moves + np.cumsum(has.astype(np.int32))
                          - has.astype(np.int32)) < budget)
        delta = np.where(has_won[:, None], sizes[win], np.float32(0.0))
        used = used + delta
        dest = np.where(won, claim, dest)
        gains = np.where(won, gain[arange_a, claim], gains)
        moves = np.int32(moves + won.astype(np.int32).sum())
        lam = lam + ETA * np.maximum(claims - 1, 0).astype(np.float32)
        lam = np.where(
            claims == 0, np.maximum(lam - ETA, np.float32(0.0)), lam
        )
        progress = bool(claimable.any()) and bool(moves < budget)
        rounds += int(claimable.any())
        it += 1
    return dest, gains, used, int(moves), rounds, lam


def packing_efficiency(
    capacity: np.ndarray, used: np.ndarray, ready: np.ndarray
) -> float:
    """Fleet packing efficiency in [0, 1]: how many ready nodes are
    COMPLETELY empty versus the most that could be, were the current
    load repacked perfectly (per-dim ceiling over a homogeneous fleet's
    max node capacity). 1.0 = load is as consolidated as arithmetic
    allows; fragmented fleets score low because load is smeared thinly
    across many nodes. The defrag gate measures recovery of this gauge."""
    ready = np.asarray(ready, dtype=bool)
    cap = np.asarray(capacity, dtype=np.float64)[ready]
    use = np.asarray(used, dtype=np.float64)[ready]
    n = int(ready.sum())
    if n == 0:
        return 1.0
    total = use.sum(axis=0)
    per_node = cap.max(axis=0)
    need = 0
    for d in range(cap.shape[1]):
        if per_node[d] <= 0.0:
            continue
        need = max(need, int(np.ceil(total[d] / per_node[d])))
    ideal_empty = n - min(need, n)
    if ideal_empty <= 0:
        return 1.0
    empty = int((use.sum(axis=1) == 0.0).sum())
    return float(empty) / float(ideal_empty)
