"""Harness — in-memory Planner for tests and benchmarks.

Reference: scheduler/testing.go:43-279. SubmitPlan applies results to a
real StateStore exactly as the FSM would (:83-175), so scheduler tests
exercise the true state-mutation path; RejectPlan-style hooks force the
partial-commit/refresh retry path (:18). The benchmark grid drives this
same harness (scheduler/benchmarks/benchmarks_test.go).
"""

from __future__ import annotations

from typing import Callable, Optional

from ..broker.plan_apply import evaluate_plan
from ..device.cache import DeviceStateCache
from ..state import StateStore
from ..structs import Evaluation, Plan, PlanResult
from .scheduler import new_scheduler


class Harness:
    def __init__(self, store: Optional[StateStore] = None):
        self.store = store or StateStore()
        self.device_cache = DeviceStateCache()
        self.plans: list[Plan] = []
        self.evals: list[Evaluation] = []
        self.created_evals: list[Evaluation] = []
        self.reblocked_evals: list[Evaluation] = []
        self.results: list[PlanResult] = []
        self._next_index = 1000
        # Test hook: force plan rejection (testing.go:18 RejectPlan)
        self.reject_plan: Optional[Callable[[Plan], bool]] = None
        self.plan_hook: Optional[Callable[[Plan], None]] = None

    def next_index(self) -> int:
        self._next_index += 1
        return self._next_index

    # -- Planner interface -------------------------------------------------
    def submit_plan(self, plan: Plan):
        self.plans.append(plan)
        if self.plan_hook is not None:
            self.plan_hook(plan)
        if self.reject_plan is not None and self.reject_plan(plan):
            result = PlanResult(refresh_index=self.store.latest_index)
            self.results.append(result)
            return result, self.store.snapshot()

        result = evaluate_plan(self.store, plan)
        if not result.is_no_op() or result.deployment is not None:
            index = self.next_index()
            self.store.upsert_plan_results(index, result, plan.eval_id)
            result.alloc_index = index
            if result.node_preemptions:
                from ..broker.plan_apply import preemption_evals

                for ev in preemption_evals(self.store, result):
                    self.create_eval(ev)
        self.results.append(result)
        new_snap = self.store.snapshot() if result.rejected_nodes else None
        return result, new_snap

    def update_eval(self, evaluation: Evaluation) -> None:
        self.evals.append(evaluation)

    def create_eval(self, evaluation: Evaluation) -> None:
        self.created_evals.append(evaluation)
        self.store.upsert_evals(self.next_index(), [evaluation])

    def reblock_eval(self, evaluation: Evaluation) -> None:
        self.reblocked_evals.append(evaluation)

    # -- driving -----------------------------------------------------------
    def process(self, evaluation: Evaluation) -> None:
        """Run the right scheduler for the eval type against a fresh
        snapshot (testing.go:270 Process)."""
        sched = new_scheduler(
            evaluation.type, self.store.snapshot(), self,
            cache=self.device_cache,
        )
        sched.process(evaluation)
