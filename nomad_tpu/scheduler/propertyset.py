"""Property sets — per-attribute-value usage accounting.

Reference: scheduler/propertyset.go:14-52 (propertySet), :230-275
(UsedCount/GetCombinedUseMap). A property set tracks how many allocations
of a job (or one task group) sit on nodes carrying each value of an
attribute. Three layers combine:

- **existing**: non-terminal allocations already in state,
- **proposed**: allocations in the in-flight plan (NodeAllocation),
- **cleared**:  allocations the plan stops (NodeUpdate), discounted from
  the combined count — minus one per value that a proposed alloc re-uses
  (propertyset.go:199-208).

combined[v] = max(existing[v] + proposed[v] - cleared[v], 0)

Two consumers (the same split as the reference):
- spread scoring (scheduler/spread.go) reads the combined map as the
  initial per-value counts the placement kernel carries through its scan;
- distinct_property feasibility (feasible.go:604-707) turns
  ``allowedCount`` minus the combined count into a per-value cap.

The TPU twist: instead of a hash map consulted per node per placement,
the counts are flattened once into dense per-value-id vectors aligned
with a ClusterTensors attribute column (flatten.py ``attr_column``) and
the kernel updates them on device as it places.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class PropertySet:
    """Host-side combined-use accounting for one (job[, task group],
    attribute). ``allowed_count`` is 0 for spread use (no cap)."""

    namespace: str
    job_id: str
    attribute: str
    task_group: str = ""  # empty = job-level (all task groups count)
    allowed_count: int = 0
    existing: dict[str, int] = field(default_factory=dict)
    proposed: dict[str, int] = field(default_factory=dict)
    cleared: dict[str, int] = field(default_factory=dict)

    # -- population (propertyset.go:129-208) ------------------------------
    def _node_value(self, snap, node_id: str, node_cache: dict):
        node = node_cache.get(node_id)
        if node is None:
            node = snap.node_by_id(node_id)
            node_cache[node_id] = node
        if node is None:
            return None
        v = node.lookup_attribute(self.attribute)
        return None if v is None else str(v)

    def _wanted(self, alloc, *, filter_terminal: bool) -> bool:
        if filter_terminal and alloc.terminal_status():
            return False
        if self.task_group and alloc.task_group != self.task_group:
            return False
        return True

    def populate(self, snap, plan=None) -> "PropertySet":
        """Build all three layers from a state snapshot and (optionally)
        the in-flight plan."""
        node_cache: dict = {}
        self.existing = {}
        for a in snap.allocs_by_job(self.namespace, self.job_id):
            if not self._wanted(a, filter_terminal=True):
                continue
            v = self._node_value(snap, a.node_id, node_cache)
            if v is not None:
                self.existing[v] = self.existing.get(v, 0) + 1

        self.proposed = {}
        self.cleared = {}
        if plan is not None:
            for stops in plan.node_update.values():
                for a in stops:
                    if a.job_id != self.job_id or not self._wanted(
                        a, filter_terminal=False
                    ):
                        continue
                    v = self._node_value(snap, a.node_id, node_cache)
                    if v is not None:
                        self.cleared[v] = self.cleared.get(v, 0) + 1
            for allocs in plan.node_allocation.values():
                for a in allocs:
                    if a.job_id != self.job_id or not self._wanted(
                        a, filter_terminal=True
                    ):
                        continue
                    v = self._node_value(snap, a.node_id, node_cache)
                    if v is not None:
                        self.proposed[v] = self.proposed.get(v, 0) + 1
            # a cleared value re-used by a proposed alloc stops discounting
            # (propertyset.go:199-208)
            for v in self.proposed:
                cur = self.cleared.get(v)
                if cur is None:
                    continue
                if cur <= 1:
                    del self.cleared[v]
                else:
                    self.cleared[v] = cur - 1
        return self

    # -- reads (propertyset.go:230-275) -----------------------------------
    def combined_use(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for layer in (self.existing, self.proposed):
            for v, n in layer.items():
                out[v] = out.get(v, 0) + n
        for v, n in self.cleared.items():
            if v in out:
                out[v] = max(out[v] - n, 0)
        return out

    def used_count(self, value: str) -> int:
        return self.combined_use().get(value, 0)

    def satisfies_distinct_property(self, value: str | None) -> tuple[bool, str]:
        """feasible.go:604 SatisfiesDistinctProperties: a node is feasible
        iff its value's combined use is below allowed_count; a node
        missing the property is infeasible."""
        if value is None:
            return False, f'missing property "{self.attribute}"'
        used = self.used_count(value)
        if used < self.allowed_count:
            return True, ""
        return (
            False,
            f"distinct_property: {self.attribute}={value} used by {used} allocs",
        )
