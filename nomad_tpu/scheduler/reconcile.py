"""Alloc reconciler — declarative diff of desired vs actual state.

Reference: scheduler/reconcile.go (allocReconciler.Compute :189-259) and
reconcile_util.go (allocSet/allocNameIndex). Pure host-side set arithmetic
(SURVEY.md §7 step 7): given the job spec and its existing allocations,
produce the result taxonomy — place / stop / ignore / in-place update /
destructive update / migrate / lost — that the scheduler turns into a plan.

Round-1 scope: core service/batch reconciliation incl. tainted-node
handling, reschedule eligibility and count changes. Deployment/canary
orchestration layers on in a later round (the result taxonomy already
carries the fields it needs).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from ..structs import (
    ALLOC_CLIENT_COMPLETE,
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_LOST,
    ALLOC_DESIRED_STOP,
    Allocation,
    Job,
    JOB_TYPE_BATCH,
    Node,
    TaskGroup,
)

# Stop/update description strings (structs.go AllocUpdateReason*)
REASON_ALLOC_NOT_NEEDED = "alloc not needed due to job update"
REASON_ALLOC_STOPPED = "alloc is stopped by user"
REASON_NODE_TAINTED = "alloc was rescheduled because of a node drain/down"
REASON_ALLOC_LOST = "alloc lost since node is down"


@dataclass(slots=True)
class PlaceRequest:
    """One placement the scheduler must make."""

    name: str
    task_group: TaskGroup
    previous_alloc: Optional[Allocation] = None  # replacement chains
    reschedule_penalty_node: str = ""  # node to penalize (rank.go:606)
    canary: bool = False


@dataclass(slots=True)
class StopRequest:
    alloc: Allocation
    reason: str
    client_status: str = ""


@dataclass(slots=True)
class UpdateRequest:
    alloc: Allocation
    new_job: Job


@dataclass(slots=True)
class ReconcileResults:
    """Mirrors reconcileResults (reconcile.go:93-125)."""

    place: list[PlaceRequest] = field(default_factory=list)
    stop: list[StopRequest] = field(default_factory=list)
    inplace_update: list[UpdateRequest] = field(default_factory=list)
    destructive_update: list[tuple[Allocation, PlaceRequest]] = field(
        default_factory=list
    )
    ignore: list[Allocation] = field(default_factory=list)
    # failed allocs whose replacement must wait (backoff) — become
    # followup evals with wait_until (generic_sched.go:718-753)
    disconnect_followups: list[tuple[Allocation, float]] = field(default_factory=list)
    desired_tg_updates: dict[str, dict] = field(default_factory=dict)
    # groups that need a (new) deployment to track their rollout:
    # tg name → DeploymentState template (reconcile.go's deployment logic)
    deployment_states: dict[str, object] = field(default_factory=dict)


def tasks_updated(old_job: Job, new_job: Job, group_name: str) -> bool:
    """Would updating to new_job require restarting the group's tasks?
    Mirrors scheduler/util.go tasksUpdated: drivers, config, env, resources,
    constraints, artifacts, networks are destructive; count is not."""
    a = old_job.lookup_task_group(group_name)
    b = new_job.lookup_task_group(group_name)
    if a is None or b is None:
        return True
    if len(a.tasks) != len(b.tasks):
        return True
    if a.ephemeral_disk.size_mb != b.ephemeral_disk.size_mb:
        return True
    if [c.key() for c in a.constraints] != [c.key() for c in b.constraints]:
        return True
    by_name = {t.name: t for t in b.tasks}
    for ta in a.tasks:
        tb = by_name.get(ta.name)
        if tb is None:
            return True
        if (
            ta.driver != tb.driver
            or ta.user != tb.user
            or ta.config != tb.config
            or ta.env != tb.env
            or ta.artifacts != tb.artifacts
            or ta.resources.cpu != tb.resources.cpu
            or ta.resources.memory_mb != tb.resources.memory_mb
            or len(ta.resources.networks) != len(tb.resources.networks)
            or [c.key() for c in ta.constraints] != [c.key() for c in tb.constraints]
        ):
            return True
    return False


class AllocNameIndex:
    """Bitmap-style tracker of claimed alloc name indices per group
    (reconcile_util.go allocNameIndex): freed indices are reused so names
    stay dense in [0, count)."""

    def __init__(self, job_id: str, group: str, count: int, existing):
        self.job_id = job_id
        self.group = group
        self.count = count
        self.used: set[int] = set()
        for a in existing:
            idx = a.index()
            if idx >= 0:
                self.used.add(idx)

    def next(self, n: int) -> list[str]:
        out = []
        i = 0
        while len(out) < n:
            if i not in self.used:
                self.used.add(i)
                out.append(f"{self.job_id}.{self.group}[{i}]")
            i += 1
        return out

    def highest(self, n: int) -> set[int]:
        return set(sorted(self.used, reverse=True)[:n])


def reconcile(
    job: Optional[Job],
    job_id: str,
    existing: list[Allocation],
    tainted_nodes: dict[str, Node],
    *,
    batch: bool = False,
    now_ns: Optional[int] = None,
    deployment=None,
) -> ReconcileResults:
    """Compute the diff for one job.

    ``job`` None or stopped ⇒ stop everything. ``tainted_nodes`` maps node
    id → Node for down/draining nodes (scheduler/util.go:354 taintedNodes).
    ``deployment`` is the job's latest deployment (if any): groups with an
    update strategy gate their destructive replacements on it — canaries
    first, then at most ``max_parallel`` in-flight unhealthy replacements
    (reconcile.go's deployment-aware computeGroup logic).
    """
    r = ReconcileResults()
    # injection fallback only: schedulers pass now_ns from their context
    # clock so replays are deterministic
    if now_ns is None:
        now_ns = time.time_ns()  # nta: allow=NTA001
    stopped = job is None or job.stopped()

    live = [a for a in existing if not a.terminal_status()]

    if stopped:
        for a in live:
            r.stop.append(StopRequest(a, REASON_ALLOC_STOPPED))
        return r

    by_group: dict[str, list[Allocation]] = {tg.name: [] for tg in job.task_groups}
    for a in existing:
        by_group.setdefault(a.task_group, []).append(a)

    for tg_name, allocs in by_group.items():
        tg = job.lookup_task_group(tg_name)
        counts = {
            "place": 0, "stop": 0, "migrate": 0, "ignore": 0,
            "in_place_update": 0, "destructive_update": 0,
        }
        if tg is None:
            # group removed from job
            for a in allocs:
                if not a.terminal_status():
                    r.stop.append(StopRequest(a, REASON_ALLOC_NOT_NEEDED))
                    counts["stop"] += 1
            r.desired_tg_updates[tg_name] = counts
            continue

        desired = tg.count
        keep: list[Allocation] = []  # allocs that count toward desired
        replace: list[tuple[Allocation, str]] = []  # (prev, penalty_node)

        for a in allocs:
            node = tainted_nodes.get(a.node_id)
            if a.terminal_status():
                if (
                    a.client_status == ALLOC_CLIENT_FAILED
                    and a.desired_status == "run"
                ):
                    # failed: reschedule or leave to followup
                    pol = tg.reschedule_policy
                    if a.followup_eval_id:
                        r.ignore.append(a)
                        counts["ignore"] += 1
                    elif a.next_allocation:
                        r.ignore.append(a)
                        counts["ignore"] += 1
                    elif a.should_reschedule(pol, now_ns):
                        delay = a.next_reschedule_delay(pol) if pol else 0.0
                        if delay > 0:
                            r.disconnect_followups.append((a, delay))
                            counts["ignore"] += 1
                        else:
                            replace.append((a, a.node_id))
                    else:
                        r.ignore.append(a)
                        counts["ignore"] += 1
                elif batch and a.client_status == ALLOC_CLIENT_COMPLETE:
                    # batch jobs: successful completions are not replaced
                    keep.append(a)
                    r.ignore.append(a)
                    counts["ignore"] += 1
                else:
                    r.ignore.append(a)
                    counts["ignore"] += 1
                continue

            if node is not None:
                # tainted node
                if node.terminal_status():
                    # node down ⇒ alloc lost; replace
                    r.stop.append(
                        StopRequest(a, REASON_ALLOC_LOST, ALLOC_CLIENT_LOST)
                    )
                    counts["stop"] += 1
                    replace.append((a, ""))
                elif a.desired_transition.migrate:
                    # draining migrates wave-by-wave: only allocs the
                    # NodeDrainer marked (DesiredTransition.ShouldMigrate,
                    # reconcile_util.go filterByTainted) move now —
                    # migrate.max_parallel is enforced by the drainer
                    r.stop.append(StopRequest(a, REASON_NODE_TAINTED))
                    counts["migrate"] += 1
                    replace.append((a, a.node_id))
                else:
                    # still on a draining node, waiting for its wave
                    keep.append(a)
                    r.ignore.append(a)
                    counts["ignore"] += 1
                continue

            if a.desired_transition.migrate:
                # migrate mark on a HEALTHY node: `alloc stop`
                # (alloc_endpoint.go Stop sets DesiredTransition and the
                # reconciler replaces the alloc wherever it sits)
                r.stop.append(StopRequest(a, REASON_ALLOC_STOPPED))
                counts["migrate"] += 1
                replace.append((a, a.node_id))
                continue

            keep.append(a)

        # deployment gating context for this group
        u = tg.update
        dstate = (
            deployment.task_groups.get(tg_name)
            if deployment is not None
            and deployment.active()
            and deployment.job_version == job.version
            else None
        )
        # a FAILED deployment for this very version halts the rollout —
        # no further replacements, no fresh deployment — until a new job
        # version (e.g. auto-revert) arrives; a PAUSED one freezes it the
        # same way until the operator resumes (deployment_endpoint.go
        # Pause: an eval arriving mid-pause must not advance the rollout)
        rollout_halted = (
            deployment is not None
            and deployment.job_version == job.version
            and deployment.status in ("failed", "paused")
        )
        # unpromoted canaries run *beside* the old version: they don't
        # count toward desired and must not trigger surplus stops
        canaries: list[Allocation] = []
        if u is not None and u.canary > 0 and (
            dstate is None or not dstate.promoted
        ):
            canaries = [
                a for a in keep if a.canary and a.job_version == job.version
            ]
            keep = [a for a in keep if a not in canaries]

        # count adjustment over the kept (healthy, untainted) allocs
        n_target = desired - len(replace)
        if len(keep) > n_target:
            # stop surplus: old-version allocs first (a promoted canary on
            # the new version must survive the count convergence), then
            # highest name indices (allocNameIndex)
            surplus = len(keep) - max(n_target, 0)
            keep_sorted = sorted(
                keep,
                key=lambda a: (a.job_version == job.version, -a.index()),
            )
            for a in keep_sorted[:surplus]:
                if a.terminal_status():
                    continue
                r.stop.append(StopRequest(a, REASON_ALLOC_NOT_NEEDED))
                counts["stop"] += 1
            keep = keep_sorted[surplus:]

        # in-place vs destructive updates for survivors on old job versions;
        # the verdict is cached per old job *version* (allocs in one group
        # can sit on different stale versions with different diffs)
        updated_by_version: dict[int, bool] = {}
        destructive_candidates: list[tuple[Allocation, PlaceRequest]] = []
        for a in keep:
            if a.job_version == job.version or a.terminal_status():
                r.ignore.append(a)
                counts["ignore"] += 1
                continue
            if a.job_version not in updated_by_version:
                old = a.job if a.job is not None else job
                updated_by_version[a.job_version] = tasks_updated(
                    old, job, tg_name
                )
            if updated_by_version[a.job_version]:
                pr = PlaceRequest(name=a.name, task_group=tg, previous_alloc=a)
                destructive_candidates.append((a, pr))
            else:
                r.inplace_update.append(UpdateRequest(a, job))
                counts["in_place_update"] += 1

        # rollout gating (reconcile.go computeGroup): with an update
        # strategy, destructive replacements are throttled by the
        # deployment's health signal instead of happening all at once
        if rollout_halted and u is not None:
            for a, _pr in destructive_candidates:
                r.ignore.append(a)
                counts["ignore"] += 1
            destructive_candidates = []
        canary_phase = (
            u is not None
            and u.canary > 0
            and destructive_candidates
            and (dstate is None or not dstate.promoted)
        )
        if canary_phase:
            # canary phase: place missing canaries, leave old version alone
            need = u.canary - len(
                [a for a in canaries if not a.terminal_status()]
            )
            cname_idx = AllocNameIndex(job.id, tg_name, desired, allocs)
            for name in cname_idx.next(max(need, 0)):
                r.place.append(
                    PlaceRequest(name=name, task_group=tg, canary=True)
                )
                counts["place"] += 1
            for a, _pr in destructive_candidates:
                r.ignore.append(a)
                counts["ignore"] += 1
            destructive_candidates = []
        elif (
            u is not None and u.rolling() and destructive_candidates
        ):
            current = [
                a
                for a in keep + canaries
                if a.job_version == job.version and not a.terminal_status()
            ]
            healthy = len(
                [
                    a
                    for a in current
                    if a.deployment_status is not None
                    and a.deployment_status.is_healthy()
                ]
            )
            in_flight = len(current) - healthy
            budget = max(u.max_parallel - in_flight, 0)
            deferred = destructive_candidates[budget:]
            destructive_candidates = destructive_candidates[:budget]
            for a, _pr in deferred:
                r.ignore.append(a)
                counts["ignore"] += 1

        for a, pr in destructive_candidates:
            r.destructive_update.append((a, pr))
            counts["destructive_update"] += 1

        # signal that this rollout needs deployment tracking
        if (
            not rollout_halted
            and u is not None
            and u.rolling()
            and (destructive_candidates or canary_phase or dstate is None)
            and (
                deployment is None
                or not deployment.active()
                or deployment.job_version != job.version
            )
            and (destructive_candidates or canary_phase or job.version > 0)
        ):
            from ..structs.deployment import DeploymentState

            r.deployment_states[tg_name] = DeploymentState(
                auto_revert=u.auto_revert,
                auto_promote=u.auto_promote,
                desired_canaries=u.canary if canary_phase else 0,
                desired_total=desired,
                progress_deadline_s=u.progress_deadline_s,
            )

        # placements for missing + replacements; batch-complete allocs in
        # ``keep`` count toward desired (their work is done, not missing)
        live_count = len(keep)
        missing = max(desired - live_count - len(replace), 0)
        # terminal allocs release their name index for reuse
        # (reconcile_util.go allocNameIndex tracks live names only)
        name_idx = AllocNameIndex(
            job.id,
            tg_name,
            desired,
            [a for a in allocs if not a.terminal_status()],
        )
        for prev, penalty in replace:
            r.place.append(
                PlaceRequest(
                    name=prev.name,
                    task_group=tg,
                    previous_alloc=prev,
                    reschedule_penalty_node=penalty,
                )
            )
            counts["place"] += 1
        for name in name_idx.next(missing):
            r.place.append(PlaceRequest(name=name, task_group=tg))
            counts["place"] += 1

        r.desired_tg_updates[tg_name] = counts

    return r
