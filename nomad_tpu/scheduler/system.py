"""SystemScheduler — place one alloc per feasible node (system/sysbatch).

Reference: scheduler/scheduler_system.go (:27 SystemScheduler, :72 Process).
Where the generic scheduler asks "which node for each alloc", the system
scheduler asks "which nodes at all" — on device that's simply the
feasibility mask itself: every eligible node that fits gets a placement,
computed in one vectorized pass (no greedy scan needed; allocs of a system
job never stack on one node).
"""

from __future__ import annotations

import numpy as np

from ..device import flatten_group_ask
from ..device.cache import DeviceStateCache
from .algorithms import score_group
from ..structs import (
    ALLOC_DESIRED_RUN,
    Allocation,
    AllocMetric,
    ComparableResources,
    EVAL_STATUS_COMPLETE,
    Evaluation,
    new_id,
)
from .generic import tainted_nodes
from .reconcile import REASON_ALLOC_LOST, REASON_ALLOC_NOT_NEEDED
from .scheduler import Planner, register_scheduler

MAX_SYSTEM_SCHEDULE_ATTEMPTS = 5  # scheduler_system.go:12-21


@register_scheduler("system")
@register_scheduler("sysbatch")
class SystemScheduler:
    def __init__(
        self,
        snapshot,
        planner: Planner,
        *,
        sysbatch: bool = False,
        cache=None,
        overlay=None,  # accepted for factory uniformity; system placement
        # is per-node (no greedy packing), so the overlay isn't consulted
        node_filter=None,  # likewise unused: a system job runs on EVERY
        # eligible node, so lane restriction would be semantically wrong
    ):
        self.snapshot = snapshot
        self.planner = planner
        self.sysbatch = sysbatch
        self.cache = cache if cache is not None else DeviceStateCache()
        self.eval = None
        self.job = None
        self.plan = None
        self.failed_tg_allocs: dict[str, AllocMetric] = {}
        self.explanations: dict[str, object] = {}  # tg → PlacementExplanation

    def process(self, evaluation: Evaluation) -> None:
        self.eval = evaluation
        self.sysbatch = self.sysbatch or evaluation.type == "sysbatch"
        self._explain = bool(
            getattr(
                self.snapshot.scheduler_config(),
                "placement_explanations",
                True,
            )
        )
        for _ in range(MAX_SYSTEM_SCHEDULE_ATTEMPTS):
            if self._process_once():
                break
        if self.explanations and not evaluation.annotate_plan:
            from ..obs.explain import explanation_to_dict
            from ..obs.recorder import flight_recorder

            flight_recorder.record_explanation(
                evaluation.id,
                {
                    "eval_id": evaluation.id,
                    "job_id": evaluation.job_id,
                    "namespace": evaluation.namespace,
                    "groups": {
                        tg: explanation_to_dict(ex)
                        for tg, ex in self.explanations.items()
                    },
                },
            )
        import copy

        updated = copy.copy(evaluation)
        updated.status = EVAL_STATUS_COMPLETE
        updated.failed_tg_allocs = dict(self.failed_tg_allocs)
        self.planner.update_eval(updated)

    def _process_once(self) -> bool:
        ev = self.eval
        self.job = self.snapshot.job_by_id(ev.namespace, ev.job_id)
        self.plan = ev.make_plan(self.job)
        existing = self.snapshot.allocs_by_job(ev.namespace, ev.job_id)
        tainted = tainted_nodes(self.snapshot, existing)

        live_by_node_group: dict[tuple[str, str], Allocation] = {}
        for a in existing:
            if a.terminal_status():
                # a completed sysbatch alloc satisfies its node permanently
                # (the batch don't-rerun rule, scheduler_system.go sysbatch)
                if self.sysbatch and a.client_status == "complete":
                    live_by_node_group.setdefault((a.node_id, a.task_group), a)
                continue
            node = tainted.get(a.node_id)
            if node is not None:
                if node.terminal_status():
                    self.plan.append_lost_alloc(a)
                elif a.desired_transition.migrate:
                    # draining: wait for the NodeDrainer's wave mark
                    # (reconcile_util.go filterByTainted — system allocs
                    # leave a draining node only when marked migrating)
                    self.plan.append_stopped_alloc(
                        a, "alloc stopped because node is draining"
                    )
                else:
                    live_by_node_group[(a.node_id, a.task_group)] = a
                continue
            if a.desired_transition.migrate:
                # migrate mark on a HEALTHY node: `alloc stop` — the
                # system reconcile stops it and (the node still being a
                # live placement target below) replaces it in place
                self.plan.append_stopped_alloc(
                    a, "alloc is stopped by user"
                )
                continue
            live_by_node_group[(a.node_id, a.task_group)] = a

        stopped_job = self.job is None or self.job.stopped()
        if stopped_job:
            for a in live_by_node_group.values():
                self.plan.append_stopped_alloc(a, REASON_ALLOC_NOT_NEEDED)
            return self._submit()

        ct = self.cache.tensors(self.snapshot)
        nodes_sorted = ct.nodes

        for tg in self.job.task_groups:
            ga = flatten_group_ask(
                ct, self.snapshot, self.job, tg, 1, nodes_sorted=nodes_sorted
            )
            scored = score_group(
                ct, ga, float(max(tg.count, 1)), explain=self._explain
            )
            if self._explain:
                finals, fits_np, ex = scored
                self.explanations[tg.name] = ex
                # breakdowns are derived against the usage the finals
                # were scored with, not the post-placement overlay
                used_at_score = np.asarray(ct.used).copy()
            else:
                finals, fits_np = scored
                ex = None
            eligible_rows = np.nonzero(ga.eligible[: ct.num_nodes])[0]
            ask_res = tg.combined_resources()
            comparable = ComparableResources(
                cpu=ask_res.cpu,
                memory_mb=ask_res.memory_mb,
                disk_mb=ask_res.disk_mb,
                bandwidth_mbits=ask_res.bandwidth_mbits(),
            )
            for row in eligible_rows:
                node_id = ct.node_ids[row]
                if (node_id, tg.name) in live_by_node_group:
                    continue  # already running there
                preempted_ids: list[str] = []
                if not fits_np[row]:
                    preempted_ids = self._try_preempt_node(ct, tg, row, ga.ask)
                    if not preempted_ids:
                        m = self._fail_metric(node_id, "resources", ex)
                        self._record_failure(tg.name, m)
                        continue
                if (
                    not preempted_ids
                    and ga.slot_caps is not None
                    and ga.slot_caps[row] < 1
                ):
                    # device instances exist but are all held — system
                    # preemption may free them (PreemptForDevice)
                    preempted_ids = self._try_preempt_node(ct, tg, row, ga.ask)
                    if not preempted_ids:
                        m = self._fail_metric(node_id, "devices", ex)
                        self._record_failure(tg.name, m)
                        continue
                alloc_id = new_id()
                # victims enter the plan BEFORE device assignment so
                # collect_in_use sees their instances as freed; a failed
                # assignment rolls the eviction back (the generic path's
                # dev_ok contract, generic.py _try_preempt)
                victim_total = None
                for vid in preempted_ids:
                    victim = self.snapshot.alloc_by_id(vid)
                    if victim is not None:
                        self.plan.append_preempted_alloc(victim, alloc_id)
                        vec = victim.comparable_resources().to_vector()
                        victim_total = (
                            vec if victim_total is None else victim_total + vec
                        )
                devices, dev_ok = self._assign_devices(tg, node_id)
                if not dev_ok:
                    from .device import rollback_plan_preemptions

                    rollback_plan_preemptions(
                        self.plan, node_id, preempted_ids
                    )
                    m = self._fail_metric(node_id, "devices", ex)
                    self._record_failure(tg.name, m)
                    continue
                metric = AllocMetric(nodes_evaluated=1)
                metric.scores[f"{node_id}.score"] = float(finals[row])
                if ex is not None:
                    from ..obs.explain import score_meta_for_row

                    metric.score_meta = [
                        score_meta_for_row(
                            ct,
                            ga,
                            used_at_score,
                            int(row),
                            desired_total=float(max(tg.count, 1)),
                        )
                    ]
                    ex.placed_nodes.append(node_id)
                alloc = Allocation(
                    id=alloc_id,
                    namespace=self.job.namespace,
                    eval_id=ev.id,
                    name=f"{self.job.id}.{tg.name}[0]",
                    node_id=node_id,
                    job_id=self.job.id,
                    job=self.job,
                    job_version=self.job.version,
                    task_group=tg.name,
                    resources=comparable.copy(),
                    desired_status=ALLOC_DESIRED_RUN,
                    client_status="pending",
                    metrics=metric,
                    allocated_devices=devices or [],
                )
                if preempted_ids:
                    alloc.preempted_allocations = list(preempted_ids)
                    if victim_total is not None:
                        ct.used[row] -= victim_total
                # every placement debits the (private) usage overlay so
                # later task groups' fit checks and victim selection see
                # this plan's own load
                ct.used[row] += ga.ask
                self.plan.append_alloc(alloc)
            # stop allocs on nodes no longer eligible (e.g. constraint
            # change) — but NOT draining nodes: those drain via the
            # NodeDrainer's migrate marks, not eligibility loss
            eligible_ids = {ct.node_ids[r] for r in eligible_rows}
            for (node_id, tg_name), a in list(live_by_node_group.items()):
                if (
                    tg_name == tg.name
                    and node_id not in eligible_ids
                    and node_id not in tainted
                    and not a.terminal_status()
                ):
                    self.plan.append_stopped_alloc(a, REASON_ALLOC_NOT_NEEDED)

        return self._submit()

    def _try_preempt_node(self, ct, tg, row, ask_vec) -> list[str]:
        """System-job preemption on one node (the node IS the target for
        system placements — no search needed). Enabled by default per
        SchedulerConfiguration.PreemptionConfig.SystemSchedulerEnabled
        (nomad/structs/operator.go:164-169, scheduler_system.go:27);
        victim selection is the reference-exact host greedy
        (preempt_host.select_victims: maxParallel, ports, devices)."""
        cfg = self.snapshot.scheduler_config()
        if not cfg.preemption_system_enabled or self.job is None:
            return []
        from ..device.preempt import PREEMPTION_PRIORITY_DELTA
        from .preempt_host import select_victims

        if self.job.priority < PREEMPTION_PRIORITY_DELTA:
            return []
        already = {
            a.id
            for allocs in self.plan.node_preemptions.values()
            for a in allocs
        }
        ids = select_victims(
            ct,
            self.snapshot,
            self.job,
            tg,
            ask_vec,
            row,
            plan=self.plan,
            exclude_ids=already,
        )
        return ids or []

    def _assign_devices(self, tg, node_id):
        from .device import assign_devices_for_plan

        return assign_devices_for_plan(self.snapshot, self.plan, tg, node_id)

    @staticmethod
    def _fail_metric(node_id: str, dim: str, ex) -> AllocMetric:
        m = AllocMetric(nodes_evaluated=1)
        m.exhausted_node(node_id, dim)
        if ex is not None:
            # fleet-wide rejection histogram rides the (coalesced) failed
            # metric so `eval status` explains the whole group, not just
            # the first failing node
            m.rejections = dict(ex.rejections)
        return m

    def _record_failure(self, tg_name: str, metric: AllocMetric) -> None:
        existing = self.failed_tg_allocs.get(tg_name)
        if existing is not None:
            existing.coalesced_failures += 1
        else:
            self.failed_tg_allocs[tg_name] = metric

    def _submit(self) -> bool:
        if self.plan.is_no_op():
            return True
        result, new_snap = self.planner.submit_plan(self.plan)
        if new_snap is not None:
            self.snapshot = new_snap
        full, _, _ = result.full_commit(self.plan)
        return full
