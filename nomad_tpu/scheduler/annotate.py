"""Dry-run planning (`job plan`) — run the scheduler without committing.

Reference: SURVEY.md §3.3 — Job.Plan runs the scheduler inline on a
snapshot with AnnotatePlan=true and the plan is *not* submitted
(scheduler/annotate.go produces the per-group desired-update counts the
CLI renders as "+2 create, ~1 in-place, -1 destroy"). This is also the
zero-risk harness for A/B-ing the TPU scorer against a reference cluster.
"""

from __future__ import annotations

import copy
from typing import Optional

from ..structs import Evaluation, Plan, PlanResult
from .scheduler import new_scheduler


class _OverlaySnapshot:
    """A snapshot view with the candidate job overlaid (uncommitted)."""

    def __init__(self, snap, job):
        self._snap = snap
        self._job = job

    def job_by_id(self, namespace, job_id):
        if (namespace, job_id) == (self._job.namespace, self._job.id):
            return self._job
        return self._snap.job_by_id(namespace, job_id)

    def __getattr__(self, name):
        return getattr(self._snap, name)


class _DryRunPlanner:
    """Planner that records the plan instead of submitting it."""

    def __init__(self):
        self.plan: Optional[Plan] = None
        self.evals: list[Evaluation] = []

    def submit_plan(self, plan: Plan):
        self.plan = plan
        # pretend full commit so the scheduler doesn't retry
        result = PlanResult(
            node_allocation={k: list(v) for k, v in plan.node_allocation.items()},
            node_update={k: list(v) for k, v in plan.node_update.items()},
            node_preemptions={
                k: list(v) for k, v in plan.node_preemptions.items()
            },
        )
        return result, None

    def update_eval(self, ev):
        self.evals.append(ev)

    def create_eval(self, ev):
        self.evals.append(ev)

    def reblock_eval(self, ev):
        self.evals.append(ev)


def plan_job(store, job) -> dict:
    """Dry-run the registration of ``job`` and annotate the outcome."""
    existing = store.job_by_id(job.namespace, job.id)
    candidate = copy.deepcopy(job)
    candidate.version = existing.version + 1 if existing is not None else 0
    snap = _OverlaySnapshot(store.snapshot(), candidate)
    planner = _DryRunPlanner()
    ev = Evaluation(
        namespace=candidate.namespace,
        priority=candidate.priority,
        type=candidate.type,
        job_id=candidate.id,
        annotate_plan=True,
    )
    sched = new_scheduler(candidate.type, snap, planner)
    sched.process(ev)

    plan = planner.plan
    annotations: dict[str, dict] = {}
    failed = {}
    for e in planner.evals:
        if e.failed_tg_allocs:
            for tg, metric in e.failed_tg_allocs.items():
                # structured failure detail straight off the AllocMetric
                # the scheduler built — the explain seam stamped its
                # rejection histogram and near-miss score table onto it,
                # so the dry run reports the same counts a live eval
                # would (no re-derivation here)
                failed[tg] = {
                    "coalesced_failures": getattr(
                        metric, "coalesced_failures", 0
                    )
                    + 1,
                    "nodes_evaluated": getattr(metric, "nodes_evaluated", 0),
                    "nodes_exhausted": getattr(metric, "nodes_exhausted", 0),
                    "dimension_exhausted": dict(
                        getattr(metric, "dimension_exhausted", {}) or {}
                    ),
                    "class_exhausted": dict(
                        getattr(metric, "class_exhausted", {}) or {}
                    ),
                    "rejections": dict(
                        getattr(metric, "rejections", {}) or {}
                    ),
                }
    # score provenance without commit: the scheduler kept its per-group
    # explanations (annotate_plan suppresses the flight-recorder ring),
    # so `job plan -verbose` can render candidate tables for a job that
    # never ran
    explanations = {}
    sched_ex = getattr(sched, "explanations", None)
    if sched_ex:
        from ..obs.explain import explanation_to_dict

        explanations = {
            tg: explanation_to_dict(ex) for tg, ex in sched_ex.items()
        }
    if plan is not None:
        placed = {}
        for allocs in plan.node_allocation.values():
            for a in allocs:
                placed[a.task_group] = placed.get(a.task_group, 0) + 1
        stopped = {}
        for allocs in plan.node_update.values():
            for a in allocs:
                stopped[a.task_group] = stopped.get(a.task_group, 0) + 1
        preempted = sum(len(v) for v in plan.node_preemptions.values())
        for tg in candidate.task_groups:
            annotations[tg.name] = {
                "place": placed.get(tg.name, 0),
                "stop": stopped.get(tg.name, 0),
                "preemptions": preempted,
            }
    # gang feasibility verdict: a gang job either commits every member
    # or releases them all (scheduler/generic.py _enforce_gang_atomicity,
    # law 15) — so the dry run can state the all-or-nothing outcome
    # directly instead of making the operator infer it from per-group
    # failure rows
    gang_verdict = None
    gang = getattr(candidate, "gang", None) or {}
    members = list(gang.get("groups") or ())
    if members:
        reasons = sorted({
            r
            for m in members
            for r in (failed.get(m, {}).get("rejections") or {})
            if r.startswith("gang-")
        })
        commits = not any(m in failed for m in members)
        gang_verdict = {
            "members": {
                m: {"place": annotations.get(m, {}).get("place", 0)}
                for m in sorted(members)
            },
            "feasible": commits,
            "released": bool(reasons) or not commits,
            "reasons": reasons,
        }
    return {
        "job_id": candidate.id,
        "version": candidate.version,
        "diff_type": "edited" if existing is not None else "added",
        "annotations": annotations,
        "failed_tg_allocs": failed,
        "placement_explanations": explanations,
        **({"gang": gang_verdict} if gang_verdict is not None else {}),
    }
