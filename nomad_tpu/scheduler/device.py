"""Device allocator — GPU-style device feasibility, affinity scoring, and
concrete instance assignment.

Reference semantics: scheduler/device.go (deviceAllocator.AssignDevice
:32-131 — device-id hierarchy matching, constraint filtering on device
attributes, affinity-scored group selection), scheduler/feasible.go:1173
(DeviceChecker hard filter), structs.DeviceAccounter
(nomad/structs/devices.go — per-instance free accounting), and
rank.go:388-434 (device assignment inside BinPackIterator, with the
matched-affinity sum folded into the node score).

TPU split of labor: device inventories are tiny (a handful of groups ×
instances per node) and string-typed, so feasibility/assignment stay
host-side; the *batch accounting* — "this node can take at most K more
placements of this group" — is flattened to a dense ``slot_caps[N]``
vector consumed by the greedy placement scan on device (score.py), the
same way constraints flatten to the eligibility mask.
"""

from __future__ import annotations

from typing import Optional

from ..structs.job import Constraint, TaskGroup
from ..structs.resources import (
    AllocatedDeviceResource,
    RequestedDevice,
    _dev_id_matches,
)
from .feasible import check_constraint_values


def resolve_device_target(dev, target: str) -> Optional[str]:
    """Resolve a constraint/affinity target against a device group.
    Supported: ``${device.vendor}``, ``${device.type}``, ``${device.model}``,
    ``${device.attr.<name>}`` (device.go nodeDeviceResource resolution)."""
    t = target.strip()
    if t.startswith("${") and t.endswith("}"):
        t = t[2:-1]
    if t == "device.vendor":
        return dev.vendor
    if t == "device.type":
        return dev.type
    if t in ("device.model", "device.name"):
        return dev.name
    if t.startswith("device.attr."):
        v = dev.attributes.get(t[len("device.attr.") :])
        return None if v is None else str(v)
    return None


def _check_device_constraint(dev, c) -> bool:
    lval = resolve_device_target(dev, c.l_target) if c.l_target else None
    rval = c.r_target
    # literal right-hand side unless it's itself a device interpolation
    if rval.startswith("${"):
        rval = resolve_device_target(dev, rval) or ""
    return check_constraint_values(c.operand, lval, rval)


def device_group_matches(dev, ask: RequestedDevice) -> bool:
    """Name hierarchy (type | vendor/type | vendor/type/name) + all hard
    constraints on device attributes."""
    if not dev.matches(ask):
        return False
    return all(_check_device_constraint(dev, c) for c in ask.constraints)


def device_affinity_score(dev, ask: RequestedDevice) -> float:
    """Weight-normalized affinity score of this device group for the ask,
    in [-1, 1] (device.go:94-115 sums matched affinity weights)."""
    if not ask.affinities:
        return 0.0
    total = float(sum(abs(a.weight) for a in ask.affinities)) or 1.0
    score = 0.0
    for a in ask.affinities:
        c = Constraint(
            l_target=a.l_target, r_target=a.r_target, operand=a.operand
        )
        if _check_device_constraint(dev, c):
            score += a.weight
    return score / total


def group_device_asks(tg: TaskGroup) -> list[RequestedDevice]:
    """All device asks across the group's tasks."""
    return [d for t in tg.tasks for d in t.resources.devices]


def free_instances(node, in_use: dict[str, set]) -> dict[str, list[str]]:
    """device full-id → healthy instance ids not currently held.
    ``in_use`` maps full-id → set of held instance ids (DeviceAccounter's
    view, built from the node's non-terminal allocs)."""
    out: dict[str, list[str]] = {}
    for dev in node.node_resources.devices:
        held = in_use.get(dev.id(), set())
        out[dev.id()] = [
            i.id for i in dev.instances if i.healthy and i.id not in held
        ]
    return out


def collect_in_use(allocs) -> dict[str, set]:
    """Union of device instances held by non-terminal allocs on a node.
    Allocs without concrete instance ids (older placements) reserve
    anonymous slots — represented by counting placeholders."""
    in_use: dict[str, set] = {}
    anon = 0
    for a in allocs:
        if a.terminal_status():
            continue
        ids = a.device_instance_ids()
        if ids:
            for did, inst in ids.items():
                in_use.setdefault(did, set()).update(inst)
        else:
            for did, count in a.device_asks().items():
                s = in_use.setdefault(did, set())
                for _ in range(count):
                    s.add(f"__anon{anon}")
                    anon += 1
    return in_use


def assign_devices(
    node, in_use: dict[str, set], tg: TaskGroup
) -> Optional[list[AllocatedDeviceResource]]:
    """Pick concrete instances for every device ask of the group.

    Per ask: among matching device groups with enough free instances,
    choose the highest affinity score (ties → most free, mirroring
    AssignDevice's preference for the offer with the best score,
    device.go:117-129). Returns None if any ask cannot be satisfied.
    Anonymous reservations (``__anon*``) consume capacity but are never
    assigned out.
    """
    free = free_instances(node, in_use)
    avail = {did: len(ids) for did, ids in free.items()}
    # Anonymous reservations (allocs without concrete instance ids) are
    # keyed by the *asked* id, possibly partial (``gpu``). Drain them from
    # matching pools greedily, most-specific debts first — the same shared-
    # pool rule as structs.DeviceAccounter (_device_accounting_fits).
    anon_by_ask: dict[str, int] = {}
    for ask_id, held in in_use.items():
        n = sum(1 for i in held if i.startswith("__anon"))
        if n:
            anon_by_ask[ask_id] = anon_by_ask.get(ask_id, 0) + n
    for ask_id in sorted(anon_by_ask, key=lambda d: -d.count("/")):
        debt = anon_by_ask[ask_id]
        for did in sorted(d for d in avail if _dev_id_matches(d, ask_id)):
            take = min(avail[did], debt)
            avail[did] -= take
            debt -= take
            if debt == 0:
                break
        if debt > 0:
            return None  # node is already device-overcommitted
    devs_by_id = {d.id(): d for d in node.node_resources.devices}
    out: list[AllocatedDeviceResource] = []
    # most-specific asks first so a full-id ask isn't starved by a wildcard
    for ask in sorted(group_device_asks(tg), key=lambda d: -d.name.count("/")):
        best = None  # ((score, avail), dev_id)
        for did, dev in devs_by_id.items():
            if not device_group_matches(dev, ask):
                continue
            if avail.get(did, 0) < ask.count:
                continue
            score = device_affinity_score(dev, ask)
            key = (score, avail[did])
            if best is None or key > best[0]:
                best = (key, did)
        if best is None:
            return None
        did = best[1]
        dev = devs_by_id[did]
        taken = free[did][: ask.count]
        free[did] = free[did][ask.count :]
        avail[did] -= ask.count
        out.append(
            AllocatedDeviceResource(
                vendor=dev.vendor,
                type=dev.type,
                name=dev.name,
                device_ids=list(taken),
            )
        )
    return out


def assign_devices_for_plan(
    snapshot, plan, tg: TaskGroup, node_id: str
) -> tuple[Optional[list[AllocatedDeviceResource]], bool]:
    """Concrete device assignment for one placement, seeing both snapshot
    allocs and the in-flight plan's changes (stops + preemptions free
    instances, in-plan placements hold them) — shared by the generic and
    system schedulers (reference rank.go:388-434). Returns
    (devices | None, ok): ok is False only when the group asks for
    devices the node can't supply."""
    if not group_device_asks(tg):
        return None, True
    node = snapshot.node_by_id(node_id)
    if node is None:
        return None, False
    stopped = {a.id for a in plan.node_update.get(node_id, [])}
    stopped |= {a.id for a in plan.node_preemptions.get(node_id, [])}
    live = [
        a for a in snapshot.allocs_by_node(node_id) if a.id not in stopped
    ]
    live.extend(plan.node_allocation.get(node_id, []))
    devices = assign_devices(node, collect_in_use(live), tg)
    return devices, devices is not None


def rollback_plan_preemptions(plan, node_id: str, victim_ids) -> None:
    """Remove this placement's victims from the plan (device assignment
    failed after the eviction was staged); drop the key entirely when
    emptied so the plan stays a no-op if nothing else touched it."""
    remaining = [
        a
        for a in plan.node_preemptions.get(node_id, [])
        if a.id not in set(victim_ids)
    ]
    if remaining:
        plan.node_preemptions[node_id] = remaining
    else:
        plan.node_preemptions.pop(node_id, None)


def feasible_sets(node, in_use: dict[str, set], tg: TaskGroup, cap: int) -> int:
    """How many *additional* placements of this group the node can take,
    device-wise, up to ``cap``. This is the DeviceChecker hard filter
    (feasible.go:1173) generalized to a count for batch accounting."""
    asks = group_device_asks(tg)
    if not asks:
        return cap
    sets = 0
    sim_in_use = {k: set(v) for k, v in in_use.items()}
    while sets < cap:
        assigned = assign_devices(node, sim_in_use, tg)
        if assigned is None:
            break
        for ad in assigned:
            sim_in_use.setdefault(ad.id(), set()).update(ad.device_ids)
        sets += 1
    return sets


def node_device_affinity(node, tg: TaskGroup) -> tuple[float, bool]:
    """Best-case matched device affinity for the group on this node, used
    as the node-score contribution (rank.go:388-434 adds the assignment's
    matched affinity sum). Mean over asks with affinities."""
    scores = []
    for ask in group_device_asks(tg):
        if not ask.affinities:
            continue
        best = None
        for dev in node.node_resources.devices:
            if device_group_matches(dev, ask):
                s = device_affinity_score(dev, ask)
                best = s if best is None else max(best, s)
        if best is not None:
            scores.append(best)
    if not scores:
        return 0.0, False
    return float(sum(scores) / len(scores)), True
