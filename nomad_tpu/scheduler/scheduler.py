"""Scheduler interfaces and factory registry.

Reference: scheduler/scheduler.go — the ``Scheduler`` interface (:55-60),
the read-only ``State`` seam (:66-110), the write-side ``Planner`` seam
(:113-132), and the ``BuiltinSchedulers`` factory map (:23-28). These two
seams are what keep the whole scheduler package side-effect-free: a state
snapshot goes in, a plan comes out, and everything else (Raft, queues,
RPC) lives behind the Planner.
"""

from __future__ import annotations

from typing import Callable, Optional, Protocol

from ..structs import Evaluation, Plan, PlanResult


class Planner(Protocol):
    """Write-side seam (scheduler/scheduler.go:113-132). submit_plan may
    return a fresher state snapshot when the applier's result carries a
    refresh index (worker.go:585-652)."""

    def submit_plan(self, plan: Plan) -> tuple[PlanResult, Optional[object]]: ...

    def update_eval(self, evaluation: Evaluation) -> None: ...

    def create_eval(self, evaluation: Evaluation) -> None: ...

    def reblock_eval(self, evaluation: Evaluation) -> None: ...


SchedulerFactory = Callable[..., "object"]

BUILTIN_SCHEDULERS: dict[str, SchedulerFactory] = {}


def register_scheduler(name: str):
    def deco(factory):
        BUILTIN_SCHEDULERS[name] = factory
        return factory

    return deco


def new_scheduler(name: str, snapshot, planner: Planner, **kw):
    """Factory dispatch (scheduler.go NewScheduler)."""
    try:
        factory = BUILTIN_SCHEDULERS[name]
    except KeyError:
        raise ValueError(f"unknown scheduler '{name}'") from None
    return factory(snapshot, planner, **kw)
