"""Exact per-node preemption victim selection — the reference-parity host
pass that finishes what the device kernel starts.

Split of labor: device/preempt.py ranks ALL nodes in one vectorized
[N, V] pass (feasibility of freeing room + a preemption-penalty-scaled
fit score); this module then selects the final victim set on a chosen
node with the reference's exact greedy semantics. The candidate sets per
node are tiny (a handful of allocs), so exactness is cheap here while the
10k-node search stays on device.

Reference semantics implemented (scheduler/preemption.go):
- eligibility: victim job priority ≤ job priority − 10
  (filterAndGroupPreemptibleAllocs :663-697), grouped by priority asc;
- victim choice: repeatedly take the candidate minimizing
  ``basicResourceDistance(remaining_need, victim) + maxParallel penalty``
  (PreemptForTaskGroup :198-265, scoreForTaskGroup :640-646,
  maxParallelPenalty = 50 :13, distance :608-624) until the freed +
  node-remaining resources form a superset of the ask;
- redundancy: filterSuperset (:702-733) — re-sort the chosen victims by
  distance to the *original* ask descending (no penalty) and keep the
  minimal prefix that meets requirements;
- reserved ports: allocations holding a reserved port the ask needs MUST
  be preempted; a non-preemptible (priority-delta < 10) holder makes the
  node infeasible (PreemptForNetwork :270-395's reserved-port phase).
  Deviation: the reference tracks bandwidth per NIC device and only
  preempts within one device; this build models one aggregate NIC per
  node (SURVEY §7 hard-parts: port bitmaps stay host-side), so bandwidth
  rides the resource vector's 4th dim through the same distance/superset
  math instead of a per-device phase;
- devices: victims holding matching device instances, taken in priority
  order until freed + free instances cover the ask, choosing the option
  with minimal net unique-priority sum (PreemptForDevice :472-555,
  selectBestAllocs :558-604).
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ..device.preempt import PREEMPTION_PRIORITY_DELTA
from ..structs.resources import _dev_id_matches

MAX_PARALLEL_PENALTY = 50.0  # preemption.go:13


class Candidate:
    """One preemptible allocation on the node under consideration."""

    __slots__ = ("alloc", "priority", "res", "max_parallel", "job_key", "tg")

    def __init__(self, alloc):
        self.alloc = alloc
        self.priority = alloc.job.priority if alloc.job is not None else 50
        self.res = alloc.comparable_resources().to_vector().astype(np.float64)
        self.job_key = (alloc.namespace, alloc.job_id)
        self.tg = alloc.task_group
        mp = 0
        if alloc.job is not None:
            tg = alloc.job.lookup_task_group(alloc.task_group)
            if tg is not None and tg.migrate is not None:
                mp = tg.migrate.max_parallel
        self.max_parallel = mp


def collect_candidates(snap, node_id, job, exclude_ids=frozenset()):
    """Preemptible allocs on a node: non-terminal, not of the placing job
    (SetCandidates :146-163), not already evicted by the in-flight plan,
    and within the priority delta (:663-697)."""
    out = []
    max_prio = job.priority - PREEMPTION_PRIORITY_DELTA
    for a in snap.allocs_by_node(node_id):
        if a.terminal_status() or a.id in exclude_ids:
            continue
        if a.job_id == job.id and a.namespace == job.namespace:
            continue
        c = Candidate(a)
        if c.priority <= max_prio:
            out.append(c)
    return out


def basic_resource_distance(ask: np.ndarray, used: np.ndarray) -> float:
    """preemption.go:608-624 — relative per-dim deltas over cpu/mem/disk
    (dims 0..2; bandwidth is excluded from the basic distance just as the
    reference's basic distance ignores networks)."""
    total = 0.0
    for d in range(3):
        if ask[d] > 0:
            coord = (ask[d] - used[d]) / ask[d]
            total += coord * coord
    return math.sqrt(total)


def _superset(available: np.ndarray, ask: np.ndarray) -> bool:
    return bool(np.all(available + 1e-6 >= ask))


def _alloc_reserved_ports(alloc) -> set[int]:
    ports: set[int] = set()
    job = alloc.job
    if job is None:
        return ports
    tg = job.lookup_task_group(alloc.task_group)
    if tg is None:
        return ports
    for t in tg.tasks:
        for net in t.resources.networks:
            ports.update(net.reserved_ports)
    return ports


def preempt_for_ports(
    snap, node_id, job, ask_ports: set[int], exclude_ids=frozenset()
) -> Optional[list[Candidate]]:
    """Reserved-port phase (PreemptForNetwork :280-395): holders of needed
    ports must go; a high-priority holder makes the node infeasible
    (returns None). Empty list = no port conflicts."""
    if not ask_ports:
        return []
    victims: dict[str, Candidate] = {}
    max_prio = job.priority - PREEMPTION_PRIORITY_DELTA
    for a in snap.allocs_by_node(node_id):
        if a.terminal_status() or a.id in exclude_ids:
            continue
        if a.job_id == job.id and a.namespace == job.namespace:
            continue
        held = _alloc_reserved_ports(a)
        if not (held & ask_ports):
            continue
        c = Candidate(a)
        if c.priority > max_prio:
            return None  # un-preemptible holder (filteredReservedPorts)
        victims[a.id] = c
    return list(victims.values())


def preempt_for_task_group(
    capacity: np.ndarray,
    used: np.ndarray,
    ask: np.ndarray,
    candidates: list[Candidate],
    prior_counts: Optional[dict] = None,
    already_chosen: Optional[list[Candidate]] = None,
) -> Optional[list[Candidate]]:
    """PreemptForTaskGroup (:198-265) + filterSuperset (:702-733), exact.

    ``prior_counts`` maps (job_key, tg) → allocs of that group already
    preempted by the in-flight plan (SetPreemptions :166-183; the penalty
    is NOT updated for picks within this call, matching getNumPreemptions
    reading only the plan). ``already_chosen`` seeds the freed pool with
    victims selected by an earlier phase (ports)."""
    prior_counts = prior_counts or {}
    chosen: list[Candidate] = list(already_chosen or [])
    chosen_ids = {c.alloc.id for c in chosen}
    ask = ask.astype(np.float64)
    node_remaining = (capacity - used).astype(np.float64)

    available = node_remaining.copy()
    for c in chosen:
        available = available + c.res
    if _superset(available, ask):
        return _filter_superset(chosen, node_remaining, ask)

    needed = ask.copy()
    for c in chosen:
        needed = needed - c.res

    by_prio: dict[int, list[Candidate]] = {}
    for c in candidates:
        if c.alloc.id in chosen_ids:
            continue
        by_prio.setdefault(c.priority, []).append(c)

    met = False
    for prio in sorted(by_prio):
        grp = by_prio[prio]
        while grp and not met:
            best_i, best_score = -1, float("inf")
            for i, c in enumerate(grp):
                n_pre = prior_counts.get((c.job_key, c.tg), 0)
                penalty = 0.0
                if c.max_parallel > 0 and n_pre >= c.max_parallel:
                    penalty = ((n_pre + 1) - c.max_parallel) * MAX_PARALLEL_PENALTY
                score = basic_resource_distance(needed, c.res) + penalty
                if score < best_score:
                    best_score, best_i = score, i
            c = grp.pop(best_i)
            chosen.append(c)
            available = available + c.res
            needed = needed - c.res
            met = _superset(available, ask)
        if met:
            break
    if not met:
        return None
    return _filter_superset(chosen, node_remaining, ask)


def _filter_superset(
    chosen: list[Candidate], node_remaining: np.ndarray, ask: np.ndarray
) -> list[Candidate]:
    """filterSuperset (:702-733): distance-descending vs the ORIGINAL ask,
    keep the minimal prefix meeting requirements."""
    ordered = sorted(
        chosen,
        key=lambda c: basic_resource_distance(ask, c.res),
        reverse=True,
    )
    available = node_remaining.copy()
    out = []
    for c in ordered:
        out.append(c)
        available = available + c.res
        if _superset(available, ask):
            break
    return out


def preempt_for_devices(
    snap, node, job, tg, exclude_ids=frozenset()
) -> Optional[list[Candidate]]:
    """PreemptForDevice (:472-555): per device ask, free held instances by
    preempting their holders in priority order; among sufficient options
    pick minimal net unique-priority (selectBestAllocs :558-604).
    Returns None when an ask can't be covered even with preemption."""
    from .device import collect_in_use, device_group_matches, group_device_asks

    asks = group_device_asks(tg)
    if not asks:
        return []
    max_prio = job.priority - PREEMPTION_PRIORITY_DELTA
    live = [
        a
        for a in snap.allocs_by_node(node.id)
        if not a.terminal_status()
        and a.id not in exclude_ids
        and not (a.job_id == job.id and a.namespace == job.namespace)
    ]
    in_use = collect_in_use(live)
    victims: dict[str, Candidate] = {}
    for ask in asks:
        # free instances per matching device group
        options = []
        for dev in node.node_resources.devices:
            if not device_group_matches(dev, ask):
                continue
            did = dev.id()
            held = in_use.get(did, set())
            free = sum(
                1 for i in dev.instances if i.healthy and i.id not in held
            )
            if free >= ask.count:
                options = []  # no preemption needed for this ask
                break
            # holders of this device's instances, priority-grouped
            holders: list[tuple[Candidate, int]] = []
            for a in live:
                ids = a.device_instance_ids().get(did)
                n = len(ids) if ids else a.device_asks().get(did, 0)
                if not n:
                    # partial-id asks (e.g. bare "gpu") also hold instances
                    for aid, cnt in a.device_asks().items():
                        if _dev_id_matches(did, aid):
                            n = cnt
                            break
                if n:
                    c = Candidate(a)
                    if c.priority <= max_prio:
                        holders.append((c, n))
            holders.sort(key=lambda h: h[0].priority)
            freed, option = 0, []
            for c, n in holders:
                freed += n
                option.append((c, n))
                if freed + free >= ask.count:
                    options.append((option, free))
                    break
        else:
            if not options:
                return None  # ask cannot be covered on this node
            # minimal net unique-priority option (selectBestAllocs).
            # Deviation: the reference filter counts preempted instances
            # against the FULL ask (selectBestAllocs :558-604), evicting
            # holders whose instances the device's already-free pool
            # could cover; we count against (ask − free), which frees the
            # same capacity with strictly fewer evictions.
            best, best_net = None, None
            for option, dev_free in options:
                option.sort(key=lambda h: -h[1])  # instance count desc
                taken, count, prios = [], 0, set()
                need = max(ask.count - dev_free, 0)
                for c, n in option:
                    if count >= need:
                        break
                    taken.append(c)
                    count += n
                    prios.add(c.priority)
                net = sum(prios)
                if best_net is None or net < best_net:
                    best_net, best = net, taken
            for c in best or []:
                victims[c.alloc.id] = c
    return list(victims.values())


def select_victims(
    ct,
    snap,
    job,
    tg,
    ask_vec: np.ndarray,
    row: int,
    plan=None,
    exclude_ids=frozenset(),
) -> Optional[list]:
    """Full exact victim selection on one node: port phase → device phase
    → resource phase, all sharing one freed pool. Returns alloc-id list
    or None when the node can't be made to fit."""
    node_id = ct.node_ids[row]
    node = snap.node_by_id(node_id)
    if node is None:
        return None

    ask_ports: set[int] = set()
    for t in tg.tasks:
        for net in t.resources.networks:
            ask_ports.update(net.reserved_ports)

    port_victims = preempt_for_ports(
        snap, node_id, job, ask_ports, exclude_ids
    )
    if port_victims is None:
        return None
    dev_victims = preempt_for_devices(snap, node, job, tg, exclude_ids)
    if dev_victims is None:
        return None
    seed = {c.alloc.id: c for c in port_victims}
    for c in dev_victims:
        seed.setdefault(c.alloc.id, c)

    prior_counts: dict = {}
    if plan is not None:
        for allocs in plan.node_preemptions.values():
            for a in allocs:
                victim = snap.alloc_by_id(a.id) or a
                key = ((victim.namespace, victim.job_id), victim.task_group)
                prior_counts[key] = prior_counts.get(key, 0) + 1

    candidates = collect_candidates(snap, node_id, job, exclude_ids)
    chosen = preempt_for_task_group(
        np.asarray(ct.capacity[row], dtype=np.float64),
        np.asarray(ct.used[row], dtype=np.float64),
        np.asarray(ask_vec, dtype=np.float64),
        candidates,
        prior_counts=prior_counts,
        already_chosen=list(seed.values()),
    )
    if chosen is None:
        return None
    # device/port victims are mandatory even if the resource pass's
    # superset filter would drop them
    ids = [c.alloc.id for c in chosen]
    for aid in seed:
        if aid not in ids:
            ids.append(aid)
    return ids
