"""SchedulerAlgorithm plugin registry — the one seam for kernel dispatch.

The reference hard-codes two algorithms behind a config enum
(SchedulerConfiguration.SchedulerAlgorithm, nomad/structs/operator.go);
this build turns that enum into a registry so heterogeneity policies
(scheduler/hetero.py) and future experiments plug in without touching
the schedulers. Mirrors the ``register_scheduler``/BUILTIN_SCHEDULERS
idiom one layer up (scheduler/scheduler.py) at the kernel layer.

Everything that dispatches a placement kernel or the dense score matrix
MUST route through this module — enforced by lint rule NTA013: direct
``PlacementKernel(...)``/``score_matrix_kernel(...)`` calls inside
scheduler/server modules are findings. The payoffs: algorithm names
validate in ONE place (api/http.py asks ``available()``), the CP
dispatcher (ROADMAP item 5) inherits new policies for free, and the
registry is where per-algorithm host oracles pair with their device
kernels for parity pinning.
"""

from __future__ import annotations

import numpy as np


class UnknownAlgorithmError(ValueError):
    """Raised for algorithm names nothing registered (API surfaces 400)."""


ALGORITHMS: dict[str, "SchedulerAlgorithm"] = {}


class SchedulerAlgorithm:
    """One registered placement algorithm: a name plus a kernel factory.

    ``make_kernel`` must return an object with the PlacementKernel
    ``place(cluster, asks, **kwargs) -> list[PlacementResult]`` contract
    (device/score.py); the generic scheduler treats all algorithms
    uniformly through it.
    """

    name: str = ""
    description: str = ""
    # hetero algorithms only differentiate on fleets with device classes;
    # the API surfaces this so operators know what a selection changes
    requires_device_classes: bool = False

    def make_kernel(self, force_scan: bool = False, mesh=None):
        """``mesh`` is a utils.backend.MeshConfig override; None means
        the kernel binds the process-wide mesh (get_mesh()) — the seam
        through which the production scheduler path inherits multi-chip
        sharding without any per-scheduler wiring."""
        raise NotImplementedError


def register_algorithm(cls):
    """Class decorator: instantiate and index by ``name`` (last wins,
    like register_scheduler — tests override with instrumented doubles)."""
    inst = cls()
    if not inst.name:
        raise ValueError("SchedulerAlgorithm needs a non-empty name")
    ALGORITHMS[inst.name] = inst
    return cls


def available() -> list[str]:
    return sorted(ALGORITHMS)


def is_registered(name: str) -> bool:
    return name in ALGORITHMS


def get_algorithm(name: str) -> SchedulerAlgorithm:
    algo = ALGORITHMS.get(name)
    if algo is None:
        raise UnknownAlgorithmError(
            f"unknown scheduler algorithm {name!r}; "
            f"available: {', '.join(available())}"
        )
    return algo


def make_kernel(name: str, force_scan: bool = False, mesh=None):
    """The factory seam: scheduler_algorithm config string → kernel."""
    return get_algorithm(name).make_kernel(force_scan, mesh=mesh)


# -- built-ins ---------------------------------------------------------------


@register_algorithm
class BinpackAlgorithm(SchedulerAlgorithm):
    name = "binpack"
    description = "maximize per-node utilization (reference default)"

    def make_kernel(self, force_scan: bool = False, mesh=None):
        from ..device.score import PlacementKernel

        return PlacementKernel("binpack", force_scan, mesh=mesh)


@register_algorithm
class SpreadAlgorithm(SchedulerAlgorithm):
    name = "spread"
    description = "prefer empty nodes (inverse binpack fit)"

    def make_kernel(self, force_scan: bool = False, mesh=None):
        from ..device.score import PlacementKernel

        return PlacementKernel("spread", force_scan, mesh=mesh)


class _HeteroAlgorithm(SchedulerAlgorithm):
    requires_device_classes = True
    policy = ""

    def make_kernel(self, force_scan: bool = False, mesh=None):
        from .hetero import HeteroPlacementKernel

        return HeteroPlacementKernel(self.policy, force_scan, mesh=mesh)


@register_algorithm
class HeteroMaxMinAlgorithm(_HeteroAlgorithm):
    name = "hetero-maxmin"
    policy = "maxmin"
    description = "max-min fair normalized throughput across jobs (Gavel)"


@register_algorithm
class HeteroMakespanAlgorithm(_HeteroAlgorithm):
    name = "hetero-makespan"
    policy = "makespan"
    description = "minimize modeled batch makespan (LPT on class rates)"


@register_algorithm
class HeteroCostAlgorithm(_HeteroAlgorithm):
    name = "hetero-cost"
    policy = "cost"
    description = "maximize throughput per device-class cost"


@register_algorithm
class CpPackAlgorithm(SchedulerAlgorithm):
    name = "cp-pack"
    description = (
        "whole-batch joint placement: assignment relaxation over the "
        "score matrix, solved on device by iterated proportional rounding"
    )

    def make_kernel(self, force_scan: bool = False, mesh=None):
        from .cp import CpPlacementKernel

        return CpPlacementKernel(force_scan, mesh=mesh)


@register_algorithm
class CpGangAlgorithm(SchedulerAlgorithm):
    name = "cp-gang"
    description = (
        "cp-pack plus all-or-nothing gangs: topology-priced co/anti-"
        "location with atomic release of incomplete gangs"
    )

    def make_kernel(self, force_scan: bool = False, mesh=None):
        from .cp import CpGangPlacementKernel

        return CpGangPlacementKernel(force_scan, mesh=mesh)


# -- registry-routed score matrix -------------------------------------------


def score_group(
    ct,
    ga,
    desired_total: float,
    algorithm_spread: bool = False,
    explain: bool = False,
):
    """Dense score row for one flattened group ask — the registry-routed
    wrapper over score_matrix_kernel for matrix consumers (system
    scheduler, annotation). Feeds the heterogeneity axis when the ask
    carries one: coefficients normalize by the job's best eligible class
    so the score term lands in [0, 1] like every other component.

    Returns (finals f32[N], fits bool[N]) as numpy; with ``explain``
    (Python-gated like the throughput ``None`` gate: the kernel call
    below is untouched either way) the return grows a third element, an
    ``obs.explain.PlacementExplanation`` carrying top-k candidates and
    the feasibility-rejection histogram."""
    from ..device.score import score_matrix_kernel, used_device
    from ..utils.backend import get_mesh, shard_put

    cfg = get_mesh()
    throughputs = None
    if ga.has_throughputs and ga.throughputs is not None:
        tp = ga.throughputs.astype(np.float32)
        best = float(np.max(np.where(ga.eligible, tp, 0.0)))
        if best > 0.0:
            throughputs = (tp / np.float32(best))[None, :]
    finals, fits = score_matrix_kernel(
        shard_put(np.asarray(ct.capacity), ("nodes",), cfg),
        used_device(ct, np.asarray(ct.used), cfg),
        shard_put(ga.ask[None, :], ("groups",), cfg),
        shard_put(ga.eligible[None, :], ("groups", "nodes"), cfg),
        shard_put(ga.job_counts[None, :], ("groups", "nodes"), cfg),
        np.array([float(max(desired_total, 1))], dtype=np.float32),
        shard_put(ga.penalty_nodes[None, :], ("groups", "nodes"), cfg),
        shard_put(ga.affinity_scores[None, :], ("groups", "nodes"), cfg),
        np.array([ga.has_affinities]),
        np.array([ga.distinct_hosts]),
        np.asarray(algorithm_spread),
        None
        if throughputs is None
        else shard_put(throughputs, ("groups", "nodes"), cfg),
    )
    if not explain:
        return np.asarray(finals)[0], np.asarray(fits)[0]
    from ..obs.explain import explain_group

    ex = explain_group(
        ct,
        ga,
        np.asarray(ct.used),
        algorithm="spread" if algorithm_spread else "binpack",
        algorithm_spread=algorithm_spread,
        throughputs=throughputs[0] if throughputs is not None else None,
        desired_total=float(max(desired_total, 1)),
    )
    return np.asarray(finals)[0], np.asarray(fits)[0], ex
