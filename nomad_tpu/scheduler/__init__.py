"""L2 scheduler layer: pure business logic — snapshot in, plan out.

Importing this package registers the builtin schedulers
(service/batch/system/sysbatch), mirroring BuiltinSchedulers
(scheduler/scheduler.go:23-28)."""

from .scheduler import BUILTIN_SCHEDULERS, Planner, new_scheduler, register_scheduler
from .reconcile import (
    PlaceRequest,
    ReconcileResults,
    StopRequest,
    reconcile,
    tasks_updated,
)
from .generic import GenericScheduler, tainted_nodes
from .system import SystemScheduler
from .feasible import check_constraint, check_constraint_values
from .testing import Harness

__all__ = [
    "BUILTIN_SCHEDULERS",
    "Planner",
    "new_scheduler",
    "register_scheduler",
    "reconcile",
    "tasks_updated",
    "PlaceRequest",
    "StopRequest",
    "ReconcileResults",
    "GenericScheduler",
    "SystemScheduler",
    "tainted_nodes",
    "check_constraint",
    "check_constraint_values",
    "Harness",
]
