"""GenericScheduler — service and batch job scheduling with the TPU
placement backend.

Reference control flow: scheduler/generic_sched.go — Process (:125) retry
loop, process (:216), computeJobAllocs (:332), computePlacements (:472),
blocked-eval creation (:193-212), attempt limits (:15-22: 5 service /
2 batch). The per-placement iterator walk the reference does inside
computePlacements is replaced wholesale by one batched device kernel call
per (job, task group): flatten → greedy placement scan on device → build
allocations from the chosen rows (SURVEY.md §7 steps 3+5).
"""

from __future__ import annotations

import time
from typing import Optional

from ..device import flatten_group_ask
from ..device.cache import DeviceStateCache
from .algorithms import make_kernel
from ..obs.trace import global_tracer as tracer
from ..structs import (
    ALLOC_DESIRED_RUN,
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
    Allocation,
    AllocMetric,
    ComparableResources,
    Evaluation,
    Plan,
    TRIGGER_MAX_PLANS,
    new_id,
)
from ..structs.evaluation import (
    EVAL_STATUS_BLOCKED,
    TRIGGER_JOB_REGISTER,
    TRIGGER_QUEUED_ALLOCS,
)
from .reconcile import reconcile
from .scheduler import Planner, register_scheduler

MAX_SERVICE_SCHEDULE_ATTEMPTS = 5  # generic_sched.go:15-18
MAX_BATCH_SCHEDULE_ATTEMPTS = 2  # generic_sched.go:19-22

BLOCKED_EVAL_MAX_PLAN_DESC = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENTS_DESC = "created to place remaining allocations"


class FailedTGAlloc:
    """Per-group placement-failure metrics attached to the eval
    (structs.AllocMetric in Evaluation.FailedTGAllocs)."""

    def __init__(self, metric: AllocMetric):
        self.metric = metric


def wire_throughput_source(kernel, cfg) -> None:
    """Calibration seam: in learned mode the hetero kernel reads the
    process-global ThroughputEstimator instead of declared jobspec
    coefficients. Same Python-level gating discipline as explain —
    "declared" (the default, and every non-hetero kernel) touches
    nothing, so the pre-calibration path stays bit-identical."""
    if (
        getattr(cfg, "throughput_source", "declared") == "learned"
        and hasattr(kernel, "throughput_source")
    ):
        from ..obs.calibrate import global_estimator

        kernel.throughput_source = "learned"
        kernel.estimator = global_estimator


def tainted_nodes(snapshot, allocs) -> dict:
    """Map node id → Node for nodes that are down or draining
    (scheduler/util.go:354-378). Nodes missing from state count as tainted
    (down)."""
    out = {}
    for a in allocs:
        if a.node_id in out:
            continue
        node = snapshot.node_by_id(a.node_id)
        if node is None:
            from ..structs import Node, NODE_STATUS_DOWN

            out[a.node_id] = Node(id=a.node_id, status=NODE_STATUS_DOWN)
        elif node.terminal_status() or node.drain is not None or not node.ready():
            if node.status != "initializing":
                out[a.node_id] = node
    return out


@register_scheduler("service")
@register_scheduler("batch")
class GenericScheduler:
    def __init__(
        self,
        snapshot,
        planner: Planner,
        *,
        batch: bool = False,
        cache=None,
        overlay=None,
        clock=None,
        node_filter=None,
    ):
        self.snapshot = snapshot
        self.planner = planner
        self.batch = batch
        # injectable clock: every wall-time the scheduler stamps into a
        # plan (deployment deadlines, followup-eval times, reschedule
        # events) reads this, so replaying an eval stream against a fixed
        # clock reproduces byte-identical plans (NTA001 enforces it)
        self.clock = clock if clock is not None else time.time
        # resident device-state cache — per-server in production (the
        # worker threads share it); a private one here keeps standalone
        # scheduler construction working
        self.cache = cache if cache is not None else DeviceStateCache()
        # server-shared optimistic overlay (server/overlay.py): single-
        # eval processing runs CONCURRENTLY with pipelined batch commits
        # (fallback evals execute inside commit threads), so an
        # overlay-blind single pass seeds the very conflicts it was
        # retrying — it must score against, and reserve into, the same
        # in-flight accounting as the batched passes
        self.overlay = overlay
        # optional eligibility restriction: callable(ct) → bool[padded_n]
        # row mask ANDed into every ask. Lane mode uses it to keep a
        # batch worker's solo fallback inside its own lanes (a solo
        # plan has no cross-lane handoff, so foreign nodes are out);
        # shortfalls become blocked evals, never foreign-node writes.
        self.node_filter = node_filter
        # any registered algorithm's kernel (scheduler/algorithms.py) —
        # all satisfy the PlacementKernel.place contract
        self.kernel = None
        self.eval: Optional[Evaluation] = None
        self.job = None
        self.plan: Optional[Plan] = None
        self.failed_tg_allocs: dict[str, AllocMetric] = {}
        self.queued_allocs: dict[str, int] = {}
        self.followup_evals: list[Evaluation] = []
        self.blocked: Optional[Evaluation] = None

    # -- entry point ------------------------------------------------------
    def process(self, evaluation: Evaluation) -> None:
        """Retry loop (generic_sched.go:125-214)."""
        self.eval = evaluation
        self.batch = self.batch or evaluation.type == "batch"
        limit = (
            MAX_BATCH_SCHEDULE_ATTEMPTS
            if self.batch
            else MAX_SERVICE_SCHEDULE_ATTEMPTS
        )
        cfg = self.snapshot.scheduler_config()
        self.scheduler_config = cfg
        self.kernel = make_kernel(cfg.scheduler_algorithm)
        wire_throughput_source(self.kernel, cfg)
        self._explain = bool(getattr(cfg, "placement_explanations", True))

        success = False
        for _attempt in range(limit):
            done, reschedule = self._process_once()
            if done:
                success = True
                break
            if not reschedule:
                break
        if not success and not self._finished:
            # max plan attempts: mark failed, roll a new blocked eval so the
            # job eventually converges (generic_sched.go:156-193)
            self._set_status(EVAL_STATUS_FAILED, "maximum attempts reached")
            blocked = evaluation.create_blocked_eval({}, True, "", {})
            blocked.triggered_by = TRIGGER_MAX_PLANS
            blocked.status_description = BLOCKED_EVAL_MAX_PLAN_DESC
            self.planner.create_eval(blocked)
            return
        self._finalize()

    _finished = False

    # -- one attempt ------------------------------------------------------
    def _process_once(self) -> tuple[bool, bool]:
        """Returns (done, should_retry)."""
        placements = self._start_attempt()
        if placements and self.job is not None:
            ct, tg_order = self._build_group_asks(placements)
            asks = [t[3] for t in tg_order]
            if self.node_filter is not None and asks:
                mask = self.node_filter(ct)
                for a in asks:
                    a.eligible &= mask
            used_override = None
            if self.overlay is not None:
                used_override = self.overlay.begin_pass(ct)
            try:
                with tracer.span(
                    "kernel_score",
                    tags={"lanes": len(asks), "explain": self._explain},
                ):
                    results = self.kernel.place(
                        ct, asks, used_override=used_override,
                        explain=self._explain,
                    )
                    # the repair walk is also the single-eval safety net:
                    # it resolves cross-TG conflicts within this plan and
                    # re-places kernel shortfalls (e.g. chunked-path
                    # truncation) by exact host re-score before they read
                    # as placement failures
                    from ..device.score import repair_batch_conflicts

                    repair_batch_conflicts(
                        ct, asks, results,
                        algorithm_spread=self.kernel.algorithm_spread,
                        # single-eval: no fresh state to re-run against,
                        # so an unplaceable placement fails into the
                        # blocked-eval accounting instead of aborting the
                        # lane
                        fail_on_contention=True,
                        used_override=used_override,
                    )
                    if self._explain:
                        # repair moves rows in place, so provenance is
                        # stamped from the POST-repair (= committed) rows
                        from ..obs.explain import finalize_explanations

                        finalize_explanations(
                            ct, asks, results, used_override=used_override
                        )
                if self.overlay is not None:
                    for a, res in zip(asks, results):
                        rows = res.node_rows[res.node_rows >= 0]
                        if rows.size:
                            self.overlay.add_delta(ct, rows, a.ask)
                self._finish_placements(ct, tg_order, results)
                self._adjust_queued()
                # the pass marker is held through plan SUBMISSION: once
                # released with the commit not yet applied, a concurrent
                # worker's maybe_reset() could drop the overlay while
                # these placements are still only predictions
                return self._submit_attempt()
            finally:
                if self.overlay is not None:
                    self.overlay.pass_finished()
        return self._submit_attempt()

    # -- batched multi-eval pass (SURVEY.md §7 step 5) --------------------
    def prepare_batch_attempt(self, evaluation: Evaluation, ct=None):
        """Phase A of a batched multi-eval device pass: run the host side
        (reconcile + flatten) and return this eval's group asks for the
        caller to merge into one kernel call across evals — the batch
        dimension replacing the reference's worker-per-core concurrency
        (nomad/worker.go:85, SURVEY.md §2.7).

        ``ct`` is the batch-shared ClusterTensors the caller fetched ONCE
        for the whole batch: every eval's masks must be built against the
        same row order as the capacity/used arrays of the combined kernel
        call (a mid-batch cache-generation advance would otherwise hand
        later evals a differently-ordered transient build).

        Returns the list of GroupAsks, or None when the eval needs the
        individual path: no placement work at all, or a plan whose
        evictions couple placements to freed capacity (the in-plan used
        overlay is eval-local and can't share one batched ``used0``).
        """
        self.eval = evaluation
        self.batch = self.batch or evaluation.type == "batch"
        cfg = self.snapshot.scheduler_config()
        self.scheduler_config = cfg
        self.kernel = make_kernel(cfg.scheduler_algorithm)
        wire_throughput_source(self.kernel, cfg)
        self._explain = bool(getattr(cfg, "placement_explanations", True))
        placements = self._start_attempt()
        if not placements or self.job is None:
            return None
        if self.plan.node_update or self.plan.node_preemptions:
            return None  # evictions free capacity only for this eval's plan
        ct, tg_order = self._build_group_asks(placements, ct=ct)
        self._batch_ctx = (ct, tg_order)
        return [t[3] for t in tg_order]

    def complete_batch_attempt(self, results) -> bool:
        """Phase B: consume this eval's slice of the combined kernel
        results. Returns True when the eval is fully handled (plan
        committed, eval finalized); False when the caller must fall back
        to the individual retry path on a fresh scheduler (partial
        commit against the optimistic shared snapshot)."""
        plan = self.build_batch_plan(results)
        if plan is None:
            return True
        result, new_snap = self.planner.submit_plan(plan)
        return self.complete_merged_attempt(result, new_snapshot=new_snap)

    def build_batch_plan(self, results) -> Optional[Plan]:
        """Phase B1 of the coalesced commit path: consume this eval's
        slice of the combined kernel results and hand back the plan for
        the worker to merge into ONE batch submit. Creates any followup
        evals eagerly (their ids are referenced by in-plan allocs, so
        they must commit before the plan does). Returns None when there
        is nothing to submit — the eval is finalized in place."""
        ct, tg_order = self._batch_ctx
        self._finish_placements(ct, tg_order, results)
        self._adjust_queued()
        if self.plan.is_no_op() and not self.followup_evals:
            self._finished = True
            self._finalize()
            return None
        for f in self.followup_evals:
            self.planner.create_eval(f)
        return self.plan

    def complete_merged_attempt(self, result, new_snapshot=None) -> bool:
        """Phase B2: consume this member's PlanResult from the merged
        apply. Full commit → finalize, True. Partial commit (this member
        went stale under the shared optimistic snapshot) → False: the
        caller retries the eval individually on fresh state; batch
        siblings are unaffected."""
        if new_snapshot is not None:
            self.snapshot = new_snapshot
        full, _expected, _actual = result.full_commit(self.plan)
        if not full:
            return False
        self._finished = True
        self._finalize()
        return True

    def _start_attempt(self):
        """Host-side first half of one attempt: reconcile and build the
        plan's stops/updates; returns the placements list."""
        ev = self.eval
        self.failed_tg_allocs = {}
        self.explanations = {}  # tg_name → PlacementExplanation
        self.followup_evals = []
        self._preempt_rank_cache = {}  # per-attempt: ct/used change
        self.job = self.snapshot.job_by_id(ev.namespace, ev.job_id)
        self.plan = ev.make_plan(self.job)
        self.plan.snapshot_index = getattr(self.snapshot, "index", 0)

        existing = self.snapshot.allocs_by_job(ev.namespace, ev.job_id)
        tainted = tainted_nodes(self.snapshot, existing)
        deployment = self.snapshot.latest_deployment_by_job(
            ev.namespace, ev.job_id
        )
        results = reconcile(
            self.job,
            ev.job_id,
            existing,
            tainted,
            batch=self.batch,
            now_ns=int(self.clock() * 1e9),
            deployment=deployment,
        )

        # deployment lifecycle (reconcile.go + deploymentwatcher semantics):
        # create one for a gated rollout; cancel one superseded by a newer
        # job version
        self.deployment = None
        if deployment is not None and deployment.active() and self.job is not None:
            if deployment.job_version == self.job.version:
                self.deployment = deployment
            else:
                from ..structs.deployment import DESC_NEW_VERSION

                self.plan.deployment_updates.append(
                    {
                        "deployment_id": deployment.id,
                        "status": "cancelled",
                        "description": DESC_NEW_VERSION,
                    }
                )
        if results.deployment_states and self.job is not None:
            from ..structs.deployment import Deployment

            now = self.clock()
            for s in results.deployment_states.values():
                s.require_progress_by_unix = now + s.progress_deadline_s
            new_d = Deployment(
                namespace=self.job.namespace,
                job_id=self.job.id,
                job_version=self.job.version,
                task_groups=dict(results.deployment_states),
            )
            self.plan.deployment = new_d
            self.deployment = new_d

        # stops
        for stop in results.stop:
            self.plan.append_stopped_alloc(
                stop.alloc, stop.reason, stop.client_status
            )
        # in-place updates: same node, new job version
        for upd in results.inplace_update:
            a = upd.alloc.copy_for_update()
            a.job = upd.new_job
            a.job_version = upd.new_job.version
            self.plan.append_alloc(a)
        # destructive updates: stop old + place new
        destructive_places = []
        for old, pr in results.destructive_update:
            self.plan.append_stopped_alloc(
                old, "alloc updated in-place failed; destructive update"
            )
            destructive_places.append(pr)

        placements = results.place + destructive_places

        # delayed reschedules become followup evals (generic_sched.go:718-753);
        # the failed alloc is updated in-plan with followup_eval_id so later
        # reconciles don't spawn duplicates (reconcile.py checks it)
        now = self.clock()
        by_delay: dict[float, Evaluation] = {}
        for alloc, delay in results.disconnect_followups:
            f = by_delay.get(delay)
            if f is None:
                f = ev.create_failed_follow_up_eval(delay, now)
                by_delay[delay] = f
                self.followup_evals.append(f)
            linked = alloc.copy_for_update()
            linked.followup_eval_id = f.id
            self.plan.append_alloc(linked)

        # baseline queued = everything this eval will try to place (fresh
        # placements AND destructive replacements, both in ``placements``)
        self.queued_allocs = {
            tg: c["place"] + c["destructive_update"]
            for tg, c in results.desired_tg_updates.items()
        }
        return placements

    def _adjust_queued(self) -> None:
        """queued = what we could NOT place (adjustQueuedAllocations,
        scheduler/util.go:954 — planned allocs are subtracted)."""
        placed_per_tg: dict[str, int] = {}
        for allocs in self.plan.node_allocation.values():
            for a in allocs:
                if a.eval_id == self.eval.id and a.client_status == "pending":
                    placed_per_tg[a.task_group] = (
                        placed_per_tg.get(a.task_group, 0) + 1
                    )
        for tg in list(self.queued_allocs):
            self.queued_allocs[tg] = max(
                0, self.queued_allocs[tg] - placed_per_tg.get(tg, 0)
            )

    def _submit_attempt(self) -> tuple[bool, bool]:
        """Second half of one attempt: no-op check → submit → full-commit
        check. Returns (done, should_retry)."""
        if self.plan.is_no_op() and not self.followup_evals:
            self._finished = True
            return True, False

        for f in self.followup_evals:
            self.planner.create_eval(f)
        # link placements awaiting delayed evals
        result, new_snap = self.planner.submit_plan(self.plan)
        if new_snap is not None:
            self.snapshot = new_snap

        full, expected, actual = result.full_commit(self.plan)
        if not full:
            # partial commit — retry against refreshed state
            return False, True
        self._finished = True
        return True, False

    # -- placement via the device kernel ---------------------------------
    def _build_group_asks(self, placements, ct=None):
        """Flatten this eval's placements into dense group asks against
        the resident tensors (replaces computePlacements' per-alloc
        stack.Select walk). Returns (ct, tg_order). ``ct`` lets a batch
        caller supply one shared tensors object for all evals."""
        snap = self.snapshot
        if ct is None:
            ct = self.cache.tensors(snap)
        nodes_sorted = ct.nodes
        # overlay this plan's own stops (evicted allocs free capacity)
        for node_id, stops in self.plan.node_update.items():
            row = ct.node_row.get(node_id)
            if row is None:
                continue
            for a in stops:
                ct.used[row] -= a.comparable_resources().to_vector()

        # group placements by task group
        by_tg: dict[str, list] = {}
        for pr in placements:
            by_tg.setdefault(pr.task_group.name, []).append(pr)

        tg_order = []
        for tg_name, prs in by_tg.items():
            tg = self.job.lookup_task_group(tg_name)
            penalty_nodes = {
                pr.reschedule_penalty_node
                for pr in prs
                if pr.reschedule_penalty_node
            }
            ga = flatten_group_ask(
                ct,
                snap,
                self.job,
                tg,
                len(prs),
                nodes_sorted=nodes_sorted,
                penalty_node_ids=penalty_nodes,
                plan=self.plan,
            )
            tg_order.append((tg_name, prs, tg, ga))
        return ct, tg_order

    def _finish_placements(self, ct, tg_order, results) -> None:
        """Consume kernel results: build allocations, run the preemption
        fallback for failures, record metrics."""
        # per-DC ready-node counts walk the whole cluster — filled once
        # per cache generation into the shared dc_ready_counts dict (see
        # ClusterTensors; profiled at 450k ready() calls per 75-eval
        # commit window without it). Mutated in place: rebinding would
        # only update this call's wrapper object.
        nodes_available = ct.dc_ready_counts
        if not nodes_available:
            for n in ct.nodes:
                if n.ready():
                    nodes_available[n.datacenter] = (
                        nodes_available.get(n.datacenter, 0) + 1
                    )
        from .device import group_device_asks

        for (tg_name, prs, tg, ga), res in zip(tg_order, results):
            explanation = getattr(res, "explanation", None)
            if explanation is not None:
                self.explanations[tg_name] = explanation
            instance_meta = getattr(explanation, "instance_meta", None)
            ask_res = tg.combined_resources()
            comparable = ComparableResources(
                cpu=ask_res.cpu,
                memory_mb=ask_res.memory_mb,
                disk_mb=ask_res.disk_mb,
                bandwidth_mbits=ask_res.bandwidth_mbits(),
            )
            # device assignment is per-ALLOC; skip the whole path for the
            # common deviceless group (profiled at 23µs × every alloc)
            tg_has_devices = bool(group_device_asks(tg))
            for i, (pr, row, score) in enumerate(
                zip(prs, res.node_rows, res.scores)
            ):
                metric = AllocMetric(
                    nodes_evaluated=ct.num_nodes,
                    nodes_available=dict(nodes_available),
                )
                if row < 0:
                    # second pass with preemption enabled
                    # (generic_sched.go:773-792 selectNextOption)
                    placed = self._try_preempt(ct, pr, tg_name, ga, comparable)
                    if placed:
                        continue
                    metric.coalesced_failures = 0
                    # explainability: why nodes were filtered/exhausted
                    # (AllocMetric, structs.go:10034-10079)
                    fs = ga.filter_stats
                    metric.nodes_filtered = fs.get("nodes_filtered", 0)
                    metric.constraint_filtered = dict(
                        fs.get("constraint_filtered", {})
                    )
                    metric.class_filtered = dict(fs.get("class_filtered", {}))
                    self._record_exhaustion(metric, ct, ga)
                    if explanation is not None:
                        # near-miss table + structured rejection histogram
                        # ride the failed metric into the blocked eval
                        from ..obs.explain import candidates_as_score_meta

                        metric.score_meta = candidates_as_score_meta(
                            explanation
                        )
                        metric.rejections = dict(explanation.rejections)
                    self._record_failure(tg_name, metric)
                    continue
                node_id = ct.node_ids[row]
                metric.scores[f"{node_id}.score"] = float(score)
                if instance_meta is not None and instance_meta[i] is not None:
                    # this alloc's own per-component breakdown (the
                    # reference's ScoreMetaData row for the winner)
                    metric.score_meta = [instance_meta[i]]
                devices, dev_ok = (
                    self._assign_devices(tg, node_id)
                    if tg_has_devices
                    else (None, True)
                )
                if not dev_ok:
                    # slot_caps are snapshot-scoped; a sibling group in
                    # this same plan took the instances. Fail the
                    # placement rather than shipping a device-less alloc
                    # that would poison the whole node plan at apply time.
                    metric.exhausted_node(node_id, "devices")
                    self._record_failure(tg_name, metric)
                    continue
                alloc = Allocation(
                    id=new_id(),
                    namespace=self.job.namespace,
                    eval_id=self.eval.id,
                    name=pr.name,
                    node_id=node_id,
                    job_id=self.job.id,
                    job=self.job,
                    job_version=self.job.version,
                    task_group=tg_name,
                    resources=comparable.copy(),
                    desired_status=ALLOC_DESIRED_RUN,
                    client_status="pending",
                    metrics=metric,
                )
                if devices:
                    alloc.allocated_devices = devices
                if self.deployment is not None and tg_name in (
                    self.deployment.task_groups
                ):
                    alloc.deployment_id = self.deployment.id
                    alloc.canary = pr.canary
                if pr.previous_alloc is not None:
                    alloc.previous_allocation = pr.previous_alloc.id
                    prev = pr.previous_alloc
                    if prev.client_status in ("failed", "lost"):
                        # carry the reschedule history forward + record this
                        # attempt (generic_sched.go updateRescheduleTracker)
                        from ..structs import RescheduleEvent, RescheduleTracker

                        events = list(
                            prev.reschedule_tracker.events
                            if prev.reschedule_tracker
                            else []
                        )
                        events.append(
                            RescheduleEvent(
                                reschedule_time_ns=int(self.clock() * 1e9),
                                prev_alloc_id=prev.id,
                                prev_node_id=prev.node_id,
                            )
                        )
                        alloc.reschedule_tracker = RescheduleTracker(events=events)
                self.plan.append_alloc(alloc)
        self._enforce_gang_atomicity(ct)

    GANG_RELEASE_DESC = "alloc released: gang member group failed placement"

    def _enforce_gang_atomicity(self, ct) -> None:
        """All-or-nothing commit for the job's gang stanza (invariant
        law 15): if any member group failed placement this pass — or the
        ``gang.commit_drop`` chaos site drops the commit mid-gang — the
        whole gang releases: this plan's member placements come back
        out, surviving member allocs from prior evals are stopped, and
        EVERY member lands in ``failed_tg_allocs`` with per-group
        rejection detail, so the gang rides one blocked eval instead of
        striping a partial plan. Algorithm-independent on purpose: the
        cp-gang kernel already releases within a pass, and this seam
        holds the invariant across passes, fallbacks, and partial plan
        commits (a partially-committed gang from an optimistic plan is
        clawed back by the stop path on the retry eval)."""
        job = self.job
        gang = getattr(job, "gang", None) if job is not None else None
        members = set((gang or {}).get("groups") or ())
        if not members or job.stopped():
            return
        from ..chaos.plane import chaos_site

        failed = members & set(self.failed_tg_allocs)
        reason = "gang-infeasible"
        if not failed:
            # a kill here is the mid-gang-commit thread death the
            # worker's recovery contract must absorb (plan unsubmitted
            # → nothing committed → trivially atomic)
            if chaos_site("gang.commit_drop") == "drop":
                reason = "gang-commit-drop"
            else:
                return
        from ..utils.metrics import global_metrics

        released = 0
        for node_id in list(self.plan.node_allocation):
            allocs = self.plan.node_allocation[node_id]
            kept = [
                a for a in allocs
                if a.job_id != job.id or a.task_group not in members
            ]
            released += len(allocs) - len(kept)
            if kept:
                self.plan.node_allocation[node_id] = kept
            else:
                del self.plan.node_allocation[node_id]
        already = {
            a.id for ups in self.plan.node_update.values() for a in ups
        }
        stopped = 0
        if self.snapshot is not None:
            for a in self.snapshot.allocs_by_job(job.namespace, job.id):
                if (
                    a.terminal_status()
                    or a.desired_status != ALLOC_DESIRED_RUN
                    or a.task_group not in members
                    or a.id in already
                ):
                    continue
                self.plan.append_stopped_alloc(a, self.GANG_RELEASE_DESC)
                stopped += 1
        for tg_name in sorted(members):
            metric = self.failed_tg_allocs.get(tg_name)
            if metric is None:
                metric = AllocMetric(
                    nodes_evaluated=ct.num_nodes if ct is not None else 0
                )
                self.failed_tg_allocs[tg_name] = metric
            metric.rejections[reason] = metric.rejections.get(reason, 0) + 1
        global_metrics.incr("nomad.gang.releases")
        if released:
            global_metrics.incr("nomad.gang.released_allocs", released)
        if stopped:
            global_metrics.incr("nomad.gang.stopped_allocs", stopped)

    def _assign_devices(self, tg, node_id):
        from .device import assign_devices_for_plan

        return assign_devices_for_plan(self.snapshot, self.plan, tg, node_id)

    @staticmethod
    def _record_exhaustion(metric, ct, ga) -> None:
        """Count eligible nodes that lacked free capacity, per dimension
        (BinPackIterator's 'dimension exhausted' accounting, rank.go:483)."""
        import numpy as np

        from ..structs.resources import RESOURCE_DIMS

        elig = ga.eligible[: ct.num_nodes]
        if not elig.any():
            return
        free = (ct.capacity - ct.used)[: ct.num_nodes][elig]
        short = free < ga.ask[None, :]
        exhausted = short.any(axis=1)
        metric.nodes_exhausted = int(exhausted.sum())
        for d, dim in enumerate(RESOURCE_DIMS):
            n = int(short[:, d].sum())
            if n:
                metric.dimension_exhausted[dim] = (
                    metric.dimension_exhausted.get(dim, 0) + n
                )
        if ga.slot_caps is not None:
            # eligible nodes whose device instances are the binding limit
            # (resource dims fit but the device pool is drained)
            dev_capped = (~exhausted) & np.isfinite(
                ga.slot_caps[: ct.num_nodes][elig]
            )
            n = int(dev_capped.sum())
            if n:
                metric.nodes_exhausted += n
                metric.dimension_exhausted["devices"] = (
                    metric.dimension_exhausted.get("devices", 0) + n
                )
        if ga.has_throughputs and ga.throughputs is not None:
            # class-infeasible accounting: eligible nodes whose device
            # class the job cannot run on (tp == 0), bucketed by class
            # name so `eval status` says which classes to expand
            infeasible = ga.throughputs[: ct.num_nodes][elig] <= 0.0
            if infeasible.any():
                classes = ct.device_class_column()[: ct.num_nodes][elig]
                vocab = ct.device_class_vocab
                for cid in np.unique(classes[infeasible]):
                    name = vocab[int(cid)] or "none"
                    metric.class_exhausted[name] = metric.class_exhausted.get(
                        name, 0
                    ) + int((classes[infeasible] == cid).sum())

    def _preemption_enabled(self) -> bool:
        cfg = self.scheduler_config
        return (
            cfg.preemption_batch_enabled
            if self.batch
            else cfg.preemption_service_enabled
        )

    def _try_preempt(self, ct, pr, tg_name, ga, comparable) -> bool:
        """Preemption fallback for one failed placement: one device pass
        per GROUP ranks every node's cheapest feasible victim set
        (device/preempt.py — the shortlist is cached across this plan's
        failures, so G failed placements cost one [N, V] kernel pass, not
        G); the final victim set on a shortlisted node is chosen by the
        reference-exact host greedy (preempt_host.select_victims:
        maxParallel penalty, reserved ports, device instances). Victims
        are evicted in-plan and the placement lands on their node
        (generic_sched.go:795 handlePreemptions)."""
        if not self._preemption_enabled() or self.job is None:
            return False
        from ..device.preempt import (
            PREEMPTION_PRIORITY_DELTA,
            rank_preemption_nodes,
        )
        from .preempt_host import select_victims

        if self.job.priority < PREEMPTION_PRIORITY_DELTA:
            return False
        # hard constraints still bind under preemption: distinct_hosts
        # excludes nodes already holding this job (snapshot + in-plan)
        eligible = ga.eligible
        if ga.distinct_hosts:
            eligible = eligible & (ga.job_counts == 0)
            for node_id, allocs in self.plan.node_allocation.items():
                if any(a.job_id == self.job.id for a in allocs):
                    r = ct.node_row.get(node_id)
                    if r is not None:
                        eligible = eligible.copy()
                        eligible[r] = False
        # allocs already evicted by this plan free capacity exactly once
        already_preempted = {
            a.id
            for allocs in self.plan.node_preemptions.values()
            for a in allocs
        }
        cache = getattr(self, "_preempt_rank_cache", None)
        if cache is None:
            cache = self._preempt_rank_cache = {}
        shortlist = cache.get(tg_name)
        if shortlist is None:
            shortlist = rank_preemption_nodes(
                ct,
                self.snapshot,
                self.job,
                ga.ask,
                eligible,
                exclude_ids=already_preempted,
            )
            cache[tg_name] = shortlist
        tg = self.job.lookup_task_group(tg_name)
        row, victim_ids = None, []
        for cand_row in shortlist:
            # the shortlist is cached per group, but eligibility is
            # recomputed per failure (distinct_hosts excludes nodes this
            # plan already used) — stale rows are skipped, not trusted
            if not eligible[cand_row]:
                continue
            ids = select_victims(
                ct,
                self.snapshot,
                self.job,
                tg,
                ga.ask,
                cand_row,
                plan=self.plan,
                exclude_ids=already_preempted,
            )
            if ids:
                row, victim_ids = cand_row, ids
                break
        if row is None or not victim_ids:
            return False
        node_id = ct.node_ids[row]
        alloc_id = new_id()
        victim_total = None
        for vid in victim_ids:
            victim = self.snapshot.alloc_by_id(vid)
            if victim is None:
                return False
            self.plan.append_preempted_alloc(victim, alloc_id)
            vec = victim.comparable_resources().to_vector()
            victim_total = vec if victim_total is None else victim_total + vec
        metric = AllocMetric(nodes_evaluated=ct.num_nodes)
        metric.scores[f"{node_id}.preemption"] = 1.0
        alloc = Allocation(
            id=alloc_id,
            namespace=self.job.namespace,
            eval_id=self.eval.id,
            name=pr.name,
            node_id=node_id,
            job_id=self.job.id,
            job=self.job,
            job_version=self.job.version,
            task_group=tg_name,
            resources=comparable.copy(),
            desired_status=ALLOC_DESIRED_RUN,
            client_status="pending",
            metrics=metric,
            preempted_allocations=list(victim_ids),
        )
        if pr.previous_alloc is not None:
            alloc.previous_allocation = pr.previous_alloc.id
        tg = self.job.lookup_task_group(tg_name)
        if tg is not None:
            devices, dev_ok = self._assign_devices(tg, node_id)
            if not dev_ok:
                # victims chosen by resource distance didn't free the
                # needed device instances — abandon this preemption
                # rather than shipping a device-less alloc
                from .device import rollback_plan_preemptions

                rollback_plan_preemptions(self.plan, node_id, victim_ids)
                return False
            if devices:
                alloc.allocated_devices = devices
        self.plan.append_alloc(alloc)
        # keep the device-resident usage honest for subsequent fallbacks
        ct.used[row] += ga.ask - (victim_total if victim_total is not None else 0)
        return True

    def _record_failure(self, tg_name: str, metric: AllocMetric) -> None:
        existing = self.failed_tg_allocs.get(tg_name)
        if existing is not None:
            existing.coalesced_failures += 1
        else:
            self.failed_tg_allocs[tg_name] = metric

    # -- completion -------------------------------------------------------
    def _finalize(self) -> None:
        ev = self.eval
        if self.failed_tg_allocs and not self.batch:
            # create/update blocked eval to hold unplaced work
            # (generic_sched.go:193-212)
            blocked = ev.create_blocked_eval({}, True, "", self.failed_tg_allocs)
            blocked.status_description = BLOCKED_EVAL_FAILED_PLACEMENTS_DESC
            # carry the unplaced counts so parked blocked evals are
            # auditable (bench accounting: placed + blocked == total)
            blocked.queued_allocations = dict(self.queued_allocs)
            # record the snapshot the failure was computed against, so the
            # blocked-evals tracker can detect missed unblocks
            blocked.snapshot_index = getattr(self.snapshot, "index", 0)
            self.planner.create_eval(blocked)
            self.blocked = blocked
        if self.explanations and not ev.annotate_plan:
            # ring the per-group explanations so `alloc why` /
            # `/v1/evaluations/:id/placement` can answer after the fact;
            # dry-run (job plan) returns them inline and skips the ring
            from ..obs.explain import explanation_to_dict
            from ..obs.recorder import flight_recorder

            flight_recorder.record_explanation(
                ev.id,
                {
                    "eval_id": ev.id,
                    "job_id": ev.job_id,
                    "namespace": getattr(ev, "namespace", "default"),
                    "groups": {
                        tg: explanation_to_dict(ex)
                        for tg, ex in self.explanations.items()
                    },
                },
            )
        self._set_status(EVAL_STATUS_COMPLETE, "")

    def _set_status(self, status: str, desc: str) -> None:
        ev = self.eval
        import copy

        updated = copy.copy(ev)
        updated.status = status
        updated.status_description = desc
        updated.failed_tg_allocs = dict(self.failed_tg_allocs)
        updated.queued_allocations = dict(self.queued_allocs)
        self.planner.update_eval(updated)
