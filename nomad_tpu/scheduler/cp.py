"""Constraint-programming dispatcher: whole-batch joint placement.

The ``cp-pack`` algorithm plugin (scheduler/algorithms.py). One pass
takes EVERY pending group at once, assembles the dense score matrix
through the registry's ``score_group`` seam (the same finals binpack
ranks by), and hands the whole batch to ``device/cp.py``'s iterated
proportional rounding kernel — an auction-style relaxation where
congestion prices mediate contention instead of per-group greedy order:

- per-node capacity across all resource dims is exact by construction
  (one instance per node per round, fit-checked against committed use);
- ``distinct_hosts`` holds against existing allocs AND instances rounded
  earlier in the same pass;
- same-job groups repel each other through an in-batch anti-affinity
  price (the cross-task-group coupling per-group kernels cannot see);
- priority tiers win contested nodes before any score comparison.

What the relaxation does not model — spread/distinct_property value
blocks and device slot caps — delegates the whole batch to the base
binpack kernel, exactly like scheduler/hetero.py's gate, so those
features keep their battle-tested path. A tripped ``cp_place_kernel``
circuit breaker (resilience/breaker.py) also falls back to greedy
binpack for the pass (``nomad.cp.fallback_passes``).

Conservation accounting for chaos invariant law 13
(``cp_assignment_conservation``): every group in a CP pass ends exactly
one of placed / deferred / failed, and committed usage never exceeds
capacity (``nomad.cp.*`` counters). Chaos site ``cp.round_perturb``
perturbs the solver's initial prices — the solution may legitimately
shift, but law 13 must still hold.

``run_cp_ab`` is the ``bench.py cp`` acceptance harness: binpack vs
cp-pack on the seeded 1k-node mixed fleet, device kernel cross-checked
byte-identical against the NumPy oracle, canonical byte-reproducible
report.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..device.cp import (
    _steps_bucket,
    cp_gang_place_kernel,
    cp_place_kernel,
    oracle_cp_gang_place,
    oracle_cp_place,
    release_incomplete_gangs,
    topo_onehot,
)

#: per-node initial-price perturbation applied when chaos fires
#: ``cp.round_perturb``: exact f32 (power-of-two scale, small ints) so a
#: perturbed run is still byte-deterministic for its schedule.
PERTURB_SCALE = np.float32(0.0625)


@dataclass
class CpBatch:
    """Assembled dense inputs for one joint CP pass."""

    capacity: np.ndarray
    used: np.ndarray
    asks: np.ndarray
    counts: np.ndarray
    eligible: np.ndarray
    scores: np.ndarray
    prio: np.ndarray
    job_counts: np.ndarray
    distinct: np.ndarray
    jobgrp: np.ndarray
    lam0: np.ndarray
    steps: int
    max_c: int


def perturb_prices(pn: int) -> np.ndarray:
    """Deterministic non-uniform initial-price vector for the
    ``cp.round_perturb`` chaos action (zeros would be a no-op: a
    uniform shift cancels inside every argmax)."""
    return (PERTURB_SCALE * (np.arange(pn) % 8)).astype(np.float32)


def build_cp_batch(cluster, asks: list, used_override=None,
                   lam0=None) -> CpBatch:
    """Score rows come from the registry's ``score_group`` seam — the
    identical finals binpack ranks by, so the A/B compares solvers, not
    scoring functions. Scoring runs against the cluster's base usage
    snapshot (like the base kernel's batch pass); feasibility inside the
    solver is exact against ``used_override`` + committed rounds."""
    from .algorithms import score_group

    pn = cluster.padded_n
    g = len(asks)
    ask_m = np.stack([a.ask for a in asks]).astype(np.float32)
    counts = np.array([a.count for a in asks], dtype=np.int32)
    eligible = np.stack([a.eligible for a in asks]).copy()
    scores = np.zeros((g, pn), dtype=np.float32)
    for i, a in enumerate(asks):
        finals, fits = score_group(cluster, a, float(a.desired_total))
        scores[i] = np.where(fits, finals, np.float32(0.0))
        eligible[i] &= fits
    prio = np.array(
        [float(getattr(a, "priority", 50)) for a in asks], dtype=np.float32
    )
    job_counts = np.stack([a.job_counts for a in asks]).astype(np.int32)
    distinct = np.array([a.distinct_hosts for a in asks], dtype=bool)
    codes: dict[str, int] = {}
    jobgrp = np.array(
        [codes.setdefault(a.job_id, len(codes)) for a in asks],
        dtype=np.int32,
    )
    used = (
        used_override if used_override is not None else cluster.used
    ).astype(np.float32)
    if lam0 is None:
        lam0 = np.zeros(pn, dtype=np.float32)
    total = int(counts.sum())
    return CpBatch(
        capacity=cluster.capacity.astype(np.float32),
        used=used,
        asks=ask_m,
        counts=counts,
        eligible=eligible,
        scores=scores,
        prio=prio,
        job_counts=job_counts,
        distinct=distinct,
        jobgrp=jobgrp,
        lam0=lam0.astype(np.float32),
        steps=_steps_bucket(total + 1),
        max_c=_steps_bucket(max(int(counts.max(initial=1)), 1)),
    )


def solver_stats(batch: CpBatch, choices: np.ndarray,
                 choice_scores: np.ndarray, rounds: int) -> dict:
    """Host-side solver provenance (one implementation — computed from
    the kernel's outputs, so device and oracle paths agree by
    construction):

    - ``gap``: duality-gap proxy = fractional upper bound (each group's
      count best eligible rows, per-node capacity relaxed) − the rounded
      objective;
    - ``agreement``: fraction of committed slots that landed inside
      their group's fractional-optimum row set (rounding confidence)."""
    masked = np.where(batch.eligible, batch.scores, -np.inf)  # f32[G, N]
    committed = choices >= 0
    achieved = float(choice_scores[committed].astype(np.float64).sum())
    bound = 0.0
    in_opt = 0
    for i, c in enumerate(batch.counts):
        order = np.argsort(-masked[i], kind="stable")[: int(c)]
        top = masked[i, order]
        top = top[np.isfinite(top)]
        bound += float(top.astype(np.float64).sum())
        opt_rows = set(order[: top.size].tolist())
        rows = choices[i][committed[i]]
        in_opt += sum(int(r) in opt_rows for r in rows)
    n_placed = int(committed.sum())
    return {
        "iterations": int(rounds),
        "gap": round(max(bound - achieved, 0.0), 6),
        "agreement": round(in_opt / n_placed, 6) if n_placed else 1.0,
    }


class CpPlacementKernel:
    """Drop-in for device/score.py's PlacementKernel behind the
    algorithm registry: one joint CP pass per batch; blocks/slot-caps
    batches and breaker-tripped passes delegate to greedy binpack."""

    def __init__(self, force_scan: bool = False, mesh=None):
        from ..device.score import PlacementKernel

        self.algorithm_spread = False
        self.force_scan = force_scan
        self._mesh = mesh
        self._base = PlacementKernel("binpack", force_scan, mesh=mesh)

    def mesh_cfg(self):
        from ..utils.backend import get_mesh

        return self._mesh if self._mesh is not None else get_mesh()

    def _cp_eligible(self, asks: list) -> bool:
        # value blocks (spread / distinct_property) and device slot caps
        # are not modeled by the relaxation — battle-tested base scan
        return not any(
            a.blocks is not None or a.slot_caps is not None for a in asks
        )

    def _fallback_open(self) -> bool:
        from ..resilience.breaker import CLOSED, breaker_for, forced_open

        if forced_open():
            return True
        return breaker_for("cp_place_kernel").state != CLOSED

    def place(self, cluster, asks: list, **kwargs):
        from ..device.score import PlacementResult
        from ..utils.metrics import global_metrics

        if not asks:
            return []
        if self._fallback_open():
            global_metrics.incr("nomad.cp.fallback_passes")
            return self._base.place(cluster, asks, **kwargs)
        if not self._cp_eligible(asks):
            return self._base.place(cluster, asks, **kwargs)

        from ..chaos.plane import chaos_site

        lam0 = None
        if chaos_site("cp.round_perturb") == "perturb":
            lam0 = perturb_prices(cluster.padded_n)
            global_metrics.incr("nomad.cp.chaos_perturbs")
        batch = build_cp_batch(
            cluster, asks,
            used_override=kwargs.get("used_override"),
            lam0=lam0,
        )
        from ..device.score import used_device
        from ..utils.backend import shard_put

        cfg = self.mesh_cfg()
        choices, choice_scores, used, rounds, _lam = cp_place_kernel(
            shard_put(batch.capacity, ("nodes",), cfg),
            used_device(cluster, batch.used, cfg),
            shard_put(batch.asks, ("groups",), cfg),
            shard_put(batch.counts, ("groups",), cfg),
            shard_put(batch.eligible, ("groups", "nodes"), cfg),
            shard_put(batch.scores, ("groups", "nodes"), cfg),
            shard_put(batch.prio, ("groups",), cfg),
            shard_put(batch.job_counts, ("groups", "nodes"), cfg),
            shard_put(batch.distinct, ("groups",), cfg),
            batch.jobgrp,
            batch.lam0,
            steps=batch.steps,
            max_c=batch.max_c,
        )
        choices = np.asarray(choices)
        choice_scores = np.asarray(choice_scores)
        used_out = np.asarray(used)

        # law 13 (cp_assignment_conservation) accounting
        g = len(asks)
        placed_g = deferred_g = failed_g = 0
        for i, a in enumerate(asks):
            k = int((choices[i, : a.count] >= 0).sum())
            if k >= a.count:
                placed_g += 1
            elif k > 0:
                deferred_g += 1
            else:
                failed_g += 1
        violations = int((used_out > batch.capacity).any(axis=1).sum())
        global_metrics.incr("nomad.cp.groups_in", g)
        global_metrics.incr("nomad.cp.placed_groups", placed_g)
        global_metrics.incr("nomad.cp.deferred_groups", deferred_g)
        global_metrics.incr("nomad.cp.failed_groups", failed_g)
        if violations:
            global_metrics.incr("nomad.cp.capacity_violations", violations)

        explain = bool(kwargs.get("explain", False))
        stats = (
            solver_stats(batch, choices, choice_scores, int(rounds))
            if explain
            else None
        )
        results = []
        for i, a in enumerate(asks):
            rows = choices[i, : a.count].astype(np.int32)
            scores_row = np.where(
                rows >= 0,
                choice_scores[i, : a.count],
                np.float32(-np.inf),
            ).astype(np.float32)
            res = PlacementResult(node_rows=rows, scores=scores_row)
            if explain:
                # same Python-level gate as the base/hetero kernels:
                # explain-off traces and places exactly as before
                from ..obs.explain import explain_cp_group

                res.explanation = explain_cp_group(
                    cluster, a, batch.used,
                    scores_row=batch.scores[i],
                    cp=stats,
                )
            results.append(res)
        return results


# -- gang/topology dispatcher (cp-gang) --------------------------------------


@dataclass
class GangInputs:
    """Gang-axis arrays for one batch, aligned with a CpBatch's rows."""

    gang: np.ndarray  # i32[G] gang ids (0 = not in a gang)
    w_rack: np.ndarray  # f32[G] signed rack weight
    w_pod: np.ndarray  # f32[G] signed pod weight
    w_ici: np.ndarray  # f32[G] signed ici weight
    rack_oh: np.ndarray  # i32[N, R] one-hot rack ids (col 0 zeroed)
    pod_oh: np.ndarray  # i32[N, P] one-hot pod ids (col 0 zeroed)
    ici_oh: np.ndarray  # i32[N, I] one-hot ici slice ids (col 0 zeroed)
    job_of: dict  # gang id → job id
    members: dict  # gang id → [tg_name, ...]


def build_gang_inputs(cluster, asks: list) -> GangInputs:
    """Gang ids are per job (every gang-member group of one job shares
    an id; 0 = not ganged); topology one-hots come from the tensors'
    factored per-level columns, bucket-padded so the kernel's static
    shapes stay in the retrace budget."""
    g = len(asks)
    gang = np.zeros(g, dtype=np.int32)
    w_rack = np.zeros(g, dtype=np.float32)
    w_pod = np.zeros(g, dtype=np.float32)
    w_ici = np.zeros(g, dtype=np.float32)
    codes: dict[str, int] = {}
    members: dict[int, list] = {}
    for i, a in enumerate(asks):
        if not getattr(a, "gang_member", False):
            continue
        gid = codes.setdefault(a.job_id, len(codes) + 1)
        gang[i] = gid
        w_rack[i] = np.float32(a.gang_weight_rack)
        w_pod[i] = np.float32(a.gang_weight_pod)
        w_ici[i] = np.float32(getattr(a, "gang_weight_ici", 0.0))
        members.setdefault(gid, []).append(a.tg_name)
    rack_ids, pod_ids, ici_ids = cluster.topology_columns()
    rw = _steps_bucket(max(int(rack_ids.max(initial=0)) + 1, 2))
    pw = _steps_bucket(max(int(pod_ids.max(initial=0)) + 1, 2))
    iw = _steps_bucket(max(int(ici_ids.max(initial=0)) + 1, 2))
    return GangInputs(
        gang=gang,
        w_rack=w_rack,
        w_pod=w_pod,
        w_ici=w_ici,
        rack_oh=topo_onehot(np.asarray(rack_ids, dtype=np.int32), rw),
        pod_oh=topo_onehot(np.asarray(pod_ids, dtype=np.int32), pw),
        ici_oh=topo_onehot(np.asarray(ici_ids, dtype=np.int32), iw),
        job_of={v: k for k, v in codes.items()},
        members=members,
    )


class CpGangPlacementKernel(CpPlacementKernel):
    """The ``cp-gang`` algorithm plugin: cp-pack plus all-or-nothing
    gangs with topology-priced co/anti-location.

    Batches with no gang members take the parent's path through the
    UNCHANGED cp_place_kernel — bit-identical to cp-pack by
    construction. Batches the relaxation cannot model (value blocks /
    slot caps) or a tripped breaker fall back to greedy binpack for the
    NON-gang asks only; gang asks fail outright rather than stripe a
    gang through a greedy kernel that cannot hold its atomicity
    (``nomad.cp.gang_fallback_failures``)."""

    def place(self, cluster, asks: list, **kwargs):
        from ..device.score import PlacementResult
        from ..utils.metrics import global_metrics

        if not asks:
            return []
        gang_idx = [
            i for i, a in enumerate(asks)
            if getattr(a, "gang_member", False)
        ]
        if not gang_idx:
            return super().place(cluster, asks, **kwargs)
        if self._fallback_open() or not self._cp_eligible(asks):
            return self._fallback_failing_gangs(
                cluster, asks, gang_idx, **kwargs
            )

        from ..chaos.plane import chaos_site
        from ..device.cp import (
            _cp_gang_same,
            _cp_topo_mates,
            _cp_topo_quant,
            _cp_topo_term,
        )
        from ..device.score import used_device
        from ..utils.backend import shard_put

        lam0 = None
        if chaos_site("cp.round_perturb") == "perturb":
            lam0 = perturb_prices(cluster.padded_n)
            global_metrics.incr("nomad.cp.chaos_perturbs")
        batch = build_cp_batch(
            cluster, asks,
            used_override=kwargs.get("used_override"),
            lam0=lam0,
        )
        gi = build_gang_inputs(cluster, asks)
        cfg = self.mesh_cfg()
        out = cp_gang_place_kernel(
            shard_put(batch.capacity, ("nodes",), cfg),
            used_device(cluster, batch.used, cfg),
            shard_put(batch.asks, ("groups",), cfg),
            shard_put(batch.counts, ("groups",), cfg),
            shard_put(batch.eligible, ("groups", "nodes"), cfg),
            shard_put(batch.scores, ("groups", "nodes"), cfg),
            shard_put(batch.prio, ("groups",), cfg),
            shard_put(batch.job_counts, ("groups", "nodes"), cfg),
            shard_put(batch.distinct, ("groups",), cfg),
            batch.jobgrp,
            gi.gang,
            gi.w_rack,
            gi.w_pod,
            gi.w_ici,
            shard_put(gi.rack_oh, ("nodes",), cfg),
            shard_put(gi.pod_oh, ("nodes",), cfg),
            shard_put(gi.ici_oh, ("nodes",), cfg),
            batch.lam0,
            steps=batch.steps,
            max_c=batch.max_c,
        )
        choices = np.asarray(out[0])
        choice_scores = np.asarray(out[1])
        used_out = np.asarray(out[2])
        rounds = int(np.asarray(out[3]))
        waits = np.asarray(out[5])

        # all-or-nothing: reservations of any gang short of its counts
        # release before anything leaves the solver layer
        choices, choice_scores, used_out, released = (
            release_incomplete_gangs(
                choices, choice_scores, used_out,
                batch.asks, batch.counts, gi.gang,
            )
        )
        released_set = set(released)
        global_metrics.incr("nomad.cp.gang_groups_in", len(gang_idx))
        global_metrics.incr(
            "nomad.cp.gang_commits",
            sum(1 for gid in gi.members if gid not in released_set),
        )
        if released:
            global_metrics.incr("nomad.cp.gang_releases", len(released))

        # law 13 (cp_assignment_conservation) accounting, post-release
        g = len(asks)
        placed_g = deferred_g = failed_g = 0
        for i, a in enumerate(asks):
            k = int((choices[i, : a.count] >= 0).sum())
            if k >= a.count:
                placed_g += 1
            elif k > 0:
                deferred_g += 1
            else:
                failed_g += 1
        violations = int((used_out > batch.capacity).any(axis=1).sum())
        global_metrics.incr("nomad.cp.groups_in", g)
        global_metrics.incr("nomad.cp.placed_groups", placed_g)
        global_metrics.incr("nomad.cp.deferred_groups", deferred_g)
        global_metrics.incr("nomad.cp.failed_groups", failed_g)
        if violations:
            global_metrics.incr("nomad.cp.capacity_violations", violations)

        explain = bool(kwargs.get("explain", False))
        stats = topo_final = None
        if explain:
            stats = solver_stats(batch, choices, choice_scores, rounds)
            assigned = np.zeros(
                (g, batch.capacity.shape[0]), dtype=np.int32
            )
            for i in range(g):
                for node in choices[i][choices[i] >= 0]:
                    assigned[i, int(node)] += 1
            same = _cp_gang_same(gi.gang)
            topo_final = _cp_topo_term(
                _cp_topo_quant(gi.w_rack),
                _cp_topo_quant(gi.w_pod),
                _cp_topo_quant(gi.w_ici),
                _cp_topo_mates(same, assigned, gi.rack_oh),
                _cp_topo_mates(same, assigned, gi.pod_oh),
                _cp_topo_mates(same, assigned, gi.ici_oh),
            )
        results = []
        for i, a in enumerate(asks):
            rows = choices[i, : a.count].astype(np.int32)
            scores_row = np.where(
                rows >= 0,
                choice_scores[i, : a.count],
                np.float32(-np.inf),
            ).astype(np.float32)
            res = PlacementResult(node_rows=rows, scores=scores_row)
            if explain:
                from ..obs.explain import explain_cp_gang, explain_cp_group

                gid = int(gi.gang[i])
                if gid > 0:
                    ok = rows >= 0
                    res.explanation = explain_cp_gang(
                        cluster, a, batch.used,
                        scores_row=batch.scores[i],
                        cp=stats,
                        gang_info={
                            "gang_id": gi.job_of[gid],
                            "members": list(gi.members[gid]),
                            "topology_score": round(
                                float(
                                    topo_final[i, rows[ok]]
                                    .astype(np.float64)
                                    .sum()
                                ),
                                6,
                            ),
                            "release_rounds": int(waits[i]),
                        },
                    )
                else:
                    res.explanation = explain_cp_group(
                        cluster, a, batch.used,
                        scores_row=batch.scores[i],
                        cp=stats,
                    )
            results.append(res)
        return results

    def _fallback_failing_gangs(self, cluster, asks, gang_idx, **kwargs):
        """Greedy fallback that preserves gang atomicity by failing the
        gang asks outright: the base binpack kernel places the non-gang
        asks exactly as cp-pack's fallback would, while every gang
        member reports zero placements (→ blocked eval with per-group
        rejection detail, scheduler/generic.py) instead of a striped
        fragment the release pass could not claw back."""
        from ..device.score import PlacementResult
        from ..utils.metrics import global_metrics

        global_metrics.incr("nomad.cp.fallback_passes")
        global_metrics.incr(
            "nomad.cp.gang_fallback_failures", len(gang_idx)
        )
        gang_set = set(gang_idx)
        rest = [a for i, a in enumerate(asks) if i not in gang_set]
        rest_results = (
            self._base.place(cluster, rest, **kwargs) if rest else []
        )
        results = []
        it = iter(rest_results)
        for i, a in enumerate(asks):
            if i in gang_set:
                results.append(
                    PlacementResult(
                        node_rows=np.full(a.count, -1, dtype=np.int32),
                        scores=np.full(
                            a.count, -np.inf, dtype=np.float32
                        ),
                    )
                )
            else:
                results.append(next(it))
        return results


# -- seeded A/B harness (bench.py cp) ----------------------------------------


def build_cp_asks(ct, n_jobs: int, count_per_job: int, seed: int = 7):
    """Contended CP workload on the mixed fleet: the hetero profile asks
    scaled up so top-ranked nodes hold only a few instances, every 4th
    job demanding distinct hosts, and three priority tiers — the
    co-placement regime where greedy order matters and the joint
    relaxation has room to win."""
    from .hetero import build_mixed_asks

    asks = build_mixed_asks(ct, n_jobs, count_per_job, seed=seed)
    for j, a in enumerate(asks):
        a.ask = (a.ask * np.float32(4.0)).astype(np.float32)
        a.priority = (30, 50, 80)[j % 3]
        if j % 4 == 3:
            a.distinct_hosts = True
    return asks


def _cp_quality(asks, results, scores: np.ndarray) -> dict:
    """Canonical quality block for one algorithm's output: slots placed,
    slots left unplaced (preemption pressure), and the assignment's
    value under ONE shared objective — the dense score matrix both
    solvers rank by. Kernels report per-slot scores on their own
    internal scales (binpack re-scores against evolving usage), so the
    like-for-like A/B re-values both assignments under the matrix."""
    placed = 0
    unplaced = 0
    aggregate = 0.0
    for i, (a, r) in enumerate(zip(asks, results)):
        rows = np.asarray(r.node_rows)
        ok = rows >= 0
        placed += int(ok.sum())
        unplaced += int(a.count - ok.sum())
        aggregate += float(scores[i, rows[ok]].astype(np.float64).sum())
    return {
        "placed": placed,
        "unplaced": unplaced,
        "aggregate_score": round(aggregate, 4),
    }


def run_cp_ab(
    n_nodes: int = 1000,
    n_jobs: int = 12,
    count_per_job: int = 40,
    seed: int = 42,
) -> dict:
    """The ``bench.py cp`` A/B block: greedy binpack vs cp-pack on one
    seeded contended mixed fleet. Placements are deterministic for a
    seed, so the whole report is byte-reproducible. The device kernel is
    cross-checked byte-identical against the NumPy host oracle on two
    seeds (uint32 views)."""
    from ..device.score import PlacementKernel
    from .hetero import build_mixed_fleet

    ct = build_mixed_fleet(n_nodes, seed=seed)
    asks = build_cp_asks(ct, n_jobs, count_per_job, seed=seed + 1)

    base = PlacementKernel("binpack")
    base_results = base.place(ct, asks)
    kern = CpPlacementKernel()
    cp_results = kern.place(ct, asks)

    mismatches = 0
    stats = {}
    for check_seed in (seed, seed + 1):
        ct2 = build_mixed_fleet(n_nodes, seed=check_seed)
        asks2 = build_cp_asks(ct2, n_jobs, count_per_job, seed=check_seed + 1)
        batch = build_cp_batch(ct2, asks2)
        d = cp_place_kernel(
            batch.capacity, batch.used, batch.asks, batch.counts,
            batch.eligible, batch.scores, batch.prio, batch.job_counts,
            batch.distinct, batch.jobgrp, batch.lam0,
            steps=batch.steps, max_c=batch.max_c,
        )
        o = oracle_cp_place(
            batch.capacity, batch.used, batch.asks, batch.counts,
            batch.eligible, batch.scores, batch.prio, batch.job_counts,
            batch.distinct, batch.jobgrp, batch.lam0,
            batch.steps, batch.max_c,
        )
        d_choices, d_scores, d_used = (
            np.asarray(d[0]), np.asarray(d[1]), np.asarray(d[2])
        )
        mismatches += int(
            (d_choices != o[0]).sum()
            + (d_scores.view(np.uint32) != o[1].view(np.uint32)).sum()
            + (d_used.view(np.uint32) != o[2].view(np.uint32)).sum()
            + (int(np.asarray(d[3])) != o[3])
        )
        if check_seed == seed:
            stats = solver_stats(batch, d_choices, d_scores,
                                 int(np.asarray(d[3])))

    value_batch = build_cp_batch(ct, asks)
    b = _cp_quality(asks, base_results, value_batch.scores)
    c = _cp_quality(asks, cp_results, value_batch.scores)
    score_delta = round(c["aggregate_score"] - b["aggregate_score"], 4)
    preempt_avoided = b["unplaced"] - c["unplaced"]
    report = {
        "config": {
            "nodes": n_nodes,
            "jobs": n_jobs,
            "count_per_job": count_per_job,
            "seed": seed,
            "device_classes": sorted(
                k for k in ct.device_class_vocab if k
            ),
        },
        "binpack": b,
        "cp": {**c, "solver": stats},
        "oracle_mismatches": mismatches,
        "ab": {
            "score_delta": score_delta,
            "preemptions_avoided": preempt_avoided,
            "cp_beats_score": score_delta > 0,
            "cp_avoids_preemptions": preempt_avoided > 0,
        },
    }
    ab = report["ab"]
    report["ok"] = mismatches == 0 and (
        (ab["cp_beats_score"] and preempt_avoided >= 0)
        or (ab["cp_avoids_preemptions"] and score_delta >= 0)
    )
    return report


CP_SCHEMA = (
    "ab.cp_avoids_preemptions",
    "ab.cp_beats_score",
    "ab.preemptions_avoided",
    "ab.score_delta",
    "binpack.aggregate_score",
    "binpack.placed",
    "binpack.unplaced",
    "config.count_per_job",
    "config.device_classes",
    "config.jobs",
    "config.nodes",
    "config.seed",
    "cp.aggregate_score",
    "cp.placed",
    "cp.solver.agreement",
    "cp.solver.gap",
    "cp.solver.iterations",
    "cp.unplaced",
    "ok",
    "oracle_mismatches",
)


def cp_schema_of(report: dict) -> tuple[str, ...]:
    """Sorted dotted key paths of a run_cp_ab report (lists are leaves),
    pinned against CP_SCHEMA by the tier-1 smoke test."""
    paths: list[str] = []

    def walk(prefix: str, obj) -> None:
        if isinstance(obj, dict):
            for k, v in obj.items():
                walk(f"{prefix}.{k}" if prefix else str(k), v)
        else:
            paths.append(prefix)

    walk("", report)
    return tuple(sorted(paths))


# -- seeded gang A/B harness (bench.py gang) ---------------------------------


def build_topo_fleet(
    n_nodes: int, seed: int = 42, racks: int = 8, pods: int = 2
):
    """Seeded homogeneous fleet with rack/pod structure as
    ClusterTensors: racks are contiguous row blocks (rack r holds rows
    [r·N/racks, (r+1)·N/racks)), pods are contiguous rack blocks, and a
    seeded 0–30% background load scatters binpack's best-scoring nodes
    ACROSS racks — the regime where topology-blind greedy fragments a
    gang over the fabric."""
    from ..device.flatten import ClusterTensors, node_bucket

    rng = np.random.default_rng(seed)
    pn = node_bucket(n_nodes)
    capacity = np.zeros((pn, 4), dtype=np.float32)
    capacity[:n_nodes, 0] = 4000
    capacity[:n_nodes, 1] = 8192
    capacity[:n_nodes, 2] = 100 * 1024
    capacity[:n_nodes, 3] = 1000
    used = np.zeros_like(capacity)
    load = rng.uniform(0.0, 0.3, size=(n_nodes, 1)).astype(np.float32)
    used[:n_nodes, :2] = capacity[:n_nodes, :2] * load
    ready = np.zeros(pn, dtype=bool)
    ready[:n_nodes] = True
    rack_of = (np.arange(n_nodes) * racks // max(n_nodes, 1)).astype(
        np.int32
    )
    pod_of = (rack_of * pods // max(racks, 1)).astype(np.int32)
    # ici slices halve each rack: the normalized ICI-hop-distance
    # coordinate (client/fingerprint.py) — nodes in one slice are one
    # ICI hop apart, the tightest co-location level the pricer sees
    ici_of = (np.arange(n_nodes) * racks * 2 // max(n_nodes, 1)).astype(
        np.int32
    )
    topo_rack_ids = np.zeros(pn, dtype=np.int32)
    topo_rack_ids[:n_nodes] = rack_of + 1
    topo_pod_ids = np.zeros(pn, dtype=np.int32)
    topo_pod_ids[:n_nodes] = pod_of + 1
    topo_ici_ids = np.zeros(pn, dtype=np.int32)
    topo_ici_ids[:n_nodes] = ici_of + 1
    return ClusterTensors(
        node_ids=[f"node-{i}" for i in range(n_nodes)],
        index=1,
        num_nodes=n_nodes,
        capacity=capacity,
        used=used,
        ready=ready,
        dc_ids=np.zeros(pn, dtype=np.int32),
        class_ids=np.zeros(pn, dtype=np.int32),
        dc_vocab={"dc1": 0},
        class_vocab={"": 0},
        class_rep=[0] if n_nodes else [],
        node_row={f"node-{i}": i for i in range(n_nodes)},
        topo_rack_ids=topo_rack_ids,
        topo_pod_ids=topo_pod_ids,
        topo_ici_ids=topo_ici_ids,
        topo_rack_vocab={"": 0, **{f"r{r:02d}": r + 1 for r in range(racks)}},
        topo_pod_vocab={"": 0, **{f"p{p}": p + 1 for p in range(pods)}},
        topo_ici_vocab={
            "": 0, **{f"i{s:02d}": s + 1 for s in range(racks * 2)}
        },
    )


def build_gang_asks(
    ct, n_jobs: int, groups: int, count_per_group: int = 2, seed: int = 7
):
    """Seeded multi-group gang jobs: even jobs colocate their gang at
    rack level (the ICI-adjacent training slice), odd jobs spread it
    across pods (the failure-domain serving replica set)."""
    from ..device.flatten import GroupAsk

    rng = np.random.default_rng(seed)
    pn = ct.padded_n
    asks = []
    for j in range(n_jobs):
        colocate = j % 2 == 0
        cpu = float(rng.choice([1600, 1800, 2000]))
        memv = float(rng.choice([3200, 3600, 4000]))
        for k in range(groups):
            asks.append(
                GroupAsk(
                    job_id=f"gang-job-{j}",
                    tg_name=f"tg{k}",
                    count=count_per_group,
                    desired_total=count_per_group,
                    ask=np.array(
                        [cpu, memv, 300.0, 0.0], dtype=np.float32
                    ),
                    eligible=ct.ready.copy(),
                    job_counts=np.zeros(pn, dtype=np.int32),
                    penalty_nodes=np.zeros(pn, dtype=bool),
                    affinity_scores=np.zeros(pn, dtype=np.float32),
                    has_affinities=False,
                    distinct_hosts=False,
                    gang_member=True,
                    gang_weight_rack=2.0 if colocate else 0.0,
                    gang_weight_pod=0.0 if colocate else -1.0,
                    # colocating gangs also price the tighter ici slice
                    # — the third level — so the rack win prefers the
                    # one-hop half of the rack when room allows
                    gang_weight_ici=0.5 if colocate else 0.0,
                )
            )
    return asks


def _gang_quality(ct, asks, results, gi: GangInputs,
                  scores: np.ndarray) -> dict:
    """Canonical gang-quality block for one algorithm's assignment,
    re-valued under ONE shared objective: the dense score matrix plus
    the signed topology terms both solvers were (or were not) pricing.
    A gang is *intact* when every member placed its full count
    all-or-nothing; its topology is *satisfied* when a rack-colocate
    gang landed entirely in one rack and a pod-spread gang spans more
    than one pod."""
    from ..device.cp import (
        _cp_gang_same,
        _cp_topo_mates,
        _cp_topo_quant,
        _cp_topo_term,
    )

    g = len(asks)
    n = ct.padded_n
    assigned = np.zeros((g, n), dtype=np.int32)
    placed = np.zeros(g, dtype=np.int32)
    base_value = 0.0
    for i, (a, r) in enumerate(zip(asks, results)):
        rows = np.asarray(r.node_rows)
        rows = rows[rows >= 0]
        placed[i] = rows.size
        for node in rows:
            assigned[i, int(node)] += 1
        base_value += float(scores[i, rows].astype(np.float64).sum())
    same = _cp_gang_same(gi.gang)
    topo_final = _cp_topo_term(
        _cp_topo_quant(gi.w_rack),
        _cp_topo_quant(gi.w_pod),
        _cp_topo_quant(gi.w_ici),
        _cp_topo_mates(same, assigned, gi.rack_oh),
        _cp_topo_mates(same, assigned, gi.pod_oh),
        _cp_topo_mates(same, assigned, gi.ici_oh),
    )
    # each placed instance values the topology term at its node; self
    # pairs count once per instance on both sides (shared across A/B,
    # so the comparison is apples-to-apples)
    topo_value = float(
        (topo_final * (assigned > 0) * assigned).astype(np.float64).sum()
    )
    rack_ids, pod_ids, _ici_ids = ct.topology_columns()
    gangs_intact = 0
    topology_satisfied = 0
    fragmented = 0
    for gid, member_names in sorted(gi.members.items()):
        idx = np.flatnonzero(gi.gang == gid)
        intact = bool(
            np.all(placed[idx] >= np.array([asks[i].count for i in idx]))
        )
        nodes = np.flatnonzero(assigned[idx].sum(axis=0) > 0)
        colocate = bool(np.any(gi.w_rack[idx] > 0))
        if nodes.size == 0:
            topo_ok = False
        elif colocate:
            topo_ok = len(set(rack_ids[nodes].tolist())) == 1
        else:
            topo_ok = len(set(pod_ids[nodes].tolist())) > 1
        gangs_intact += int(intact)
        topology_satisfied += int(intact and topo_ok)
        fragmented += int(not intact or not topo_ok)
    return {
        "placed": int(placed.sum()),
        "unplaced": int(sum(a.count for a in asks) - placed.sum()),
        "gangs_intact": gangs_intact,
        "topology_satisfied": topology_satisfied,
        "gangs_fragmented": fragmented,
        "objective": round(base_value + topo_value, 4),
        "topology_value": round(topo_value, 4),
    }


def run_gang_ab(
    n_nodes: int = 64,
    n_jobs: int = 8,
    groups: int = 3,
    seed: int = 42,
) -> dict:
    """The ``bench.py gang`` A/B block: topology-blind greedy binpack vs
    cp-gang on one seeded rack/pod fleet of multi-group gang jobs. Both
    assignments are re-valued under the shared objective (score matrix +
    signed topology terms); the gate demands binpack fragment ≥ 1 gang
    while cp-gang places every gang all-or-nothing with its topology
    term satisfied and no objective regression. The gang kernel is
    cross-checked byte-identical against its NumPy oracle on two
    seeds."""
    from ..device.score import PlacementKernel

    ct = build_topo_fleet(n_nodes, seed=seed)
    asks = build_gang_asks(ct, n_jobs, groups, seed=seed + 1)

    base = PlacementKernel("binpack")
    base_results = base.place(ct, asks)
    kern = CpGangPlacementKernel()
    gang_results = kern.place(ct, asks)

    mismatches = 0
    for check_seed in (seed, seed + 1):
        ct2 = build_topo_fleet(n_nodes, seed=check_seed)
        asks2 = build_gang_asks(ct2, n_jobs, groups, seed=check_seed + 1)
        batch = build_cp_batch(ct2, asks2)
        gi2 = build_gang_inputs(ct2, asks2)
        args = (
            batch.capacity, batch.used, batch.asks, batch.counts,
            batch.eligible, batch.scores, batch.prio, batch.job_counts,
            batch.distinct, batch.jobgrp, gi2.gang, gi2.w_rack,
            gi2.w_pod, gi2.w_ici, gi2.rack_oh, gi2.pod_oh,
            gi2.ici_oh, batch.lam0,
        )
        d = cp_gang_place_kernel(
            *args, steps=batch.steps, max_c=batch.max_c
        )
        o = oracle_cp_gang_place(*args, batch.steps, batch.max_c)
        mismatches += int(
            (np.asarray(d[0]) != o[0]).sum()
            + (np.asarray(d[1]).view(np.uint32)
               != o[1].view(np.uint32)).sum()
            + (np.asarray(d[2]).view(np.uint32)
               != o[2].view(np.uint32)).sum()
            + (int(np.asarray(d[3])) != o[3])
            + (np.asarray(d[5]) != o[5]).sum()
        )

    value_batch = build_cp_batch(ct, asks)
    gi = build_gang_inputs(ct, asks)
    b = _gang_quality(ct, asks, base_results, gi, value_batch.scores)
    c = _gang_quality(ct, asks, gang_results, gi, value_batch.scores)
    n_gangs = len(gi.members)
    objective_delta = round(c["objective"] - b["objective"], 4)
    report = {
        "config": {
            "nodes": n_nodes,
            "jobs": n_jobs,
            "groups": groups,
            "gangs": n_gangs,
            "seed": seed,
            "racks": len([k for k in ct.topo_rack_vocab if k]),
            "pods": len([k for k in ct.topo_pod_vocab if k]),
        },
        "binpack": b,
        "cp_gang": c,
        "oracle_mismatches": mismatches,
        "ab": {
            "objective_delta": objective_delta,
            "binpack_fragments": b["gangs_fragmented"],
            "gangs_rescued": c["gangs_intact"] - b["gangs_intact"],
        },
    }
    report["ok"] = (
        mismatches == 0
        and b["gangs_fragmented"] >= 1
        and c["gangs_intact"] == n_gangs
        and c["topology_satisfied"] == n_gangs
        and objective_delta >= 0
    )
    return report


GANG_SCHEMA = (
    "ab.binpack_fragments",
    "ab.gangs_rescued",
    "ab.objective_delta",
    "binpack.gangs_fragmented",
    "binpack.gangs_intact",
    "binpack.objective",
    "binpack.placed",
    "binpack.topology_satisfied",
    "binpack.topology_value",
    "binpack.unplaced",
    "config.gangs",
    "config.groups",
    "config.jobs",
    "config.nodes",
    "config.pods",
    "config.racks",
    "config.seed",
    "cp_gang.gangs_fragmented",
    "cp_gang.gangs_intact",
    "cp_gang.objective",
    "cp_gang.placed",
    "cp_gang.topology_satisfied",
    "cp_gang.topology_value",
    "cp_gang.unplaced",
    "ok",
    "oracle_mismatches",
)
