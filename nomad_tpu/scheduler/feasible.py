"""Host-side hard-constraint evaluation.

Reference: scheduler/feasible.go — resolveTarget (:748-781) and
checkConstraint's operator dispatch (:785-820) with the full operand set
(=, !=, <, <=, >, >=, regexp, version, semver, set_contains*, is_set).

In the TPU design this code runs **once per computed node class** (or per
node for constraints touching ``unique.`` attributes), producing boolean
masks that ``device.flatten`` broadcasts into the dense eligibility tensor.
Regex and version parsing never reach the device — the same "classes ≪
nodes" bet the reference makes with its class memoization
(feasible.go:1029-1153).
"""

from __future__ import annotations

import re
from functools import lru_cache
from typing import Optional

from ..structs import Constraint
from ..structs.node import Node


@lru_cache(maxsize=1024)
def _compiled_regex(pattern: str):
    try:
        return re.compile(pattern)
    except re.error:
        return None


@lru_cache(maxsize=4096)
def _parse_version(v: str) -> Optional[tuple]:
    """Lenient version parse: dotted numerics with optional prerelease tag
    ("1.2.3-beta2" < "1.2.3"). Mirrors go-version's ordering closely enough
    for constraint checking."""
    v = v.strip().lstrip("v")
    if not v:
        return None
    main, _, pre = v.partition("-")
    parts = []
    for p in main.split("."):
        if not p.isdigit():
            return None
        parts.append(int(p))
    while len(parts) < 3:
        parts.append(0)
    # releases sort after prereleases of the same version
    return (tuple(parts), 1 if not pre else 0, pre)


def _check_version_constraint(lval: str, constraint_expr: str, lenient: bool) -> bool:
    """Version constraint like ">= 1.2, < 2.0" (go-version syntax).
    ``lenient`` mode (operand "version") tolerates non-semver lvals;
    strict mode ("semver") requires a clean parse."""
    lv = _parse_version(lval)
    if lv is None:
        return False
    for clause in constraint_expr.split(","):
        clause = clause.strip()
        if not clause:
            continue
        m = re.match(r"^(>=|<=|!=|><|[=<>~]+)?\s*(.+)$", clause)
        if not m:
            return False
        op = m.group(1) or "="
        rv = _parse_version(m.group(2))
        if rv is None:
            return False
        if op in ("=", "=="):
            ok = lv == rv
        elif op == "!=":
            ok = lv != rv
        elif op == ">":
            ok = lv > rv
        elif op == ">=":
            ok = lv >= rv
        elif op == "<":
            ok = lv < rv
        elif op == "<=":
            ok = lv <= rv
        elif op in ("~>",):
            # pessimistic: >= rv and < next significant release
            lo = rv[0]
            hi = list(lo[:-1])
            if len(hi) > 0:
                hi[-1] += 1
            ok = lv >= rv and lv[0] < tuple(hi) + (0,) * (3 - len(hi))
        else:
            ok = False
        if not ok:
            return False
    return True


def _lexical_or_numeric_cmp(l: str, r: str) -> Optional[int]:
    """Order comparison: numeric when both parse, else lexical
    (feasible.go checkLexicalOrder / checkOrder)."""
    try:
        lf, rf = float(l), float(r)
        return (lf > rf) - (lf < rf)
    except ValueError:
        return (l > r) - (l < r)


def check_constraint_values(operand: str, lval: Optional[str], rval: str) -> bool:
    """Operator dispatch on already-resolved values."""
    if operand == "is_set":
        return lval is not None
    if operand == "is_not_set":
        return lval is None
    if lval is None:
        return False
    if operand in ("=", "==", "is"):
        return lval == rval
    if operand in ("!=", "not"):
        return lval != rval
    if operand in ("<", "<=", ">", ">="):
        c = _lexical_or_numeric_cmp(lval, rval)
        if c is None:
            return False
        return {
            "<": c < 0,
            "<=": c <= 0,
            ">": c > 0,
            ">=": c >= 0,
        }[operand]
    if operand == "regexp":
        rx = _compiled_regex(rval)
        return rx is not None and rx.search(lval) is not None
    if operand == "version":
        return _check_version_constraint(lval, rval, lenient=True)
    if operand == "semver":
        return _check_version_constraint(lval, rval, lenient=False)
    if operand in ("set_contains", "set_contains_all"):
        have = {p.strip() for p in lval.split(",")}
        want = {p.strip() for p in rval.split(",")}
        return want <= have
    if operand == "set_contains_any":
        have = {p.strip() for p in lval.split(",")}
        want = {p.strip() for p in rval.split(",")}
        return bool(want & have)
    return False


def check_constraint(node: Node, c: Constraint) -> bool:
    """Resolve targets against the node, then dispatch. Both sides may be
    interpolations (feasible.go resolveTarget): a bare RTarget is a
    literal; an ${...} RTarget resolves against the node too."""
    lval = node.lookup_attribute(c.l_target) if c.l_target else None
    rval = c.r_target
    if rval.startswith("${") and rval.endswith("}"):
        resolved = node.lookup_attribute(rval)
        if resolved is None and c.operand not in ("is_set", "is_not_set"):
            return False
        rval = resolved if resolved is not None else ""
    return check_constraint_values(c.operand, lval, rval)


# -- volume feasibility -------------------------------------------------------

FILTER_HOST_VOLUMES = "missing compatible host volumes"
FILTER_CSI_PLUGIN = "CSI plugin is missing or unhealthy on node"
FILTER_CSI_VOLUME = "CSI volume has exhausted its available writer claims"
FILTER_CSI_NOT_FOUND = "CSI volume not found"


def check_host_volumes(node: Node, volumes: dict) -> bool:
    """HostVolumeChecker (scheduler/feasible.go:132-207): every requested
    host volume must exist on the node; a writable request can't be
    satisfied by a read-only host volume."""
    for req in volumes.values():
        if req.type not in ("", "host"):
            continue
        hv = node.host_volumes.get(req.source)
        if hv is None:
            return False
        if getattr(hv, "read_only", False) and not req.read_only:
            return False
    return True


def check_csi_volumes(snapshot, node: Node, volumes: dict) -> tuple[bool, str]:
    """CSIVolumeChecker (scheduler/feasible.go:209-339): the volume must
    exist, be schedulable, have claim capacity for the requested mode, and
    the node must run a healthy node-plugin instance for its plugin (with
    per-node volume-count budget). ``per_alloc`` requests check the
    family's first index (claims are per-source at apply time).
    """
    csi_reqs = [r for r in volumes.values() if r.type == "csi"]
    if not csi_reqs:
        return True, ""
    # seed each plugin's per-node budget with the volumes of *that plugin*
    # already attached to this node (CSIVolumeChecker counts existing
    # claims per plugin, not node-wide)
    mounted_by_plugin: dict[str, int] = {}
    attached_here: set[str] = set()
    if snapshot is not None:
        for v in snapshot.csi_volumes():
            if node.id in v.read_claims.values() or node.id in (
                v.write_claims.values()
            ):
                mounted_by_plugin[v.plugin_id] = (
                    mounted_by_plugin.get(v.plugin_id, 0) + 1
                )
                attached_here.add(v.id)
    for req in csi_reqs:
        source = f"{req.source}[0]" if req.per_alloc else req.source
        vol = snapshot.csi_volume_by_id(source) if snapshot else None
        if vol is None and req.per_alloc:
            vol = snapshot.csi_volume_by_id(req.source) if snapshot else None
        if vol is None:
            return False, FILTER_CSI_NOT_FOUND
        plugin = node.csi_node_plugins.get(vol.plugin_id)
        if plugin is None or not plugin.healthy:
            return False, FILTER_CSI_PLUGIN
        if vol.id not in attached_here:  # already-mounted volumes are free
            mounted = mounted_by_plugin.get(vol.plugin_id, 0) + 1
            mounted_by_plugin[vol.plugin_id] = mounted
            if plugin.max_volumes and mounted > plugin.max_volumes:
                return False, FILTER_CSI_PLUGIN
            attached_here.add(vol.id)  # one attach serves repeat requests
        if not vol.claimable(req.read_only):
            return False, FILTER_CSI_VOLUME
    return True, ""
