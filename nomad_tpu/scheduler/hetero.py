"""Heterogeneity-aware placement policies over the dense score matrix.

Gavel (PAPERS.md, arxiv 2008.09213) observes that once jobs carry
per-accelerator-class throughput coefficients, heterogeneity-aware
policies — max-min fairness, makespan minimization, cost-aware packing —
all become optimization passes over one (jobs × nodes) effective-rate
matrix. This module is that substrate for nomad-tpu: nodes declare a
``device_class`` (structs/node.py, folded into the computed class),
jobs declare ``throughputs`` (structs/job.py), the flattener gathers
them into per-node coefficient vectors (device/flatten.py
``job_throughput_vector``), and the policies here run a joint greedy
pass over the whole batch.

Three policies, all the same slot-at-a-time greedy skeleton with a
different (job-pick, node-pick) key pair:

``hetero-maxmin``
    each step gives the next slot to the job with the LOWEST normalized
    throughput share (accumulated rate ÷ ideal rate), on its fastest
    feasible node — discrete water-filling of Gavel's max-min objective.
``hetero-makespan``
    each step gives the next slot to the job with the LARGEST modeled
    completion time (remaining work ÷ accumulated rate), on its fastest
    feasible node — the LPT rule specialized to rate accumulation.
``hetero-cost``
    slots go to jobs most-remaining-first, each on the feasible node
    maximizing throughput-per-cost (per-class costs from
    ``DEVICE_CLASS_COSTS``; unknown classes cost 1.0).

Every policy has TWO implementations sharing one step definition: a
jitted device kernel (``lax.fori_loop``) and a pure-NumPy host oracle
(``oracle_hetero_place``). The pass is pinned BYTE-identical between
them the way device/parity.py pins binpack/spread: every carried value
is f32, every step does the same multiplies/divides/adds in the same
order, and ties break on the first index (both ``jnp.argmax`` and
``np.argmax`` take the first maximum).

Class-less batches never reach this module: ``HeteroPlacementKernel``
delegates to the base ``PlacementKernel`` whenever no ask carries a
throughput vector, so pre-heterogeneity clusters place bit-identically
to the binpack/spread kernels.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

from ..utils.backend import traced_jit

import jax
import jax.numpy as jnp

# Policy ids (the step kernels branch on these as static ints).
POLICY_MAXMIN = 0
POLICY_MAKESPAN = 1
POLICY_COST = 2

POLICY_IDS = {
    "maxmin": POLICY_MAXMIN,
    "makespan": POLICY_MAKESPAN,
    "cost": POLICY_COST,
}

# Canonical per-device-class relative cost (hetero-cost's denominator).
# Operators override per deployment; unknown classes cost 1.0 so a fleet
# without declared costs degrades to pure throughput maximization.
DEVICE_CLASS_COSTS: dict[str, float] = {
    "": 1.0,
    "cpu": 1.0,
    "tpu-v4": 2.5,
    "tpu-v5e": 2.0,
    "tpu-v5p": 4.0,
    "gpu-a100": 3.0,
    "gpu-h100": 5.0,
}

_EPS = np.float32(1e-9)

# Where the policies' throughput matrix comes from (SchedulerConfiguration
# knob; obs/calibrate.py owns "learned"). Declared is the PR-9 behavior.
THROUGHPUT_DECLARED = "declared"
THROUGHPUT_LEARNED = "learned"
THROUGHPUT_SOURCES = (THROUGHPUT_DECLARED, THROUGHPUT_LEARNED)


def class_cost_vector(ct, costs: dict | None = None) -> np.ndarray:
    """Per-node cost f32[N] from the fleet's device-class column."""
    ids, vocab = ct.device_class_column()
    table = DEVICE_CLASS_COSTS if costs is None else costs
    per_class = np.ones(len(vocab), dtype=np.float32)
    for name, cid in vocab.items():
        per_class[cid] = np.float32(table.get(name, 1.0))
    return per_class[ids]


def _steps_bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


# -- the shared greedy step --------------------------------------------------
#
# Carry: used f32[N, D], placed i32[G], accum f32[G] (Σ tp of assigned
# nodes), choices i32[G, C], choice_tp f32[G, C]. One step = pick a job
# by the policy's fairness key, pick its node by the policy's node key,
# commit. Infeasible/done lanes key to ±inf and the step masks to a
# no-op when nothing is placeable, so padded steps are exact no-ops —
# the property that lets the device loop run a bucketed step count
# while the host oracle runs exactly as many steps as it needs.


def _job_keys(policy, placed, accum, counts, tpmax, placeable):
    """f32[G] selection key, argmin semantics; +inf = not selectable."""
    countsf = counts.astype(np.float32) if isinstance(counts, np.ndarray) \
        else counts.astype(jnp.float32)
    xp = np if isinstance(placed, np.ndarray) else jnp
    placedf = placed.astype(xp.float32)
    if policy == POLICY_MAXMIN:
        ideal = countsf * tpmax  # rate if every slot ran on the best class
        key = accum / xp.maximum(ideal, _EPS)  # share in [0, 1]
    elif policy == POLICY_MAKESPAN:
        # modeled completion time = total work / accumulated rate; jobs
        # with no rate yet sort first (longest possible time)
        key = -(countsf / xp.maximum(accum, _EPS))
    else:  # POLICY_COST — most remaining work first
        key = -(countsf - placedf)
    big = xp.float32(np.inf)
    return xp.where(placeable, key, big)


def _node_keys(policy, tp_row, cost, feasible):
    """f32[N] node key, argmax semantics; -inf = infeasible."""
    xp = np if isinstance(tp_row, np.ndarray) else jnp
    if policy == POLICY_COST:
        key = tp_row / xp.maximum(cost, _EPS)
    else:
        key = tp_row
    return xp.where(feasible, key, -xp.float32(np.inf))


def _feasible_matrix(capacity, used, asks, eligible, tp):
    """bool[G, N]: room for one more instance ∧ eligible ∧ tp > 0."""
    xp = np if isinstance(capacity, np.ndarray) else jnp
    proposed = used[None, :, :] + asks[:, None, :]  # [G, N, D]
    fits = xp.all(proposed <= capacity[None, :, :], axis=-1)
    return fits & eligible & (tp > 0.0)


@functools.partial(
    traced_jit, retrace_budget=16, static_argnames=("policy", "steps", "max_c")
)
def hetero_place_kernel(
    capacity,  # f32[N, D]
    used0,  # f32[N, D]
    asks,  # f32[G, D]
    counts,  # i32[G]
    eligible,  # bool[G, N]
    tp,  # f32[G, N] per-node throughput coefficients
    tpmax,  # f32[G] max coefficient over each job's eligible nodes
    cost,  # f32[N]
    policy: int,
    steps: int,
    max_c: int,
):
    """Joint greedy hetero pass on device. Returns (choices i32[G, C],
    choice_tp f32[G, C], used f32[N, D]) — C = max_c, -1 = unfilled."""
    g, n = tp.shape

    def step(_, carry):
        used, placed, accum, choices, choice_tp = carry
        feas = _feasible_matrix(capacity, used, asks, eligible, tp)
        active = placed < counts
        placeable = active & jnp.any(feas, axis=1)
        jkey = _job_keys(policy, placed, accum, counts, tpmax, placeable)
        j = jnp.argmin(jkey)
        any_placeable = jnp.any(placeable)
        nkey = _node_keys(policy, tp[j], cost, feas[j])
        node = jnp.argmax(nkey)
        do = any_placeable
        slot = placed[j]
        used = jnp.where(
            do,
            used.at[node].add(asks[j]),
            used,
        )
        choices = jnp.where(
            do, choices.at[j, slot].set(node.astype(jnp.int32)), choices
        )
        choice_tp = jnp.where(
            do, choice_tp.at[j, slot].set(tp[j, node]), choice_tp
        )
        placed = jnp.where(do, placed.at[j].add(1), placed)
        accum = jnp.where(do, accum.at[j].add(tp[j, node]), accum)
        return used, placed, accum, choices, choice_tp

    carry = (
        used0,
        jnp.zeros(g, dtype=jnp.int32),
        jnp.zeros(g, dtype=jnp.float32),
        jnp.full((g, max_c), -1, dtype=jnp.int32),
        jnp.zeros((g, max_c), dtype=jnp.float32),
    )
    used, placed, accum, choices, choice_tp = jax.lax.fori_loop(
        0, steps, step, carry
    )
    return choices, choice_tp, used


def oracle_hetero_place(
    capacity: np.ndarray,
    used0: np.ndarray,
    asks: np.ndarray,
    counts: np.ndarray,
    eligible: np.ndarray,
    tp: np.ndarray,
    tpmax: np.ndarray,
    cost: np.ndarray,
    policy: int,
    steps: int,
    max_c: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pure-NumPy host oracle: the same step math as the device kernel,
    executed stepwise. Byte-identical output is the contract (pinned in
    tests/test_hetero.py the way device/parity.py pins binpack)."""
    g = tp.shape[0]
    used = used0.astype(np.float32).copy()
    placed = np.zeros(g, dtype=np.int32)
    accum = np.zeros(g, dtype=np.float32)
    choices = np.full((g, max_c), -1, dtype=np.int32)
    choice_tp = np.zeros((g, max_c), dtype=np.float32)
    counts = counts.astype(np.int32)
    for _ in range(steps):
        feas = _feasible_matrix(capacity, used, asks, eligible, tp)
        active = placed < counts
        placeable = active & feas.any(axis=1)
        if not placeable.any():
            continue  # exact no-op, like the device loop's masked step
        jkey = _job_keys(policy, placed, accum, counts, tpmax, placeable)
        j = int(np.argmin(jkey))
        nkey = _node_keys(policy, tp[j], cost, feas[j])
        node = int(np.argmax(nkey))
        slot = int(placed[j])
        used[node] = used[node] + asks[j]
        choices[j, slot] = node
        choice_tp[j, slot] = tp[j, node]
        placed[j] += 1
        accum[j] = accum[j] + tp[j, node]
    return choices, choice_tp, used


# -- PlacementKernel-compatible wrapper --------------------------------------


@dataclass
class HeteroBatch:
    """Assembled dense inputs for one joint hetero pass."""

    capacity: np.ndarray
    used: np.ndarray
    asks: np.ndarray
    counts: np.ndarray
    eligible: np.ndarray
    tp: np.ndarray
    tpmax: np.ndarray
    cost: np.ndarray
    steps: int
    max_c: int


def build_hetero_batch(cluster, asks: list, used_override=None) -> HeteroBatch:
    pn = cluster.padded_n
    g = len(asks)
    ask_m = np.stack([a.ask for a in asks]).astype(np.float32)
    counts = np.array([a.count for a in asks], dtype=np.int32)
    eligible = np.stack([a.eligible for a in asks])
    tp = np.ones((g, pn), dtype=np.float32)
    for i, a in enumerate(asks):
        if a.throughputs is not None:
            tp[i] = a.throughputs
    elig_tp = np.where(eligible, tp, np.float32(0.0))
    tpmax = elig_tp.max(axis=1).astype(np.float32)
    used = (
        used_override if used_override is not None else cluster.used
    ).astype(np.float32)
    total = int(counts.sum())
    return HeteroBatch(
        capacity=cluster.capacity.astype(np.float32),
        used=used,
        asks=ask_m,
        counts=counts,
        eligible=eligible,
        tp=tp,
        tpmax=tpmax,
        cost=class_cost_vector(cluster),
        steps=_steps_bucket(max(total, 1)),
        max_c=_steps_bucket(max(int(counts.max(initial=1)), 1)),
    )


class HeteroPlacementKernel:
    """Drop-in for device/score.py's PlacementKernel behind the algorithm
    registry: hetero batches run the joint policy pass; anything the
    policy doesn't model (class-less batches, spread/distinct coupling,
    device-slot caps) delegates to the base binpack kernel so behavior
    degrades to exactly the pre-heterogeneity placement."""

    def __init__(
        self,
        policy: str,
        force_scan: bool = False,
        mesh=None,
        throughput_source: str = "declared",
        estimator=None,
    ):
        from ..device.score import PlacementKernel

        if policy not in POLICY_IDS:
            raise ValueError(f"unknown hetero policy {policy!r}")
        if throughput_source not in THROUGHPUT_SOURCES:
            raise ValueError(
                f"unknown throughput source {throughput_source!r}"
            )
        self.policy = policy
        self.policy_id = POLICY_IDS[policy]
        self.algorithm_spread = False
        self.force_scan = force_scan
        self._mesh = mesh
        # calibration seam (obs/calibrate.py): in learned mode the batch's
        # declared tp matrix is substituted — same shape and dtype, pure
        # Python, so the jitted kernel never retraces. Declared mode never
        # consults the estimator at all (bit-identity gate).
        self.throughput_source = throughput_source
        self.estimator = estimator
        self._base = PlacementKernel("binpack", force_scan, mesh=mesh)

    def mesh_cfg(self):
        from ..utils.backend import get_mesh

        return self._mesh if self._mesh is not None else get_mesh()

    def _learned(self) -> bool:
        return (
            self.throughput_source == THROUGHPUT_LEARNED
            and self.estimator is not None
        )

    def _hetero_eligible(self, cluster, asks: list) -> bool:
        if not getattr(cluster, "has_device_classes", False):
            return False
        # learned mode qualifies on profile keys alone: the whole point
        # is running the policies on jobs whose declared coefficients are
        # absent (or hidden), estimated from telemetry instead
        if not any(a.has_throughputs for a in asks) and not (
            self._learned()
            and any(getattr(a, "profile", "") for a in asks)
        ):
            return False
        # coupled features stay on the battle-tested base scan
        return not any(
            a.blocks is not None or a.slot_caps is not None
            or a.distinct_hosts
            for a in asks
        )

    def place(self, cluster, asks: list, **kwargs):
        from ..device.score import PlacementResult

        if not asks:
            return []
        if not self._hetero_eligible(cluster, asks):
            return self._base.place(cluster, asks, **kwargs)
        batch = build_hetero_batch(
            cluster, asks, used_override=kwargs.get("used_override")
        )
        if self._learned():
            # Python-level substitution before device upload: learned
            # per-(class × profile) values replace the declared matrix
            # cell-wise (declared anchors stay the fallback below the
            # sample floor), shapes/dtypes unchanged — zero new traces.
            from ..obs.calibrate import learned_tp_matrix

            batch.tp = learned_tp_matrix(
                self.estimator, cluster, asks, batch.tp
            )
            elig_tp = np.where(batch.eligible, batch.tp, np.float32(0.0))
            batch.tpmax = elig_tp.max(axis=1).astype(np.float32)
        from ..device.score import used_device
        from ..utils.backend import shard_put

        cfg = self.mesh_cfg()
        choices, choice_tp, _ = hetero_place_kernel(
            shard_put(batch.capacity, ("nodes",), cfg),
            used_device(cluster, batch.used, cfg),
            shard_put(batch.asks, ("groups",), cfg),
            shard_put(batch.counts, ("groups",), cfg),
            shard_put(batch.eligible, ("groups", "nodes"), cfg),
            shard_put(batch.tp, ("groups", "nodes"), cfg),
            shard_put(batch.tpmax, ("groups",), cfg),
            batch.cost,
            policy=self.policy_id,
            steps=batch.steps,
            max_c=batch.max_c,
        )
        choices = np.asarray(choices)
        choice_tp = np.asarray(choice_tp)
        explain = bool(kwargs.get("explain", False))
        results = []
        for i, a in enumerate(asks):
            rows = choices[i, : a.count].astype(np.int32)
            # score = throughput share of the job's best class, in [0, 1]
            denom = max(float(batch.tpmax[i]), float(_EPS))
            scores = np.where(
                rows >= 0,
                choice_tp[i, : a.count] / np.float32(denom),
                np.float32(-np.inf),
            ).astype(np.float32)
            res = PlacementResult(node_rows=rows, scores=scores)
            if explain:
                # same Python-level gate as the base kernel: explain-off
                # traces and places exactly as before; explanations rank
                # by this policy's node key so the top candidate is the
                # node the joint greedy takes first for this lane
                from ..obs.explain import explain_hetero_group

                res.explanation = explain_hetero_group(
                    cluster, a, batch.used,
                    policy=self.policy,
                    tp_row=batch.tp[i],
                    tpmax=float(batch.tpmax[i]),
                    cost=batch.cost,
                )
            results.append(res)
        return results


# -- seeded mixed-fleet A/B harness (bench.py hetero) ------------------------


def build_mixed_fleet(
    n_nodes: int, seed: int = 42, classes: tuple[str, ...] = (
        "tpu-v5e", "tpu-v4", "gpu-a100", "cpu"
    )
):
    """Seeded synthetic mixed fleet as ClusterTensors (≥3 device
    classes), mirroring bench.py's build_cluster but with a populated
    device-class column."""
    from ..device.flatten import ClusterTensors, node_bucket

    rng = np.random.default_rng(seed)
    pn = node_bucket(n_nodes)
    kind = rng.integers(0, len(classes), size=n_nodes)
    cpu = np.choose(kind % 3, [4000, 8000, 16000]).astype(np.float32)
    mem = np.choose(kind % 3, [8192, 16384, 32768]).astype(np.float32)
    capacity = np.zeros((pn, 4), dtype=np.float32)
    capacity[:n_nodes, 0] = cpu
    capacity[:n_nodes, 1] = mem
    capacity[:n_nodes, 2] = 100 * 1024
    capacity[:n_nodes, 3] = 1000
    used = np.zeros_like(capacity)
    load = rng.uniform(0.0, 0.3, size=(n_nodes, 1)).astype(np.float32)
    used[:n_nodes, :2] = capacity[:n_nodes, :2] * load
    ready = np.zeros(pn, dtype=bool)
    ready[:n_nodes] = True
    device_class_vocab = {"": 0}
    for c in classes:
        device_class_vocab[c] = len(device_class_vocab)
    device_class_ids = np.zeros(pn, dtype=np.int32)
    device_class_ids[:n_nodes] = kind.astype(np.int32) + 1
    return ClusterTensors(
        node_ids=[f"node-{i}" for i in range(n_nodes)],
        index=1,
        num_nodes=n_nodes,
        capacity=capacity,
        used=used,
        ready=ready,
        dc_ids=np.zeros(pn, dtype=np.int32),
        class_ids=np.pad(kind.astype(np.int32), (0, pn - n_nodes)),
        dc_vocab={"dc1": 0},
        class_vocab={c: i for i, c in enumerate(classes)},
        class_rep=list(range(min(len(classes), n_nodes))),
        node_row={f"node-{i}": i for i in range(n_nodes)},
        device_class_ids=device_class_ids,
        device_class_vocab=device_class_vocab,
    )


def build_mixed_asks(ct, n_jobs: int, count_per_job: int, seed: int = 7):
    """Seeded GroupAsks with per-class throughput maps: some jobs are
    TPU-hungry, some GPU-leaning, some indifferent — the mixed workload
    Gavel's policies differentiate on."""
    from ..device.flatten import GroupAsk

    rng = np.random.default_rng(seed)
    ids, vocab = ct.device_class_column()
    names = [n for n in vocab if n]
    pn = ct.padded_n
    profiles = []
    for j in range(n_jobs):
        kindj = j % 3
        m: dict[str, float] = {}
        for c in names:
            if kindj == 0:  # accelerator-hungry: fast on TPUs
                m[c] = 4.0 if c.startswith("tpu") else (
                    2.0 if c.startswith("gpu") else 0.5
                )
            elif kindj == 1:  # GPU-leaning
                m[c] = 3.5 if c.startswith("gpu") else (
                    1.5 if c.startswith("tpu") else 0.75
                )
            else:  # CPU-leaning batch (accelerators waste on it)
                m[c] = 1.0 if c == "cpu" else (
                    0.9 if c.startswith("tpu") else 0.6
                )
        profiles.append(m)
    asks = []
    for j, m in enumerate(profiles):
        per_class = np.ones(len(vocab), dtype=np.float32)
        for name, cid in vocab.items():
            if name:
                per_class[cid] = np.float32(m.get(name, 1.0))
        vec = per_class[ids]
        has_tp = not bool(np.all(vec == np.float32(1.0)))
        cpu = float(rng.choice([500, 1000, 2000]))
        memv = float(rng.choice([512, 1024, 2048]))
        asks.append(
            GroupAsk(
                job_id=f"job-{j}",
                tg_name="web",
                count=count_per_job,
                desired_total=count_per_job,
                ask=np.array([cpu, memv, 300.0, 0.0], dtype=np.float32),
                eligible=ct.ready.copy(),
                job_counts=np.zeros(pn, dtype=np.int32),
                penalty_nodes=np.zeros(pn, dtype=bool),
                affinity_scores=np.zeros(pn, dtype=np.float32),
                has_affinities=False,
                distinct_hosts=False,
                throughputs=vec if has_tp else None,
                has_throughputs=has_tp,
            )
        )
    return asks


def _quality_metrics(ct, asks, results) -> dict:
    """Canonical placement-quality block for one algorithm's output."""
    ids, vocab = ct.device_class_column()
    names = {cid: name for name, cid in vocab.items()}
    per_class_alloc: dict[str, int] = {}
    per_class_cpu_used: dict[str, float] = {}
    cost_vec = class_cost_vector(ct)
    shares = []
    makespans = []
    total_cost = 0.0
    total_rate = 0.0
    placed = 0
    for a, r in zip(asks, results):
        tp_vec = (
            a.throughputs
            if a.throughputs is not None
            else np.ones(ct.padded_n, dtype=np.float32)
        )
        rows = r.node_rows[r.node_rows >= 0]
        placed += int(rows.size)
        rate = float(tp_vec[rows].sum(dtype=np.float32))
        elig_tp = np.where(a.eligible, tp_vec, 0.0)
        ideal = float(elig_tp.max()) * a.count
        shares.append(rate / ideal if ideal > 0 else 0.0)
        makespans.append(a.count / rate if rate > 0 else float("inf"))
        total_cost += float(cost_vec[rows].sum(dtype=np.float32))
        total_rate += rate
        for row in rows:
            name = names.get(int(ids[row]), "")
            per_class_alloc[name] = per_class_alloc.get(name, 0) + 1
            per_class_cpu_used[name] = per_class_cpu_used.get(name, 0.0) + float(
                a.ask[0]
            )
    class_cap: dict[str, float] = {}
    for i in range(ct.num_nodes):
        name = names.get(int(ids[i]), "")
        class_cap[name] = class_cap.get(name, 0.0) + float(ct.capacity[i, 0])
    utilization = {
        name: round(per_class_cpu_used.get(name, 0.0) / cap, 4)
        for name, cap in sorted(class_cap.items())
        if cap > 0
    }
    return {
        "placed": placed,
        "worst_share": round(min(shares), 4) if shares else 0.0,
        "mean_share": round(float(np.mean(shares)), 4) if shares else 0.0,
        "makespan": round(max(makespans), 4) if makespans else 0.0,
        "throughput_per_cost": round(total_rate / total_cost, 4)
        if total_cost > 0
        else 0.0,
        "per_class_allocs": dict(sorted(per_class_alloc.items())),
        "per_class_cpu_utilization": utilization,
    }


def run_hetero_ab(
    n_nodes: int = 1000,
    n_jobs: int = 12,
    count_per_job: int = 25,
    seed: int = 42,
) -> dict:
    """The `bench.py hetero` A/B block: binpack vs each hetero policy on
    one seeded mixed fleet. Placements are deterministic for a seed, so
    the whole report is byte-reproducible (chaos/soak-report style).
    Also cross-checks each policy's device pass against its host oracle
    and reports the mismatch count (must be 0)."""
    from ..device.score import PlacementKernel

    ct = build_mixed_fleet(n_nodes, seed=seed)
    asks = build_mixed_asks(ct, n_jobs, count_per_job, seed=seed + 1)

    base = PlacementKernel("binpack")
    base_results = base.place(ct, asks)
    report: dict = {
        "config": {
            "nodes": n_nodes,
            "jobs": n_jobs,
            "count_per_job": count_per_job,
            "seed": seed,
            "device_classes": sorted(
                k for k in ct.device_class_vocab if k
            ),
        },
        "binpack": _quality_metrics(ct, asks, base_results),
        "policies": {},
        "oracle_mismatches": 0,
    }
    for policy in ("maxmin", "makespan", "cost"):
        kern = HeteroPlacementKernel(policy)
        results = kern.place(ct, asks)
        metrics = _quality_metrics(ct, asks, results)
        batch = build_hetero_batch(ct, asks)
        o_choices, o_tp, _ = oracle_hetero_place(
            batch.capacity, batch.used, batch.asks, batch.counts,
            batch.eligible, batch.tp, batch.tpmax, batch.cost,
            POLICY_IDS[policy], batch.steps, batch.max_c,
        )
        d_choices, d_tp, _ = hetero_place_kernel(
            batch.capacity, batch.used, batch.asks, batch.counts,
            batch.eligible, batch.tp, batch.tpmax, batch.cost,
            policy=POLICY_IDS[policy], steps=batch.steps,
            max_c=batch.max_c,
        )
        mism = int(
            (np.asarray(d_choices) != o_choices).sum()
            + (np.asarray(d_tp).view(np.uint32) != o_tp.view(np.uint32)).sum()
        )
        metrics["oracle_identical"] = mism == 0
        report["oracle_mismatches"] += mism
        report["policies"][f"hetero-{policy}"] = metrics

    b = report["binpack"]
    mm = report["policies"]["hetero-maxmin"]
    ms = report["policies"]["hetero-makespan"]
    report["ab"] = {
        "maxmin_worst_share_delta": round(
            mm["worst_share"] - b["worst_share"], 4
        ),
        "makespan_delta": round(b["makespan"] - ms["makespan"], 4),
        "maxmin_improves_worst_share": mm["worst_share"] > b["worst_share"],
        "makespan_reduced": ms["makespan"] < b["makespan"],
    }
    report["ok"] = (
        report["ab"]["maxmin_improves_worst_share"]
        and report["ab"]["makespan_reduced"]
        and report["oracle_mismatches"] == 0
    )
    return report
