"""Defrag batch assembly + the ``bench.py defrag`` A/B harness.

The host half of the migration plane's solver seam (the server half —
two-phase move sequencing against the live store — is
``server/defrag.py``). This module owns:

- ``build_defrag_batch``: dense (allocs × nodes) tensors for one defrag
  pass — consolidation scores, per-alloc sizes/current rows, and the
  conservative ``used`` the kernel prices against;
- ``run_defrag_ab``: the bench gate. A seeded churned fleet is left
  fragmented (load smeared thinly across most nodes); bounded-budget
  defrag cycles then run the ``migrate_plan_kernel`` → apply → free
  loop and the gate asserts a measured fraction of packing efficiency
  comes back, byte-reproducibly, with the kernel pinned to its NumPy
  oracle along the way.

Consolidation scoring: a move's destination value is the node's
post-churn utilization (the binpack instinct — fill the fullest node
that fits), so gain = util[dest] − util[cur] − move_cost − λ[dest] and
the auction empties the thinnest nodes first. Scores are assembled on
host in f32 and fed identically to kernel and oracle — parity is the
kernel's contract, not the assembler's.

Like ``scheduler/cp.py``, only this module, ``server/defrag.py``, and
the jaxlint exercise fleet may invoke the migrate kernel (lint rule
NTA021, MigrationSeamDiscipline).
"""

from __future__ import annotations

import numpy as np

from ..device.migrate import (
    migrate_plan_kernel,
    oracle_migrate_plan,
    packing_efficiency,
)

# Flat per-alloc migration cost priced against score-delta gain: a move
# must improve its alloc's consolidation score by more than this to be
# planned at all. Power of two (exact f32).
MOVE_COST = np.float32(0.0625)


def build_defrag_fleet(
    n_nodes: int, n_allocs: int, seed: int = 42
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Seeded fragmented fleet: every alloc lands on its own
    uniformly-random node (the end state of a long arrival/stop churn —
    load smeared thin), sized so a perfect repack needs only a small
    core of nodes. Returns (capacity, used, sizes, cur, ready)."""
    rng = np.random.default_rng(seed)
    capacity = np.zeros((n_nodes, 4), dtype=np.float32)
    capacity[:, 0] = 4000
    capacity[:, 1] = 8192
    capacity[:, 2] = 100 * 1024
    capacity[:, 3] = 1000
    sizes = np.zeros((n_allocs, 4), dtype=np.float32)
    sizes[:, 0] = rng.choice([200.0, 400.0, 800.0], size=n_allocs)
    sizes[:, 1] = rng.choice([512.0, 1024.0, 2048.0], size=n_allocs)
    sizes[:, 2] = 300.0
    cur = np.zeros(n_allocs, dtype=np.int32)
    used = np.zeros_like(capacity)
    for i in range(n_allocs):
        # scatter thinly but never over capacity: a random node among
        # those with room (churn fragments, it does not overload)
        fits = np.flatnonzero(
            np.all(used + sizes[i] <= capacity, axis=1)
        )
        node = int(rng.choice(fits)) if fits.size else 0
        cur[i] = node
        used[node] += sizes[i]
    ready = np.ones(n_nodes, dtype=bool)
    return capacity, used, sizes, cur, ready


def consolidation_scores(
    capacity: np.ndarray, used: np.ndarray, sizes: np.ndarray
) -> np.ndarray:
    """f32[A, N] destination value per (alloc, node): the node's cpu+mem
    utilization fraction — higher is fuller, and the auction's positive-
    gain feasibility turns that into 'move off thin nodes onto full
    ones'. Identical host-built input for kernel and oracle."""
    denom = np.maximum(capacity[:, :2].sum(axis=1), np.float32(1.0))
    util = (used[:, :2].sum(axis=1) / denom).astype(np.float32)
    a = sizes.shape[0]
    return np.broadcast_to(util[None, :], (a, util.shape[0])).astype(
        np.float32
    ).copy()


def build_defrag_batch(capacity, used, sizes, cur, eligible=None):
    """Assemble one defrag pass's kernel arguments (minus budget/steps).
    ``used`` is the conservative committed usage — sources are NOT
    pre-freed; the kernel's used-only-increases model is exactly the
    mid-move capacity invariant (law 16)."""
    a, n = sizes.shape[0], capacity.shape[0]
    if eligible is None:
        eligible = np.ones((a, n), dtype=bool)
    scores = consolidation_scores(capacity, used, sizes)
    arange_a = np.arange(a)
    # the value of STAYING is the current node's utilization as seen
    # from outside — without the alloc's own contribution. With it
    # included, a perfectly uniform smear (every node equally thin)
    # prices every move as a loss and consolidation can never start.
    denom = np.maximum(capacity[:, :2].sum(axis=1), np.float32(1.0))
    own = (sizes[:, :2].sum(axis=1) / denom[cur]).astype(np.float32)
    cur_scores = (scores[arange_a, cur] - own).astype(np.float32)
    move_cost = np.full(a, MOVE_COST, dtype=np.float32)
    lam0 = np.zeros(n, dtype=np.float32)
    return (
        capacity.astype(np.float32),
        used.astype(np.float32),
        sizes.astype(np.float32),
        cur.astype(np.int32),
        eligible,
        scores,
        cur_scores,
        move_cost,
    )


def _steps_for(n_allocs: int) -> int:
    b = 1
    while b < n_allocs + 1:
        b <<= 1
    return b


def run_defrag_ab(
    n_nodes: int = 48,
    n_allocs: int = 96,
    budget: int = 8,
    max_cycles: int = 12,
    seed: int = 42,
) -> dict:
    """The ``bench.py defrag`` gate: fragment → cycle the kernel with a
    bounded per-cycle budget → measure recovered packing efficiency.
    Each cycle is the controller's two-phase shape in miniature: the
    kernel commits every replacement on top of live ``used`` (capacity
    conserved mid-flight), then the cycle's sources free only after the
    whole cycle lands. The kernel is cross-checked byte-identical
    against its NumPy oracle on two seeds."""
    capacity, used, sizes, cur, ready = build_defrag_fleet(
        n_nodes, n_allocs, seed=seed
    )
    eff_before = packing_efficiency(capacity, used, ready)
    steps = _steps_for(n_allocs)

    mismatches = 0
    for check_seed in (seed, seed + 1):
        c2, u2, s2, r2, _ = build_defrag_fleet(
            n_nodes, n_allocs, seed=check_seed
        )
        args = build_defrag_batch(c2, u2, s2, r2)
        lam0 = np.zeros(c2.shape[0], dtype=np.float32)
        d = migrate_plan_kernel(
            *args, np.int32(budget), lam0, steps=steps
        )
        o = oracle_migrate_plan(*args, np.int32(budget), lam0, steps)
        mismatches += int(
            (np.asarray(d[0]) != o[0]).sum()
            + (np.asarray(d[1]).view(np.uint32)
               != o[1].view(np.uint32)).sum()
            + (np.asarray(d[2]).view(np.uint32)
               != o[2].view(np.uint32)).sum()
            + (int(np.asarray(d[3])) != o[3])
            + (np.asarray(d[5]).view(np.uint32)
               != o[5].view(np.uint32)).sum()
        )

    cycles = 0
    moves_total = 0
    capacity_violations = 0
    budget_exceeded = 0
    while cycles < max_cycles:
        args = build_defrag_batch(capacity, used, sizes, cur)
        lam0 = np.zeros(n_nodes, dtype=np.float32)
        dest, gains, used_mid, moves, rounds, lam = oracle_migrate_plan(
            *args, np.int32(budget), lam0, steps
        )
        if moves == 0:
            break
        cycles += 1
        moves_total += moves
        if moves > budget:
            budget_exceeded += 1
        # phase A: every replacement committed on top of live usage —
        # the mid-move capacity invariant, checked here mid-flight
        if bool((used_mid > capacity + np.float32(1e-3)).any()):
            capacity_violations += 1
        # phase B: the cycle landed; sources free and rows move
        moved = np.flatnonzero(dest >= 0)
        np.subtract.at(used_mid, cur[moved], sizes[moved])
        used = used_mid
        cur = np.where(dest >= 0, dest, cur).astype(np.int32)
        if bool((used < -np.float32(1e-3)).any()):
            capacity_violations += 1

    eff_after = packing_efficiency(capacity, used, ready)
    gap = max(1.0 - eff_before, 1e-9)
    recovered = (eff_after - eff_before) / gap
    report = {
        "config": {
            "nodes": n_nodes,
            "allocs": n_allocs,
            "budget": budget,
            "max_cycles": max_cycles,
            "seed": seed,
        },
        "before": {"packing_efficiency": round(eff_before, 6)},
        "after": {"packing_efficiency": round(eff_after, 6)},
        "cycles": cycles,
        "moves_total": moves_total,
        "recovered_fraction": round(recovered, 6),
        "capacity_violations": capacity_violations,
        "budget_exceeded_cycles": budget_exceeded,
        "oracle_mismatches": mismatches,
    }
    report["ok"] = (
        mismatches == 0
        and capacity_violations == 0
        and budget_exceeded == 0
        and eff_after > eff_before
        and recovered >= 0.5
    )
    return report


DEFRAG_SCHEMA = (
    "after.packing_efficiency",
    "before.packing_efficiency",
    "budget_exceeded_cycles",
    "capacity_violations",
    "config.allocs",
    "config.budget",
    "config.max_cycles",
    "config.nodes",
    "config.seed",
    "cycles",
    "moves_total",
    "ok",
    "oracle_mismatches",
    "recovered_fraction",
)
