"""L7 CLI."""
