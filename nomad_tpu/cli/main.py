"""CLI — ``python -m nomad_tpu.cli``.

Reference: command/ (~120 subcommands via mitchellh/cli). The operational
core subset: agent -dev, job run/plan/status/stop, node status/drain/
eligibility, alloc status, eval status, operator scheduler-config,
server members. Talks to the HTTP API via the SDK (never in-process),
matching the reference CLI's strict HTTP boundary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from ..api.client import APIException, NomadClient

DEFAULT_ADDR = os.environ.get("NOMAD_TPU_ADDR", "http://127.0.0.1:4646")


def _client(args) -> NomadClient:
    return NomadClient(args.address, token=getattr(args, "token", ""))


def _fail(msg: str) -> int:
    print(f"error: {msg}", file=sys.stderr)
    return 1


def _load_jobfile(path: str, variables: dict | None = None) -> dict:
    """Read a job file: HCL (.hcl/.nomad, the canonical format) or JSON.
    Mirrors command/job_run.go, which feeds files through jobspec2."""
    try:
        with open(path) as f:
            src = f.read()
    except OSError as e:
        raise SystemExit(f"error: cannot read job file: {e}")
    stripped = src.lstrip()
    if path.endswith((".hcl", ".nomad")) or not stripped.startswith("{"):
        from ..api.codec import encode
        from ..jobspec import JobspecError, parse_job_file

        try:
            return encode(parse_job_file(src, variables))
        except JobspecError as e:
            raise SystemExit(f"error: {path}: {e}")
    if variables:
        raise SystemExit("error: -var only applies to HCL job files")
    try:
        data = json.loads(src)
    except json.JSONDecodeError as e:
        raise SystemExit(f"error: {path} is not valid JSON: {e}")
    return data.get("job", data)


def _parse_var_flags(var_flags) -> dict:
    out = {}
    for spec in var_flags or []:
        key, sep, val = spec.partition("=")
        if not sep:
            raise SystemExit(f"error: -var must be key=value, got {spec!r}")
        try:
            out[key] = json.loads(val)
        except json.JSONDecodeError:
            out[key] = val
    return out


# -- commands ---------------------------------------------------------------
def cmd_agent(args) -> int:
    """Run a dev agent (server+client+HTTP) in the foreground. HCL
    config files (-config, command/agent/config.go) merge over defaults;
    CLI flags override."""
    if not args.dev:
        return _fail("only -dev mode is supported in this build")
    from ..agent import DevAgent
    from ..agent_config import AgentConfig, load_agent_config
    from ..api.http import HTTPAgent

    cfg = AgentConfig()
    if getattr(args, "config", None):
        try:
            cfg = load_agent_config(args.config)
        except Exception as e:  # noqa: BLE001 — config errors are user-facing
            return _fail(f"config: {e}")
    agent = DevAgent(
        data_dir=args.data_dir or cfg.data_dir or None,
        num_workers=cfg.server.num_schedulers or 2,
        heartbeat_ttl=cfg.server.heartbeat_ttl_s,
        host_volumes=cfg.client.host_volumes or None,
        driver_mode=cfg.client.driver_mode,
    )
    if cfg.client.gc_max_allocs:
        agent.client.gc_max_terminal_allocs = cfg.client.gc_max_allocs
    if cfg.telemetry.publish_allocation_metrics:
        agent.client.publish_allocation_metrics = True
    agent.start()
    bind = args.bind if args.bind != "127.0.0.1:4646" else (
        f"{cfg.bind_addr}:{cfg.http_port}"
    )
    host, _, port = bind.partition(":")
    http = HTTPAgent(
        agent.server, agent.client, host=host or "127.0.0.1",
        port=int(port or 4646),
    )
    http.start()
    print(f"==> nomad-tpu dev agent running at {http.address}")
    print(f"    node id: {agent.client.node.id}")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        print("==> shutting down")
        http.stop()
        agent.shutdown()
    return 0


def cmd_job_run(args) -> int:
    job = _load_jobfile(args.file, _parse_var_flags(getattr(args, "var", None)))
    c = _client(args)
    try:
        out = c.jobs.register(job)
    except APIException as e:
        return _fail(str(e))
    print(f"==> evaluation {out['eval_id']} created")
    if args.detach:
        return 0
    # poll until the eval completes (command/job_run.go monitor)
    for _ in range(100):
        ev = c.evaluations.info(out["eval_id"])
        if ev["status"] in ("complete", "failed", "canceled"):
            print(f"==> evaluation {out['eval_id']} finished: {ev['status']}")
            if ev.get("failed_tg_allocs"):
                for tg, m in ev["failed_tg_allocs"].items():
                    print(f"    group {tg!r}: placement failed")
                return 2
            return 0
        time.sleep(0.2)
    return _fail("timed out waiting for evaluation")


def cmd_job_plan(args) -> int:
    job = _load_jobfile(args.file, _parse_var_flags(getattr(args, "var", None)))
    c = _client(args)
    try:
        out = c.jobs.plan(job)
    except APIException as e:
        return _fail(str(e))
    print(f"Job: {out['job_id']} ({out['diff_type']}, version {out['version']})")
    for tg, ann in out.get("annotations", {}).items():
        parts = [f"+{ann['place']} place"]
        if ann.get("stop"):
            parts.append(f"-{ann['stop']} stop")
        if ann.get("preemptions"):
            parts.append(f"!{ann['preemptions']} preempt")
        print(f"  group {tg!r}: {', '.join(parts)}")
    if out.get("failed_tg_allocs"):
        print("  WARNING: some allocations would fail to place:")
        for tg, m in out["failed_tg_allocs"].items():
            if isinstance(m, dict):
                detail = []
                dims = m.get("dimension_exhausted") or {}
                if dims:
                    detail.append(
                        "exhausted "
                        + ", ".join(
                            f"{k}={v}" for k, v in sorted(dims.items())
                        )
                    )
                rej = m.get("rejections") or {}
                if rej:
                    detail.append(
                        ", ".join(f"{k}={v}" for k, v in sorted(rej.items()))
                    )
                suffix = f" ({'; '.join(detail)})" if detail else ""
                print(
                    f"    {tg}: {m.get('coalesced_failures', 0)} "
                    f"failure(s){suffix}"
                )
            else:
                print(f"    {tg}: {m}")
    g = out.get("gang")
    if g:
        verdict = (
            "all members place"
            if g.get("feasible")
            else "infeasible — whole gang would release (all-or-nothing)"
        )
        members = ", ".join(
            f"{m}=+{row.get('place', 0)}"
            for m, row in sorted(g.get("members", {}).items())
        )
        print(f"  gang: {verdict} ({members})")
        for r in g.get("reasons", []):
            print(f"    reason: {r}")
    if getattr(args, "verbose", False):
        # -verbose: per-group candidate score tables from the dry run's
        # explain seam (scheduler/annotate.py)
        for tg, group in sorted(
            (out.get("placement_explanations") or {}).items()
        ):
            print(
                f"\nScores for group {tg!r} "
                f"(algorithm {group.get('algorithm', '?')}, "
                f"{group.get('feasible_nodes', 0)}/"
                f"{group.get('nodes_evaluated', 0)} nodes feasible)"
            )
            _render_candidate_table(group)
    return 0


def cmd_job_status(args) -> int:
    c = _client(args)
    if not args.job_id:
        jobs = c.jobs.list()
        if not jobs:
            print("no jobs registered")
            return 0
        print(f"{'ID':<30} {'Type':<10} {'Priority':<9} {'Status':<10}")
        for j in jobs:
            print(f"{j['id']:<30} {j['type']:<10} {j['priority']:<9} {j['status']:<10}")
        return 0
    try:
        job = c.jobs.info(args.job_id)
    except APIException as e:
        return _fail(str(e))
    print(f"ID       = {job['id']}")
    print(f"Name     = {job['name']}")
    print(f"Type     = {job['type']}")
    print(f"Priority = {job['priority']}")
    print(f"Status   = {job['status']}")
    print(f"Version  = {job['version']}")
    summary = c.jobs.summary(args.job_id)["summary"]
    print("\nSummary")
    hdr = f"{'Group':<15} {'Queued':<7} {'Starting':<9} {'Running':<8} {'Complete':<9} {'Failed':<7} {'Lost':<5}"
    print(hdr)
    for tg, s in summary.items():
        print(
            f"{tg:<15} {s.get('queued',0):<7} {s.get('starting',0):<9} "
            f"{s.get('running',0):<8} {s.get('complete',0):<9} "
            f"{s.get('failed',0):<7} {s.get('lost',0):<5}"
        )
    print("\nAllocations")
    print(f"{'ID':<10} {'Node':<10} {'Group':<15} {'Desired':<8} {'Status':<10}")
    for a in c.jobs.allocations(args.job_id):
        print(
            f"{a['id'][:8]:<10} {a['node_id'][:8]:<10} {a['task_group']:<15} "
            f"{a['desired_status']:<8} {a['client_status']:<10}"
        )
    return 0


def cmd_job_stop(args) -> int:
    c = _client(args)
    try:
        out = c.jobs.deregister(args.job_id)
    except APIException as e:
        return _fail(str(e))
    print(f"==> deregistered, evaluation {out.get('eval_id', '')}")
    return 0


def cmd_node_status(args) -> int:
    c = _client(args)
    if args.node_id:
        try:
            n = c.nodes.info(args.node_id)
        except APIException as e:
            return _fail(str(e))
        print(json.dumps(n, indent=2, default=str))
        return 0
    nodes = c.nodes.list()
    print(f"{'ID':<10} {'Name':<20} {'DC':<8} {'Status':<8} {'Eligibility':<12}")
    for n in nodes:
        print(
            f"{n['id'][:8]:<10} {n['name'][:18]:<20} {n['datacenter']:<8} "
            f"{n['status']:<8} {n['scheduling_eligibility']:<12}"
        )
    return 0


def cmd_node_drain(args) -> int:
    c = _client(args)
    try:
        out = c.nodes.drain(args.node_id, enabled=not args.disable)
    except APIException as e:
        return _fail(str(e))
    print(f"==> drain {'disabled' if args.disable else 'enabled'}; evals: {len(out['eval_ids'])}")
    return 0


def cmd_node_eligibility(args) -> int:
    c = _client(args)
    try:
        c.nodes.eligibility(args.node_id, eligible=args.enable)
    except APIException as e:
        return _fail(str(e))
    print("==> eligibility updated")
    return 0


def cmd_alloc_status(args) -> int:
    c = _client(args)
    try:
        a = c.allocations.info(args.alloc_id)
    except APIException as e:
        return _fail(str(e))
    print(f"ID            = {a['id']}")
    print(f"Name          = {a['name']}")
    print(f"Node ID       = {a['node_id']}")
    print(f"Job ID        = {a['job_id']}")
    print(f"Desired       = {a['desired_status']}")
    print(f"Client Status = {a['client_status']}")
    metrics = a.get("metrics") or {}
    if metrics.get("scores"):
        print("\nPlacement Metrics")
        for k, v in metrics["scores"].items():
            print(f"  {k} = {v:.4f}")
        print(f"  nodes evaluated = {metrics.get('nodes_evaluated')}")
    return 0


def cmd_alloc_logs(args) -> int:
    """nomad alloc logs [-stderr] [-f] <alloc_id> [task]
    (command/alloc_logs.go)."""
    c = _client(args)
    try:
        info = c.allocations.info(args.alloc_id)
    except APIException as e:
        return _fail(str(e))
    task = args.task
    if not task:
        tasks = list((info.get("task_states") or {}).keys())
        if len(tasks) == 1:
            task = tasks[0]
        elif not tasks:
            return _fail("allocation has no tasks with state yet; pass a task name")
        else:
            return _fail(f"allocation has multiple tasks, pick one: {tasks}")
    kind = "stderr" if args.stderr else "stdout"
    try:
        for frame in c.allocations.logs(
            info["id"], task, type=kind, follow=args.follow,
            offset=-args.tail if args.tail else 0,  # negative = tail
        ):
            print(frame["data"], end="")
    except KeyboardInterrupt:
        pass
    except APIException as e:
        return _fail(str(e))
    return 0


def cmd_alloc_fs(args) -> int:
    """nomad alloc fs <alloc_id> [path] (command/alloc_fs.go): ls for
    directories, cat for files."""
    c = _client(args)
    try:
        info = c.allocations.info(args.alloc_id)
        path = args.path or "/"
        import json as _json

        try:
            entries = c.allocations.fs_ls(info["id"], path)
            for e in entries:
                kind = "d" if e["is_dir"] else "-"
                print(f"{kind} {e['size']:>10}  {e['name']}")
        except APIException:
            print(c.allocations.fs_cat(info["id"], path), end="")
    except APIException as e:
        return _fail(str(e))
    return 0


def cmd_eval_status(args) -> int:
    c = _client(args)
    try:
        e = c.evaluations.info(args.eval_id)
    except APIException as e2:
        return _fail(str(e2))
    print(json.dumps(e, indent=2, default=str))
    failed = e.get("failed_tg_allocs") or {}
    if failed:
        # structured failure summary: what to drain or resize
        # (AllocMetric.dimension_exhausted / class_exhausted / rejections)
        print("\nFailed Placements")
        for tg, m in sorted(failed.items()):
            if not isinstance(m, dict):
                print(f"  group {tg!r}: placement failed")
                continue
            print(
                f"  group {tg!r}: {m.get('nodes_exhausted', 0)} of "
                f"{m.get('nodes_evaluated', 0)} nodes exhausted "
                f"({m.get('coalesced_failures', 0)} coalesced failures)"
            )
            dims = m.get("dimension_exhausted") or {}
            if dims:
                parts = ", ".join(
                    f"{k}={v}" for k, v in sorted(dims.items())
                )
                print(f"    exhausted dimensions: {parts}")
            classes = m.get("class_exhausted") or {}
            if classes:
                parts = ", ".join(
                    f"{k}={v}" for k, v in sorted(classes.items())
                )
                print(f"    infeasible device classes: {parts}")
            rej = m.get("rejections") or {}
            if rej:
                parts = ", ".join(
                    f"{k}={v}" for k, v in sorted(rej.items())
                )
                print(f"    rejections: {parts}")
    return 0


def _render_candidate_table(group: dict, indent: str = "  ") -> None:
    """Render one group's explanation dict (obs/explain.py
    explanation_to_dict shape) as the `alloc why` / `eval placement`
    candidate table."""
    cands = group.get("top_candidates") or []
    if cands:
        comp_keys = sorted(
            {k for c in cands for k in (c.get("components") or {})}
        )
        print(
            f"{indent}{'Rank':<5} {'Node':<10} {'Final':>9} {'Placed':>7}  "
            + "  ".join(f"{k:>22}" for k in comp_keys)
        )
        for c in cands:
            comps = c.get("components") or {}
            print(
                f"{indent}{c.get('rank', '?'):<5} "
                f"{str(c.get('node_id', ''))[:8]:<10} "
                f"{c.get('final_score', 0.0):>9.4f} {c.get('placed', 0):>7}  "
                + "  ".join(
                    f"{comps[k]:>22.4f}" if k in comps else f"{'-':>22}"
                    for k in comp_keys
                )
            )
    rej = group.get("rejections") or {}
    if rej:
        parts = ", ".join(f"{k}={v}" for k, v in sorted(rej.items()))
        print(f"{indent}rejections: {parts}")
    placed = group.get("placed_nodes") or []
    if placed:
        shown = ", ".join(n[:8] for n in placed[:8])
        more = f" (+{len(placed) - 8} more)" if len(placed) > 8 else ""
        print(f"{indent}placed on: {shown}{more}")


def cmd_alloc_why(args) -> int:
    """nomad-tpu alloc why <alloc>: per-component score provenance for
    one allocation (command analog of AllocMetric/ScoreMetaData)."""
    c = _client(args)
    try:
        out = c.allocations.explain(args.alloc_id)
    except APIException as e:
        return _fail(str(e))
    print(f"Allocation = {out.get('alloc_id', '')}")
    print(f"Job        = {out.get('job_id', '')}")
    print(f"Group      = {out.get('task_group', '')}")
    print(f"Node       = {out.get('node_id', '')}")
    print(f"Eval       = {out.get('eval_id', '')}")
    for sm in out.get("score_meta") or []:
        comps = ", ".join(
            f"{k}={v:.4f}"
            for k, v in sorted((sm.get("scores") or {}).items())
        )
        print(
            f"\nScore ({str(sm.get('node_id', ''))[:8]}) = "
            f"{sm.get('norm_score', 0.0):.4f}"
            + (f"  [{comps}]" if comps else "")
        )
    group = out.get("explanation")
    if group:
        print(
            f"\nCandidates (algorithm {group.get('algorithm', '?')}, "
            f"{group.get('feasible_nodes', 0)}/"
            f"{group.get('nodes_evaluated', 0)} nodes feasible)"
        )
        _render_candidate_table(group)
    elif not out.get("score_meta"):
        print(
            "\nno explanation available (eval aged out of the ring, or "
            "placement_explanations disabled)"
        )
    return 0


def cmd_eval_placement(args) -> int:
    """nomad-tpu eval placement <eval>: per-group candidate tables +
    rejection histograms for one evaluation."""
    c = _client(args)
    try:
        out = c.evaluations.placement(args.eval_id)
    except APIException as e:
        return _fail(str(e))
    print(f"Evaluation = {out.get('eval_id', '')}")
    print(f"Job        = {out.get('job_id', '')}")
    if out.get("source"):
        print(f"Source     = {out['source']}")
    for tg, group in sorted((out.get("groups") or {}).items()):
        algo = group.get("algorithm", "")
        detail = (
            f" (algorithm {algo}, {group.get('feasible_nodes', 0)}/"
            f"{group.get('nodes_evaluated', 0)} nodes feasible)"
            if algo
            else ""
        )
        print(f"\nGroup {tg!r}{detail}")
        _render_candidate_table(group)
    return 0


def cmd_volume_status(args) -> int:
    c = _client(args)
    if getattr(args, "volume_id", None):
        try:
            v = c.volumes.info(args.volume_id)
        except APIException as e:
            return _fail(str(e))
        print(json.dumps(v, indent=2, default=str))
        return 0
    vols = c.volumes.list()
    print(f"{'ID':<20} {'Plugin':<12} {'Access Mode':<26} {'Schedulable':<12} Claims(R/W)")
    for v in vols:
        print(
            f"{v['id'][:18]:<20} {v['plugin_id'][:10]:<12} "
            f"{v['access_mode']:<26} {str(v['schedulable']):<12} "
            f"{v['claims_read']}/{v['claims_write']}"
        )
    return 0


def cmd_volume_register(args) -> int:
    c = _client(args)
    with open(args.file) as f:
        vol = json.load(f)
    if not isinstance(vol, dict):
        return _fail(f"volume spec {args.file!r} must be a JSON object")
    # map Nomad-convention capitalized keys per-key (specs can mix cases)
    camel = {"ID": "id", "Name": "name", "PluginID": "plugin_id",
             "ExternalID": "external_id", "Namespace": "namespace",
             "AccessMode": "access_mode",
             "AttachmentMode": "attachment_mode"}
    vol = {camel.get(k, k): v for k, v in vol.items()}
    if not vol.get("id"):
        return _fail(f"volume spec {args.file!r} has no 'id' field")
    try:
        c.volumes.register(vol)
    except APIException as e:
        return _fail(str(e))
    print(f"Volume {vol['id']!r} registered")
    return 0


def cmd_volume_deregister(args) -> int:
    c = _client(args)
    try:
        c.volumes.deregister(args.volume_id, force=args.force)
    except APIException as e:
        return _fail(str(e))
    print(f"Volume {args.volume_id!r} deregistered")
    return 0


def cmd_plugin_status(args) -> int:
    c = _client(args)
    plugins = c.volumes.plugins()
    print(f"{'ID':<20} {'Healthy Nodes':<14} Healthy Controllers")
    for p in plugins:
        print(
            f"{p['id'][:18]:<20} {p['nodes_healthy']:<14} "
            f"{p['controllers_healthy']}"
        )
    return 0


def cmd_deployment_list(args) -> int:
    c = _client(args)
    deployments = c.deployments.list()
    print(f"{'ID':<10} {'Job':<25} {'Version':<8} {'Status':<12} Description")
    for d in deployments:
        print(
            f"{d['id'][:8]:<10} {d['job_id'][:23]:<25} {d['job_version']:<8} "
            f"{d['status']:<12} {d['status_description']}"
        )
    return 0


def cmd_deployment_status(args) -> int:
    c = _client(args)
    try:
        d = c.deployments.info(args.deployment_id)
    except APIException as e:
        return _fail(str(e))
    print(f"ID          = {d['id']}")
    print(f"Job ID      = {d['job_id']}")
    print(f"Job Version = {d['job_version']}")
    print(f"Status      = {d['status']}")
    print(f"Description = {d['status_description']}")
    print("\nDeployed")
    print(f"{'Group':<15} {'Auto':<6} {'Promoted':<9} {'Desired':<8} {'Canaries':<9} {'Placed':<7} {'Healthy':<8} {'Unhealthy':<9}")
    for name, s in d.get("task_groups", {}).items():
        print(
            f"{name:<15} {str(s['auto_promote']).lower():<6} "
            f"{str(s['promoted']).lower():<9} {s['desired_total']:<8} "
            f"{s['desired_canaries']:<9} {s['placed_allocs']:<7} "
            f"{s['healthy_allocs']:<8} {s['unhealthy_allocs']:<9}"
        )
    return 0


def cmd_deployment_promote(args) -> int:
    c = _client(args)
    try:
        c.deployments.promote(args.deployment_id)
    except APIException as e:
        return _fail(str(e))
    print("==> deployment promoted")
    return 0


def cmd_deployment_fail(args) -> int:
    c = _client(args)
    try:
        c.deployments.fail(args.deployment_id)
    except APIException as e:
        return _fail(str(e))
    print("==> deployment failed")
    return 0


def cmd_deployment_pause(args) -> int:
    """`nomad deployment pause|resume` (command/deployment_pause.go,
    deployment_resume.go)."""
    c = _client(args)
    pause = not getattr(args, "resume", False)
    try:
        c.deployments.pause(args.deployment_id, pause)
    except APIException as e:
        return _fail(str(e))
    print(f"==> deployment {'paused' if pause else 'resumed'}")
    return 0


def cmd_operator_debug(args) -> int:
    """`nomad operator debug` (command/operator_debug.go:54): capture a
    support bundle (metrics, broker/worker/raft stats, thread dump) to a
    file or stdout."""
    c = _client(args)
    bundle = c._request("GET", "/v1/operator/debug")
    if args.output:
        with open(args.output, "w") as f:
            json.dump(bundle, f, indent=2)
        print(f"==> debug bundle written to {args.output}")
    else:
        print(json.dumps(bundle, indent=2))
    return 0


def cmd_job_validate(args) -> int:
    """`nomad job validate` (command/job_validate.go): local admission
    validation of a jobspec file, no server round trip."""
    from ..api.codec import decode_job
    from ..structs.job import validate_job

    payload = _load_jobfile(
        args.file, _parse_var_flags(getattr(args, "var", None))
    )
    try:
        job = decode_job(payload)
        validate_job(job)
    except Exception as e:  # noqa: BLE001 — validation errors surface
        print(f"Job validation errors:\n  * {e}")
        return 1
    print("Job validation successful")
    return 0


def cmd_alloc_stop(args) -> int:
    """`nomad alloc stop` (command/alloc_stop.go): stop + replace one
    allocation."""
    c = _client(args)
    try:
        out = c._request("POST", f"/v1/allocation/{args.alloc_id}/stop")
    except APIException as e:
        return _fail(str(e))
    print(f"==> alloc {args.alloc_id[:8]} stopping "
          f"(eval {out['eval_id'][:8]})")
    return 0


def cmd_job_history(args) -> int:
    """`nomad job history` (command/job_history.go)."""
    c = _client(args)
    out = c._request("GET", f"/v1/job/{args.job_id}/versions")
    for v in out.get("versions", []):
        stable = "stable" if v.get("stable") else ""
        print(
            f"Version {v.get('version', 0):>3}  "
            f"priority={v.get('priority', 50)}  {stable}"
        )
    return 0


def cmd_job_inspect(args) -> int:
    """`nomad job inspect` (command/job_inspect.go): raw job JSON."""
    c = _client(args)
    out = c._request("GET", f"/v1/job/{args.job_id}")
    print(json.dumps(out, indent=2, default=str))
    return 0


def cmd_job_revert(args) -> int:
    """`nomad job revert <job> <version>` (command/job_revert.go)."""
    c = _client(args)
    out = c._request(
        "POST",
        f"/v1/job/{args.job_id}/revert",
        body={"job_version": int(args.version)},
    )
    print(
        f"==> reverted {args.job_id} to version {out['reverted_to']} "
        f"(eval {out.get('eval_id', '')[:8]})"
    )
    return 0


def cmd_job_eval(args) -> int:
    """`nomad job eval` (command/job_eval.go): force a re-evaluation."""
    c = _client(args)
    out = c._request("POST", f"/v1/job/{args.job_id}/evaluate")
    print(f"==> created evaluation {out['eval_id'][:8]}")
    return 0


def cmd_job_dispatch(args) -> int:
    """`nomad job dispatch` (command/job_dispatch.go)."""
    c = _client(args)
    meta = dict(kv.split("=", 1) for kv in (args.meta or []))
    out = c.jobs.dispatch(
        args.job_id, payload=(args.payload or "").encode(), meta=meta
    )
    print(f"==> dispatched {out.get('dispatched_job_id', '')}")
    return 0


def cmd_job_periodic_force(args) -> int:
    """`nomad job periodic force` (command/job_periodic_force.go)."""
    c = _client(args)
    out = c._request("POST", f"/v1/job/{args.job_id}/periodic/force")
    print(f"==> forced periodic launch, eval {out.get('eval_id', '')[:8]}")
    return 0


def cmd_eval_list(args) -> int:
    """`nomad eval list` (command/eval_list.go)."""
    c = _client(args)
    evs = c._request("GET", "/v1/evaluations")
    rows = [("ID", "Priority", "Type", "TriggeredBy", "Job", "Status")]
    for e in evs[:50]:
        rows.append((
            e.get("id", "")[:8], str(e.get("priority", "")),
            e.get("type", ""), e.get("triggered_by", ""),
            e.get("job_id", ""), e.get("status", ""),
        ))
    w = [max(len(r[i]) for r in rows) for i in range(6)]
    for r in rows:
        print("  ".join(v.ljust(x) for v, x in zip(r, w)))
    return 0


def cmd_system_gc(args) -> int:
    """`nomad system gc` (command/system_gc.go)."""
    c = _client(args)
    out = c._request("PUT", "/v1/system/gc")
    print("==> gc:", json.dumps(out.get("reaped", {})))
    return 0


def cmd_operator_snapshot_save(args) -> int:
    """`nomad operator snapshot save` (command/operator_snapshot_save.go)."""
    c = _client(args)
    out = c._request(
        "POST", "/v1/operator/snapshot/save", body={"path": args.path}
    )
    print(f"==> snapshot at index {out['index']} written to {out['path']}")
    return 0


def cmd_operator_metrics(args) -> int:
    """`nomad operator metrics` (command/operator_metrics.go)."""
    c = _client(args)
    print(json.dumps(c._request("GET", "/v1/metrics"), indent=2))
    return 0


def cmd_trace(args) -> int:
    """`nomad-tpu trace [eval_id]` — flight-recorder view. Without an
    id: recent completed traces + last error events. With one: the full
    span tree rendered as an indented duration breakdown."""
    c = _client(args)
    if args.eval_id:
        try:
            tr = c._request("GET", f"/v1/agent/trace/{args.eval_id}")
        except APIException as e:
            return _fail(str(e))
        if args.json:
            print(json.dumps(tr, indent=2))
        else:
            from ..obs.recorder import render_trace

            print(render_trace(tr))
        return 0
    out = c._request("GET", "/v1/agent/trace")
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    traces = out.get("traces", [])
    if not traces:
        print("no completed traces recorded")
    for t in traces:
        print(
            f"{t['eval_id']}  {t['status']:<7} "
            f"{t['duration_ms']:>9.2f}ms  {t['spans']:>3} spans  "
            + ",".join(f"{k}={v}" for k, v in sorted(t["tags"].items()))
        )
    errors = out.get("errors", [])
    if errors:
        print(f"\n{len(errors)} recent error event(s):")
        for ev in errors[:10]:
            tail = f"  eval={ev['eval_id']}" if ev.get("eval_id") else ""
            print(f"  [{ev['component']}] {ev['error']}{tail}")
    return 0


def cmd_resilience_status(args) -> int:
    """`nomad-tpu resilience status` — per-kernel circuit-breaker
    states, the forced-open override, recent trip events, and the
    resilience counters (/v1/agent/resilience)."""
    c = _client(args)
    try:
        out = c._request("GET", "/v1/agent/resilience")
    except APIException as e:
        return _fail(str(e))
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    breakers = out.get("breakers", {})
    if out.get("forced_open"):
        print("forced open: ALL kernels routed to the reference path")
    if not breakers:
        print("no kernel breakers registered (no kernel has run yet)")
    for name in sorted(breakers):
        b = breakers[name]
        extra = ""
        if b["state"] != "closed":
            extra = (
                f"  probe_in={b.get('probe_in_s', 0.0):.1f}s"
                f"  last_error={b.get('last_error') or '-'}"
            )
        print(
            f"{name:<40} {b['state']:<9} trips={b['trips']:<3} "
            f"consecutive_failures={b['consecutive_failures']}{extra}"
        )
    trips = out.get("recent_trips", [])
    if trips:
        print(f"\n{len(trips)} recent trip event(s):")
        for ev in trips[:10]:
            print(f"  [{ev['component']}] {ev['error']}")
    lanes = out.get("lanes", {})
    if lanes.get("lane_mode"):
        claims = lanes.get("claims", {})
        print(
            f"\nlanes: {lanes['num_lanes']} across "
            f"{lanes['num_batch_workers']} batch worker(s)"
        )
        for w in sorted(lanes.get("assignments", {}), key=int):
            owned = lanes["assignments"][w]
            print(f"  worker {w}: lanes {','.join(map(str, owned))}")
        if claims:
            cc = claims.get("counters", {})
            print(
                f"  handoffs: reserves={cc.get('reserves', 0)} "
                f"confirms={cc.get('confirms', 0)} "
                f"rejected={cc.get('confirm_rejected', 0)} "
                f"active={claims.get('active_claims', 0)}"
            )
    adm = out.get("admission")
    if adm:
        sig = adm.get("signals") or {}
        print(
            f"\nadmission: level={adm['level']} "
            f"since={adm.get('since_s', 0.0):.1f}s "
            f"changes={adm.get('level_changes', 0)}"
            + (" (forced)" if adm.get("forced") else "")
        )
        print(
            f"  signals: backlog={sig.get('backlog', 0)} "
            f"p99={sig.get('p99_ms', 0.0):.1f}ms "
            f"arrival={sig.get('arrival_rate', 0.0):.1f}/s "
            f"completion={sig.get('completion_rate', 0.0):.1f}/s"
        )
        for tier in ("high", "normal", "low"):
            c = (adm.get("counters") or {}).get(tier)
            if c and c.get("submitted"):
                print(
                    f"  {tier:<7} submitted={c['submitted']} "
                    f"admitted={c['admitted']} deferred={c['deferred']} "
                    f"shed={c['shed']}"
                )
    counters = out.get("counters", {})
    if counters:
        print("\ncounters:")
        for k in sorted(counters):
            print(f"  {k} = {counters[k]}")
    return 0


def cmd_slo_report(args) -> int:
    """`nomad-tpu slo report` — the live SLO report from
    /v1/agent/slo: eval/placement latency percentiles (always-on, fed
    by the flight recorder), queue depth, resilience/lane counters,
    ring coverage, and the verdict against declared targets."""
    c = _client(args)
    params = {}
    if args.eval_p99_ms is not None:
        params["eval_p99_ms"] = args.eval_p99_ms
    if args.placement_p99_ms is not None:
        params["placement_p99_ms"] = args.placement_p99_ms
    qs = "&".join(f"{k}={v}" for k, v in params.items())
    try:
        out = c._request("GET", "/v1/agent/slo" + (f"?{qs}" if qs else ""))
    except APIException as e:
        return _fail(str(e))
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    slo = out.get("slo", {})
    targets = out.get("targets", {})
    for key, label in (
        ("eval_latency_ms", "eval latency"),
        ("placement_latency_ms", "placement"),
        ("plan_apply_ms", "plan apply"),
    ):
        s = slo.get(key, {})
        print(
            f"{label:<14} p50={s.get('p50_ms', 0.0):>9.2f}ms "
            f"p95={s.get('p95_ms', 0.0):>9.2f}ms "
            f"p99={s.get('p99_ms', 0.0):>9.2f}ms "
            f"max={s.get('max_ms', 0.0):>9.2f}ms "
            f"(n={s.get('count', 0)})"
        )
    q = slo.get("queue_depth", {})
    print(f"queue depth    now={q.get('max', 0.0):.0f}")
    cov = slo.get("ring_coverage", {})
    print(
        f"trace ring     recorded={cov.get('traces_recorded', 0)} "
        f"evicted={cov.get('traces_evicted', 0)} "
        f"coverage={cov.get('coverage', 1.0):.2%}"
    )
    ctr = slo.get("counters", {})
    nonzero = {k: v for k, v in sorted(ctr.items()) if v}
    if nonzero:
        print("counters:")
        for k, v in nonzero.items():
            print(f"  {k} = {int(v)}")
    v = slo.get("verdict", {})
    if v.get("pass"):
        print("SLO PASS")
        return 0
    print("SLO FAIL:")
    for f in v.get("failures", ()):
        print(f"  {f}")
    checked = {k: t for k, t in targets.items() if t is not None}
    print("targets: " + " ".join(f"{k}={t:g}" for k, t in checked.items()))
    return 1


def cmd_calibrate_status(args) -> int:
    """`nomad-tpu calibrate status` — one-screen calibration summary
    from /v1/agent/calibration: constants by provenance, the loaded
    probe artifact, learned estimator cells, throughput source."""
    c = _client(args)
    try:
        out = c._request("GET", "/v1/agent/calibration")
    except APIException as e:
        return _fail(str(e))
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    table = out.get("table", {})
    by_source = table.get("by_source", {})
    print(
        f"constants: {len(table.get('constants', {}))} "
        f"(default={by_source.get('default', 0)} "
        f"probe={by_source.get('probe', 0)} "
        f"learned={by_source.get('learned', 0)})"
    )
    probe = table.get("probe")
    if probe:
        print(
            f"probe artifact: rate={probe.get('rate_evals_per_s', 0.0):g}/s "
            f"seed={probe.get('seed', 0)} nodes={probe.get('nodes', 0)} "
            f"window={probe.get('probe_seconds', 0.0):g}s"
        )
    else:
        print("probe artifact: none loaded")
    est = out.get("estimator", {})
    print(
        f"estimator: cells={est.get('cell_count', 0)} "
        f"learned={est.get('learned_cells', 0)} "
        f"samples={est.get('samples', 0)} "
        f"dropped={est.get('dropped', 0)}"
    )
    print(f"throughput source: {out.get('throughput_source', 'declared')}")
    return 0


def cmd_calibrate_report(args) -> int:
    """`nomad-tpu calibrate report` — the full calibration plane: every
    constant with value/source/provenance and every learned
    per-(device class × job profile) throughput cell."""
    c = _client(args)
    try:
        out = c._request("GET", "/v1/agent/calibration")
    except APIException as e:
        return _fail(str(e))
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    constants = (out.get("table") or {}).get("constants", {})
    print(f"{'constant':<36} {'value':>12} {'source':<8} samples window")
    for name in sorted(constants):
        e = constants[name]
        print(
            f"{name:<36} {e.get('value', 0.0):>12g} "
            f"{e.get('source', '?'):<8} "
            f"{e.get('samples', 0):>7} {e.get('window') or '-'}"
        )
    cells = (out.get("estimator") or {}).get("cells", {})
    if cells:
        print(
            f"\n{'device class × profile':<36} {'ema':>10} "
            f"{'p50':>10} {'conf':>6} samples source"
        )
        for key in sorted(cells):
            cell = cells[key]
            print(
                f"{key:<36} {cell.get('ema', 0.0):>10.3f} "
                f"{cell.get('p50', 0.0):>10.3f} "
                f"{cell.get('confidence', 0.0):>6.2f} "
                f"{cell.get('samples', 0):>7} {cell.get('source', '?')}"
            )
    else:
        print("\nno learned throughput cells yet")
    print(f"\nthroughput source: {out.get('throughput_source', 'declared')}")
    return 0


def cmd_scaling_policies(args) -> int:
    """`nomad scaling policy list` (command/scaling_policy_list.go)."""
    c = _client(args)
    print(json.dumps(c._request("GET", "/v1/scaling/policies"), indent=2))
    return 0


def cmd_acl_bootstrap(args) -> int:
    c = _client(args)
    out = c._request("POST", "/v1/acl/bootstrap")
    print(f"Accessor ID = {out['AccessorID']}")
    print(f"Secret ID   = {out['SecretID']}")
    return 0


def cmd_acl_policy_apply(args) -> int:
    c = _client(args)
    rules = open(args.rules_file).read()
    c._request(
        "POST", f"/v1/acl/policy/{args.name}", body={"Rules": rules}
    )
    print(f"==> wrote policy {args.name}")
    return 0


def cmd_acl_policy_list(args) -> int:
    c = _client(args)
    for p in c._request("GET", "/v1/acl/policies"):
        print(p.get("Name", p.get("name", "")))
    return 0


def cmd_acl_policy_delete(args) -> int:
    c = _client(args)
    c._request("DELETE", f"/v1/acl/policy/{args.name}")
    print(f"==> deleted policy {args.name}")
    return 0


def cmd_acl_token_create(args) -> int:
    c = _client(args)
    out = c._request(
        "POST",
        "/v1/acl/token",
        body={
            "Name": args.name,
            "Type": args.type,
            "Policies": args.policy or [],
        },
    )
    print(f"Accessor ID = {out['AccessorID']}")
    print(f"Secret ID   = {out['SecretID']}")
    return 0


def cmd_acl_token_list(args) -> int:
    c = _client(args)
    for t in c._request("GET", "/v1/acl/tokens"):
        print(
            f"{t.get('AccessorID', '')[:8]}  {t.get('Type', ''):<10} "
            f"{t.get('Name', '')}"
        )
    return 0


def cmd_acl_token_delete(args) -> int:
    c = _client(args)
    c._request("DELETE", f"/v1/acl/token/{args.accessor}")
    print(f"==> deleted token {args.accessor}")
    return 0


def cmd_version(args) -> int:
    import nomad_tpu

    print(f"nomad-tpu v{nomad_tpu.__version__}")
    return 0


def cmd_chaos_run(args) -> int:
    """`nomad-tpu chaos run` — deterministic fault-injection run against
    an in-process cluster (nomad_tpu.chaos). Deliberately NOT behind the
    HTTP boundary: chaos needs to reach inside the broker/applier seams,
    so it boots its own single-server cluster rather than dialing an
    agent. Exit 0 on a clean invariant report, 1 on any violation."""
    from ..chaos import FAULT_KINDS, run_chaos, shrink_schedule

    faults = tuple(args.faults.split("+")) if args.faults else FAULT_KINDS
    unknown = [f for f in faults if f not in FAULT_KINDS]
    if unknown:
        return _fail(
            f"unknown fault kind(s) {'+'.join(unknown)}; "
            f"choose from {'+'.join(FAULT_KINDS)}"
        )
    run = run_chaos(
        seed=args.seed,
        steps=args.steps,
        faults=faults,
        nodes=args.nodes,
        rate=args.rate,
        num_batch_workers=args.batch_workers,
    )
    if args.json:
        print(run.canonical_json())
    else:
        print(run.render(verbose=args.verbose))
    if run.ok:
        return 0
    if args.shrink:
        print("shrinking failing schedule...", file=sys.stderr)
        minimal, fail = shrink_schedule(
            seed=args.seed,
            steps=args.steps,
            faults=faults,
            nodes=args.nodes,
            rate=args.rate,
            num_batch_workers=args.batch_workers,
            log=lambda m: print(m, file=sys.stderr),
        )
        if fail is None:
            print("failure did not reproduce under shrink", file=sys.stderr)
        else:
            print(f"minimal failing schedule ({len(minimal)} faults):")
            for spec in minimal:
                print(f"  {spec.row()}")
    return 1


def cmd_analyze_kernels(args) -> int:
    """`nomad-tpu analyze kernels` — jaxpr lint over the traced fleet.
    In-process (not behind the HTTP boundary): the analyzer re-traces
    the kernels from the registry, which only exists where the kernels
    are importable. Exit 0 when every finding is baselined, 1 on any
    new finding or failed invariance proof."""
    from ..analysis.jaxlint import engine, fingerprint_table

    code, new, fixed, reports = engine.run_jaxlint(
        fix_baseline=args.fix_baseline
    )
    fps = fingerprint_table()
    diff_report = None
    if args.diff:
        from ..analysis.jaxlint.diff import prove_all

        diff_report = prove_all()
        code = code or (0 if diff_report["ok"] else 1)

    if args.json:
        print(json.dumps({
            "kernels": {
                name: r | {"fingerprints": fps.get(r["short"], {})}
                for name, r in reports.items()
            },
            "new": [
                f.__dict__ | {"fingerprint": f.fingerprint} for f in new
            ],
            "fixed": sorted(fixed),
            "diff": diff_report,
        }, indent=2, default=str))
        return code

    rows = [("Kernel", "Configs", "Findings", "Fingerprints")]
    for name, r in sorted(reports.items()):
        per = fps.get(r["short"], {})
        rows.append((
            r["short"],
            str(len(r["configs"])),
            str(r["findings"]),
            "; ".join(
                f"{label}: {fp}" for label, fp in sorted(per.items())
            ) or "-",
        ))
    w = [max(len(r[i]) for r in rows) for i in range(4)]
    for r in rows:
        print("  ".join(v.ljust(x) for v, x in zip(r, w)))
    for f in new:
        print(f.render())
    if fixed:
        print(
            f"note: {len(fixed)} baselined finding(s) no longer fire — "
            "run --fix-baseline to tighten the ratchet"
        )
    if diff_report is not None:
        for key in ("explain", "mesh"):
            rep = diff_report[key]
            status = "SKIP" if rep.get("skipped") else (
                "OK" if rep["ok"] else "FAIL"
            )
            print(f"invariant [{status}] {rep['claim']}")
    print(
        f"{len(new)} new finding(s) across {len(reports)} kernel(s)"
    )
    return code


def cmd_operator_raft_list(args) -> int:
    """`nomad operator raft list-peers`
    (command/operator_raft_list.go)."""
    c = _client(args)
    cfg = c._request("GET", "/v1/operator/raft/configuration")
    rows = [("ID", "Address", "State", "Voter")]
    for s in cfg.get("servers", []):
        rows.append((
            s["id"],
            s["address"],
            "leader" if s.get("leader") else "follower",
            "true" if s.get("voter") else "false",
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(4)]
    for r in rows:
        print("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    return 0


def cmd_operator_raft_remove(args) -> int:
    """`nomad operator raft remove-peer -peer-id=<id>`
    (command/operator_raft_remove.go)."""
    c = _client(args)
    c._request(
        "DELETE", "/v1/operator/raft/peer", params={"id": args.peer_id}
    )
    print(f"==> removed raft peer {args.peer_id}")
    return 0


def cmd_operator_defrag(args) -> int:
    """`nomad-tpu operator defrag [--trigger|--pause|--resume]` — the
    live-migration control plane: status/counters by default, or poke
    the controller (server/defrag.py)."""
    c = _client(args)
    if args.pause or args.resume:
        st = c._request(
            "POST", "/v1/operator/defrag", body={"paused": bool(args.pause)}
        )
        print(f"==> defrag {'paused' if st['paused'] else 'resumed'}")
    elif args.trigger:
        st = c._request("POST", "/v1/operator/defrag", body={})
        print("==> defrag cycle triggered")
    else:
        st = c._request("GET", "/v1/operator/defrag")
    mode = "continuous" if st.get("enabled") else "on-demand"
    if st.get("paused"):
        mode += " (paused)"
    print(f"==> defrag: {mode}  interval={st.get('interval')}s  "
          f"budget={st.get('budget')} moves/cycle")
    print(f"    packing efficiency: {st.get('packing_efficiency')}")
    print(f"    cycles with moves:  {st.get('cycles')}")
    for k, v in sorted((st.get("counters") or {}).items()):
        print(f"    {k}: {v:g}")
    return 0


def cmd_operator_scheduler(args) -> int:
    c = _client(args)
    if args.algorithm:
        c.operator.set_scheduler_config(scheduler_algorithm=args.algorithm)
        print(f"==> scheduler algorithm set to {args.algorithm}")
    cfg = c.operator.scheduler_config()
    print(json.dumps(cfg, indent=2))
    return 0


def cmd_operator_placements(args) -> int:
    """`nomad operator placements` — live per-device-class allocation
    counts and the active algorithm (heterogeneity observability)."""
    c = _client(args)
    rep = c._request("GET", "/v1/operator/scheduler/placements")
    print(f"==> scheduler algorithm: {rep['scheduler_algorithm']}")
    print(f"{'Device Class':<16} {'Nodes':>6} {'Allocs':>7}")
    allocs = rep.get("allocs_per_class", {})
    for dc, n in sorted(rep.get("nodes_per_class", {}).items()):
        label = dc or "(class-less)"
        print(f"{label:<16} {n:>6} {allocs.get(dc, 0):>7}")
    jobs = rep.get("jobs", {})
    if jobs:
        print("\nPer job:")
        for jk, classes in jobs.items():
            parts = ", ".join(
                f"{dc or '(class-less)'}={cnt}"
                for dc, cnt in classes.items()
            )
            print(f"  {jk}: {parts}")
    topo = rep.get("topology", {})
    for level in ("racks", "pods"):
        rows = topo.get(level, {})
        # a single "" bucket means the fleet carries no coordinates at
        # this level — nothing to show
        if not rows or set(rows) == {""}:
            continue
        print(f"\n{level.capitalize():<16} {'Nodes':>6} {'Allocs':>7}")
        for name, row in sorted(rows.items()):
            label = name or "(none)"
            print(
                f"{label:<16} {row.get('nodes', 0):>6} "
                f"{row.get('allocs', 0):>7}"
            )
    gangs = rep.get("gangs", {})
    if gangs:
        print("\nGangs:")
        for jk, g in sorted(gangs.items()):
            state = "intact" if g.get("intact") else "released"
            parts = ", ".join(
                f"{m}={cnt}/{g.get('desired', {}).get(m, 0)}"
                for m, cnt in sorted(g.get("members", {}).items())
            )
            print(f"  {jk}: {state} ({parts})")
    return 0


def cmd_namespace(args) -> int:
    c = _client(args)
    try:
        if args.ns_cmd == "list":
            for n in c.namespaces.list():
                print(f"{n['name']:<20} {n.get('description','')}")
        elif args.ns_cmd == "apply":
            c.namespaces.apply(args.name, args.description or "")
            print(f"namespace {args.name!r} applied")
        elif args.ns_cmd == "delete":
            c.namespaces.delete(args.name)
            print(f"namespace {args.name!r} deleted")
        elif args.ns_cmd == "status":
            print(json.dumps(c.namespaces.info(args.name), indent=2))
    except APIException as e:
        return _fail(str(e))
    return 0


def cmd_job_scale(args) -> int:
    """nomad job scale <job> [group] <count> (command/job_scale.go)."""
    sa = args.scale_args
    if len(sa) == 2:
        job_id, group, count_s = sa[0], None, sa[1]
    elif len(sa) == 3:
        job_id, group, count_s = sa
    else:
        return _fail("usage: job scale <job> [group] <count>")
    try:
        count = int(count_s)
    except ValueError:
        return _fail(f"count must be an integer, got {count_s!r}")
    args.job_id, args.count = job_id, count
    c = _client(args)
    if group is None:
        try:
            info = c.jobs.info(args.job_id)
        except APIException as e:
            return _fail(str(e))
        tgs = [tg["name"] for tg in info.get("task_groups", [])]
        if len(tgs) != 1:
            return _fail(f"job has multiple groups, pick one: {tgs}")
        group = tgs[0]
    try:
        out = c.jobs.scale(args.job_id, group, args.count)
    except APIException as e:
        return _fail(str(e))
    print(f"==> scaled {args.job_id}/{group} to {args.count}; "
          f"evaluation {out['eval_id']}")
    return 0


def cmd_status(args) -> int:
    """nomad status <prefix>: cross-context search dispatch
    (command/status.go + search_endpoint.go)."""
    c = _client(args)
    try:
        if not args.prefix:
            return cmd_job_status(argparse.Namespace(
                address=args.address, job_id=None))
        res = c.search(args.prefix)
        hits = [(ctx, m) for ctx, ms in res["matches"].items() for m in ms]
        if not hits:
            return _fail(f"no matches for {args.prefix!r}")
        if len(hits) > 1:
            print(f"multiple matches for {args.prefix!r}:")
            for ctx, m in hits:
                print(f"  {ctx[:-1]:<12} {m}")
            return 0
        ctx, m = hits[0]
        ns = argparse.Namespace(address=args.address)
        if ctx == "jobs":
            ns.job_id = m
            return cmd_job_status(ns)
        if ctx == "nodes":
            ns.node_id = m
            return cmd_node_status(ns)
        if ctx == "allocs":
            ns.alloc_id = m
            return cmd_alloc_status(ns)
        if ctx == "evals":
            ns.eval_id = m
            return cmd_eval_status(ns)
        print(f"{ctx[:-1]}: {m}")
    except APIException as e:
        return _fail(str(e))
    return 0


def cmd_server_members(args) -> int:
    c = _client(args)
    info = c.agent.self()
    print(json.dumps(info, indent=2))
    return 0


# -- parser -----------------------------------------------------------------
def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="nomad-tpu")
    p.add_argument("-address", "--address", default=DEFAULT_ADDR)
    p.add_argument(
        "-token", "--token",
        default=os.environ.get("NOMAD_TOKEN", ""),
        help="ACL secret (or env NOMAD_TOKEN)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    agent = sub.add_parser("agent", help="run an agent")
    agent.add_argument("-dev", action="store_true", dest="dev")
    agent.add_argument("--data-dir", default="")
    agent.add_argument("--bind", default="127.0.0.1:4646")
    agent.add_argument(
        "-config", action="append", dest="config", default=[],
        help="HCL agent config file (repeatable; merged in order)",
    )
    agent.set_defaults(fn=cmd_agent)

    job = sub.add_parser("job", help="job commands").add_subparsers(
        dest="sub", required=True
    )
    run = job.add_parser("run")
    run.add_argument("file")
    run.add_argument("-detach", action="store_true")
    run.add_argument("-var", action="append", dest="var", metavar="key=value")
    run.set_defaults(fn=cmd_job_run)
    plan = job.add_parser("plan")
    plan.add_argument("file")
    plan.add_argument("-var", action="append", dest="var", metavar="key=value")
    plan.add_argument(
        "-verbose", action="store_true", dest="verbose",
        help="show per-group candidate score tables",
    )
    plan.set_defaults(fn=cmd_job_plan)
    status = job.add_parser("status")
    status.add_argument("job_id", nargs="?")
    status.set_defaults(fn=cmd_job_status)
    scale = job.add_parser("scale")
    scale.add_argument("scale_args", nargs="+",
                       metavar="job [group] count")
    scale.set_defaults(fn=cmd_job_scale)
    stop = job.add_parser("stop")
    stop.add_argument("job_id")
    stop.set_defaults(fn=cmd_job_stop)
    hist = job.add_parser("history")
    hist.add_argument("job_id")
    hist.set_defaults(fn=cmd_job_history)
    insp = job.add_parser("inspect")
    insp.add_argument("job_id")
    insp.set_defaults(fn=cmd_job_inspect)
    rev = job.add_parser("revert")
    rev.add_argument("job_id")
    rev.add_argument("version")
    rev.set_defaults(fn=cmd_job_revert)
    jeval = job.add_parser("eval")
    jeval.add_argument("job_id")
    jeval.set_defaults(fn=cmd_job_eval)
    disp = job.add_parser("dispatch")
    disp.add_argument("job_id")
    disp.add_argument("--payload", default="")
    disp.add_argument("--meta", action="append", metavar="key=value")
    disp.set_defaults(fn=cmd_job_dispatch)
    pforce = job.add_parser("periodic-force")
    pforce.add_argument("job_id")
    pforce.set_defaults(fn=cmd_job_periodic_force)
    jval = job.add_parser("validate")
    jval.add_argument("file")
    jval.add_argument("-var", action="append", dest="var", metavar="key=value")
    jval.set_defaults(fn=cmd_job_validate)

    node = sub.add_parser("node", help="node commands").add_subparsers(
        dest="sub", required=True
    )
    nstatus = node.add_parser("status")
    nstatus.add_argument("node_id", nargs="?")
    nstatus.set_defaults(fn=cmd_node_status)
    drain = node.add_parser("drain")
    drain.add_argument("node_id")
    drain.add_argument("-disable", action="store_true")
    drain.set_defaults(fn=cmd_node_drain)
    elig = node.add_parser("eligibility")
    elig.add_argument("node_id")
    elig.add_argument("-enable", action="store_true")
    elig.set_defaults(fn=cmd_node_eligibility)

    alloc = sub.add_parser("alloc", help="alloc commands").add_subparsers(
        dest="sub", required=True
    )
    alogs = alloc.add_parser("logs")
    alogs.add_argument("alloc_id")
    alogs.add_argument("task", nargs="?", default=None)
    alogs.add_argument("-stderr", dest="stderr", action="store_true")
    alogs.add_argument("-f", dest="follow", action="store_true")
    alogs.add_argument("-tail", dest="tail", type=int, default=0)
    alogs.set_defaults(fn=cmd_alloc_logs)
    afs = alloc.add_parser("fs")
    afs.add_argument("alloc_id")
    afs.add_argument("path", nargs="?", default="/")
    afs.set_defaults(fn=cmd_alloc_fs)
    astop = alloc.add_parser("stop")
    astop.add_argument("alloc_id")
    astop.set_defaults(fn=cmd_alloc_stop)
    astatus = alloc.add_parser("status")
    astatus.add_argument("alloc_id")
    astatus.set_defaults(fn=cmd_alloc_status)
    awhy = alloc.add_parser(
        "why", help="score provenance: why the alloc landed on its node"
    )
    awhy.add_argument("alloc_id")
    awhy.set_defaults(fn=cmd_alloc_why)

    ev = sub.add_parser("eval", help="eval commands").add_subparsers(
        dest="sub", required=True
    )
    estatus = ev.add_parser("status")
    estatus.add_argument("eval_id")
    estatus.set_defaults(fn=cmd_eval_status)
    elist = ev.add_parser("list")
    elist.set_defaults(fn=cmd_eval_list)
    eplace = ev.add_parser(
        "placement", help="per-group candidate tables for an eval"
    )
    eplace.add_argument("eval_id")
    eplace.set_defaults(fn=cmd_eval_placement)

    dep = sub.add_parser("deployment", help="deployment commands").add_subparsers(
        dest="sub", required=True
    )
    dlist = dep.add_parser("list")
    dlist.set_defaults(fn=cmd_deployment_list)
    dstatus = dep.add_parser("status")
    dstatus.add_argument("deployment_id")
    dstatus.set_defaults(fn=cmd_deployment_status)
    dpromote = dep.add_parser("promote")
    dpromote.add_argument("deployment_id")
    dpromote.set_defaults(fn=cmd_deployment_promote)
    dfail = dep.add_parser("fail")
    dfail.add_argument("deployment_id")
    dfail.set_defaults(fn=cmd_deployment_fail)
    dpause = dep.add_parser("pause")
    dpause.add_argument("deployment_id")
    dpause.set_defaults(fn=cmd_deployment_pause, resume=False)
    dresume = dep.add_parser("resume")
    dresume.add_argument("deployment_id")
    dresume.set_defaults(fn=cmd_deployment_pause, resume=True)

    vol = sub.add_parser("volume", help="volume commands").add_subparsers(
        dest="sub", required=True
    )
    vstatus = vol.add_parser("status")
    vstatus.add_argument("volume_id", nargs="?")
    vstatus.set_defaults(fn=cmd_volume_status)
    vreg = vol.add_parser("register")
    vreg.add_argument("file", help="volume spec JSON file")
    vreg.set_defaults(fn=cmd_volume_register)
    vdereg = vol.add_parser("deregister")
    vdereg.add_argument("volume_id")
    vdereg.add_argument("-force", action="store_true")
    vdereg.set_defaults(fn=cmd_volume_deregister)

    plugin = sub.add_parser("plugin", help="plugin commands").add_subparsers(
        dest="sub", required=True
    )
    pstatus = plugin.add_parser("status")
    pstatus.set_defaults(fn=cmd_plugin_status)

    op = sub.add_parser("operator", help="operator commands").add_subparsers(
        dest="sub", required=True
    )
    from ..scheduler.algorithms import available as _algos

    sched = op.add_parser("scheduler")
    sched.add_argument("--algorithm", choices=_algos())
    sched.set_defaults(fn=cmd_operator_scheduler)
    placements = op.add_parser(
        "placements",
        help="per-device-class and per-rack/pod allocation counts, "
             "plus gang intactness",
    )
    placements.set_defaults(fn=cmd_operator_placements)
    dbg = op.add_parser("debug", help="capture a support bundle")
    dbg.add_argument("--output", "-o", default="")
    dbg.set_defaults(fn=cmd_operator_debug)
    raft = op.add_parser("raft", help="raft operator commands").add_subparsers(
        dest="raft_cmd", required=True
    )
    rlist = raft.add_parser("list-peers")
    rlist.set_defaults(fn=cmd_operator_raft_list)
    rrem = raft.add_parser("remove-peer")
    rrem.add_argument("--peer-id", dest="peer_id", required=True)
    rrem.set_defaults(fn=cmd_operator_raft_remove)
    osnap = op.add_parser("snapshot", help="snapshot commands").add_subparsers(
        dest="snap_cmd", required=True
    )
    osave = osnap.add_parser("save")
    osave.add_argument("path")
    osave.set_defaults(fn=cmd_operator_snapshot_save)
    omet = op.add_parser("metrics")
    omet.set_defaults(fn=cmd_operator_metrics)
    odefrag = op.add_parser(
        "defrag",
        help="live-migration status; --trigger runs a cycle now",
    )
    odefrag.add_argument("--trigger", action="store_true")
    odefrag.add_argument("--pause", action="store_true")
    odefrag.add_argument("--resume", action="store_true")
    odefrag.set_defaults(fn=cmd_operator_defrag)

    system = sub.add_parser("system", help="system commands").add_subparsers(
        dest="sub", required=True
    )
    sgc = system.add_parser("gc")
    sgc.set_defaults(fn=cmd_system_gc)

    scaling = sub.add_parser("scaling", help="scaling commands").add_subparsers(
        dest="sub", required=True
    )
    spol = scaling.add_parser("policies")
    spol.set_defaults(fn=cmd_scaling_policies)

    acl = sub.add_parser("acl", help="acl commands").add_subparsers(
        dest="acl_cmd", required=True
    )
    aboot = acl.add_parser("bootstrap")
    aboot.set_defaults(fn=cmd_acl_bootstrap)
    apol = acl.add_parser("policy").add_subparsers(
        dest="pol_cmd", required=True
    )
    apapply = apol.add_parser("apply")
    apapply.add_argument("name")
    apapply.add_argument("rules_file")
    apapply.set_defaults(fn=cmd_acl_policy_apply)
    aplist = apol.add_parser("list")
    aplist.set_defaults(fn=cmd_acl_policy_list)
    apdel = apol.add_parser("delete")
    apdel.add_argument("name")
    apdel.set_defaults(fn=cmd_acl_policy_delete)
    atok = acl.add_parser("token").add_subparsers(
        dest="tok_cmd", required=True
    )
    atcreate = atok.add_parser("create")
    atcreate.add_argument("--name", default="")
    atcreate.add_argument("--type", default="client")
    atcreate.add_argument("--policy", action="append")
    atcreate.set_defaults(fn=cmd_acl_token_create)
    atlist = atok.add_parser("list")
    atlist.set_defaults(fn=cmd_acl_token_list)
    atdel = atok.add_parser("delete")
    atdel.add_argument("accessor")
    atdel.set_defaults(fn=cmd_acl_token_delete)

    tr = sub.add_parser("trace", help="show recent eval traces")
    tr.add_argument("eval_id", nargs="?", default="")
    tr.add_argument("-json", action="store_true")
    tr.set_defaults(fn=cmd_trace)

    res = sub.add_parser(
        "resilience", help="circuit-breaker / degraded-mode status"
    ).add_subparsers(dest="res_cmd", required=True)
    rstat = res.add_parser("status")
    rstat.add_argument("-json", action="store_true")
    rstat.set_defaults(fn=cmd_resilience_status)

    slo = sub.add_parser(
        "slo", help="steady-state SLO report"
    ).add_subparsers(dest="slo_cmd", required=True)
    srep = slo.add_parser("report")
    srep.add_argument("-json", action="store_true")
    srep.add_argument(
        "--eval-p99-ms", type=float, default=None, dest="eval_p99_ms",
        help="override the eval-latency p99 target for the verdict",
    )
    srep.add_argument(
        "--placement-p99-ms", type=float, default=None,
        dest="placement_p99_ms",
        help="override the placement-latency p99 target for the verdict",
    )
    srep.set_defaults(fn=cmd_slo_report)

    calib = sub.add_parser(
        "calibrate", help="calibration plane: constant provenance, "
        "learned throughputs"
    ).add_subparsers(dest="calib_cmd", required=True)
    cstat = calib.add_parser("status")
    cstat.add_argument("-json", action="store_true")
    cstat.set_defaults(fn=cmd_calibrate_status)
    crep = calib.add_parser("report")
    crep.add_argument("-json", action="store_true")
    crep.set_defaults(fn=cmd_calibrate_report)

    ver = sub.add_parser("version", help="show version")
    ver.set_defaults(fn=cmd_version)

    nsp = sub.add_parser("namespace", help="namespace commands").add_subparsers(
        dest="ns_cmd", required=True
    )
    nlist = nsp.add_parser("list")
    nlist.set_defaults(fn=cmd_namespace)
    napply = nsp.add_parser("apply")
    napply.add_argument("name")
    napply.add_argument("-description", default="")
    napply.set_defaults(fn=cmd_namespace)
    ndel = nsp.add_parser("delete")
    ndel.add_argument("name")
    ndel.set_defaults(fn=cmd_namespace)
    nstat = nsp.add_parser("status")
    nstat.add_argument("name")
    nstat.set_defaults(fn=cmd_namespace)

    st = sub.add_parser("status", help="search across objects")
    st.add_argument("prefix", nargs="?", default="")
    st.set_defaults(fn=cmd_status)

    server = sub.add_parser("server", help="server commands").add_subparsers(
        dest="sub", required=True
    )
    members = server.add_parser("members")
    members.set_defaults(fn=cmd_server_members)

    chaos = sub.add_parser(
        "chaos", help="deterministic fault injection"
    ).add_subparsers(dest="chaos_cmd", required=True)
    crun = chaos.add_parser(
        "run", help="run a seeded in-process cluster under injected faults"
    )
    crun.add_argument("--seed", type=int, default=7)
    crun.add_argument("--steps", type=int, default=200)
    crun.add_argument(
        "--faults",
        default="",
        help="'+'-joined subset of raise+delay+duplicate+drop+kill+skew "
        "(default: all)",
    )
    crun.add_argument("--nodes", type=int, default=6)
    crun.add_argument(
        "--rate", type=float, default=0.04,
        help="fraction of each site's call horizon that faults",
    )
    crun.add_argument("--json", action="store_true",
                      help="emit the canonical (bit-reproducible) report")
    crun.add_argument("--batch-workers", type=int, default=1,
                      help="batching workers for the in-process cluster "
                      "(lane-partitioned commit path when > 1)")
    crun.add_argument("--verbose", action="store_true",
                      help="include timing-dependent diagnostics")
    crun.add_argument("--shrink", action="store_true",
                      help="on violation, shrink to a minimal failing "
                      "fault subset")
    crun.set_defaults(fn=cmd_chaos_run)

    analyze = sub.add_parser(
        "analyze", help="static analysis over the traced kernel fleet"
    ).add_subparsers(dest="analyze_cmd", required=True)
    akern = analyze.add_parser(
        "kernels",
        help="re-trace every traced_jit kernel, run the JXL rules, and "
        "print the fingerprint table (ratchets vs jaxlint/baseline.json)",
    )
    akern.add_argument("--json", action="store_true")
    akern.add_argument(
        "--fix-baseline", action="store_true",
        help="absorb current findings into the jaxpr baseline and exit 0",
    )
    akern.add_argument(
        "--diff", action="store_true",
        help="also run the JXL006 invariance differ (mesh-on/off and "
        "explain-on/off jaxpr equality, fleet-wide)",
    )
    akern.set_defaults(fn=cmd_analyze_kernels)

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # output piped to a closed reader (e.g. `| head`) — not an error
        try:
            sys.stdout.close()
        except Exception:  # noqa: BLE001
            pass
        return 0


if __name__ == "__main__":
    sys.exit(main())
