"""nomad_tpu — a TPU-native cluster-scheduling framework.

A ground-up re-architecture of a Nomad-class workload orchestrator
(reference: goatmale/nomad v1.2.3-dev) in which the host control plane
(state store, eval broker, plan queue, serialized plan applier, client
runners) stays conventional Python/C++, while the per-evaluation placement
decision — feasibility filtering, bin-pack/spread/affinity scoring, and
preemption victim search — runs as compiled JAX/XLA device programs over a
dense ``evals × nodes × resource-dims`` tensor representation of the
cluster.

Layer map (mirrors SURVEY.md §1):

- ``nomad_tpu.structs``    — the shared data model (Job/Node/Alloc/Eval/Plan).
- ``nomad_tpu.state``      — MVCC snapshot state store with index watermarks.
- ``nomad_tpu.device``     — cluster flattening + JAX placement/score kernels.
- ``nomad_tpu.parallel``   — mesh/sharding policy for multi-chip scaling.
- ``nomad_tpu.scheduler``  — reconciler + generic/system schedulers (host logic).
- ``nomad_tpu.broker``     — eval broker, blocked evals, plan queue, plan applier.
- ``nomad_tpu.server``     — the agent composition root: workers, heartbeats.
- ``nomad_tpu.client``     — node agent: fingerprinting, alloc/task runners.
- ``nomad_tpu.api``        — HTTP API + Python SDK.
- ``nomad_tpu.cli``        — command-line interface.
"""

__version__ = "0.1.0"
SCHEDULER_VERSION = 1  # mirrors scheduler/scheduler.go:18 (SchedulerVersion)
