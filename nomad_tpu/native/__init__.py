"""Native runtime components (C++, bound via ctypes).

The reference gets its durable-state performance from native-backed Go
libraries — raft-boltdb for the Raft log, BoltDB for client state
(nomad/server.go:105-109, client/state/). Here that layer is a C++
segmented WAL + durable KV (native/walstore.cpp) compiled lazily on first
import and bound with ctypes (pybind11 is not in the image). A pure-Python
fallback keeps the framework importable if no toolchain is present.
"""

from .wal import WalStore, native_available  # noqa: F401
