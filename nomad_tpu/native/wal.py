"""ctypes binding for the C++ WAL store (native/walstore.cpp), with a
pure-Python fallback implementing the identical interface.

Role in the framework (mirrors the reference's native-speed durable
stores): Raft log + stable store (raft-boltdb analog) and client local
state (BoltDB / helper/boltdd analog, client/state/). Entries are
(index, term, type, payload) records with CRC framing; torn tails are
truncated on open; suffix truncation serves Raft conflict resolution and
prefix compaction follows snapshots.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
import zlib
from typing import Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libnomadwal.so")
_SRC_PATH = os.path.join(_NATIVE_DIR, "walstore.cpp")

_lib = None
_lib_lock = threading.Lock()


def _build_so() -> bool:
    try:
        os.makedirs(os.path.dirname(_SO_PATH), exist_ok=True)
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-std=c++17", "-shared", "-o", _SO_PATH, _SRC_PATH],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if os.path.exists(_SRC_PATH):
            stale = (
                not os.path.exists(_SO_PATH)
                or os.path.getmtime(_SO_PATH) < os.path.getmtime(_SRC_PATH)
            )
            if stale and not _build_so():
                return None
        elif not os.path.exists(_SO_PATH):
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        lib.wal_open.restype = ctypes.c_void_p
        lib.wal_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.wal_close.argtypes = [ctypes.c_void_p]
        lib.wal_first_index.restype = ctypes.c_uint64
        lib.wal_first_index.argtypes = [ctypes.c_void_p]
        lib.wal_last_index.restype = ctypes.c_uint64
        lib.wal_last_index.argtypes = [ctypes.c_void_p]
        lib.wal_append.restype = ctypes.c_int
        lib.wal_append.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.wal_get.restype = ctypes.c_int
        lib.wal_get.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_char_p, ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.wal_truncate_suffix.restype = ctypes.c_int
        lib.wal_truncate_suffix.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.wal_compact_prefix.restype = ctypes.c_int
        lib.wal_compact_prefix.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.wal_sync.restype = ctypes.c_int
        lib.wal_sync.argtypes = [ctypes.c_void_p]
        lib.wal_kv_set.restype = ctypes.c_int
        lib.wal_kv_set.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.wal_kv_get.restype = ctypes.c_int
        lib.wal_kv_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.wal_last_error.restype = ctypes.c_char_p
        lib.wal_last_error.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


class WalError(Exception):
    pass


class _NativeWal:
    def __init__(self, lib, path: str, max_segment_bytes: int):
        self._lib = lib
        self._h = lib.wal_open(path.encode(), max_segment_bytes)
        if not self._h:
            raise WalError(f"wal_open failed for {path}")

    def close(self):
        if self._h:
            self._lib.wal_close(self._h)
            self._h = None

    def _handle(self):
        h = self._h
        if not h:
            raise WalError("wal store is closed")
        return h

    def first_index(self) -> int:
        return self._lib.wal_first_index(self._handle())

    def last_index(self) -> int:
        return self._lib.wal_last_index(self._handle())

    def append(self, index: int, term: int, type_: int, data: bytes) -> None:
        rc = self._lib.wal_append(self._handle(), index, term, type_, data, len(data))
        if rc != 0:
            raise WalError(self._lib.wal_last_error(self._h).decode())

    def get(self, index: int) -> Tuple[int, int, bytes]:
        term = ctypes.c_uint64()
        type_ = ctypes.c_uint32()
        outlen = ctypes.c_uint32()
        rc = self._lib.wal_get(self._handle(), index, term, type_, None, 0, outlen)
        if rc == -3:
            raise KeyError(index)
        if rc != 0:
            raise WalError(self._lib.wal_last_error(self._h).decode())
        buf = ctypes.create_string_buffer(outlen.value)
        rc = self._lib.wal_get(self._handle(), index, term, type_, buf, outlen.value, outlen)
        if rc != 0:
            raise WalError(self._lib.wal_last_error(self._h).decode())
        return term.value, type_.value, buf.raw[: outlen.value]

    def truncate_suffix(self, from_index: int) -> None:
        if self._lib.wal_truncate_suffix(self._handle(), from_index) != 0:
            raise WalError(self._lib.wal_last_error(self._h).decode())

    def compact_prefix(self, to_index: int) -> None:
        if self._lib.wal_compact_prefix(self._handle(), to_index) != 0:
            raise WalError(self._lib.wal_last_error(self._h).decode())

    def sync(self) -> None:
        self._lib.wal_sync(self._handle())

    def kv_set(self, key: str, value: bytes) -> None:
        if self._lib.wal_kv_set(self._handle(), key.encode(), value, len(value)) != 0:
            raise WalError("kv_set failed")

    def kv_get(self, key: str) -> Optional[bytes]:
        n = self._lib.wal_kv_get(self._handle(), key.encode(), None, 0)
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(n or 1)
        self._lib.wal_kv_get(self._handle(), key.encode(), buf, n)
        return buf.raw[:n]


_REC = struct.Struct("<IIQQI")  # crc, len, index, term, type — matches C++


class _PyWal:
    """Pure-Python fallback; same on-disk format as the C++ store, so the
    two are interchangeable on the same directory."""

    def __init__(self, path: str, max_segment_bytes: int):
        self.dir = path
        self.max_segment_bytes = max_segment_bytes or (16 << 20)
        os.makedirs(path, exist_ok=True)
        self._entries: dict[int, tuple[int, int, bytes]] = {}
        self._first = 0
        self._last = 0
        self._kv: dict[str, bytes] = {}
        self._segments: list[tuple[int, str]] = []  # (first_index, path)
        self._tail: Optional[object] = None
        self._tail_size = 0
        self._scan()
        self._load_kv()

    def _scan(self):
        segs = sorted(
            f for f in os.listdir(self.dir) if f.endswith(".seg") and len(f) == 24
        )
        for si, name in enumerate(segs):
            p = os.path.join(self.dir, name)
            good_off = 0
            with open(p, "rb") as f:
                data = f.read()
            off = 0
            while off + _REC.size <= len(data):
                crc, ln, index, term, typ = _REC.unpack_from(data, off)
                end = off + _REC.size + ln
                if ln > (64 << 20) or end > len(data):
                    break
                body = data[off + 4 : end]
                if zlib.crc32(body) & 0xFFFFFFFF != crc:
                    break
                expect = index if self._first == 0 else self._last + 1
                if self._first != 0 and index != expect:
                    break
                if self._first == 0:
                    self._first = index
                self._last = index
                self._entries[index] = (term, typ, data[off + _REC.size : end])
                off = end
                good_off = off
            if good_off < len(data):
                with open(p, "r+b") as f:
                    f.truncate(good_off)
            self._segments.append((int(name[:20]), p))
            # A later segment is orphaned only when it is NON-CONTIGUOUS
            # with what survived (lost entries); a torn tail whose entries
            # all parsed keeps its successors — exactly the C++ open()
            # rule, keeping the two backends interchangeable on one dir.
            next_first = (
                int(segs[si + 1][:20]) if si + 1 < len(segs) else None
            )
            if next_first is not None and (
                self._last == 0 or next_first != self._last + 1
            ):
                for later in segs[si + 1 :]:
                    os.unlink(os.path.join(self.dir, later))
                break
        if self._segments:
            first, p = self._segments[-1]
            self._tail = open(p, "ab")
            self._tail_size = os.path.getsize(p)

    def _load_kv(self):
        p = os.path.join(self.dir, "meta.kv")
        if not os.path.exists(p):
            return
        with open(p, "rb") as f:
            data = f.read()
        if len(data) < 8:
            return
        crc, count = struct.unpack_from("<II", data, 0)
        if zlib.crc32(data[4:]) & 0xFFFFFFFF != crc:
            return
        off = 8
        for _ in range(count):
            kl, vl = struct.unpack_from("<II", data, off)
            off += 8
            k = data[off : off + kl].decode()
            v = data[off + kl : off + kl + vl]
            off += kl + vl
            self._kv[k] = v

    def _save_kv(self):
        body = struct.pack("<I", len(self._kv))
        for k, v in sorted(self._kv.items()):
            kb = k.encode()
            body += struct.pack("<II", len(kb), len(v)) + kb + v
        blob = struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF) + body
        tmp = os.path.join(self.dir, "meta.kv.tmp")
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.dir, "meta.kv"))

    def close(self):
        if self._tail:
            self._tail.close()
            self._tail = None

    def first_index(self) -> int:
        return self._first

    def last_index(self) -> int:
        return self._last

    def _roll(self, next_index: int):
        if self._tail:
            self._tail.close()
        name = f"{next_index:020d}.seg"
        p = os.path.join(self.dir, name)
        self._tail = open(p, "wb")
        self._tail_size = 0
        self._segments.append((next_index, p))

    def append(self, index: int, term: int, type_: int, data: bytes) -> None:
        if len(data) > (64 << 20):  # scanner rejects larger as corruption
            raise WalError("record exceeds 64MB limit")
        expect = index if self._first == 0 else self._last + 1
        if index != expect:
            raise WalError(f"non-contiguous append at {index}")
        if self._tail is None or self._tail_size >= self.max_segment_bytes:
            self._roll(index)
        body = _REC.pack(0, len(data), index, term, type_)[4:] + data
        crc = zlib.crc32(body) & 0xFFFFFFFF
        self._tail.write(struct.pack("<I", crc) + body)
        # Flush through to the OS so a crash-stop (SIGKILL) loses nothing —
        # the native store writes via unbuffered fds and has the same
        # property; fsync (power-loss durability) remains sync()'s job.
        self._tail.flush()
        self._tail_size += _REC.size + len(data)
        if self._first == 0:
            self._first = index
        self._last = index
        self._entries[index] = (term, type_, data)

    def get(self, index: int) -> Tuple[int, int, bytes]:
        if index not in self._entries:
            raise KeyError(index)
        return self._entries[index]

    def truncate_suffix(self, from_index: int) -> None:
        if self._first == 0 or from_index > self._last:
            return
        # Simple fallback: rewrite surviving entries into one fresh segment.
        survivors = [
            (i, *self._entries[i]) for i in range(self._first, from_index)
        ]
        if self._tail:
            self._tail.close()
            self._tail = None
        for _, p in self._segments:
            os.unlink(p)
        self._segments = []
        self._entries = {}
        self._first = self._last = 0
        for i, term, typ, data in survivors:
            self.append(i, term, typ, data)
        if self._tail:
            self._tail.flush()

    def compact_prefix(self, to_index: int) -> None:
        # Segment-granular like the native store: drop whole segments whose
        # entries all fall at or below to_index.
        drop = 0
        for i in range(len(self._segments) - 1):
            if self._segments[i + 1][0] - 1 <= to_index:
                drop = i + 1
            else:
                break
        if not drop:
            return
        new_first = self._segments[drop][0]
        for _, p in self._segments[:drop]:
            os.unlink(p)
        self._segments = self._segments[drop:]
        for i in range(self._first, new_first):
            self._entries.pop(i, None)
        self._first = new_first

    def sync(self) -> None:
        if self._tail:
            self._tail.flush()
            os.fsync(self._tail.fileno())

    def kv_set(self, key: str, value: bytes) -> None:
        self._kv[key] = value
        self._save_kv()

    def kv_get(self, key: str) -> Optional[bytes]:
        return self._kv.get(key)


def WalStore(path: str, max_segment_bytes: int = 0, force_python: bool = False):
    """Open (creating if needed) a WAL store at ``path``.

    Returns the native C++ store when the toolchain/library is available,
    else the pure-Python fallback. Both speak the same on-disk format.
    """
    if not force_python:
        lib = _load()
        if lib is not None:
            return _NativeWal(lib, path, max_segment_bytes)
    return _PyWal(path, max_segment_bytes)
